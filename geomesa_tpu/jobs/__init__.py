"""Batch jobs: input splits + parallel map over a datastore
(geomesa-jobs analog — GeoMesaAccumuloInputFormat.scala:45,163 turns a
query plan into input splits; GeoMesaOutputFormat.scala:29 writes
features; jobs/accumulo/index/ has AttributeIndexJob / SchemaCopyJob;
tools ConverterIngestJob is the distributed ingest).

Here a "split" is a unit the host can process independently — a file
list, a partition, or an index-range slab — and workers are a thread
pool (the JVM's M/R cluster collapses to host threads feeding one TPU;
multi-host would fan splits over controller processes).
"""

from __future__ import annotations

import dataclasses
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterable, Sequence

import numpy as np

from ..features.batch import FeatureBatch
from ..index.api import Query

__all__ = ["InputSplit", "query_splits", "fs_partition_splits", "run_job",
           "ConverterIngestJob", "SchemaCopyJob", "AttributeIndexJob"]


@dataclasses.dataclass
class InputSplit:
    """One independently-processable unit (mapreduce InputSplit)."""
    index: int
    payload: Any          # files, partition name, (lo, hi) row slice, ...
    kind: str = "generic"


def query_splits(store, type_name: str, ecql: str = "INCLUDE",
                 n_splits: int = 8) -> list[InputSplit]:
    """Row-range splits of a query result (the QueryPlan->ranges->splits
    path of GeoMesaAccumuloInputFormat, collapsed to row slabs)."""
    res = store.query(Query(type_name, ecql))
    n = 0 if res.batch is None else res.batch.n
    if n == 0:
        return []
    bounds = np.linspace(0, n, min(n_splits, n) + 1).astype(int)
    return [InputSplit(i, (res.batch, int(bounds[i]), int(bounds[i + 1])),
                       "rows")
            for i in range(len(bounds) - 1) if bounds[i + 1] > bounds[i]]


def fs_partition_splits(fs_store, type_name: str) -> list[InputSplit]:
    """One split per fs-store partition (ParquetConverterJob shape)."""
    return [InputSplit(i, p, "partition")
            for i, p in enumerate(fs_store.partitions(type_name))]


def run_job(map_fn: Callable[[InputSplit], Any],
            splits: Sequence[InputSplit], n_workers: int = 4,
            reduce_fn: Callable[[list], Any] | None = None):
    """Map splits in parallel, optionally reduce. Errors propagate."""
    if not splits:
        return reduce_fn([]) if reduce_fn else []
    with ThreadPoolExecutor(max_workers=min(n_workers, len(splits))) as ex:
        results = list(ex.map(map_fn, splits))
    return reduce_fn(results) if reduce_fn else results


class ConverterIngestJob:
    """Parallel file ingest through a converter into a store
    (tools/ingest ConverterIngestJob analog; local threads instead of
    mappers). Thread-safe: each worker converts independently, writes
    serialize on a lock (the store's write path is host-side append)."""

    def __init__(self, store, sft, converter_config: dict,
                 n_workers: int = 4):
        from ..convert.converter import converter_for
        self.store = store
        self.sft = sft
        self.config = converter_config
        self.n_workers = n_workers
        self._lock = threading.Lock()
        self._converter_for = converter_for

    def run(self, files: Iterable[str]) -> dict:
        if self.sft.type_name not in self.store.get_type_names():
            self.store.create_schema(self.sft)
        counts = {"success": 0, "failure": 0, "files": 0}

        def _map(split: InputSplit):
            conv = self._converter_for(self.sft, self.config)
            with open(split.payload) as fh:
                batch, ctx = conv.process(fh)
            with self._lock:
                if batch.n:
                    self.store.write(self.sft.type_name, batch)
                counts["success"] += ctx.success
                counts["failure"] += ctx.failure
                counts["files"] += 1
            return ctx

        run_job(_map, [InputSplit(i, f, "file")
                       for i, f in enumerate(files)], self.n_workers)
        return counts


class SchemaCopyJob:
    """Copy a type between stores, optionally filtered
    (jobs/accumulo/index/SchemaCopyJob analog)."""

    def __init__(self, source, dest, n_workers: int = 4):
        self.source = source
        self.dest = dest
        self.n_workers = n_workers
        self._lock = threading.Lock()

    def run(self, type_name: str, ecql: str = "INCLUDE") -> int:
        sft = self.source.get_schema(type_name)
        if type_name not in self.dest.get_type_names():
            self.dest.create_schema(sft)
        copied = [0]

        def _map(split: InputSplit):
            batch, lo, hi = split.payload
            sub = batch.take(np.arange(lo, hi))
            with self._lock:
                self.dest.write(type_name, sub)
                copied[0] += sub.n

        run_job(_map, query_splits(self.source, type_name, ecql),
                self.n_workers)
        return copied[0]


class AttributeIndexJob:
    """Backfill an attribute index over existing data
    (jobs/accumulo/index/AttributeIndexJob analog): recompute the
    store's index structures including the named attribute."""

    def __init__(self, store):
        self.store = store

    def run(self, type_name: str, attribute: str) -> int:
        st = self.store._state(type_name)
        attr = st.sft.attr(attribute)  # raises KeyError if absent
        attr.options["index"] = "true"
        st.dirty = True  # force index rebuild on next query
        return st.n
