"""Plain typed API without the query/filter machinery
(geomesa-native-api analog: api/GeoMesaIndex.java:23 — query/insert/
delete of user values with a pluggable ValueSerializer, no GeoTools).

    idx = GeoMesaIndex.memory(PickleSerializer())
    idx.insert("id1", my_obj, x=-75.0, y=38.0, dtg=millis)
    for v in idx.query(bbox=(-80, 35, -70, 40),
                       interval=(t0, t1)): ...
"""

from __future__ import annotations

import json
import pickle
from typing import Any, Callable, Generic, Iterable, TypeVar

import numpy as np

from ..features.batch import FeatureBatch
from ..features.sft import parse_spec
from ..index.api import Query
from ..store.memory import InMemoryDataStore

T = TypeVar("T")

__all__ = ["ValueSerializer", "PickleSerializer", "JsonSerializer",
           "GeoMesaIndex"]


class ValueSerializer(Generic[T]):
    """api/ValueSerializer: user value <-> bytes."""

    def to_bytes(self, value: T) -> bytes:
        raise NotImplementedError

    def from_bytes(self, data: bytes) -> T:
        raise NotImplementedError


class PickleSerializer(ValueSerializer[Any]):
    def to_bytes(self, value) -> bytes:
        return pickle.dumps(value)

    def from_bytes(self, data: bytes):
        return pickle.loads(data)


class JsonSerializer(ValueSerializer[Any]):
    def to_bytes(self, value) -> bytes:
        return json.dumps(value).encode()

    def from_bytes(self, data: bytes):
        return json.loads(data.decode())


_SPEC = ("payload:String,dtg:Date,*geom:Point:srid=4326;"
         "geomesa.index.dtg='dtg'")


class GeoMesaIndex(Generic[T]):
    """Spatio-temporal index of arbitrary values: the stable, GeoTools-free
    entry point (BaseBigTableIndex analog over the in-memory TPU store)."""

    def __init__(self, serializer: ValueSerializer[T],
                 store=None, type_name: str = "values"):
        self.serializer = serializer
        self.type_name = type_name
        self.store = store or InMemoryDataStore()
        if type_name not in self.store.get_type_names():
            self.store.create_schema(parse_spec(type_name, _SPEC))
        self._sft = self.store.get_schema(type_name)

    @classmethod
    def memory(cls, serializer: "ValueSerializer[T]",
               type_name: str = "values") -> "GeoMesaIndex[T]":
        return cls(serializer, InMemoryDataStore(), type_name)

    # -- mutations ---------------------------------------------------------

    def insert(self, fid: str, value: T, x: float, y: float,
               dtg: int | None = None) -> str:
        self.insert_batch([fid], [value], [x], [y],
                          None if dtg is None else [dtg])
        return fid

    def insert_batch(self, fids: Iterable[str], values: Iterable[T],
                     x, y, dtg=None):
        vals = [self.serializer.to_bytes(v).hex() for v in values]
        n = len(vals)
        batch = FeatureBatch.from_dict(
            self._sft, list(fids),
            {"payload": vals,
             "dtg": np.zeros(n, dtype=np.int64) if dtg is None
             else np.asarray(list(dtg), dtype=np.int64),
             "geom": (np.asarray(x, dtype=np.float64),
                      np.asarray(y, dtype=np.float64))})
        self.store.write(self.type_name, batch)

    def delete(self, fid: str):
        self.store.delete(self.type_name, [fid])

    # -- queries -----------------------------------------------------------

    def query(self, bbox=None, interval=None, cql: str | None = None,
              with_ids: bool = False):
        """Values whose point is in bbox and time in interval."""
        clauses = []
        if bbox is not None:
            clauses.append(f"BBOX(geom, {bbox[0]}, {bbox[1]}, "
                           f"{bbox[2]}, {bbox[3]})")
        if interval is not None:
            clauses.append(f"dtg BETWEEN {int(interval[0])} "
                           f"AND {int(interval[1])}")
        if cql:
            clauses.append(cql)
        ecql = " AND ".join(clauses) if clauses else "INCLUDE"
        res = self.store.query(Query(self.type_name, ecql))
        out = []
        if res.batch is not None:
            col = res.batch.col("payload")
            for i in range(res.batch.n):
                v = self.serializer.from_bytes(bytes.fromhex(col.value(i)))
                out.append((str(res.batch.ids[i]), v) if with_ids else v)
        return out

    def get(self, fid: str) -> T | None:
        res = self.store.query(Query(self.type_name, f"IN ('{fid}')"))
        if res.batch is None or res.batch.n == 0:
            return None
        return self.serializer.from_bytes(
            bytes.fromhex(res.batch.col("payload").value(0)))

    def size(self) -> int:
        return self.store.count(self.type_name)
