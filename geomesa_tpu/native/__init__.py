"""Native (C++) runtime components, loaded via ctypes.

The reference is 100% JVM, so "native" there means external Java libs
(Kryo, Arrow, CQEngine — SURVEY.md top note); here the host-side
byte-wrangling hot paths are real C++ compiled on demand with g++ and
loaded with ctypes (no pybind11 in this image). Every native entry
point has a pure-numpy fallback so the framework works without a
toolchain; `load()` returns None when compilation is impossible.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src")
_BUILD = os.path.join(_DIR, "_build")

_lock = threading.Lock()
_cache: dict = {}

_SOURCES = ["feature_codec.cpp", "zrange.cpp", "zencode.cpp",
            "zsort.cpp", "zbuild.cpp"]


def _source_files() -> list:
    return [os.path.join(_SRC, s) for s in _SOURCES
            if os.path.exists(os.path.join(_SRC, s))]


def _digest(paths) -> str:
    h = hashlib.sha256()
    for p in paths:
        with open(p, "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def load() -> "ctypes.CDLL | None":
    """Compile (if needed) and load the native library; None on failure."""
    with _lock:
        if "lib" in _cache:
            return _cache["lib"]
        lib = _build_and_load()
        _cache["lib"] = lib
        return lib


def symbols(signatures: dict) -> "ctypes.CDLL | None":
    """Load the library and configure the given symbols, or None when
    the library or any symbol is unavailable.

    ``signatures`` maps symbol name -> (restype, argtypes). The single
    probe point for every native fast path (zranges/zencode/zsort/...)."""
    lib = load()
    if lib is None:
        return None
    for name, (restype, argtypes) in signatures.items():
        fn = getattr(lib, name, None)
        if fn is None:
            return None
        fn.restype = restype
        fn.argtypes = argtypes
    return lib


def _build_and_load():
    if os.environ.get("GEOMESA_TPU_NO_NATIVE"):
        return None
    srcs = _source_files()
    if not srcs:
        return None
    so = os.path.join(_BUILD, f"libgeomesa_{_digest(srcs)}.so")
    if not os.path.exists(so):
        os.makedirs(_BUILD, exist_ok=True)
        tmp = so + f".tmp{os.getpid()}"
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
               "-pthread", "-o", tmp] + srcs
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, so)
        except (OSError, subprocess.SubprocessError):
            return None
    try:
        return ctypes.CDLL(so)
    except OSError:
        return None
