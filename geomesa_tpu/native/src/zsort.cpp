// Sorted-order construction for the z-key indexes.
//
// np.lexsort((z, bins)) at 100M rows costs two indirect O(N log N)
// argsorts; time bins are small non-negative ints, so a counting sort
// by bin (O(N), stable) followed by a per-segment sort of (z, idx)
// pairs does the same work with one cache-friendly pass per segment.
// Tie order matches lexsort's stability: pairs sort by (z, original
// index), and the bin scatter preserves input order within each bin.
//
// Exported (ctypes):
//   geomesa_sort_bin_z(bins i32[n], z i64[n], n, max_bin,
//                      perm_out i32[n], z_sorted_out i64[n],
//                      offsets_out i64[max_bin+2]) -> 0/-1
//     offsets_out[b] = start of bin b's segment (prefix sums), so the
//     caller derives per-bin boundaries without re-scanning the array
//   geomesa_sort_z(z i64[n], n, perm_out i32[n], z_sorted_out i64[n])

#include <algorithm>
#include <cstdint>
#include <vector>

namespace {

struct Pair {
    int64_t z;
    int32_t idx;
};

inline bool pair_less(const Pair& a, const Pair& b) {
    return a.z != b.z ? a.z < b.z : a.idx < b.idx;
}

}  // namespace

extern "C" int64_t geomesa_sort_bin_z(const int32_t* bins,
                                      const int64_t* z, int64_t n,
                                      int64_t max_bin,
                                      int32_t* perm_out,
                                      int64_t* z_sorted_out,
                                      int64_t* offsets_out) {
    if (n < 0 || max_bin < 0 || max_bin > (1 << 20)) return -1;
    const size_t nb = (size_t)max_bin + 2;
    for (size_t b = 0; b < nb; ++b) offsets_out[b] = 0;
    for (int64_t i = 0; i < n; ++i) {
        const int32_t b = bins[i];
        if (b < 0 || b > max_bin) return -1;
        ++offsets_out[(size_t)b + 1];
    }
    for (size_t b = 1; b < nb; ++b) offsets_out[b] += offsets_out[b - 1];

    std::vector<Pair> pairs((size_t)n);
    {
        std::vector<int64_t> cursor(offsets_out, offsets_out + nb - 1);
        for (int64_t i = 0; i < n; ++i) {
            const int64_t pos = cursor[(size_t)bins[i]]++;
            pairs[(size_t)pos].z = z[i];
            pairs[(size_t)pos].idx = (int32_t)i;
        }
    }
    for (size_t b = 0; b + 1 < nb; ++b) {
        const int64_t s = offsets_out[b], e = offsets_out[b + 1];
        if (e - s > 1)
            std::sort(pairs.begin() + s, pairs.begin() + e, pair_less);
    }
    for (int64_t i = 0; i < n; ++i) {
        z_sorted_out[i] = pairs[(size_t)i].z;
        perm_out[i] = pairs[(size_t)i].idx;
    }
    return 0;
}

extern "C" int64_t geomesa_sort_z(const int64_t* z, int64_t n,
                                  int32_t* perm_out,
                                  int64_t* z_sorted_out) {
    if (n < 0) return -1;
    std::vector<Pair> pairs((size_t)n);
    for (int64_t i = 0; i < n; ++i) {
        pairs[(size_t)i].z = z[i];
        pairs[(size_t)i].idx = (int32_t)i;
    }
    std::sort(pairs.begin(), pairs.end(), pair_less);
    for (int64_t i = 0; i < n; ++i) {
        z_sorted_out[i] = pairs[(size_t)i].z;
        perm_out[i] = pairs[(size_t)i].idx;
    }
    return 0;
}
