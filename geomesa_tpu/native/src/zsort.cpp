// Sorted-order construction for the z-key indexes.
//
// np.lexsort((z, bins)) at 100M rows costs two indirect O(N log N)
// argsorts; time bins are small non-negative ints, so a counting sort
// by bin (O(N), stable) followed by a per-segment sort of (z, idx)
// pairs does the same work with cache-friendly passes. Large segments
// are not std::sort'ed directly: one MSD pass buckets them by the top
// 16 bits of z (another stable counting scatter), leaving sub-runs
// that fit in cache for the final comparison sort — at 100M rows in a
// handful of time bins this is ~3x faster than per-segment std::sort.
// Tie order matches lexsort's stability throughout: pairs sort by
// (z, original index) and every scatter preserves input order.
//
// Work parallelizes over std::thread when the host has cores to spare
// (GEOMESA_TPU_THREADS overrides; hardware_concurrency by default):
// chunked histogram+scatter with per-(thread, bin) cursors keeps the
// scatter stable, and segment sorts drain a shared atomic work queue.
//
// Exported (ctypes):
//   geomesa_sort_bin_z(bins i32[n], z i64[n], n, max_bin,
//                      perm_out i32[n], z_sorted_out i64[n],
//                      offsets_out i64[max_bin+2]) -> 0/-1
//     offsets_out[b] = start of bin b's segment (prefix sums), so the
//     caller derives per-bin boundaries without re-scanning the array
//   geomesa_sort_z(z i64[n], n, perm_out i32[n], z_sorted_out i64[n])

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <thread>
#include <vector>

namespace {

struct Pair {
    int64_t z;
    int32_t idx;
};

inline bool pair_less(const Pair& a, const Pair& b) {
    return a.z != b.z ? a.z < b.z : a.idx < b.idx;
}

int nthreads(int64_t n) {
    const char* e = std::getenv("GEOMESA_TPU_THREADS");
    if (e) {
        // explicit override: honored even for small n, so tests can
        // exercise the parallel scatter without 1M+-row fixtures
        int t = std::atoi(e);
        if (t < 1) t = 1;
        if (t > 64) t = 64;
        if ((int64_t)t > n && n > 0) t = (int)n;
        return t < 1 ? 1 : t;
    }
    if (n < (1 << 18)) return 1;  // not worth the thread spawn
    int t = (int)std::thread::hardware_concurrency();
    if (t < 1) t = 1;
    if (t > 64) t = 64;
    const int64_t per = (int64_t)1 << 20;  // >=1M rows per thread
    if ((int64_t)t > (n + per - 1) / per) t = (int)((n + per - 1) / per);
    return t < 1 ? 1 : t;
}

void run_parallel(int t, void (*fn)(void*, int), void* ctx) {
    if (t <= 1) {
        fn(ctx, 0);
        return;
    }
    std::vector<std::thread> pool;
    pool.reserve(t - 1);
    for (int i = 1; i < t; ++i) pool.emplace_back(fn, ctx, i);
    fn(ctx, 0);
    for (auto& th : pool) th.join();
}

// Segments at or below this go straight to std::sort; larger ones use
// the LSD radix below (comparison sorts of millions of 12-byte pairs
// with a branchy comparator were ~2-4x slower than counting passes on
// the single-core builders this runs on).
constexpr int64_t KSMALL = 1 << 15;
// NBUCKETS is kept as the `hist` scratch contract with callers
constexpr int MAX_BUCKET_BITS = 16;
constexpr int64_t NBUCKETS = 1 << MAX_BUCKET_BITS;

// LSD digit width: 2^11 write streams keep the scatter's active cache
// lines (~128KB) inside L2; 16-bit digits halve the passes but thrash
// (64k streams x 64B lines = 4MB of hot write lines).
constexpr int RADIX_BITS = 11;
constexpr int64_t RADIX_B = (int64_t)1 << RADIX_BITS;
constexpr int RADIX_PASSES = (63 + RADIX_BITS - 1) / RADIX_BITS;

// Sort one contiguous segment of pairs by (z, idx): stable LSD radix.
// Input pairs arrive with idx ascending (the bin scatter is stable),
// and LSD stability preserves that on z ties — identical order to
// std::sort with pair_less. Constant digits (all rows share one
// bucket) skip their pass: z3 keys are 63 bits but a time-binned
// segment's top bits rarely span the full range. `scratch` must hold
// at least the segment; `hist` at least NBUCKETS+1 entries (only
// RADIX_PASSES * RADIX_B of it is used).
// segments above this get one MSD split (top RADIX_BITS) first so the
// work below runs over (near-)cache-resident sub-runs. Mid-size
// segments (z3's per-time-bin runs, a few M pairs) measure FASTER
// under direct LSD than under MSD + per-bucket sorts, so the split
// only engages for huge single segments (the z2 whole-table sort).
constexpr int64_t CACHE_PAIRS = 1 << 23;

void sort_segment(Pair* seg, int64_t len, Pair* scratch, int64_t* hist,
                  int depth = 0);

// One stable MSD pass on an 11-bit window (window lowers with depth so
// skewed data cannot recurse forever), then recurse into each bucket
// (whose LSD skips its now-constant upper digits).
void msd_split(Pair* seg, int64_t len, Pair* scratch, int64_t* hist,
               int depth) {
    const int shift = 63 - RADIX_BITS * (depth + 1);
    for (int64_t b = 0; b <= RADIX_B; ++b) hist[b] = 0;
    for (int64_t i = 0; i < len; ++i)
        ++hist[(((uint64_t)seg[i].z >> shift) & (RADIX_B - 1)) + 1];
    for (int64_t b = 1; b <= RADIX_B; ++b) hist[b] += hist[b - 1];
    std::vector<int64_t> bounds(hist, hist + RADIX_B + 1);
    {
        std::vector<int64_t> cursor(hist, hist + RADIX_B);
        for (int64_t i = 0; i < len; ++i)
            scratch[cursor[((uint64_t)seg[i].z >> shift)
                           & (RADIX_B - 1)]++] = seg[i];
    }
    for (int64_t b = 0; b < RADIX_B; ++b) {
        const int64_t s = bounds[b], e = bounds[b + 1];
        if (e - s > 1)
            sort_segment(scratch + s, e - s, seg + s, hist, depth + 1);
    }
    std::copy(scratch, scratch + len, seg);
}

void sort_segment(Pair* seg, int64_t len, Pair* scratch, int64_t* hist,
                  int depth) {
    if (len <= 1) return;
    if (len <= KSMALL) {
        std::sort(seg, seg + len, pair_less);
        return;
    }
    if (len > CACHE_PAIRS && 63 - RADIX_BITS * (depth + 1) >= 0) {
        msd_split(seg, len, scratch, hist, depth);
        return;
    }
    // one read pass builds every digit's histogram
    int64_t* h = hist;  // RADIX_PASSES x RADIX_B, zeroed below
    for (int64_t i = 0; i < RADIX_PASSES * RADIX_B; ++i) h[i] = 0;
    for (int64_t i = 0; i < len; ++i) {
        const uint64_t v = (uint64_t)seg[i].z;
        for (int p = 0; p < RADIX_PASSES; ++p)
            ++h[p * RADIX_B + ((v >> (p * RADIX_BITS)) & (RADIX_B - 1))];
    }
    Pair* src = seg;
    Pair* dst = scratch;
    for (int p = 0; p < RADIX_PASSES; ++p) {
        int64_t* hp = h + p * RADIX_B;
        // skip constant digits
        bool constant = false;
        for (int64_t b = 0; b < RADIX_B; ++b)
            if (hp[b] == len) { constant = true; break; }
        if (constant) continue;
        // exclusive prefix sums -> write cursors
        int64_t run = 0;
        for (int64_t b = 0; b < RADIX_B; ++b) {
            const int64_t cnt = hp[b];
            hp[b] = run;
            run += cnt;
        }
        const int shift = p * RADIX_BITS;
        for (int64_t i = 0; i < len; ++i)
            dst[hp[((uint64_t)src[i].z >> shift) & (RADIX_B - 1)]++] =
                src[i];
        std::swap(src, dst);
    }
    if (src != seg) std::copy(src, src + len, seg);
}

struct SortCtx {
    const int32_t* bins;
    const int64_t* z;
    int64_t n;
    int64_t nb;  // bin-count array length (max_bin + 2)
    int nt;
    Pair* pairs;
    int32_t* perm_out;
    int64_t* z_sorted_out;
    std::vector<std::vector<int64_t>> local_hist;  // per-thread bin counts
    std::vector<int64_t> chunk_lo, chunk_hi;
    // segment work queue (bin starts), drained atomically
    const int64_t* seg_offsets;
    int64_t nsegs;
    std::atomic<int64_t> next_seg{0};
};

void histogram_worker(void* p, int t) {
    auto* c = (SortCtx*)p;
    auto& h = c->local_hist[t];
    for (int64_t i = c->chunk_lo[t]; i < c->chunk_hi[t]; ++i) {
        const int32_t b = c->bins[i];
        if (b < 0 || b + 1 >= c->nb) {
            h[0] = -1;  // out-of-range flag, checked by caller
            return;
        }
        ++h[(size_t)b + 1];
    }
}

void scatter_worker(void* p, int t) {
    auto* c = (SortCtx*)p;
    auto& cursor = c->local_hist[t];  // repurposed: per-bin write pos
    for (int64_t i = c->chunk_lo[t]; i < c->chunk_hi[t]; ++i) {
        const int64_t pos = cursor[(size_t)c->bins[i]]++;
        c->pairs[pos].z = c->z[i];
        c->pairs[pos].idx = (int32_t)i;
    }
}

void segment_worker(void* p, int) {
    auto* c = (SortCtx*)p;
    std::vector<Pair> scratch;
    std::vector<int64_t> hist;
    for (;;) {
        const int64_t s = c->next_seg.fetch_add(1);
        if (s >= c->nsegs) break;
        const int64_t lo = c->seg_offsets[s], hi = c->seg_offsets[s + 1];
        const int64_t len = hi - lo;
        if (len <= 1) continue;
        if (len > KSMALL) {
            if ((int64_t)scratch.size() < len) scratch.resize(len);
            if (hist.empty()) hist.resize(NBUCKETS + 1);
        }
        sort_segment(c->pairs + lo, len, scratch.data(), hist.data());
    }
}

void emit_worker(void* p, int t) {
    auto* c = (SortCtx*)p;
    for (int64_t i = c->chunk_lo[t]; i < c->chunk_hi[t]; ++i) {
        c->z_sorted_out[i] = c->pairs[i].z;
        c->perm_out[i] = c->pairs[i].idx;
    }
}

}  // namespace

extern "C" int64_t geomesa_sort_bin_z(const int32_t* bins,
                                      const int64_t* z, int64_t n,
                                      int64_t max_bin,
                                      int32_t* perm_out,
                                      int64_t* z_sorted_out,
                                      int64_t* offsets_out) {
    if (n < 0 || max_bin < 0 || max_bin > (1 << 20)) return -1;
    const int64_t nb = max_bin + 2;
    const int t = nthreads(n);

    SortCtx c;
    c.bins = bins;
    c.z = z;
    c.n = n;
    c.nb = nb;
    c.nt = t;
    c.perm_out = perm_out;
    c.z_sorted_out = z_sorted_out;
    c.local_hist.assign(t, std::vector<int64_t>((size_t)nb, 0));
    c.chunk_lo.resize(t);
    c.chunk_hi.resize(t);
    const int64_t chunk = (n + t - 1) / t;
    for (int i = 0; i < t; ++i) {
        c.chunk_lo[i] = std::min<int64_t>(i * chunk, n);
        c.chunk_hi[i] = std::min<int64_t>((i + 1) * chunk, n);
    }

    run_parallel(t, histogram_worker, &c);
    for (int i = 0; i < t; ++i)
        if (c.local_hist[i][0] == -1) return -1;  // bin out of range

    // global prefix sums -> offsets_out; per-(thread, bin) cursors so
    // the parallel scatter lands rows of equal bins in chunk order
    // (== original order: stability preserved)
    for (int64_t b = 0; b < nb; ++b) offsets_out[b] = 0;
    for (int i = 0; i < t; ++i)
        for (int64_t b = 1; b < nb; ++b)
            offsets_out[b] += c.local_hist[i][(size_t)b];
    for (int64_t b = 1; b < nb; ++b) offsets_out[b] += offsets_out[b - 1];
    // cursor[t][b] = offsets[b] + sum of earlier threads' counts for b
    std::vector<int64_t> running(offsets_out, offsets_out + nb - 1);
    for (int i = 0; i < t; ++i) {
        auto& h = c.local_hist[i];
        for (int64_t b = 0; b + 1 < nb; ++b) {
            const int64_t cnt = h[(size_t)b + 1];
            h[(size_t)b] = running[(size_t)b];
            running[(size_t)b] += cnt;
        }
    }

    std::vector<Pair> pairs((size_t)n);
    c.pairs = pairs.data();
    run_parallel(t, scatter_worker, &c);

    c.seg_offsets = offsets_out;
    c.nsegs = nb - 1;
    run_parallel(t, segment_worker, &c);
    run_parallel(t, emit_worker, &c);
    return 0;
}

extern "C" int64_t geomesa_sort_z(const int64_t* z, int64_t n,
                                  int32_t* perm_out,
                                  int64_t* z_sorted_out) {
    if (n < 0) return -1;
    const int t = nthreads(n);
    std::vector<Pair> pairs((size_t)n);
    SortCtx c;
    c.z = z;
    c.n = n;
    c.nt = t;
    c.pairs = pairs.data();
    c.perm_out = perm_out;
    c.z_sorted_out = z_sorted_out;
    c.chunk_lo.resize(t);
    c.chunk_hi.resize(t);
    const int64_t chunk = (n + t - 1) / t;
    for (int i = 0; i < t; ++i) {
        c.chunk_lo[i] = std::min<int64_t>(i * chunk, n);
        c.chunk_hi[i] = std::min<int64_t>((i + 1) * chunk, n);
    }
    for (int64_t i = 0; i < n; ++i) {
        pairs[(size_t)i].z = z[i];
        pairs[(size_t)i].idx = (int32_t)i;
    }
    if (n <= KSMALL) {
        std::sort(pairs.data(), pairs.data() + n, pair_less);
    } else {
        // one MSD pass on the top RADIX_BITS splits the array into
        // segments that fit the cache; each segment then LSD-radixes
        // its remaining bits touching (near-)resident lines only. The
        // MSD scatter is stable, so segment order == input order and
        // ties stay lexsort-compatible. Sub-runs drain in parallel
        // when the host has cores.
        const int bits = RADIX_BITS;
        const int shift = 63 - bits;
        const int64_t nb = (int64_t)1 << bits;
        std::vector<int64_t> hist((size_t)nb + 1, 0);
        std::vector<Pair> scratch((size_t)n);
        for (int64_t i = 0; i < n; ++i)
            ++hist[((uint64_t)pairs[(size_t)i].z >> shift) + 1];
        for (int64_t b = 1; b <= nb; ++b) hist[b] += hist[b - 1];
        {
            std::vector<int64_t> cursor(hist.begin(), hist.end() - 1);
            for (int64_t i = 0; i < n; ++i)
                scratch[cursor[(uint64_t)pairs[(size_t)i].z >> shift]++] =
                    pairs[(size_t)i];
        }
        pairs.swap(scratch);
        c.pairs = pairs.data();
        c.seg_offsets = hist.data();
        c.nsegs = nb;
        run_parallel(t, segment_worker, &c);
    }
    c.pairs = pairs.data();
    run_parallel(t, emit_worker, &c);
    return 0;
}

// -- fused sorted-order payload gather -----------------------------------
//
// Building the sorted-order coordinate copies (x[perm], y[perm],
// ms[perm]) with numpy costs three separate single-threaded random
// gathers over the full columns; at 100M rows that is seconds of
// wall-clock on the FIRST query. One chunked multi-threaded pass reads
// perm once per row and writes all three outputs sequentially.
//   geomesa_gather_xyz(x f64[n], y f64[n], ms i64[n] (may be null),
//                      perm i32[n], n, xo, yo, mo) -> 0
namespace {

struct GatherCtx {
    const double* x;
    const double* y;
    const int64_t* ms;
    const int32_t* perm;
    int64_t n;
    double* xo;
    double* yo;
    int64_t* mo;
    int nthreads;
};

void gather_worker(void* p, int tid) {
    GatherCtx& c = *(GatherCtx*)p;
    const int64_t chunk = (c.n + c.nthreads - 1) / c.nthreads;
    const int64_t lo = std::min<int64_t>((int64_t)tid * chunk, c.n);
    const int64_t hi = std::min<int64_t>(lo + chunk, c.n);
    if (c.ms != nullptr) {
        for (int64_t i = lo; i < hi; ++i) {
            const int32_t j = c.perm[i];
            c.xo[i] = c.x[j];
            c.yo[i] = c.y[j];
            c.mo[i] = c.ms[j];
        }
    } else {
        for (int64_t i = lo; i < hi; ++i) {
            const int32_t j = c.perm[i];
            c.xo[i] = c.x[j];
            c.yo[i] = c.y[j];
        }
    }
}

}  // namespace

extern "C" int64_t geomesa_gather_xyz(const double* x, const double* y,
                                      const int64_t* ms,
                                      const int32_t* perm, int64_t n,
                                      double* xo, double* yo,
                                      int64_t* mo) {
    if (n < 0) return -1;
    if (n == 0) return 0;
    GatherCtx c{x, y, ms, perm, n, xo, yo, mo, nthreads(n)};
    run_parallel(c.nthreads, gather_worker, &c);
    return 0;
}
