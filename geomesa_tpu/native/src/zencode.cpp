// Fused z-key encoding: clamp + normalize + interleave in ONE pass.
//
// The numpy pipeline (curves/normalize.py + curves/zorder.py) walks the
// arrays ~30 times through temporaries; at 100M rows the index build is
// memory-bandwidth bound on those passes. This kernel reads x/y(/t)
// once and writes z once, with semantics matching the Python path
// EXACTLY (including NaN -> bin 0, the numpy int64->int32 cast chain):
//
//   clamp to [min, max]; floor((v - min) * bins / (max - min));
//   clamp to bins - 1; NaN -> 0; interleave (x bit 0, y bit 1, t bit 2)
//
// Parity is enforced by tests/test_native_zencode.py against the
// reference implementation in curves/.

#include <cmath>
#include <cstdint>

namespace {

inline uint64_t split2(uint64_t v) {
    v &= 0x7FFFFFFFULL;
    v = (v ^ (v << 16)) & 0x0000FFFF0000FFFFULL;
    v = (v ^ (v << 8)) & 0x00FF00FF00FF00FFULL;
    v = (v ^ (v << 4)) & 0x0F0F0F0F0F0F0F0FULL;
    v = (v ^ (v << 2)) & 0x3333333333333333ULL;
    v = (v ^ (v << 1)) & 0x5555555555555555ULL;
    return v;
}

inline uint64_t split3(uint64_t v) {
    v &= 0x1FFFFFULL;
    v = (v | (v << 32)) & 0x1F00000000FFFFULL;
    v = (v | (v << 16)) & 0x1F0000FF0000FFULL;
    v = (v | (v << 8)) & 0x100F00F00F00F00FULL;
    v = (v | (v << 4)) & 0x10C30C30C30C30C3ULL;
    v = (v | (v << 2)) & 0x1249249249249249ULL;
    return v;
}

inline uint64_t norm(double v, double lo, double hi, double normalizer,
                     uint64_t max_index) {
    if (std::isnan(v)) return 0;            // numpy cast chain -> bin 0
    if (v < lo) v = lo;                     // lenient clamp
    if (v > hi) v = hi;
    double f = std::floor((v - lo) * normalizer);
    int64_t i = (int64_t)f;
    if (i < 0) i = 0;
    return (uint64_t)i > max_index ? max_index : (uint64_t)i;
}

}  // namespace

extern "C" void geomesa_z2_encode(const double* x, const double* y,
                                  int64_t n, int64_t* out) {
    const double nx = 2147483648.0 / 360.0;  // 2^31 bins over [-180,180]
    const double ny = 2147483648.0 / 180.0;
    const uint64_t mi = (1ULL << 31) - 1;
    for (int64_t i = 0; i < n; ++i) {
        const uint64_t xi = norm(x[i], -180.0, 180.0, nx, mi);
        const uint64_t yi = norm(y[i], -90.0, 90.0, ny, mi);
        out[i] = (int64_t)(split2(xi) | (split2(yi) << 1));
    }
}

extern "C" void geomesa_z3_encode(const double* x, const double* y,
                                  const double* t, int64_t n,
                                  double t_max, int64_t* out) {
    const double bins = 2097152.0;           // 2^21
    const double nx = bins / 360.0;
    const double ny = bins / 180.0;
    const double nt = bins / t_max;
    const uint64_t mi = (1ULL << 21) - 1;
    for (int64_t i = 0; i < n; ++i) {
        const uint64_t xi = norm(x[i], -180.0, 180.0, nx, mi);
        const uint64_t yi = norm(y[i], -90.0, 90.0, ny, mi);
        const uint64_t ti = norm(t[i], 0.0, t_max, nt, mi);
        out[i] = (int64_t)(split3(xi) | (split3(yi) << 1)
                           | (split3(ti) << 2));
    }
}
