// Fused index-build kernels: epoch-millis binning and binned z3
// encoding in ONE pass over the inputs.
//
// The index build at 100M rows is bandwidth-bound on host passes:
// numpy's int64 division in to_binned() alone walks the column several
// times (and scalar-loops the divide), then the z encode reads the
// coordinates again. These kernels fuse clamp + bin-split + normalize
// + interleave so each input byte is read once and each output byte
// written once, with semantics matching curves/timebin.py::to_binned
// (lenient) and curves/sfc.py::Z3SFC.index(lenient=True) EXACTLY —
// parity enforced by tests/test_native_zencode.py.
//
// Only DAY and WEEK periods are handled natively (compile-time-constant
// divisors become multiply-shift); MONTH/YEAR calendar binning stays on
// the numpy datetime64 path.
//
// Exported (ctypes):
//   geomesa_binned(millis i64[n], n, period_code {0=day,1=week},
//                  bins_out i32[n], offs_out i64[n]) -> 0/-1
//   geomesa_encode_binned_z3(x f64[n], y f64[n], millis i64[n], n,
//                  period_code, t_max, bins_out i32[n], z_out i64[n])
//                  -> 0/-1

#include <cmath>
#include <cstdint>

namespace {

constexpr int64_t MS_DAY = 86'400'000;
constexpr int64_t MS_WEEK = 7 * MS_DAY;
constexpr int64_t MAX_BIN = 32767;  // Short.MaxValue bins (BinnedTime)

inline uint64_t split3(uint64_t v) {
    v &= 0x1FFFFFULL;
    v = (v | (v << 32)) & 0x1F00000000FFFFULL;
    v = (v | (v << 16)) & 0x1F0000FF0000FFULL;
    v = (v | (v << 8)) & 0x100F00F00F00F00FULL;
    v = (v | (v << 4)) & 0x10C30C30C30C30C3ULL;
    v = (v | (v << 2)) & 0x1249249249249249ULL;
    return v;
}

inline uint64_t norm(double v, double lo, double hi, double normalizer,
                     uint64_t max_index) {
    if (std::isnan(v)) return 0;            // numpy cast chain -> bin 0
    if (v < lo) v = lo;                     // lenient clamp
    if (v > hi) v = hi;
    double f = std::floor((v - lo) * normalizer);
    int64_t i = (int64_t)f;
    if (i < 0) i = 0;
    return (uint64_t)i > max_index ? max_index : (uint64_t)i;
}

// Constant-divisor bin split (the compiler lowers the divisions to
// multiply-shift). Returns the clamped (bin, offset-in-bin) pair.
template <bool WEEK>
inline void bin_split(int64_t ms, int32_t* bin, int64_t* off) {
    constexpr int64_t period = WEEK ? MS_WEEK : MS_DAY;
    constexpr int64_t hi = (MAX_BIN + 1) * period - 1;  // lenient clamp
    if (ms < 0) ms = 0;
    if (ms > hi) ms = hi;
    const int64_t b = ms / period;
    *bin = (int32_t)b;
    const int64_t rem = ms - b * period;
    *off = WEEK ? rem / 1000 : rem;
}

template <bool WEEK>
void binned_loop(const int64_t* millis, int64_t n, int32_t* bins_out,
                 int64_t* offs_out) {
    for (int64_t i = 0; i < n; ++i)
        bin_split<WEEK>(millis[i], &bins_out[i], &offs_out[i]);
}

template <bool WEEK>
void encode_loop(const double* x, const double* y, const int64_t* millis,
                 int64_t n, double t_max, int32_t* bins_out,
                 int64_t* z_out) {
    const double bins = 2097152.0;  // 2^21
    const double nx = bins / 360.0;
    const double ny = bins / 180.0;
    const double nt = bins / t_max;
    const uint64_t mi = (1ULL << 21) - 1;
    for (int64_t i = 0; i < n; ++i) {
        int32_t b;
        int64_t off;
        bin_split<WEEK>(millis[i], &b, &off);
        bins_out[i] = b;
        const uint64_t xi = norm(x[i], -180.0, 180.0, nx, mi);
        const uint64_t yi = norm(y[i], -90.0, 90.0, ny, mi);
        const uint64_t ti = norm((double)off, 0.0, t_max, nt, mi);
        z_out[i] = (int64_t)(split3(xi) | (split3(yi) << 1)
                             | (split3(ti) << 2));
    }
}

}  // namespace

extern "C" int64_t geomesa_binned(const int64_t* millis, int64_t n,
                                  int32_t period_code, int32_t* bins_out,
                                  int64_t* offs_out) {
    if (n < 0) return -1;
    if (period_code == 0)
        binned_loop<false>(millis, n, bins_out, offs_out);
    else if (period_code == 1)
        binned_loop<true>(millis, n, bins_out, offs_out);
    else
        return -1;
    return 0;
}

extern "C" int64_t geomesa_encode_binned_z3(
    const double* x, const double* y, const int64_t* millis, int64_t n,
    int32_t period_code, double t_max, int32_t* bins_out,
    int64_t* z_out) {
    if (n < 0 || !(t_max > 0.0)) return -1;
    if (period_code == 0)
        encode_loop<false>(x, y, millis, n, t_max, bins_out, z_out);
    else if (period_code == 1)
        encode_loop<true>(x, y, millis, n, t_max, bins_out, z_out);
    else
        return -1;
    return 0;
}

// Calendar-binned variant (MONTH/YEAR): bin boundaries are irregular,
// so the caller passes the precomputed bin-edge epoch millis (edges
// has nbins+1 entries, edges[b] = first instant of bin b) and the
// offset divisor (1000 for month-seconds, 60000 for year-minutes).
// Rows clamp leniently into [edges[0], edges[nbins]-1] and binary
// search their bin — fused with the z3 encode in the same pass.
extern "C" int64_t geomesa_encode_binned_z3_edges(
    const double* x, const double* y, const int64_t* millis, int64_t n,
    const int64_t* edges, int64_t nbins, int64_t off_div, double t_max,
    int32_t* bins_out, int64_t* z_out) {
    if (n < 0 || nbins <= 0 || off_div <= 0 || !(t_max > 0.0)) return -1;
    const double bins_f = 2097152.0;  // 2^21
    const double nx = bins_f / 360.0;
    const double ny = bins_f / 180.0;
    const double nt = bins_f / t_max;
    const uint64_t mi = (1ULL << 21) - 1;
    const int64_t lo = edges[0];
    const int64_t hi = edges[nbins] - 1;
    int32_t prev_bin = 0;  // locality: consecutive rows share bins
    for (int64_t i = 0; i < n; ++i) {
        int64_t ms = millis[i];
        if (ms < lo) ms = lo;
        if (ms > hi) ms = hi;
        int32_t b;
        if (edges[prev_bin] <= ms && ms < edges[prev_bin + 1]) {
            b = prev_bin;
        } else {
            // upper_bound(edges, ms) - 1
            int64_t l = 0, r = nbins;
            while (l < r) {
                const int64_t m = (l + r) / 2;
                if (edges[m] <= ms) l = m + 1; else r = m;
            }
            b = (int32_t)(l - 1);
            prev_bin = b;
        }
        bins_out[i] = b;
        const int64_t off = (ms - edges[b]) / off_div;
        const uint64_t xi = norm(x[i], -180.0, 180.0, nx, mi);
        const uint64_t yi = norm(y[i], -90.0, 90.0, ny, mi);
        const uint64_t ti = norm((double)off, 0.0, t_max, nt, mi);
        z_out[i] = (int64_t)(split3(xi) | (split3(yi) << 1)
                             | (split3(ti) << 2));
    }
    return 0;
}
