// Native feature codec: row-interleave / column-extract for the SFB
// (simple-feature-binary) format — the TPU framework's analog of the
// reference's Kryo serializer hot path (geomesa-features/.../kryo/
// KryoFeatureSerializer.scala, KryoBufferSimpleFeature.scala).
//
// Row layout (version 1, little-endian):
//   u8   version
//   u8[] null bitmap, ceil(n_attrs/8) bytes (bit set = non-null)
//   u32  offsets[n_attrs]   // payload-relative start of each attr
//   u8[] payload            // attr i spans [off[i], off[i+1]) where
//                           // off[n_attrs] == payload length
// Null attrs are zero-length. Lazy single-attribute access = read the
// offset table, jump, decode one cell (the KryoBufferSimpleFeature
// offset-table trick, without deserializing the rest of the row).
//
// Python (features/codec.py) prepares columnar inputs — fixed-width
// cells as contiguous arrays, var-width as bytes+offsets — and this
// library does the per-row byte shuffling both directions.

#include <cstdint>
#include <cstring>

extern "C" {

// Exact blob size for a batch (python uses this to pre-allocate).
int64_t sfb_encoded_size(int32_t n_rows, int32_t n_attrs,
                         const uint8_t *kinds, const int32_t *widths,
                         const int64_t *const *var_offsets,
                         const uint8_t *const *valids) {
  const int32_t bitmap = (n_attrs + 7) / 8;
  const int64_t header = 1 + bitmap + 4LL * n_attrs;
  int64_t total = header * n_rows;
  for (int32_t a = 0; a < n_attrs; ++a) {
    const uint8_t *valid = valids[a];
    if (kinds[a] == 0) {
      const int64_t w = widths[a];
      for (int32_t r = 0; r < n_rows; ++r)
        if (valid[r]) total += w;
    } else {
      const int64_t *off = var_offsets[a];
      for (int32_t r = 0; r < n_rows; ++r)
        if (valid[r]) total += off[r + 1] - off[r];
    }
  }
  return total;
}

// Interleave columns into row buffers. Returns bytes written, or -1 on
// overflow of `out_cap`. row_offsets gets n_rows+1 entries.
int64_t sfb_encode_batch(int32_t n_rows, int32_t n_attrs,
                         const uint8_t *kinds, const int32_t *widths,
                         const uint8_t *const *fixed_data,
                         const uint8_t *const *var_data,
                         const int64_t *const *var_offsets,
                         const uint8_t *const *valids, uint8_t *out,
                         int64_t out_cap, int64_t *row_offsets) {
  const int32_t bitmap_len = (n_attrs + 7) / 8;
  const int64_t header = 1 + bitmap_len + 4LL * n_attrs;
  int64_t pos = 0;
  for (int32_t r = 0; r < n_rows; ++r) {
    row_offsets[r] = pos;
    if (pos + header > out_cap) return -1;
    uint8_t *row = out + pos;
    row[0] = 1;  // version
    uint8_t *bm = row + 1;
    std::memset(bm, 0, bitmap_len);
    uint32_t *offs = reinterpret_cast<uint32_t *>(row + 1 + bitmap_len);
    uint8_t *payload = row + header;
    uint32_t ppos = 0;
    for (int32_t a = 0; a < n_attrs; ++a) {
      offs[a] = ppos;
      if (!valids[a][r]) continue;
      bm[a >> 3] |= uint8_t(1u << (a & 7));
      if (kinds[a] == 0) {
        const int32_t w = widths[a];
        if (pos + header + ppos + w > out_cap) return -1;
        std::memcpy(payload + ppos, fixed_data[a] + int64_t(r) * w, w);
        ppos += w;
      } else {
        const int64_t *off = var_offsets[a];
        const int64_t len = off[r + 1] - off[r];
        if (pos + header + ppos + len > out_cap) return -1;
        std::memcpy(payload + ppos, var_data[a] + off[r], len);
        ppos += uint32_t(len);
      }
    }
    pos += header + ppos;
  }
  row_offsets[n_rows] = pos;
  return pos;
}

static inline const uint8_t *row_payload(const uint8_t *row, int32_t n_attrs,
                                         int32_t bitmap_len, int32_t attr,
                                         uint32_t *start, uint32_t *end,
                                         int64_t row_len, bool *valid) {
  const uint8_t *bm = row + 1;
  *valid = (bm[attr >> 3] >> (attr & 7)) & 1;
  const uint32_t *offs =
      reinterpret_cast<const uint32_t *>(row + 1 + bitmap_len);
  const int64_t header = 1 + bitmap_len + 4LL * n_attrs;
  *start = offs[attr];
  *end = (attr + 1 < n_attrs) ? offs[attr + 1]
                              : uint32_t(row_len - header);
  return row + header;
}

// Extract one fixed-width attribute column. out_vals must hold
// n_rows*width bytes (null rows left zeroed); out_valid n_rows bytes.
int64_t sfb_decode_fixed(const uint8_t *blob, const int64_t *row_offsets,
                         int32_t n_rows, int32_t n_attrs, int32_t attr,
                         int32_t width, uint8_t *out_vals,
                         uint8_t *out_valid) {
  const int32_t bitmap_len = (n_attrs + 7) / 8;
  for (int32_t r = 0; r < n_rows; ++r) {
    const uint8_t *row = blob + row_offsets[r];
    uint32_t s, e;
    bool valid;
    const uint8_t *payload =
        row_payload(row, n_attrs, bitmap_len, attr, &s, &e,
                    row_offsets[r + 1] - row_offsets[r], &valid);
    out_valid[r] = valid ? 1 : 0;
    if (valid) {
      if (int32_t(e - s) != width) return -1;
      std::memcpy(out_vals + int64_t(r) * width, payload + s, width);
    }
  }
  return n_rows;
}

// Pass 1 for var-width extraction: per-row byte lengths (0 for null).
int64_t sfb_decode_varlen_sizes(const uint8_t *blob,
                                const int64_t *row_offsets, int32_t n_rows,
                                int32_t n_attrs, int32_t attr,
                                int64_t *out_lens, uint8_t *out_valid) {
  const int32_t bitmap_len = (n_attrs + 7) / 8;
  int64_t total = 0;
  for (int32_t r = 0; r < n_rows; ++r) {
    const uint8_t *row = blob + row_offsets[r];
    uint32_t s, e;
    bool valid;
    row_payload(row, n_attrs, bitmap_len, attr, &s, &e,
                row_offsets[r + 1] - row_offsets[r], &valid);
    out_valid[r] = valid ? 1 : 0;
    out_lens[r] = valid ? (e - s) : 0;
    total += out_lens[r];
  }
  return total;
}

// Pass 2: copy var-width cells into a concatenated buffer at out_offsets.
int64_t sfb_decode_varlen(const uint8_t *blob, const int64_t *row_offsets,
                          int32_t n_rows, int32_t n_attrs, int32_t attr,
                          const int64_t *out_offsets, uint8_t *out_bytes) {
  const int32_t bitmap_len = (n_attrs + 7) / 8;
  for (int32_t r = 0; r < n_rows; ++r) {
    const uint8_t *row = blob + row_offsets[r];
    uint32_t s, e;
    bool valid;
    const uint8_t *payload =
        row_payload(row, n_attrs, bitmap_len, attr, &s, &e,
                    row_offsets[r + 1] - row_offsets[r], &valid);
    if (valid && e > s)
      std::memcpy(out_bytes + out_offsets[r], payload + s, e - s);
  }
  return n_rows;
}

}  // extern "C"
