// Z-range decomposition: the planner-time hot loop in native code.
//
// Mirrors geomesa_tpu/curves/zranges.py (itself the analog of sfcurve's
// Z3.zranges divide-and-conquer used by the reference at
// geomesa-z3/src/main/scala/org/locationtech/geomesa/curve/Z3SFC.scala:54-62)
// EXACTLY — same level-by-level BFS, same contained/partial emit rules,
// same budget semantics, same sort+coalesce merge — so the Python and
// native paths are interchangeable and differential-tested for equality.
//
// Exported C ABI (ctypes):
//   geomesa_zranges(lows, highs, dims, max_bits, max_level, max_ranges,
//                   out, out_cap) -> number of [lo,hi] rows written,
//                                    0 if empty, -1 if out_cap too small

#include <algorithm>
#include <cstdint>
#include <utility>
#include <vector>

namespace {

inline uint64_t split2(uint64_t x) {
    x &= 0x7FFFFFFFULL;
    x = (x ^ (x << 16)) & 0x0000FFFF0000FFFFULL;
    x = (x ^ (x << 8)) & 0x00FF00FF00FF00FFULL;
    x = (x ^ (x << 4)) & 0x0F0F0F0F0F0F0F0FULL;
    x = (x ^ (x << 2)) & 0x3333333333333333ULL;
    x = (x ^ (x << 1)) & 0x5555555555555555ULL;
    return x;
}

inline uint64_t split3(uint64_t x) {
    x &= 0x1FFFFFULL;
    x = (x | (x << 32)) & 0x1F00000000FFFFULL;
    x = (x | (x << 16)) & 0x1F0000FF0000FFULL;
    x = (x | (x << 8)) & 0x100F00F00F00F00FULL;
    x = (x | (x << 4)) & 0x10C30C30C30C30C3ULL;
    x = (x | (x << 2)) & 0x1249249249249249ULL;
    return x;
}

inline uint64_t interleave(const int64_t* c, int dims) {
    if (dims == 2)
        return split2((uint64_t)c[0]) | (split2((uint64_t)c[1]) << 1);
    return split3((uint64_t)c[0]) | (split3((uint64_t)c[1]) << 1)
         | (split3((uint64_t)c[2]) << 2);
}

}  // namespace

extern "C" int64_t geomesa_zranges(
    const int64_t* lows, const int64_t* highs, int64_t dims_i,
    int64_t max_bits, int64_t max_level, int64_t max_ranges,
    int64_t* out, int64_t out_cap) {
    const int dims = (int)dims_i;
    if (dims != 2 && dims != 3) return -1;
    for (int d = 0; d < dims; ++d)
        if (highs[d] < lows[d]) return 0;

    const int nchild = 1 << dims;
    std::vector<int64_t> frontier(dims, 0);  // root cell, stride = dims
    size_t ncells = 1;
    std::vector<std::pair<int64_t, int64_t>> emitted;

    for (int64_t level = 0; level <= max_level && ncells; ++level) {
        const int64_t shift = max_bits - level;
        const int64_t side = (int64_t)1 << shift;
        // dims*shift <= 63 for (2,31)/(3,21), so the span never wraps
        const int64_t span = (int64_t)(((uint64_t)1 << (dims * shift)) - 1);

        std::vector<int64_t> partial;
        size_t npartial = 0;
        for (size_t i = 0; i < ncells; ++i) {
            const int64_t* cell = &frontier[i * dims];
            bool disjoint = false, contained = true;
            for (int d = 0; d < dims; ++d) {
                const int64_t clo = cell[d] * side;
                const int64_t chi = clo + (side - 1);
                if (chi < lows[d] || clo > highs[d]) { disjoint = true; break; }
                if (clo < lows[d] || chi > highs[d]) contained = false;
            }
            if (disjoint) continue;
            if (contained) {
                int64_t origin[3];
                for (int d = 0; d < dims; ++d) origin[d] = cell[d] * side;
                const int64_t zlo = (int64_t)interleave(origin, dims);
                emitted.emplace_back(zlo, zlo + span);
            } else {
                for (int d = 0; d < dims; ++d) partial.push_back(cell[d]);
                ++npartial;
            }
        }
        if (!npartial) break;
        const bool budget_blown =
            (int64_t)(emitted.size() + npartial * (size_t)nchild) > max_ranges;
        if (level == max_level || budget_blown) {
            for (size_t i = 0; i < npartial; ++i) {
                int64_t origin[3];
                for (int d = 0; d < dims; ++d)
                    origin[d] = partial[i * dims + d] * side;
                const int64_t zlo = (int64_t)interleave(origin, dims);
                emitted.emplace_back(zlo, zlo + span);
            }
            break;
        }
        // split partial cells; child order matches np.indices (first
        // dimension varies slowest)
        std::vector<int64_t> next;
        next.reserve(npartial * (size_t)nchild * dims);
        for (size_t i = 0; i < npartial; ++i)
            for (int j = 0; j < nchild; ++j)
                for (int d = 0; d < dims; ++d)
                    next.push_back(partial[i * dims + d] * 2
                                   + ((j >> (dims - 1 - d)) & 1));
        frontier.swap(next);
        ncells = npartial * (size_t)nchild;
    }

    if (emitted.empty()) return 0;
    std::sort(emitted.begin(), emitted.end());
    int64_t n_out = 0;
    int64_t cur_lo = emitted[0].first, cur_hi = emitted[0].second;
    for (size_t i = 1; i < emitted.size(); ++i) {
        if (emitted[i].first - cur_hi <= 1) {  // overlap or adjacency
            cur_hi = std::max(cur_hi, emitted[i].second);
        } else {
            if (n_out >= out_cap) return -1;
            out[2 * n_out] = cur_lo;
            out[2 * n_out + 1] = cur_hi;
            ++n_out;
            cur_lo = emitted[i].first;
            cur_hi = emitted[i].second;
        }
    }
    if (n_out >= out_cap) return -1;
    out[2 * n_out] = cur_lo;
    out[2 * n_out + 1] = cur_hi;
    return n_out + 1;
}
