"""Ingest converters: raw records -> FeatureBatch.

Mirrors geomesa-convert (SimpleFeatureConverterFactory.scala:194 +
format modules): a converter config declares an id expression and per-
attribute transform expressions over parsed input records. Formats:
delimited text (CSV/TSV), JSON (with dotted paths), and an in-memory
list-of-rows form.

Config shape (the TypeSafe-config structure, as a dict):
    {"type": "delimited-text", "format": "CSV",
     "id-field": "md5($0)",
     "fields": [{"name": "dtg", "transform": "isoDate($3)"},
                {"name": "geom", "transform": "point($1::double, $2::double)"}]}
"""

from __future__ import annotations

import csv
import io
import json
from typing import Any, Iterable

from ..features.batch import FeatureBatch
from ..features.sft import SimpleFeatureType
from .dsl import EvaluationContext, compile_expression

__all__ = ["SimpleFeatureConverter", "DelimitedTextConverter",
           "JsonConverter", "converter_for"]

# sentinel yielded by _records for unparseable inputs; process() counts
# it as a failure without evaluating transforms
_BAD_RECORD: list = []


class SimpleFeatureConverter:
    """Base: compile field transforms, process record streams."""

    def __init__(self, sft: SimpleFeatureType, config: dict):
        self.sft = sft
        self.config = config
        self.id_expr = compile_expression(config.get("id-field", "uuid()"))
        # every named field compiles IN DECLARATION ORDER — later
        # transforms (and the id expression) may reference earlier ones
        # as $fieldName (Transformers' fieldLookup). Intermediate fields
        # not in the SFT are building blocks only. Nameless entries are
        # column bindings (e.g. a bare JSON path referenced by number).
        self.ordered_exprs: list[tuple[str, Any]] = []
        declared = {}
        for f in config.get("fields", []):
            if "name" not in f or f.get("transform") is None:
                continue
            declared[f["name"]] = True
            self.ordered_exprs.append(
                (f["name"], compile_expression(f["transform"])))
        for attr in sft.attributes:
            if attr.name not in declared:
                raise ValueError(f"no transform for attribute {attr.name!r}")
        from .validators import build_validators
        self.validators = build_validators(
            config.get("options", {}).get("validators", []), sft)

    def _records(self, source) -> Iterable[list]:
        """Yield column lists; cols[0] is the raw record."""
        raise NotImplementedError

    def process(self, source, ctx: EvaluationContext | None = None
                ) -> tuple[FeatureBatch, EvaluationContext]:
        ctx = ctx or EvaluationContext()
        ids: list[str] = []
        data: dict[str, list] = {a.name: [] for a in self.sft.attributes}
        for cols in self._records(source):
            ctx.line += 1
            if cols is _BAD_RECORD:
                ctx.failure += 1
                continue
            try:
                fields: dict[str, Any] = {}
                for name, expr in self.ordered_exprs:
                    fields[name] = expr(cols, fields)
                fid = str(self.id_expr(cols, fields))
                values = {a.name: fields[a.name]
                          for a in self.sft.attributes}
            except Exception:
                ctx.failure += 1
                continue
            if self.validators:
                from .validators import validate
                if validate(self.validators, values) is not None:
                    ctx.failure += 1
                    continue
            ids.append(fid)
            for name, v in values.items():
                data[name].append(v)
            ctx.success += 1
        # point columns arrive as Point objects; from_dict handles them
        batch = FeatureBatch.from_dict(self.sft, ids, data)
        return batch, ctx


class DelimitedTextConverter(SimpleFeatureConverter):
    """CSV/TSV lines -> features ($1..$N are the delimited columns)."""

    def __init__(self, sft: SimpleFeatureType, config: dict):
        super().__init__(sft, config)
        fmt = config.get("format", "CSV").upper()
        self.delimiter = {"CSV": ",", "TSV": "\t"}.get(fmt, ",")
        self.skip_lines = int(config.get("options", {}).get("skip-lines", 0))

    def _records(self, source):
        if isinstance(source, str):
            source = io.StringIO(source)
        reader = csv.reader(source, delimiter=self.delimiter)
        for i, row in enumerate(reader):
            if i < self.skip_lines or not row:
                continue
            yield [self.delimiter.join(row)] + row


class JsonConverter(SimpleFeatureConverter):
    """JSON objects (one per line, or a top-level array) -> features.

    Field transforms use jsonPath('$.a.b') via the `$0` record: the
    config's fields may use ``jsonPath`` expressions written as
    ``path('a.b')`` which this converter resolves before transforms, so
    `$1..$N` bind to the declared paths in order.
    """

    def __init__(self, sft: SimpleFeatureType, config: dict):
        self.paths = [f["path"] for f in config.get("fields", [])
                      if "path" in f]
        # fields with a path but no transform default to the column ref
        fields = []
        col = 0
        for f in config.get("fields", []):
            f = dict(f)
            if "path" in f:
                col += 1
                if "name" in f:
                    f.setdefault("transform", f"${col}")
            fields.append(f)
        config = dict(config)
        config["fields"] = fields
        super().__init__(sft, config)

    @staticmethod
    def _resolve(obj: Any, path: str):
        cur = obj
        for part in path.replace("$.", "").split("."):
            if isinstance(cur, dict):
                cur = cur.get(part)
            elif isinstance(cur, list) and part.isdigit():
                cur = cur[int(part)]
            else:
                return None
        return cur

    def _records(self, source):
        if not isinstance(source, str):
            source = source.read()  # file-like: parse the whole stream
        stripped = source.strip()
        if stripped.startswith("["):
            try:
                objs = json.loads(stripped)
            except ValueError:
                yield _BAD_RECORD
                return
        else:
            objs = []
            for line in stripped.splitlines():
                if not line.strip():
                    continue
                try:
                    objs.append(json.loads(line))
                except ValueError:
                    objs.append(_BAD_RECORD)
        for obj in objs:
            if obj is _BAD_RECORD:
                yield _BAD_RECORD
                continue
            try:
                yield [obj] + [self._resolve(obj, p) for p in self.paths]
            except Exception:
                # a bad record must count as a failure, not kill the run
                yield _BAD_RECORD


def converter_for(sft: SimpleFeatureType, config: dict):
    kind = config.get("type", "delimited-text")
    if kind == "delimited-text":
        return DelimitedTextConverter(sft, config)
    if kind == "json":
        return JsonConverter(sft, config)
    if kind in ("xml", "fixed-width", "avro", "composite"):
        from .formats import (AvroConverter, CompositeConverter,
                              FixedWidthConverter, XmlConverter)
        cls = {"xml": XmlConverter, "fixed-width": FixedWidthConverter,
               "avro": AvroConverter, "composite": CompositeConverter}[kind]
        return cls(sft, config)
    if kind in ("shapefile", "jdbc", "osm"):
        from .geo_formats import (JdbcConverter, OsmConverter,
                                  ShapefileConverter)
        cls = {"shapefile": ShapefileConverter, "jdbc": JdbcConverter,
               "osm": OsmConverter}[kind]
        return cls(sft, config)
    raise ValueError(f"unknown converter type: {kind}")
