"""Ingest converters: raw records -> FeatureBatch.

Mirrors geomesa-convert (SimpleFeatureConverterFactory.scala:194 +
format modules): a converter config declares an id expression and per-
attribute transform expressions over parsed input records. Formats:
delimited text (CSV/TSV), JSON (with dotted paths), and an in-memory
list-of-rows form.

Config shape (the TypeSafe-config structure, as a dict):
    {"type": "delimited-text", "format": "CSV",
     "id-field": "md5($0)",
     "fields": [{"name": "dtg", "transform": "isoDate($3)"},
                {"name": "geom", "transform": "point($1::double, $2::double)"}]}
"""

from __future__ import annotations

import csv
import io
import json
from itertools import chain, islice
from typing import Any, Iterable

import numpy as np

from ..features.batch import FeatureBatch
from ..features.sft import SimpleFeatureType
from .dsl import EvaluationContext, compile_expression, parse_expression

__all__ = ["SimpleFeatureConverter", "DelimitedTextConverter",
           "JsonConverter", "converter_for"]

# sentinel yielded by _records for unparseable inputs; process() counts
# it as a failure without evaluating transforms
_BAD_RECORD: list = []


class SimpleFeatureConverter:
    """Base: compile field transforms, process record streams."""

    def __init__(self, sft: SimpleFeatureType, config: dict):
        self.sft = sft
        self.config = config
        self.id_ast = parse_expression(config.get("id-field", "uuid()"))
        self.id_expr = compile_expression(config.get("id-field", "uuid()"))
        # every named field compiles IN DECLARATION ORDER — later
        # transforms (and the id expression) may reference earlier ones
        # as $fieldName (Transformers' fieldLookup). Intermediate fields
        # not in the SFT are building blocks only. Nameless entries are
        # column bindings (e.g. a bare JSON path referenced by number).
        # The AST is kept next to each compiled closure so the columnar
        # backend (convert/vectorized.py) can evaluate the same program.
        self.ordered_exprs: list[tuple[str, Any]] = []
        self.ordered_asts: list[tuple[str, tuple]] = []
        declared = {}
        for f in config.get("fields", []):
            if "name" not in f or f.get("transform") is None:
                continue
            declared[f["name"]] = True
            self.ordered_exprs.append(
                (f["name"], compile_expression(f["transform"])))
            self.ordered_asts.append(
                (f["name"], parse_expression(f["transform"])))
        for attr in sft.attributes:
            if attr.name not in declared:
                raise ValueError(f"no transform for attribute {attr.name!r}")
        from .validators import build_validators
        self.validator_names = list(
            config.get("options", {}).get("validators", []))
        self.validators = build_validators(self.validator_names, sft)

    def _records(self, source) -> Iterable[list]:
        """Yield column lists; cols[0] is the raw record."""
        raise NotImplementedError

    def process(self, source, ctx: EvaluationContext | None = None
                ) -> tuple[FeatureBatch, EvaluationContext]:
        ctx = ctx or EvaluationContext()
        return self._process_scalar(self._records(source), ctx), ctx

    def _process_scalar(self, records: Iterable[list],
                        ctx: EvaluationContext) -> FeatureBatch:
        """The record-at-a-time oracle; ``iter_batches`` is the fast path."""
        ids: list[str] = []
        data: dict[str, list] = {a.name: [] for a in self.sft.attributes}
        for cols in records:
            ctx.line += 1
            if cols is _BAD_RECORD:
                ctx.failure += 1
                continue
            try:
                fields: dict[str, Any] = {}
                for name, expr in self.ordered_exprs:
                    fields[name] = expr(cols, fields)
                fid = str(self.id_expr(cols, fields))
                values = {a.name: fields[a.name]
                          for a in self.sft.attributes}
            except Exception:
                ctx.failure += 1
                continue
            if self.validators:
                from .validators import validate
                if validate(self.validators, values) is not None:
                    ctx.failure += 1
                    continue
            ids.append(fid)
            for name, v in values.items():
                data[name].append(v)
            ctx.success += 1
        # point columns arrive as Point objects; from_dict handles them
        return FeatureBatch.from_dict(self.sft, ids, data)

    def iter_batches(self, source, ctx: EvaluationContext | None = None,
                     batch_rows: int | None = None):
        """Stream ``FeatureBatch``es of ``geomesa.ingest.batch.rows``
        records — the firehose entry point. Columnar evaluation by
        default (see convert/vectorized.py); ``geomesa.ingest.
        vectorized=false`` kills it back to the scalar oracle, and
        ``geomesa.ingest.verify=true`` runs both per chunk and asserts
        id-for-id equivalence.

        Yields (batch, ctx) per chunk; ctx is cumulative (pass one in to
        aggregate across sources).
        """
        from .vectorized import (INGEST_BATCH_ROWS, INGEST_VECTORIZED,
                                 INGEST_VERIFY, process_columnar,
                                 process_columns)
        ctx = ctx or EvaluationContext()
        rows = batch_rows or INGEST_BATCH_ROWS.as_int()
        vectorized = INGEST_VECTORIZED.as_bool()
        verify = INGEST_VERIFY.as_bool()

        # formats with a columnar source (CSV cell-splitting) skip the
        # per-record generator entirely; verify mode needs the record
        # stream for the scalar oracle, so it takes the row path
        col_chunks = getattr(self, "iter_column_chunks", None)
        if vectorized and not verify and col_chunks is not None:
            for cols, n, ragged, n_bad in col_chunks(source, rows):
                yield process_columns(self, cols, n, ragged, n_bad, ctx), ctx
            return

        def emit(chunk: list[list]) -> FeatureBatch:
            if not vectorized:
                return self._process_scalar(chunk, ctx)
            batch = process_columnar(self, chunk, ctx)
            if verify:
                oracle = self._process_scalar(chunk, EvaluationContext())
                if list(batch.ids) != list(oracle.ids):
                    raise AssertionError(
                        "vectorized/scalar id divergence: "
                        f"{len(batch.ids)} vs {len(oracle.ids)} rows")
            return batch

        chunk: list[list] = []
        for rec in self._records(source):
            chunk.append(rec)
            if len(chunk) >= rows:
                yield emit(chunk), ctx
                chunk = []
        if chunk:
            yield emit(chunk), ctx


def _uses_col0(node: tuple) -> bool:
    kind = node[0]
    if kind == "col":
        return node[1] == 0
    if kind in ("lit", "relit", "field"):
        return False
    if kind == "recast":
        return _uses_col0(node[1])
    if kind == "cast":
        return _uses_col0(node[2])
    if kind in ("try", "withdefault"):
        return _uses_col0(node[1]) or _uses_col0(node[2])
    return any(_uses_col0(a) for a in node[2])


class DelimitedTextConverter(SimpleFeatureConverter):
    """CSV/TSV lines -> features ($1..$N are the delimited columns)."""

    def __init__(self, sft: SimpleFeatureType, config: dict):
        super().__init__(sft, config)
        fmt = config.get("format", "CSV").upper()
        self.delimiter = {"CSV": ",", "TSV": "\t"}.get(fmt, ",")
        self.skip_lines = int(config.get("options", {}).get("skip-lines", 0))
        # re-joining every parsed row into the $0 raw record costs more
        # than the parse itself on wide rows — skip it when no transform
        # (and not the id expression) ever reads $0
        self._needs_raw = (_uses_col0(self.id_ast)
                           or any(_uses_col0(a)
                                  for _, a in self.ordered_asts))

    def _records(self, source):
        if isinstance(source, str):
            source = io.StringIO(source)
        reader = csv.reader(source, delimiter=self.delimiter)
        if self._needs_raw:
            for i, row in enumerate(reader):
                if i < self.skip_lines or not row:
                    continue
                yield [self.delimiter.join(row)] + row
        else:
            for i, row in enumerate(reader):
                if i < self.skip_lines or not row:
                    continue
                yield [""] + row

    def iter_column_chunks(self, source, rows: int):
        """Columnar CSV parse: split whole chunks of text into cell
        arrays instead of iterating records — the per-row work drops to
        two C string splits plus one numpy reshape. Yields
        ``(cols, n, ragged, n_bad)`` tuples for ``process_columns``.

        The split is only CSV-correct while no quote character appears;
        the first chunk containing ``"`` degrades the REST of the stream
        to the csv.reader row path (a quoted newline may span chunk
        boundaries, so per-chunk fallback would tear records). Ragged
        chunks (uneven delimiter counts) re-parse row-wise so column
        references err exactly the rows the scalar path would.
        """
        from .vectorized import _transpose, parse_csv_arrow
        if isinstance(source, str):
            source = io.StringIO(source)
        for _ in range(self.skip_lines):
            if not source.readline():
                return
        d = self.delimiter
        # Arrow's reader drops the raw line, so $0 users stay on the
        # python split (they need the unsplit text in column 0)
        use_arrow = not self._needs_raw

        def row_chunks(line_iter):
            reader = csv.reader(line_iter, delimiter=d)
            chunk: list[list] = []
            for row in reader:
                if not row:
                    continue
                chunk.append(([d.join(row)] + row) if self._needs_raw
                             else ([""] + row))
                if len(chunk) >= rows:
                    cols, ragged = _transpose(chunk)
                    yield cols, len(chunk), ragged, 0
                    chunk = []
            if chunk:
                cols, ragged = _transpose(chunk)
                yield cols, len(chunk), ragged, 0

        est = 0  # learned bytes/line; first chunk iterates to calibrate
        carry = ""
        while True:
            if est:
                # block read: one syscall-ish slab instead of `rows`
                # readline calls, cut at the last complete line
                block = source.read(rows * est)
                joined = carry + block
                if not joined:
                    return
                if block:
                    cut = joined.rfind("\n")
                    if cut < 0:  # line longer than the slab: keep growing
                        carry = joined
                        continue
                    carry, joined = joined[cut + 1:], joined[:cut + 1]
                else:
                    carry = ""  # EOF: flush the unterminated tail line
            else:
                raw = list(islice(source, rows))
                if not raw:
                    return
                joined = "".join(raw)
                est = max(16, len(joined) // len(raw))
            if '"' in joined:
                if carry:  # finish the cut-off line before re-splitting
                    carry += source.readline()
                yield from row_chunks(chain(
                    io.StringIO(joined), [carry] if carry else [], source))
                return
            got = parse_csv_arrow(joined, d) if use_arrow else None
            if got is None:
                got = self._split_chunk(joined, d)
            if got is not None:
                yield got

    def _split_chunk(self, joined: str, d: str):
        body = joined[:-1] if joined.endswith("\n") else joined
        if "\r" in body:  # str sources; text-mode files normalize already
            body = body.replace("\r\n", "\n").replace("\r", "\n")
        ls = body.split("\n")
        if "" in ls:  # blank lines are skipped, not counted
            ls = [line for line in ls if line]
            body = "\n".join(ls)
        n = len(ls)
        if n == 0:
            return None
        w1 = ls[0].count(d)
        flat = body.replace("\n", d).split(d)
        if (len(flat) == n * (w1 + 1)
                and all(line.count(d) == w1 for line in ls)):
            arr = np.array(flat, dtype=object).reshape(n, w1 + 1)
            raw_col = (np.array(ls, dtype=object) if self._needs_raw
                       else np.full(n, "", dtype=object))
            cols = [raw_col] + [arr[:, i] for i in range(w1 + 1)]
            return cols, n, False, 0
        # ragged: row-wise parse isolates exactly the short/long rows
        from .vectorized import _transpose
        recs = [(([d.join(r)] + r) if self._needs_raw else ([""] + r))
                for r in csv.reader(io.StringIO(joined), delimiter=d) if r]
        if not recs:
            return None
        cols, ragged = _transpose(recs)
        return cols, len(recs), ragged, 0


class JsonConverter(SimpleFeatureConverter):
    """JSON objects (one per line, or a top-level array) -> features.

    Field transforms use jsonPath('$.a.b') via the `$0` record: the
    config's fields may use ``jsonPath`` expressions written as
    ``path('a.b')`` which this converter resolves before transforms, so
    `$1..$N` bind to the declared paths in order.
    """

    def __init__(self, sft: SimpleFeatureType, config: dict):
        self.paths = [f["path"] for f in config.get("fields", [])
                      if "path" in f]
        # fields with a path but no transform default to the column ref
        fields = []
        col = 0
        for f in config.get("fields", []):
            f = dict(f)
            if "path" in f:
                col += 1
                if "name" in f:
                    f.setdefault("transform", f"${col}")
            fields.append(f)
        config = dict(config)
        config["fields"] = fields
        super().__init__(sft, config)

    @staticmethod
    def _resolve(obj: Any, path: str):
        cur = obj
        for part in path.replace("$.", "").split("."):
            if isinstance(cur, dict):
                cur = cur.get(part)
            elif isinstance(cur, list) and part.isdigit():
                cur = cur[int(part)]
            else:
                return None
        return cur

    def iter_column_chunks(self, source, rows: int):
        """Columnar JSON-lines parse: Arrow ``read_json`` decodes whole
        blocks in C and declared paths resolve as struct-field hops
        instead of a python dict walk per record. Yields
        ``(cols, n, ragged, n_bad)`` tuples for ``process_columns``.

        Degradations keep scalar semantics exactly: top-level-array
        sources, configs whose transforms read the ``$0`` record, and
        paths with list indices take the record path from the start; a
        block Arrow refuses (malformed line, mixed field types) sends
        that block AND the rest of the stream to the per-record parser,
        which isolates bad lines row-for-row."""
        from .vectorized import parse_json_arrow
        if not isinstance(source, str):
            source = source.read()
        stripped = source.strip()
        use_arrow = not (_uses_col0(self.id_ast)
                         or any(_uses_col0(a)
                                for _, a in self.ordered_asts))

        def record_chunks(records_iter):
            chunk: list[list] = []
            for rec in records_iter:
                chunk.append(rec)
                if len(chunk) >= rows:
                    yield self._record_cols(chunk)
                    chunk = []
            if chunk:
                yield self._record_cols(chunk)

        if not use_arrow or stripped.startswith("["):
            yield from record_chunks(self._records(stripped))
            return
        lines = [ln for ln in stripped.splitlines() if ln.strip()]
        for at in range(0, len(lines), rows):
            got = parse_json_arrow("\n".join(lines[at:at + rows]),
                                   self.paths)
            if got is None:
                yield from record_chunks(
                    self._records("\n".join(lines[at:])))
                return
            yield got

    @staticmethod
    def _record_cols(chunk: list[list]):
        """Scalar record chunk -> the (cols, n, ragged, n_bad) shape
        ``process_columns`` takes (bad records masked out, counted)."""
        from .vectorized import _transpose
        good = [r for r in chunk if r is not _BAD_RECORD]
        n_bad = len(chunk) - len(good)
        if not good:
            return [np.empty(0, dtype=object)], 0, False, n_bad
        cols, ragged = _transpose(good)
        return cols, len(good), ragged, n_bad

    def _records(self, source):
        if not isinstance(source, str):
            source = source.read()  # file-like: parse the whole stream
        stripped = source.strip()
        if stripped.startswith("["):
            try:
                objs = json.loads(stripped)
            except ValueError:
                yield _BAD_RECORD
                return
        else:
            objs = []
            for line in stripped.splitlines():
                if not line.strip():
                    continue
                try:
                    objs.append(json.loads(line))
                except ValueError:
                    objs.append(_BAD_RECORD)
        for obj in objs:
            if obj is _BAD_RECORD:
                yield _BAD_RECORD
                continue
            try:
                yield [obj] + [self._resolve(obj, p) for p in self.paths]
            except Exception:
                # a bad record must count as a failure, not kill the run
                yield _BAD_RECORD


def converter_for(sft: SimpleFeatureType, config: dict):
    kind = config.get("type", "delimited-text")
    if kind == "delimited-text":
        return DelimitedTextConverter(sft, config)
    if kind == "json":
        return JsonConverter(sft, config)
    if kind in ("xml", "fixed-width", "avro", "composite"):
        from .formats import (AvroConverter, CompositeConverter,
                              FixedWidthConverter, XmlConverter)
        cls = {"xml": XmlConverter, "fixed-width": FixedWidthConverter,
               "avro": AvroConverter, "composite": CompositeConverter}[kind]
        return cls(sft, config)
    if kind in ("shapefile", "jdbc", "osm"):
        from .geo_formats import (JdbcConverter, OsmConverter,
                                  ShapefileConverter)
        cls = {"shapefile": ShapefileConverter, "jdbc": JdbcConverter,
               "osm": OsmConverter}[kind]
        return cls(sft, config)
    raise ValueError(f"unknown converter type: {kind}")
