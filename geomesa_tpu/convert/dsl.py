"""Converter transform-expression DSL.

The reference's ingest converters evaluate a small expression language
per field (convert/Transformers.scala — scala parser-combinators):
column refs ``$1``, casts ``::int``, function calls, ``try(expr,
fallback)``, string/date/geometry helpers. This is a from-scratch
recursive-descent implementation of that grammar over Python values.

Supported:
    $0 .. $N                 raw input columns ($0 = whole record)
    'literal'  123  4.5      literals
    expr::int  ::long ::float ::double ::string ::boolean
    concat(a, b, ...)        trim(s) lowercase(s) uppercase(s)
    regexReplace('rx','rep',s)     substring(s, i, j)
    date('fmt', s)           isoDate(s)  millisToDate(n)  (epoch millis)
    point(x, y)              geometry(wkt)
    md5(s)  uuid()           stringToBytes(s)
    try(expr, fallback)
    withDefault(expr, default)
"""

from __future__ import annotations

import hashlib
import re
import uuid as _uuid
from typing import Any, Callable

import numpy as np

from ..geometry import Point, parse_wkt

__all__ = ["compile_expression", "EvaluationContext"]


class EvaluationContext:
    """Per-ingest counters + caches (convert/EvaluationContext analog)."""

    def __init__(self):
        self.success = 0
        self.failure = 0
        self.line = 0

    def counters(self) -> dict[str, int]:
        return {"success": self.success, "failure": self.failure,
                "line": self.line}


class _P:
    def __init__(self, s: str):
        self.s = s
        self.i = 0

    def ws(self):
        while self.i < len(self.s) and self.s[self.i].isspace():
            self.i += 1

    def peek(self) -> str:
        self.ws()
        return self.s[self.i] if self.i < len(self.s) else ""

    def eat(self, ch: str):
        self.ws()
        if self.peek() != ch:
            raise ValueError(f"expected {ch!r} at {self.i} in {self.s!r}")
        self.i += 1

    def match_re(self, rx: str):
        self.ws()
        m = re.match(rx, self.s[self.i:])
        if m:
            self.i += m.end()
            return m
        return None


_CASTS: dict[str, Callable[[Any], Any]] = {
    "int": lambda v: int(float(v)),
    "integer": lambda v: int(float(v)),
    "long": lambda v: int(float(v)),
    "float": float,
    "double": float,
    "string": str,
    "boolean": lambda v: str(v).strip().lower() in ("true", "1", "t", "yes"),
}


def _fn_date(fmt: str, s: str) -> int:
    """Parse with a java-SimpleDateFormat-flavored pattern -> millis."""
    py = (fmt.replace("yyyy", "%Y").replace("MM", "%m").replace("dd", "%d")
          .replace("HH", "%H").replace("mm", "%M").replace("ss", "%S")
          .replace("SSS", "%f").replace("'T'", "T").replace("'Z'", "Z"))
    import datetime as _dt
    dt = _dt.datetime.strptime(str(s).strip(), py)
    return int(dt.replace(tzinfo=_dt.timezone.utc).timestamp() * 1000)


def _fn_iso_date(s: str) -> int:
    return int(np.datetime64(str(s).strip().rstrip("Z"), "ms").astype(np.int64))


_FUNCTIONS: dict[str, Callable[..., Any]] = {
    "concat": lambda *a: "".join(str(x) for x in a),
    "trim": lambda s: str(s).strip(),
    "lowercase": lambda s: str(s).lower(),
    "uppercase": lambda s: str(s).upper(),
    "regexReplace": lambda rx, rep, s: re.sub(rx, rep, str(s)),
    "substring": lambda s, i, j: str(s)[int(i):int(j)],
    "length": lambda s: len(str(s)),
    "date": _fn_date,
    "isoDate": _fn_iso_date,
    "millisToDate": lambda n: int(n),
    "secsToDate": lambda n: int(float(n) * 1000),
    "point": lambda x, y: Point(float(x), float(y)),
    "geometry": lambda wkt: parse_wkt(str(wkt)),
    "md5": lambda s: hashlib.md5(str(s).encode()).hexdigest(),
    "uuid": lambda: str(_uuid.uuid4()),
    "stringToBytes": lambda s: str(s).encode(),
    "toString": str,
    # dict/tag access for record formats whose $0 is a mapping (OSM)
    "mapValue": lambda m, k, default=None: (m or {}).get(str(k), default),
    "cacheLookup": lambda name, key, field=None: __import__(
        "geomesa_tpu.convert.enrichment", fromlist=["cache_lookup"]
    ).cache_lookup(name, key, field),
}


def compile_expression(text: str) -> Callable[[list], Any]:
    """Compile an expression to fn(columns) -> value. columns[0] is the
    whole record; columns[1:] are fields."""
    p = _P(text)
    expr = _parse_expr(p)
    p.ws()
    if p.i != len(p.s):
        raise ValueError(f"trailing input in expression: {text[p.i:]!r}")
    return expr


def _parse_expr(p: _P):
    e = _parse_primary(p)
    # postfix casts, possibly chained
    while True:
        m = p.match_re(r"::(\w+)")
        if not m:
            return e
        cast = _CASTS.get(m.group(1).lower())
        if cast is None:
            raise ValueError(f"unknown cast ::{m.group(1)}")
        inner = e
        e = (lambda inner, cast: lambda cols: cast(inner(cols)))(inner, cast)


def _parse_primary(p: _P):
    m = p.match_re(r"\$(\d+)")
    if m:
        idx = int(m.group(1))
        return lambda cols: cols[idx]
    m = p.match_re(r"'((?:[^']|'')*)'")
    if m:
        lit = m.group(1).replace("''", "'")
        return lambda cols: lit
    m = p.match_re(r"[-+]?\d+\.\d+(?:[eE][-+]?\d+)?")
    if m:
        lit = float(m.group(0))
        return lambda cols: lit
    m = p.match_re(r"[-+]?\d+")
    if m:
        lit = int(m.group(0))
        return lambda cols: lit
    m = p.match_re(r"null\b")
    if m:
        return lambda cols: None
    m = p.match_re(r"(\w+)\s*\(")
    if m:
        name = m.group(1)
        args = []
        if p.peek() != ")":
            args.append(_parse_expr(p))
            while p.peek() == ",":
                p.eat(",")
                args.append(_parse_expr(p))
        p.eat(")")
        if name == "try":
            if len(args) != 2:
                raise ValueError("try(expr, fallback) takes 2 args")
            expr, fallback = args

            def _try(cols, expr=expr, fallback=fallback):
                try:
                    return expr(cols)
                except Exception:
                    return fallback(cols)
            return _try
        if name == "withDefault":
            expr, default = args

            def _wd(cols, expr=expr, default=default):
                v = expr(cols)
                return default(cols) if v in (None, "") else v
            return _wd
        fn = _FUNCTIONS.get(name)
        if fn is None:
            raise ValueError(f"unknown function {name!r}")
        return (lambda fn, args: lambda cols: fn(*(a(cols) for a in args)))(fn, args)
    raise ValueError(f"cannot parse expression at {p.i} in {p.s!r}")
