"""Converter transform-expression DSL.

The reference's ingest converters evaluate a small expression language
per field (convert/Transformers.scala — scala parser-combinators):
column refs ``$1``, field refs ``$name``, regex literals ``'rx'::r``,
casts ``::int``, function calls, ``try(expr, fallback)``, a date-format
zoo, hashes, math, list/map helpers, geometry constructors. This is a
from-scratch recursive-descent implementation of that grammar over
Python values.

Supported grammar:
    $0 .. $N                 raw input columns ($0 = whole record)
    $name                    previously-computed field (declaration order)
    'literal'  123  4.5      literals; 'pattern'::r compiles a regex
    expr::int  ::long ::float ::double ::string ::boolean
    fn(args...)              from the registry below
    try(expr, fallback)      withDefault(expr, default)

Function registry (Transformers.scala parity): strings (concat, trim,
capitalize, stripQuotes, emptyToNull, mkstring, regexReplace,
substring, length...), dates (now, date, datetime, isodate,
isodatetime, basicDateTimeNoMillis, dateHourMinuteSecondMillis,
millisToDate, secsToDate, dateToString), hashes (md5, murmur3_32,
murmur3_64, base64), math (add, subtract, multiply, divide, mean, min,
max), lists/maps (list, listItem, parseList, parseMap, mapValue),
conversions (stringToInt/Long/Float/Double/Boolean), geometry (point,
linestring, polygon, multi*, geometry), uuid, stringToBytes,
cacheLookup.
"""

from __future__ import annotations

import base64 as _b64
import hashlib
import re
import struct
import threading
import uuid as _uuid
from typing import Any, Callable

import numpy as np

from ..geometry import Point, parse_wkt

__all__ = ["compile_expression", "parse_expression", "EvaluationContext",
           "murmur3_32", "murmur3_128"]


class EvaluationContext:
    """Per-ingest counters + caches (convert/EvaluationContext analog).

    Counter bumps are NOT atomic in CPython across `+=` on attributes,
    so the ingest pipeline's worker threads each get their own context
    and `merge()` them into the caller's at flush — the per-worker-
    context strategy of the reference's EvaluationContext.copy. A lock
    still guards `merge`/`counters` so a live metrics scrape racing a
    flush reads a consistent triple."""

    def __init__(self):
        self.success = 0
        self.failure = 0
        self.line = 0
        self._lock = threading.Lock()

    def merge(self, other: "EvaluationContext") -> "EvaluationContext":
        """Fold another context's counts into this one (thread-safe)."""
        with other._lock:
            s, f, ln = other.success, other.failure, other.line
        with self._lock:
            self.success += s
            self.failure += f
            self.line += ln
        return self

    def counters(self) -> dict[str, int]:
        with self._lock:
            return {"success": self.success, "failure": self.failure,
                    "line": self.line}


# -- murmur3 (x86_32 and x64_128) — pure-python, test-vector checked ------

def murmur3_32(data: bytes, seed: int = 0) -> int:
    """MurmurHash3 x86 32-bit (the guava Hashing.murmur3_32 the
    reference's hash transformer uses)."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & 0xFFFFFFFF
    n = len(data)
    rounded = n - (n % 4)
    for i in range(0, rounded, 4):
        k = struct.unpack_from("<I", data, i)[0]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    k = 0
    tail = data[rounded:]
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def _fmix64(k: int) -> int:
    k ^= k >> 33
    k = (k * 0xFF51AFD7ED558CCD) & 0xFFFFFFFFFFFFFFFF
    k ^= k >> 33
    k = (k * 0xC4CEB9FE1A85EC53) & 0xFFFFFFFFFFFFFFFF
    k ^= k >> 33
    return k


def murmur3_128(data: bytes, seed: int = 0):
    """MurmurHash3 x64 128-bit; murmur3_64 is its leading 8 bytes."""
    m = 0xFFFFFFFFFFFFFFFF
    c1, c2 = 0x87C37B91114253D5, 0x4CF5AD432745937F
    h1 = h2 = seed & m
    n = len(data)
    rounded = n - (n % 16)
    for i in range(0, rounded, 16):
        k1, k2 = struct.unpack_from("<QQ", data, i)
        k1 = (k1 * c1) & m
        k1 = ((k1 << 31) | (k1 >> 33)) & m
        k1 = (k1 * c2) & m
        h1 ^= k1
        h1 = ((h1 << 27) | (h1 >> 37)) & m
        h1 = (h1 + h2) & m
        h1 = (h1 * 5 + 0x52DCE729) & m
        k2 = (k2 * c2) & m
        k2 = ((k2 << 33) | (k2 >> 31)) & m
        k2 = (k2 * c1) & m
        h2 ^= k2
        h2 = ((h2 << 31) | (h2 >> 33)) & m
        h2 = (h2 + h1) & m
        h2 = (h2 * 5 + 0x38495AB5) & m
    tail = data[rounded:]
    k1 = k2 = 0
    for j in range(min(len(tail), 16) - 1, 7, -1):
        k2 ^= tail[j] << ((j - 8) * 8)
    for j in range(min(len(tail), 8) - 1, -1, -1):
        k1 ^= tail[j] << (j * 8)
    if len(tail) > 8:
        k2 = (k2 * c2) & m
        k2 = ((k2 << 33) | (k2 >> 31)) & m
        k2 = (k2 * c1) & m
        h2 ^= k2
    if len(tail) > 0:
        k1 = (k1 * c1) & m
        k1 = ((k1 << 31) | (k1 >> 33)) & m
        k1 = (k1 * c2) & m
        h1 ^= k1
    h1 ^= n
    h2 ^= n
    h1 = (h1 + h2) & m
    h2 = (h2 + h1) & m
    h1 = _fmix64(h1)
    h2 = _fmix64(h2)
    h1 = (h1 + h2) & m
    h2 = (h2 + h1) & m
    return h1, h2


def _to_bytes(v) -> bytes:
    return v if isinstance(v, (bytes, bytearray)) else str(v).encode()


# -- parser ----------------------------------------------------------------

class _P:
    def __init__(self, s: str):
        self.s = s
        self.i = 0

    def ws(self):
        while self.i < len(self.s) and self.s[self.i].isspace():
            self.i += 1

    def peek(self) -> str:
        self.ws()
        return self.s[self.i] if self.i < len(self.s) else ""

    def eat(self, ch: str):
        self.ws()
        if self.peek() != ch:
            raise ValueError(f"expected {ch!r} at {self.i} in {self.s!r}")
        self.i += 1

    def match_re(self, rx: str):
        self.ws()
        m = re.match(rx, self.s[self.i:])
        if m:
            self.i += m.end()
            return m
        return None


_CASTS: dict[str, Callable[[Any], Any]] = {
    "int": lambda v: int(float(v)),
    "integer": lambda v: int(float(v)),
    "long": lambda v: int(float(v)),
    "float": float,
    "double": float,
    "string": str,
    "boolean": lambda v: str(v).strip().lower() in ("true", "1", "t", "yes"),
}


def _java_fmt(fmt: str) -> str:
    return (fmt.replace("yyyy", "%Y").replace("MM", "%m")
            .replace("dd", "%d").replace("HH", "%H").replace("mm", "%M")
            .replace("ss", "%S").replace("SSS", "%f")
            .replace("'T'", "T").replace("'Z'", "Z"))


def _fn_date(fmt: str, s: str) -> int:
    """Parse with a java-SimpleDateFormat-flavored pattern -> millis."""
    import datetime as _dt
    dt = _dt.datetime.strptime(str(s).strip(), _java_fmt(fmt))
    return int(dt.replace(tzinfo=_dt.timezone.utc).timestamp() * 1000)


def _fn_iso_date(s: str) -> int:
    return int(np.datetime64(str(s).strip().rstrip("Z"), "ms").astype(np.int64))


def _fn_date_to_string(fmt: str, millis: int) -> str:
    import datetime as _dt
    dt = _dt.datetime.fromtimestamp(int(millis) / 1000.0,
                                    tz=_dt.timezone.utc)
    # java SSS means 3-digit millis; strftime %f is 6-digit micros, and
    # a placeholder char cannot ride through C strftime (glibc
    # truncates the format at a NUL) — format around the SSS runs
    ms = f"{dt.microsecond // 1000:03d}"
    return ms.join(dt.strftime(_java_fmt(p)) for p in fmt.split("SSS"))


def _line_geom(cls_wkt: str, arg):
    """Geometry constructor accepting full WKT text or, for the types
    where a bare body is unambiguous, just the coordinates
    (Transformers' linestring('0 0, 1 1') convenience). A bare
    POLYGON/MULTIPOLYGON body is a single shell; a bare
    MULTILINESTRING body is a single line; GEOMETRYCOLLECTION requires
    full WKT (a bare body has no type tags)."""
    s = str(arg).strip()
    if not s.upper().startswith(cls_wkt):
        if cls_wkt == "GEOMETRYCOLLECTION":
            raise ValueError(
                "geometrycollection() requires full WKT input")
        if cls_wkt in ("POLYGON", "MULTILINESTRING") \
                and not s.startswith("("):
            s = f"({s})"
        elif cls_wkt == "MULTIPOLYGON" and not s.startswith("(("):
            if not s.startswith("("):
                s = f"({s})"
            s = f"({s})"
        s = f"{cls_wkt} ({s})"
    return parse_wkt(s)


def _num_args(args):
    return [float(a) for a in args]


_FUNCTIONS: dict[str, Callable[..., Any]] = {
    # strings (Transformers.scala string fns)
    "concat": lambda *a: "".join(str(x) for x in a),
    "concatenate": lambda *a: "".join(str(x) for x in a),
    "trim": lambda s: str(s).strip(),
    "strip": lambda s, chars=None: str(s).strip(chars),
    "stripQuotes": lambda s: str(s).strip("'\""),
    "stripPrefix": lambda s, p: str(s)[len(str(p)):]
        if str(s).startswith(str(p)) else str(s),
    "stripSuffix": lambda s, p: str(s)[: -len(str(p))]
        if str(p) and str(s).endswith(str(p)) else str(s),
    "capitalize": lambda s: str(s).capitalize(),
    "lowercase": lambda s: str(s).lower(),
    "uppercase": lambda s: str(s).upper(),
    "emptyToNull": lambda s: None if s is None or str(s).strip() == ""
        else s,
    "mkstring": lambda sep, *a: str(sep).join(str(x) for x in a),
    "regexReplace": lambda rx, rep, s: (
        rx.sub(str(rep), str(s)) if isinstance(rx, re.Pattern)
        else re.sub(str(rx), str(rep), str(s))),
    "regexExtract": lambda rx, s, group=None: _regex_extract(rx, s,
                                                             group),
    "substring": lambda s, i, j: str(s)[int(i):int(j)],
    "substr": lambda s, i, j: str(s)[int(i):int(j)],
    "length": lambda s: len(str(s)),
    "strlen": lambda s: len(str(s)),
    "stringLength": lambda s: len(str(s)),
    "toString": str,
    # dates (the reference's StandardDateParser zoo)
    "now": lambda: int(np.datetime64("now", "ms").astype(np.int64)),
    "date": _fn_date,
    "customFormatDateParser": _fn_date,
    "datetime": _fn_iso_date,
    "isoDate": _fn_iso_date,
    "isodate": lambda s: _fn_date("yyyyMMdd", s),
    "basicDate": lambda s: _fn_date("yyyyMMdd", s),
    "isodatetime": lambda s: _fn_date("yyyyMMdd'T'HHmmss.SSS",
                                      str(s).rstrip("Z")),
    "basicDateTime": lambda s: _fn_date("yyyyMMdd'T'HHmmss.SSS",
                                        str(s).rstrip("Z")),
    "basicDateTimeNoMillis": lambda s: _fn_date("yyyyMMdd'T'HHmmss",
                                                str(s).rstrip("Z")),
    "dateHourMinuteSecondMillis":
        lambda s: _fn_date("yyyy-MM-dd'T'HH:mm:ss.SSS", s),
    "millisToDate": lambda n: int(n),
    "secsToDate": lambda n: int(float(n) * 1000),
    "dateToString": _fn_date_to_string,
    # geometry constructors
    "point": lambda x, y: Point(float(x), float(y)),
    "geometry": lambda wkt: parse_wkt(str(wkt)),
    "linestring": lambda a: _line_geom("LINESTRING", a),
    "polygon": lambda a: _line_geom("POLYGON", a),
    "multipoint": lambda a: _line_geom("MULTIPOINT", a),
    "multilinestring": lambda a: _line_geom("MULTILINESTRING", a),
    "multipolygon": lambda a: _line_geom("MULTIPOLYGON", a),
    "geometrycollection": lambda a: _line_geom("GEOMETRYCOLLECTION", a),
    # hashes / ids / bytes
    "md5": lambda s: hashlib.md5(_to_bytes(s)).hexdigest(),
    "murmur3_32": lambda s: murmur3_32(_to_bytes(s)),
    "murmur3_64": lambda s: struct.unpack(
        "<q", struct.pack("<Q", murmur3_128(_to_bytes(s))[0]))[0],
    "murmurHash3": lambda s: murmur3_128(_to_bytes(s))[0],
    "base64": lambda s: _b64.b64encode(_to_bytes(s)).decode(),
    "uuid": lambda: str(_uuid.uuid4()),
    "stringToBytes": lambda s: str(s).encode(),
    "string2bytes": lambda s: str(s).encode(),
    # math (numeric-string tolerant, like the reference's)
    "add": lambda *a: sum(_num_args(a)),
    "subtract": lambda *a: (lambda v: v[0] - sum(v[1:]))(_num_args(a)),
    "multiply": lambda *a: float(np.prod(_num_args(a))),
    "divide": lambda *a: (lambda v: float(np.divide.reduce(v)))(_num_args(a)),
    "mean": lambda *a: float(np.mean(_num_args(a))),
    "min": lambda *a: min(_num_args(a)),
    "max": lambda *a: max(_num_args(a)),
    # lists / maps
    "list": lambda *a: list(a),
    "listItem": lambda lst, i: lst[int(i)],
    "parseList": lambda typ, s, sep=",": [
        _CASTS.get(str(typ).lower(), str)(x)
        for x in str(s).split(str(sep)) if x != ""],
    "parseMap": lambda typ, s, sep=",", kv="->": {
        (p.split(str(kv))[0].strip()):
        _CASTS.get(str(typ).lower(), str)(p.split(str(kv))[1].strip())
        for p in str(s).split(str(sep)) if str(kv) in p},
    "mapValue": lambda m, k, default=None: (m or {}).get(str(k), default),
    # conversions
    "stringToInt": lambda s, d=None: _try_cast(s, int, d),
    "stringToInteger": lambda s, d=None: _try_cast(s, int, d),
    "stringToLong": lambda s, d=None: _try_cast(s, int, d),
    "stringToFloat": lambda s, d=None: _try_cast(s, float, d),
    "stringToDouble": lambda s, d=None: _try_cast(s, float, d),
    "stringToBool": lambda s, d=None: _try_cast(s, _parse_bool, d),
    "stringToBoolean": lambda s, d=None: _try_cast(s, _parse_bool, d),
    "cacheLookup": lambda name, key, field=None: __import__(
        "geomesa_tpu.convert.enrichment", fromlist=["cache_lookup"]
    ).cache_lookup(name, key, field),
}


def _try_cast(s, fn, default):
    try:
        return fn(s)
    except (TypeError, ValueError):
        return default


def _regex_extract(rx, s, group):
    """First match of rx in s: group 1 when the pattern captures,
    else the whole match; an explicit out-of-range group is a clear
    error, not a silent per-record failure."""
    pat = rx if isinstance(rx, re.Pattern) else re.compile(str(rx))
    g = int(group) if group is not None else (1 if pat.groups else 0)
    if g > pat.groups:
        raise ValueError(f"regexExtract: pattern has {pat.groups} "
                         f"group(s), requested {g}")
    m = pat.search(str(s))
    return m.group(g) if m else None


def _parse_bool(v):
    s = str(v).strip().lower()
    if s in ("true", "1", "t", "yes", "y"):
        return True
    if s in ("false", "0", "f", "no", "n"):
        return False
    raise ValueError(f"not a boolean: {v!r}")


def parse_expression(text: str) -> tuple:
    """Parse an expression into its AST — tagged tuples shared by the
    scalar compiler below and the columnar compiler in ``vectorized``:

        ("col", i)               column reference $i
        ("field", name)          $fieldName cross-reference
        ("lit", value)           literal (str only from quoted literals)
        ("relit", pattern)       '...'::r constant-folded at parse time
        ("recast", node)         dynamic ::r over a non-literal
        ("cast", name, node)     ::int / ::double / ...
        ("fn", name, [nodes])    registry function call
        ("try", expr, fallback)
        ("withdefault", expr, default)
    """
    p = _P(text)
    node = _parse_expr(p)
    p.ws()
    if p.i != len(p.s):
        raise ValueError(f"trailing input in expression: {text[p.i:]!r}")
    return node


def compile_expression(text: str) -> Callable[..., Any]:
    """Compile an expression to ``fn(columns, fields=None)``.
    ``columns[0]`` is the whole record; ``columns[1:]`` are input
    fields; ``fields`` maps previously-computed field names to values
    (the reference's `$fieldName` cross-references, evaluated in
    declaration order)."""
    expr = _compile_node(parse_expression(text))

    def run(cols, fields=None):
        return expr((cols, fields or {}))
    return run


def _parse_expr(p: _P) -> tuple:
    e = _parse_primary(p)
    # postfix casts, possibly chained; '...'::r compiles a regex literal
    while True:
        m = p.match_re(r"::(\w+)")
        if not m:
            return e
        name = m.group(1).lower()
        if name == "r":
            if e[0] == "lit" and isinstance(e[1], str):
                # constant-fold: string literals compile ONCE at
                # expression-compile time, not per record
                e = ("relit", re.compile(e[1]))
            else:
                e = ("recast", e)
            continue
        if name not in _CASTS:
            raise ValueError(f"unknown cast ::{m.group(1)}")
        e = ("cast", name, e)


def _parse_primary(p: _P) -> tuple:
    m = p.match_re(r"\$(\d+)")
    if m:
        return ("col", int(m.group(1)))
    m = p.match_re(r"\$([A-Za-z_]\w*)")
    if m:
        return ("field", m.group(1))
    m = p.match_re(r"'((?:[^']|'')*)'")
    if m:
        return ("lit", m.group(1).replace("''", "'"))
    m = p.match_re(r"[-+]?\d+\.\d+(?:[eE][-+]?\d+)?")
    if m:
        return ("lit", float(m.group(0)))
    m = p.match_re(r"[-+]?\d+(?![\w.])")
    if m:
        return ("lit", int(m.group(0)))
    m = p.match_re(r"null\b")
    if m:
        return ("lit", None)
    m = p.match_re(r"(\w+)\s*\(")
    if m:
        name = m.group(1)
        args = []
        if p.peek() != ")":
            args.append(_parse_expr(p))
            while p.peek() == ",":
                p.eat(",")
                args.append(_parse_expr(p))
        p.eat(")")
        if name == "try":
            if len(args) != 2:
                raise ValueError("try(expr, fallback) takes 2 args")
            return ("try", args[0], args[1])
        if name == "withDefault":
            if len(args) != 2:
                raise ValueError("withDefault(expr, default) takes 2 args")
            return ("withdefault", args[0], args[1])
        if name not in _FUNCTIONS:
            raise ValueError(f"unknown function {name!r}")
        return ("fn", name, args)
    raise ValueError(f"cannot parse expression at {p.i} in {p.s!r}")


def _compile_node(node: tuple) -> Callable[[tuple], Any]:
    """Scalar backend: AST -> closure over ctx=(cols, fields)."""
    kind = node[0]
    if kind == "col":
        idx = node[1]
        return lambda ctx: ctx[0][idx]
    if kind == "field":
        name = node[1]

        def _field(ctx, name=name):
            if name not in ctx[1]:
                raise ValueError(f"unknown field reference ${name} "
                                 "(fields evaluate in declaration order)")
            return ctx[1][name]
        return _field
    if kind == "lit":
        lit = node[1]
        return lambda ctx: lit
    if kind == "relit":
        pat = node[1]
        return lambda ctx: pat
    if kind == "recast":
        inner = _compile_node(node[1])
        return lambda ctx: re.compile(str(inner(ctx)))
    if kind == "cast":
        cast = _CASTS[node[1]]
        inner = _compile_node(node[2])
        return lambda ctx: cast(inner(ctx))
    if kind == "try":
        expr = _compile_node(node[1])
        fallback = _compile_node(node[2])

        def _try(ctx, expr=expr, fallback=fallback):
            try:
                return expr(ctx)
            except Exception:
                return fallback(ctx)
        return _try
    if kind == "withdefault":
        expr = _compile_node(node[1])
        default = _compile_node(node[2])

        def _wd(ctx, expr=expr, default=default):
            v = expr(ctx)
            return default(ctx) if v in (None, "") else v
        return _wd
    fn = _FUNCTIONS[node[1]]
    args = [_compile_node(a) for a in node[2]]
    return lambda ctx: fn(*(a(ctx) for a in args))
