"""Geo input formats: shapefile, JDBC (sqlite), and OSM XML converters.

The reference ships these as converter modules
(/root/reference/geomesa-convert/ geomesa-convert-jdbc, -osm; shapefile
ingest via geomesa-tools/.../ingest ShapefileConverter). Here each is a
``SimpleFeatureConverter`` whose record stream exposes columns to the
same transform DSL as every other format:

- **shapefile** — a from-scratch reader of the ESRI .shp (geometry) +
  .dbf (dBase III attributes) pair; no external libraries. Record
  columns: $1 = geometry WKT, $2.. = dbf attribute values in file
  order. Config: {"type": "shapefile", ...fields}
- **jdbc** — rows from a SQL query against a sqlite database (the
  stdlib stand-in for the reference's JDBC connections). Record
  columns: $1.. = selected columns. Config: {"type": "jdbc",
  "query": "SELECT ..."}; the process() input is the database path.
- **osm** — OpenStreetMap XML: nodes become points, ways become
  linestrings (closed ways polygons) via node-reference resolution.
  Record columns: $1 = element id, $2 = element type ('node'/'way'),
  $3 = geometry WKT, $0 = the tags dict (transforms can use
  ``mapValue($0, 'name')``). Config: {"type": "osm"}.
"""

from __future__ import annotations

import struct
from typing import Any, Iterable

from .converter import _BAD_RECORD, SimpleFeatureConverter

__all__ = ["ShapefileConverter", "JdbcConverter", "OsmConverter",
           "read_shapefile"]


# -- shapefile (.shp + .dbf) ----------------------------------------------


def _ring_wkt(pts) -> str:
    return "(" + ", ".join(f"{x!r} {y!r}" for x, y in pts) + ")"


def _signed_area(pts) -> float:
    a = 0.0
    for (x1, y1), (x2, y2) in zip(pts, pts[1:]):
        a += x1 * y2 - x2 * y1
    return a / 2.0


def _polygon_wkt(rings) -> str:
    """Group shapefile rings (outer = clockwise = negative area) into
    polygons; counter-clockwise rings are holes of the preceding outer."""
    polys: list[list] = []
    for ring in rings:
        if _signed_area(ring) <= 0 or not polys:
            polys.append([ring])
        else:
            polys[-1].append(ring)
    if len(polys) == 1:
        return "POLYGON (" + ", ".join(_ring_wkt(r)
                                       for r in polys[0]) + ")"
    return "MULTIPOLYGON (" + ", ".join(
        "(" + ", ".join(_ring_wkt(r) for r in p) + ")"
        for p in polys) + ")"


def _shape_wkt(shape_type: int, buf: bytes) -> str | None:
    if shape_type == 0:
        return None
    if shape_type == 1:                      # Point
        x, y = struct.unpack_from("<2d", buf, 0)
        return f"POINT ({x!r} {y!r})"
    if shape_type == 8:                      # MultiPoint
        (n,) = struct.unpack_from("<i", buf, 32)
        pts = struct.unpack_from(f"<{2 * n}d", buf, 36)
        return "MULTIPOINT (" + ", ".join(
            f"{pts[2*i]!r} {pts[2*i+1]!r}" for i in range(n)) + ")"
    if shape_type in (3, 5):                 # PolyLine / Polygon
        nparts, npoints = struct.unpack_from("<2i", buf, 32)
        parts = struct.unpack_from(f"<{nparts}i", buf, 40)
        coords = struct.unpack_from(f"<{2 * npoints}d", buf,
                                    40 + 4 * nparts)
        pts = [(coords[2 * i], coords[2 * i + 1]) for i in range(npoints)]
        rings = [pts[parts[i]:(parts[i + 1] if i + 1 < nparts
                               else npoints)]
                 for i in range(nparts)]
        if shape_type == 3:
            if len(rings) == 1:
                return "LINESTRING " + _ring_wkt(rings[0])
            return "MULTILINESTRING (" + ", ".join(
                _ring_wkt(r) for r in rings) + ")"
        return _polygon_wkt(rings)
    raise ValueError(f"unsupported shape type {shape_type}")


def _read_dbf(path: str) -> list[list]:
    """dBase III attribute rows (strings/numbers/bools/date strings)."""
    with open(path, "rb") as f:
        data = f.read()
    n_rec, hdr_len, rec_len = struct.unpack_from("<IHH", data, 4)
    fields = []
    off = 32
    while off < hdr_len - 1 and data[off] != 0x0D:
        name = data[off:off + 11].split(b"\x00")[0].decode("ascii",
                                                           "replace")
        ftype = chr(data[off + 11])
        flen = data[off + 16]
        fields.append((name, ftype, flen))
        off += 32
    rows = []
    pos = hdr_len
    for _ in range(n_rec):
        if pos + rec_len > len(data):
            break
        rec = data[pos:pos + rec_len]
        pos += rec_len
        if rec[:1] == b"*":                  # deleted
            continue
        vals: list[Any] = []
        o = 1
        for name, ftype, flen in fields:
            raw = rec[o:o + flen].decode("latin-1").strip()
            o += flen
            if ftype in ("N", "F"):
                try:
                    vals.append(float(raw) if ("." in raw or "e" in raw)
                                else int(raw))
                except ValueError:
                    vals.append(None)
            elif ftype == "L":
                vals.append(raw.upper() in ("T", "Y"))
            else:                            # C, D, ...
                vals.append(raw or None)
        rows.append(vals)
    return rows


def read_shapefile(shp_path: str) -> Iterable[tuple]:
    """Yield (wkt, *dbf_values) per feature from a .shp/.dbf pair."""
    with open(shp_path, "rb") as f:
        data = f.read()
    dbf_path = shp_path[:-4] + ".dbf"
    import os
    dbf = _read_dbf(dbf_path) if os.path.exists(dbf_path) else None
    pos = 100                                # past the file header
    i = 0
    while pos + 8 <= len(data):
        (_recno, content_words) = struct.unpack_from(">2i", data, pos)
        pos += 8
        (shape_type,) = struct.unpack_from("<i", data, pos)
        wkt = _shape_wkt(shape_type, data[pos + 4:pos + content_words * 2])
        pos += content_words * 2
        attrs = dbf[i] if dbf is not None and i < len(dbf) else []
        yield (wkt, *attrs)
        i += 1


class ShapefileConverter(SimpleFeatureConverter):
    """process() input: path to a .shp file (its .dbf sits beside it)."""

    def _records(self, source) -> Iterable[list]:
        for tup in read_shapefile(str(source)):
            yield [None, *tup]


# -- JDBC (sqlite) --------------------------------------------------------


class JdbcConverter(SimpleFeatureConverter):
    """process() input: sqlite database path; config['query'] selects
    the rows ($1.. = columns in SELECT order)."""

    def _records(self, source) -> Iterable[list]:
        import sqlite3
        conn = sqlite3.connect(str(source))
        try:
            cur = conn.execute(self.config["query"])
            for row in cur:
                yield [row, *row]
        finally:
            conn.close()


# -- OSM XML --------------------------------------------------------------


class OsmConverter(SimpleFeatureConverter):
    """process() input: OSM XML text, bytes, or a path to an .osm file."""

    def _records(self, source) -> Iterable[list]:
        import os
        import xml.etree.ElementTree as ET
        if isinstance(source, bytes):
            text = source.decode()
        elif isinstance(source, str) and not source.lstrip().startswith("<") \
                and os.path.exists(source):
            with open(source) as f:
                text = f.read()
        else:
            text = str(source)
        try:
            root = ET.fromstring(text)
        except ET.ParseError:
            yield _BAD_RECORD
            return
        nodes: dict[str, tuple[float, float]] = {}
        for el in root:
            if el.tag == "node":
                try:
                    nid = el.get("id")
                    lon, lat = float(el.get("lon")), float(el.get("lat"))
                except (TypeError, ValueError):
                    yield _BAD_RECORD
                    continue
                nodes[nid] = (lon, lat)
                tags = {t.get("k"): t.get("v") for t in el.findall("tag")}
                yield [tags, nid, "node", f"POINT ({lon!r} {lat!r})"]
        for el in root:
            if el.tag != "way":
                continue
            refs = [nd.get("ref") for nd in el.findall("nd")]
            pts = [nodes[r] for r in refs if r in nodes]
            if len(pts) < 2:
                yield _BAD_RECORD
                continue
            tags = {t.get("k"): t.get("v") for t in el.findall("tag")}
            if pts[0] == pts[-1] and len(pts) >= 4:
                wkt = "POLYGON (" + _ring_wkt(pts) + ")"
            else:
                wkt = "LINESTRING " + _ring_wkt(pts)
            yield [tags, el.get("id"), "way", wkt]
