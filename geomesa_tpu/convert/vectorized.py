"""Columnar converter execution: transform whole column arrays at once.

The scalar path in ``converter.process`` evaluates every compiled
closure once per record into Python lists — fine for correctness, far
too slow for the ingest firehose. This module is the second backend
over the same expression AST (``dsl.parse_expression``): each node
evaluates to a whole numpy column plus a per-row error mask, so a bad
record is masked out and counted instead of aborting the batch, and the
hot conversions (numeric casts, date parses, point assembly, arithmetic)
run as single numpy operations over the chunk.

Execution contract (mirrors one scalar ``process`` iteration, chunked):

- every node returns ``(values, err)`` — values is an ndarray of length
  n (object or typed) or an ``_XY`` packed point pair; ``err`` marks
  rows whose evaluation raised in the scalar semantics
- typed fast paths are *optimistic*: a bulk ``astype`` is attempted
  first, and only a chunk containing an unparseable cell falls back to
  the per-row loop that isolates exactly the failing rows
- ``try``/``withDefault`` merge per-row between expr and fallback
  columns; a row errs only where the scalar evaluation would raise
- validator rejection is evaluated columnar for the registry validators
  (has-geo / has-dtg / bounds-geo / index / none)

The scalar path stays the oracle: ``geomesa.ingest.vectorized=false``
kills this path entirely, and ``geomesa.ingest.verify=true`` runs both
and asserts id-for-id equivalence per chunk.
"""

from __future__ import annotations

import io
import re
from itertools import zip_longest
from typing import Any

import numpy as np

from ..features.batch import FeatureBatch
from ..geometry import Point
from ..utils.properties import SystemProperty
from .dsl import _CASTS, _FUNCTIONS, EvaluationContext, parse_expression

try:  # the repo's WAL already rides on Arrow; ingest reuses it for CSV
    import pyarrow as pa
    from pyarrow import compute as pc
    from pyarrow import csv as pacsv
except Exception:  # pragma: no cover — arrow-less fallback stays live
    pa = None
    pc = None
    pacsv = None

__all__ = ["process_columnar", "process_columns", "INGEST_BATCH_ROWS",
           "INGEST_VECTORIZED", "INGEST_VERIFY", "INGEST_ARROW_CSV",
           "INGEST_ARROW_JSON"]

INGEST_BATCH_ROWS = SystemProperty("geomesa.ingest.batch.rows", "65536")
INGEST_VECTORIZED = SystemProperty("geomesa.ingest.vectorized", "true")
INGEST_VERIFY = SystemProperty("geomesa.ingest.verify", "false")
INGEST_ARROW_CSV = SystemProperty("geomesa.ingest.arrow.csv", "true")
INGEST_ARROW_JSON = SystemProperty("geomesa.ingest.arrow.json", "true")

# pads ragged delimited rows in the chunk transpose; a column reference
# that lands on the pad errs that row (the scalar path's IndexError)
_MISSING = object()


class _ArrowCol:
    """A string column still living in Arrow. The hot conversions
    (float/timestamp casts, element-wise join) run on ``arr`` in C++
    with the GIL released; ``objs()`` materializes Python strings once,
    lazily, for everything else."""

    __slots__ = ("arr", "_obj")

    def __init__(self, arr):
        self.arr = arr
        self._obj = None

    def __len__(self) -> int:
        return len(self.arr)

    def objs(self) -> np.ndarray:
        if self._obj is None:
            self._obj = np.asarray(
                self.arr.to_numpy(zero_copy_only=False), dtype=object)
        return self._obj


def parse_csv_arrow(joined: str, delimiter: str):
    """One quote-free CSV chunk -> Arrow string columns, or None when
    Arrow is unavailable or the chunk isn't rectangular (ragged rows
    raise inside read_csv; the caller's split path isolates them
    row-for-row). Column types are pinned to string so transforms — not
    the reader — decide every conversion, exactly like the scalar
    path."""
    if pacsv is None or not INGEST_ARROW_CSV.as_bool():
        return None
    nl = joined.find("\n")
    first = joined[:nl] if nl >= 0 else joined
    w = first.count(delimiter) + 1
    names = [f"c{i}" for i in range(1, w + 1)]
    try:
        table = pacsv.read_csv(
            io.BytesIO(joined.encode("utf-8")),
            read_options=pacsv.ReadOptions(column_names=names),
            parse_options=pacsv.ParseOptions(delimiter=delimiter,
                                             quote_char=False),
            convert_options=pacsv.ConvertOptions(
                column_types={nm: pa.string() for nm in names}))
    except Exception:
        return None
    n = table.num_rows
    if n == 0:
        return None
    cols: list[Any] = [np.full(n, "", dtype=object)]
    for i in range(w):
        cols.append(_ArrowCol(table.column(i).combine_chunks()))
    return cols, n, False, 0


def parse_json_arrow(joined: str, paths: list[str]):
    """One block of JSON-lines -> converter columns, or None when Arrow
    (or its json module) is unavailable, the block fails to parse
    (malformed line, mixed field types), or a declared path needs
    semantics ``read_json`` can't give (list indexing). Declared paths
    resolve through Arrow struct columns in C; string results stay in
    Arrow (``_ArrowCol``) for the C cast paths, everything else
    materializes to python objects so null/err semantics match the
    scalar ``_resolve`` exactly (missing field -> None column)."""
    if pa is None or not INGEST_ARROW_JSON.as_bool():
        return None
    norm = [p.replace("$.", "").split(".") for p in paths]
    if any(part.isdigit() for parts in norm for part in parts):
        return None  # list-index path: scalar traversal only
    try:
        from pyarrow import json as pajson
    except Exception:  # pragma: no cover — arrow build without json
        return None
    try:
        table = pajson.read_json(io.BytesIO(joined.encode("utf-8")))
    except Exception:
        return None
    n = table.num_rows
    if n == 0:
        return None
    # $0 (the parsed record) is never materialized here; converters
    # whose transforms read it stay on the record path
    cols: list[Any] = [np.full(n, None, dtype=object)]
    for parts in norm:
        try:
            arr = table.column(parts[0]).combine_chunks()
            for part in parts[1:]:
                arr = pc.struct_field(arr, part)
        except (KeyError, pa.ArrowInvalid, pa.ArrowTypeError,
                TypeError):
            # absent field / non-struct traversal: the scalar resolve
            # yields None for every row
            cols.append(np.full(n, None, dtype=object))
            continue
        if pa.types.is_string(arr.type) or pa.types.is_large_string(
                arr.type):
            cols.append(_ArrowCol(arr))
        else:
            vals = np.empty(n, dtype=object)
            vals[:] = arr.to_pylist()
            cols.append(vals)
    return cols, n, False, 0


class _XY:
    """Packed point column: x/y float arrays instead of Point objects.

    This is the vectorized ``point(x, y)`` result — it flows straight
    into ``FeatureBatch.from_dict``'s (x_array, y_array) fast path
    without ever materializing per-row Point objects.
    """

    __slots__ = ("x", "y")

    def __init__(self, x: np.ndarray, y: np.ndarray):
        self.x = x
        self.y = y

    def materialize(self) -> np.ndarray:
        out = np.empty(len(self.x), dtype=object)
        for i in range(len(self.x)):
            out[i] = Point(float(self.x[i]), float(self.y[i]))
        return out


def _as_object(vals) -> np.ndarray:
    if isinstance(vals, _XY):
        return vals.materialize()
    if isinstance(vals, _ArrowCol):
        return vals.objs()
    if vals.dtype == object:
        return vals
    return vals.astype(object)


def _to_float(vals, err: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Column float(v): bulk cast, per-row fallback on a dirty chunk."""
    if isinstance(vals, _XY):
        return np.zeros(len(vals.x)), np.ones(len(vals.x), dtype=bool)
    if isinstance(vals, _ArrowCol):
        try:  # C++ parse, no Python string materialization
            f = pc.cast(vals.arr, pa.float64()).to_numpy(
                zero_copy_only=False)
            return np.asarray(f, dtype=np.float64), err
        except Exception:
            vals = vals.objs()
    if vals.dtype == np.float64:
        return vals, err
    if vals.dtype.kind in "if" or vals.dtype == bool:
        return vals.astype(np.float64), err
    try:
        return np.asarray(vals, dtype=np.float64), err
    except (TypeError, ValueError):
        pass
    n = len(vals)
    out = np.zeros(n)
    err = err.copy()
    for i in range(n):
        if err[i]:
            continue
        try:
            out[i] = float(vals[i])
        except (TypeError, ValueError):
            err[i] = True
    return out, err


def _cast_int(vals, err):
    f, err = _to_float(vals, err)
    # scalar int(float(v)) raises on nan/inf; astype would wrap silently
    bad = ~np.isfinite(f)
    if bad.any():
        err = err | bad
        f = np.where(bad, 0.0, f)
    return f.astype(np.int64), err


def _cast_string(vals, err):
    if isinstance(vals, _XY):
        vals = vals.materialize()
    if isinstance(vals, _ArrowCol):
        return vals.objs(), err
    if vals.dtype.kind == "U":  # already strings (np.str_ IS str)
        return vals, err
    if vals.dtype != object:
        vals = vals.astype(object)
    return np.array([str(v) for v in vals], dtype=object), err


def _cast_bool(vals, err):
    fn = _CASTS["boolean"]
    vals = _as_object(vals)
    return np.array([fn(v) for v in vals], dtype=bool), err


def _parse_dates_bulk(vals, err):
    """isoDate / datetime: one bulk datetime64 parse, per-row fallback."""
    if isinstance(vals, _ArrowCol):
        try:  # trim + ISO parse stay in C; Z must go (zone-naive cast)
            trimmed = pc.utf8_rtrim(pc.utf8_trim_whitespace(vals.arr), "Z")
            ms = pc.cast(pc.cast(trimmed, pa.timestamp("ms")), pa.int64())
            return (np.asarray(ms.to_numpy(zero_copy_only=False),
                               dtype=np.int64), err)
        except Exception:
            pass
    vals = _as_object(vals)
    n = len(vals)
    try:
        cleaned = [str(v).strip().rstrip("Z") for v in vals]
        return (np.array(cleaned, dtype="datetime64[ms]").astype(np.int64),
                err)
    except (TypeError, ValueError):
        pass
    out = np.zeros(n, dtype=np.int64)
    err = err.copy()
    for i in range(n):
        if err[i]:
            continue
        try:
            out[i] = int(np.datetime64(str(vals[i]).strip().rstrip("Z"),
                                       "ms").astype(np.int64))
        except (TypeError, ValueError):
            err[i] = True
    return out, err


def _merge(err_a, vals_a, vals_b):
    """Rows of b where err_a, else a (the try/withDefault select)."""
    if isinstance(vals_a, _XY) and isinstance(vals_b, _XY):
        return _XY(np.where(err_a, vals_b.x, vals_a.x),
                   np.where(err_a, vals_b.y, vals_a.y))
    a, b = _as_object(vals_a), _as_object(vals_b)
    return np.where(err_a, b, a)


class _Evaluator:
    """One chunk's evaluation state: input columns + computed fields."""

    def __init__(self, cols: list[np.ndarray], n: int, ragged: bool):
        self.cols = cols
        self.n = n
        self.ragged = ragged
        self.fields: dict[str, tuple[Any, np.ndarray]] = {}
        self._zero_err = np.zeros(n, dtype=bool)

    def eval(self, node: tuple) -> tuple[Any, np.ndarray]:
        kind = node[0]
        if kind == "col":
            idx = node[1]
            if idx >= len(self.cols):
                return (np.full(self.n, None, dtype=object),
                        np.ones(self.n, dtype=bool))
            vals = self.cols[idx]
            if self.ragged:
                err = np.fromiter((v is _MISSING for v in vals), dtype=bool,
                                  count=self.n)
                if err.any():
                    return vals, err
            return vals, self._zero_err
        if kind == "field":
            got = self.fields.get(node[1])
            if got is None:
                # scalar raises per record: every row fails
                return (np.full(self.n, None, dtype=object),
                        np.ones(self.n, dtype=bool))
            return got
        if kind == "lit":
            v = node[1]
            if isinstance(v, float):
                return np.full(self.n, v), self._zero_err
            if isinstance(v, int) and not isinstance(v, bool):
                return np.full(self.n, v, dtype=np.int64), self._zero_err
            return np.full(self.n, v, dtype=object), self._zero_err
        if kind == "relit":
            return np.full(self.n, node[1], dtype=object), self._zero_err
        if kind == "recast":
            return self._apply_rowwise(lambda v: re.compile(str(v)),
                                       [node[1]])
        if kind == "cast":
            name = node[1]
            vals, err = self.eval(node[2])
            if name in ("int", "integer", "long"):
                return _cast_int(vals, err)
            if name in ("float", "double"):
                return _to_float(vals, err)
            if name == "string":
                return _cast_string(vals, err)
            if name == "boolean":
                return _cast_bool(vals, err)
            return self._apply_rowwise(_CASTS[name], [node[2]])
        if kind == "try":
            vals, err = self.eval(node[1])
            if not err.any():
                return vals, err
            fvals, ferr = self.eval(node[2])
            return _merge(err, vals, fvals), err & ferr
        if kind == "withdefault":
            vals, err = self.eval(node[1])
            need = self._null_or_empty(vals) & ~err
            if not need.any():
                return vals, err
            dvals, derr = self.eval(node[2])
            return _merge(need, vals, dvals), err | (need & derr)
        return self._eval_fn(node[1], node[2])

    def _null_or_empty(self, vals) -> np.ndarray:
        if isinstance(vals, _XY):
            return self._zero_err
        if isinstance(vals, _ArrowCol):
            return np.asarray(
                pc.equal(vals.arr, "").to_numpy(zero_copy_only=False),
                dtype=bool)
        if vals.dtype.kind == "U":
            return np.asarray(vals == "")
        if vals.dtype != object:
            return self._zero_err
        return np.fromiter((v is None or v == "" for v in vals),
                           dtype=bool, count=self.n)

    def _eval_fn(self, name: str, arg_nodes: list) -> tuple[Any, np.ndarray]:
        if name == "point" and len(arg_nodes) == 2:
            xv, xe = self.eval(arg_nodes[0])
            yv, ye = self.eval(arg_nodes[1])
            x, xe = _to_float(xv, xe)
            y, ye = _to_float(yv, ye)
            return _XY(x, y), xe | ye
        if name in ("concat", "concatenate") and arg_nodes:
            # str() never raises: join without the per-row try machinery
            cols, err = [], self._zero_err
            for a in arg_nodes:
                v, e = self.eval(a)
                cols.append(v)
                err = err | e
            if any(isinstance(v, _ArrowCol) for v in cols):
                try:
                    # stay in Arrow: lits broadcast as scalars, "" separator
                    parts = []
                    for a, v in zip(arg_nodes, cols):
                        if isinstance(v, _ArrowCol):
                            parts.append(v.arr)
                        elif a[0] in ("lit", "relit"):
                            parts.append(str(a[1]))
                        else:
                            parts.append(pa.array(
                                [str(x) for x in _as_object(v)],
                                type=pa.string()))
                    return (_ArrowCol(
                        pc.binary_join_element_wise(*parts, "")), err)
                except Exception:
                    pass
            try:
                # fixed-width string concat is a single C op per arg;
                # np.asarray(..., "U") applies str() like the scalar join
                us = [v if isinstance(v, _ArrowCol) or (
                          not isinstance(v, _XY) and v.dtype.kind == "U")
                      else np.asarray(_as_object(v), dtype="U")
                      for v in cols]
                us = [np.asarray(v.objs(), dtype="U")
                      if isinstance(v, _ArrowCol) else v for v in us]
                out = us[0]
                for u in us[1:]:
                    out = np.char.add(out, u)
                return out, err
            except (TypeError, ValueError):
                objs = [_as_object(v) for v in cols]
                out = np.empty(self.n, dtype=object)
                out[:] = ["".join(map(str, t)) for t in zip(*objs)]
                return out, err
        if name in ("isoDate", "datetime") and len(arg_nodes) == 1:
            vals, err = self.eval(arg_nodes[0])
            return _parse_dates_bulk(vals, err)
        if name in ("add", "subtract", "multiply", "divide", "mean",
                    "min", "max") and arg_nodes:
            cols, err = [], self._zero_err
            for a in arg_nodes:
                v, e = self.eval(a)
                v, e = _to_float(v, e)
                cols.append(v)
                err = err | e
            stacked = np.stack(cols)
            with np.errstate(divide="ignore", invalid="ignore"):
                out = {"add": lambda s: s.sum(axis=0),
                       "subtract": lambda s: s[0] - s[1:].sum(axis=0),
                       "multiply": lambda s: s.prod(axis=0),
                       "divide": lambda s: _divide_reduce(s),
                       "mean": lambda s: s.mean(axis=0),
                       "min": lambda s: s.min(axis=0),
                       "max": lambda s: s.max(axis=0)}[name](stacked)
            return out, err
        return self._apply_rowwise(_FUNCTIONS[name], arg_nodes)

    def _apply_rowwise(self, fn, arg_nodes: list) -> tuple[Any, np.ndarray]:
        """Generic fallback: scalar registry function per surviving row."""
        cols, err = [], self._zero_err
        for a in arg_nodes:
            v, e = self.eval(a)
            cols.append(_as_object(v))
            err = err | e
        out = np.full(self.n, None, dtype=object)
        err = err.copy()
        if not arg_nodes:
            for i in range(self.n):
                try:
                    out[i] = fn()
                except Exception:
                    err[i] = True
            return out, err
        for i in range(self.n):
            if err[i]:
                continue
            try:
                out[i] = fn(*(c[i] for c in cols))
            except Exception:
                err[i] = True
        return out, err


def _divide_reduce(s: np.ndarray) -> np.ndarray:
    out = s[0].copy()
    for i in range(1, len(s)):
        out = out / s[i]
    return out


def _transpose(records: list[list]) -> tuple[list[np.ndarray], bool]:
    """Row lists -> object column arrays, padded where rows are ragged."""
    widths = {len(r) for r in records}
    ragged = len(widths) > 1
    cols = [np.array(c, dtype=object)
            for c in zip_longest(*records, fillvalue=_MISSING)]
    return cols, ragged


def _vector_validators(names, sft, values: dict, alive: np.ndarray,
                       n: int) -> np.ndarray:
    """Columnar registry validators; True marks a rejected row."""
    rejected = np.zeros(n, dtype=bool)
    geom, dtg = sft.geom_field, sft.dtg_field

    def _null_mask(col) -> np.ndarray:
        if isinstance(col, _XY):
            return np.zeros(n, dtype=bool)  # a Point object is never None
        col = _as_object(col)
        return np.fromiter((v is None for v in col), dtype=bool, count=n)

    def _oob_mask(col) -> np.ndarray:
        if isinstance(col, _XY):
            with np.errstate(invalid="ignore"):
                ok = ((col.x >= -180.0) & (col.x <= 180.0)
                      & (col.y >= -90.0) & (col.y <= 90.0))
            return ~ok
        col = _as_object(col)
        out = np.zeros(n, dtype=bool)
        for i in range(n):
            g = col[i]
            if g is None or not alive[i] or rejected[i]:
                continue
            e = g.envelope
            if not (-180.0 <= e.xmin <= e.xmax <= 180.0
                    and -90.0 <= e.ymin <= e.ymax <= 90.0):
                out[i] = True
        return out

    for name in names:
        checks = ([name] if name != "index"
                  else ["has-geo", "has-dtg", "bounds-geo"])
        for c in checks:
            if c == "has-geo":
                rejected |= (np.ones(n, dtype=bool) if geom is None
                             else _null_mask(values[geom]))
            elif c == "has-dtg":
                rejected |= (np.ones(n, dtype=bool) if dtg is None
                             else _null_mask(values[dtg]))
            elif c == "bounds-geo" and geom is not None:
                rejected |= _oob_mask(values[geom])
    return rejected & alive


def process_columnar(converter, records: list[list],
                     ctx: EvaluationContext) -> FeatureBatch:
    """One chunk of ``_records`` output -> FeatureBatch, columnar.

    Counts exactly what the scalar loop would: every record bumps
    ``line``, masked/invalid rows bump ``failure``, emitted rows bump
    ``success``.
    """
    from .converter import _BAD_RECORD

    good = [r for r in records if r is not _BAD_RECORD]
    n_bad = len(records) - len(good)
    if not good:
        ctx.line += len(records)
        ctx.failure += n_bad
        return FeatureBatch.from_dict(
            converter.sft, [],
            {a.name: [] for a in converter.sft.attributes})
    cols, ragged = _transpose(good)
    return process_columns(converter, cols, len(good), ragged, n_bad, ctx)


def process_columns(converter, cols: list[np.ndarray], n: int,
                    ragged: bool, n_bad: int,
                    ctx: EvaluationContext) -> FeatureBatch:
    """Column arrays -> FeatureBatch (the core the chunk sources feed:
    ``_transpose`` of a record chunk, or a format's
    ``iter_column_chunks`` columnar parse)."""
    sft = converter.sft
    ctx.line += n + n_bad
    ev = _Evaluator(cols, n, ragged)
    dead = np.zeros(n, dtype=bool)
    for name, node in converter.ordered_asts:
        vals, err = ev.eval(node)
        ev.fields[name] = (vals, err)
        dead |= err
    id_vals, id_err = ev.eval(converter.id_ast)
    dead |= id_err
    # a field declared but never computed (not possible today) or an SFT
    # attr missing from fields errs every row, like the scalar KeyError
    values: dict[str, Any] = {}
    for a in sft.attributes:
        got = ev.fields.get(a.name)
        if got is None:
            dead[:] = True
            values[a.name] = np.full(n, None, dtype=object)
        else:
            values[a.name] = got[0]

    alive = ~dead
    if converter.validator_names:
        rejected = _vector_validators(converter.validator_names, sft,
                                      values, alive, n)
        alive = alive & ~rejected

    keep = np.flatnonzero(alive)
    if isinstance(id_vals, _ArrowCol):
        ids = id_vals.objs()[keep]  # already python str objects
    elif not isinstance(id_vals, _XY) and id_vals.dtype.kind == "U":
        ids = id_vals[keep]  # np.str_ IS str: no per-row re-wrap
    else:
        id_obj = _as_object(id_vals)
        ids = [str(id_obj[i]) for i in keep]
    out: dict[str, Any] = {}
    for a in sft.attributes:
        v = values[a.name]
        if isinstance(v, _XY) and a.type.name == "Point":
            out[a.name] = (v.x[keep], v.y[keep])
        elif isinstance(v, _XY):
            out[a.name] = v.materialize()[keep]
        elif isinstance(v, _ArrowCol):
            if a.type.name in ("String", "UUID"):
                # hand the Arrow array straight to StringColumn: its
                # dictionary-encode beats materializing 1 python str/row
                out[a.name] = (v.arr if len(keep) == n
                               else v.arr.take(keep))
            else:
                out[a.name] = v.objs()[keep]
        else:
            out[a.name] = v[keep]
    ctx.failure += n_bad + int(n - len(keep))
    ctx.success += len(keep)
    return FeatureBatch.from_dict(sft, ids, out)
