"""Minimal Avro Object Container File writer (pairs with avro_reader;
no external avro dependency — the reference exports Avro via the Java
library, geomesa-tools export/formats/AvroExporter).

Writes OCF with the null codec: records of null/boolean/long/double/
string/bytes; a FeatureBatch maps to a record schema of
[fid: string] + attributes (dates as long epoch-millis with the
timestamp-millis logical type, geometries as WKT strings — matching
the reference's avro export shape of simple-feature avro files).
"""

from __future__ import annotations

import io
import json
import os
import struct
from typing import Any

from .avro_reader import _MAGIC, _w_bytes, _w_long

__all__ = ["AvroFileWriter", "write_avro_batch"]


class _Encoder:
    """Thin buffer over the shared OCF wire primitives in avro_reader
    (one zigzag-varint implementation for both writers)."""

    def __init__(self):
        self.buf = io.BytesIO()

    def write_long(self, v: int):
        _w_long(self.buf, v)

    def write_double(self, v: float):
        self.buf.write(struct.pack("<d", v))

    def write_bytes(self, v: bytes):
        _w_bytes(self.buf, v)

    def write_string(self, v: str):
        _w_bytes(self.buf, v.encode("utf-8"))

    def write_boolean(self, v: bool):
        self.buf.write(b"\x01" if v else b"\x00")

    def getvalue(self) -> bytes:
        return self.buf.getvalue()


def _avro_type(spec_type: str) -> Any:
    t = spec_type.lower()
    if t in ("integer", "int", "long"):
        return ["null", "long"]
    if t in ("float", "double"):
        return ["null", "double"]
    if t == "boolean":
        return ["null", "boolean"]
    if t == "date":
        return ["null", {"type": "long", "logicalType": "timestamp-millis"}]
    if t == "bytes":
        return ["null", "bytes"]
    return ["null", "string"]  # strings + WKT geometries


class AvroFileWriter:
    """Stream FeatureBatches into one OCF."""

    def __init__(self, sink, sft):
        self.sink = sink
        self.sft = sft
        self.sync = os.urandom(16)
        fields = [{"name": "__fid__", "type": "string"}]
        self._types = []
        for a in sft.attributes:
            fields.append({"name": a.name, "type": _avro_type(a.type.name)})
            self._types.append(a.type.name.lower())
        self.schema = {"type": "record", "name": sft.type_name,
                       "fields": fields}
        self._write_header()

    def _write_header(self):
        enc = _Encoder()
        meta = {"avro.schema": json.dumps(self.schema).encode(),
                "avro.codec": b"null"}
        enc.write_long(len(meta))
        for k, v in meta.items():
            enc.write_string(k)
            enc.write_bytes(v)
        enc.write_long(0)  # end of map
        self.sink.write(_MAGIC + enc.getvalue() + self.sync)

    def _encode_value(self, enc: _Encoder, t: str, v):
        if v is None:
            enc.write_long(0)  # union branch: null
            return
        enc.write_long(1)
        if t in ("integer", "int", "long"):
            enc.write_long(int(v))
        elif t in ("float", "double"):
            enc.write_double(float(v))
        elif t == "boolean":
            enc.write_boolean(bool(v))
        elif t == "date":
            enc.write_long(int(v))
        elif t == "bytes":
            enc.write_bytes(bytes(v))
        else:
            enc.write_string(str(v))

    def write(self, batch):
        if batch.n == 0:
            return
        enc = _Encoder()
        geom = batch.sft.geom_field
        for i in range(batch.n):
            f = batch.feature(i)
            enc.write_string(str(f["id"]))
            for a, t in zip(batch.sft.attributes, self._types):
                v = f[a.name]
                if a.name == geom or t in ("point", "polygon", "linestring",
                                           "geometry", "multipoint",
                                           "multipolygon", "multilinestring"):
                    if v is not None:
                        from ..geometry import to_wkt
                        v = to_wkt(v)
                elif t == "date" and v is not None:
                    v = int(v)
                self._encode_value(enc, t, v)
        block = enc.getvalue()
        head = _Encoder()
        head.write_long(batch.n)
        head.write_long(len(block))
        self.sink.write(head.getvalue() + block + self.sync)


def write_avro_batch(sft, batch) -> bytes:
    sink = io.BytesIO()
    w = AvroFileWriter(sink, sft)
    w.write(batch)
    return sink.getvalue()
