"""Feature validators (geomesa-convert SimpleFeatureValidator analog):
post-transform checks that drop invalid features as failures instead of
ingesting them. Configured via converter options:

    {"options": {"validators": ["has-geo", "has-dtg"]}}
"""

from __future__ import annotations

from typing import Callable

from ..features.sft import SimpleFeatureType

__all__ = ["build_validators", "validate"]


def _has_geo(sft: SimpleFeatureType) -> Callable[[dict], str | None]:
    geom = sft.geom_field

    def check(values: dict) -> str | None:
        if geom is None or values.get(geom) is None:
            return "null geometry"
        return None
    return check


def _has_dtg(sft: SimpleFeatureType) -> Callable[[dict], str | None]:
    dtg = sft.dtg_field

    def check(values: dict) -> str | None:
        if dtg is None or values.get(dtg) is None:
            return "null date"
        return None
    return check


def _bounds_geo(sft: SimpleFeatureType) -> Callable[[dict], str | None]:
    """Geometry inside the whole world (the 'index' validator's bounds
    check — z-indexing needs lon/lat in range)."""
    geom = sft.geom_field

    def check(values: dict) -> str | None:
        g = values.get(geom) if geom else None
        if g is None:
            return None  # has-geo handles nullness
        e = g.envelope
        if not (-180.0 <= e.xmin <= e.xmax <= 180.0
                and -90.0 <= e.ymin <= e.ymax <= 90.0):
            return "geometry out of bounds"
        return None
    return check


_REGISTRY = {
    "has-geo": _has_geo,
    "has-dtg": _has_dtg,
    "index": lambda sft: _composite([_has_geo(sft), _has_dtg(sft),
                                     _bounds_geo(sft)]),
    "bounds-geo": _bounds_geo,
    "none": lambda sft: (lambda values: None),
}


def _composite(checks):
    def check(values):
        for c in checks:
            err = c(values)
            if err:
                return err
        return None
    return check


def build_validators(names, sft: SimpleFeatureType):
    checks = []
    for n in names:
        if n not in _REGISTRY:
            raise ValueError(f"unknown validator {n!r} "
                             f"(have {sorted(_REGISTRY)})")
        checks.append(_REGISTRY[n](sft))
    return checks


def validate(checks, values: dict) -> str | None:
    """First error message, or None if the feature passes."""
    for c in checks:
        err = c(values)
        if err:
            return err
    return None
