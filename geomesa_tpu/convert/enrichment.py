"""Enrichment caches: lookup tables usable from transform expressions
(geomesa-convert EnrichmentCache analog — the reference backs these with
redis/simple maps; here a process-local registry keyed by name).

    register_cache("vessels", {"123": {"flag": "US", "class": "cargo"}})
    # in a transform:  cacheLookup('vessels', $1, 'flag')
"""

from __future__ import annotations

import csv
import json
from typing import Any

__all__ = ["register_cache", "get_cache", "clear_caches", "load_csv_cache",
           "EnrichmentCache"]

_CACHES: dict[str, "EnrichmentCache"] = {}


class EnrichmentCache:
    def __init__(self, data: dict[str, dict[str, Any]]):
        self._data = dict(data)

    def lookup(self, key, field: str | None = None):
        row = self._data.get(str(key))
        if row is None:
            return None
        if field is None:
            return row
        return row.get(field) if isinstance(row, dict) else row

    def __len__(self) -> int:
        return len(self._data)


def register_cache(name: str, data: dict) -> EnrichmentCache:
    cache = data if isinstance(data, EnrichmentCache) \
        else EnrichmentCache(data)
    _CACHES[name] = cache
    return cache


def get_cache(name: str) -> EnrichmentCache:
    if name not in _CACHES:
        raise KeyError(f"no enrichment cache {name!r} registered")
    return _CACHES[name]


def clear_caches():
    _CACHES.clear()


def load_csv_cache(name: str, path: str, key_column: str) -> EnrichmentCache:
    """Register a cache from a CSV with a header row."""
    with open(path, newline="") as fh:
        rows = list(csv.DictReader(fh))
    return register_cache(name, {str(r[key_column]): r for r in rows})


def cache_lookup(name, key, field=None):
    """The cacheLookup() DSL function."""
    return get_cache(str(name)).lookup(key, None if field is None
                                       else str(field))
