"""Additional converter formats: XML, Avro, fixed-width, composite
(geomesa-convert-xml / -avro / -fixedwidth / composite-converter
analogs, SURVEY.md 2.4).
"""

from __future__ import annotations

import io
import re
import xml.etree.ElementTree as ET
from typing import Any

from ..features.sft import SimpleFeatureType
from .converter import _BAD_RECORD, SimpleFeatureConverter
from .dsl import compile_expression

__all__ = ["XmlConverter", "FixedWidthConverter", "CompositeConverter",
           "AvroConverter"]


class XmlConverter(SimpleFeatureConverter):
    """XML -> features. Config:
        {"type": "xml", "feature-path": ".//entry",
         "id-field": "$1",
         "fields": [{"name": "a", "path": "name"},            # child text
                    {"name": "b", "path": "@attr"},           # attribute
                    {"name": "geom", "path": "pos",
                     "transform": "point(...)"}]}
    Paths are ElementTree paths relative to each feature element;
    '@x' reads an attribute; columns bind $1..$N in declared order
    (the reference's XPath fields, geomesa-convert-xml)."""

    def __init__(self, sft: SimpleFeatureType, config: dict):
        self.feature_path = config.get("feature-path", ".")
        self.paths = [f["path"] for f in config.get("fields", [])
                      if "path" in f]
        fields = []
        col = 0
        for f in config.get("fields", []):
            f = dict(f)
            if "path" in f:
                col += 1
                if "name" in f:
                    f.setdefault("transform", f"${col}")
            fields.append(f)
        config = dict(config)
        config["fields"] = fields
        super().__init__(sft, config)

    @staticmethod
    def _resolve(elem: ET.Element, path: str):
        if path.startswith("@"):
            return elem.get(path[1:])
        # trailing @attr on a child path
        if "/@" in path:
            p, attr = path.rsplit("/@", 1)
            child = elem.find(p)
            return None if child is None else child.get(attr)
        child = elem.find(path)
        if child is None:
            return None
        return (child.text or "").strip() or None

    def _records(self, source):
        if not isinstance(source, str):
            source = source.read()
        try:
            root = ET.fromstring(source)
        except ET.ParseError:
            yield _BAD_RECORD
            return
        elems = ([root] if self.feature_path in (".", "")
                 else root.findall(self.feature_path))
        for el in elems:
            try:
                yield [el] + [self._resolve(el, p) for p in self.paths]
            except Exception:
                yield _BAD_RECORD


class FixedWidthConverter(SimpleFeatureConverter):
    """Fixed-width lines (geomesa-convert-fixedwidth): columns declared
    as {"start": S, "width": W} slices; $1..$N bind in declared order."""

    def __init__(self, sft: SimpleFeatureType, config: dict):
        self.slices = [(f["start"], f["width"])
                       for f in config.get("fields", [])
                       if "start" in f and "width" in f]
        fields = []
        col = 0
        for f in config.get("fields", []):
            f = dict(f)
            if "start" in f and "width" in f:
                col += 1
                if "name" in f:
                    f.setdefault("transform", f"${col}")
            fields.append(f)
        config = dict(config)
        config["fields"] = fields
        super().__init__(sft, config)

    def _records(self, source):
        if isinstance(source, str):
            source = io.StringIO(source)
        for line in source:
            line = line.rstrip("\n")
            if not line.strip():
                continue
            yield [line] + [line[s:s + w].strip() or None
                            for s, w in self.slices]


class CompositeConverter:
    """Dispatch each record to the first matching delegate
    (composite-converter of geomesa-convert-common): config is
    {"type": "composite", "converters": [{"predicate": "regex", ...child
    config...}, ...]}; the predicate is a regex tested against the raw
    record line."""

    def __init__(self, sft: SimpleFeatureType, config: dict):
        from .converter import converter_for
        self.sft = sft
        self.delegates = []
        for sub in config.get("converters", []):
            sub = dict(sub)
            pred = re.compile(sub.pop("predicate", ".*"))
            self.delegates.append((pred, converter_for(sft, sub)))

    def process(self, source, ctx=None):
        from .dsl import EvaluationContext
        from ..features.batch import FeatureBatch
        ctx = ctx or EvaluationContext()
        if not isinstance(source, str):
            source = source.read()
        batches = []
        for line in source.splitlines():
            if not line.strip():
                continue
            for pred, conv in self.delegates:
                if pred.search(line):
                    b, ctx = conv.process(line, ctx)
                    if b.n:
                        batches.append(b)
                    break
            else:
                ctx.line += 1
                ctx.failure += 1
        if not batches:
            empty = FeatureBatch.from_dict(
                self.sft, [], {a.name: ((), ()) if a.type.name == "Point"
                               else [] for a in self.sft.attributes})
            return empty, ctx
        out = batches[0]
        for b in batches[1:]:
            out = out.concat(b)
        return out, ctx


class AvroConverter(SimpleFeatureConverter):
    """Avro OCF -> features (geomesa-convert-avro): record fields
    resolve by dotted path like the JSON converter; the embedded reader
    needs no external avro dependency."""

    def __init__(self, sft: SimpleFeatureType, config: dict):
        self.paths = [f["path"] for f in config.get("fields", [])
                      if "path" in f]
        fields = []
        col = 0
        for f in config.get("fields", []):
            f = dict(f)
            if "path" in f:
                col += 1
                if "name" in f:
                    f.setdefault("transform", f"${col}")
            fields.append(f)
        config = dict(config)
        config["fields"] = fields
        super().__init__(sft, config)

    @staticmethod
    def _resolve(obj: Any, path: str):
        cur = obj
        for part in path.replace("$.", "").split("."):
            if isinstance(cur, dict):
                cur = cur.get(part)
            else:
                return None
        return cur

    def _records(self, source):
        from .avro_reader import AvroFileReader
        try:
            if isinstance(source, str):
                with open(source, "rb") as fh:
                    records = list(AvroFileReader(fh.read()))
            elif isinstance(source, (bytes, bytearray)):
                records = list(AvroFileReader(bytes(source)))
            else:
                records = list(AvroFileReader(source.read()))
        except Exception:
            yield _BAD_RECORD
            return
        for obj in records:
            try:
                yield [obj] + [self._resolve(obj, p) for p in self.paths]
            except Exception:
                yield _BAD_RECORD
