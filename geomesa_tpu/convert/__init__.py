"""L8 ingest converters (geomesa-convert analog, SURVEY.md 2.4)."""

from .converter import (DelimitedTextConverter, JsonConverter,
                        SimpleFeatureConverter, converter_for)
from .dsl import EvaluationContext, compile_expression

__all__ = ["DelimitedTextConverter", "JsonConverter",
           "SimpleFeatureConverter", "converter_for",
           "EvaluationContext", "compile_expression"]
