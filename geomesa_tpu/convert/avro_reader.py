"""Minimal Avro Object Container File reader (no external avro dep —
the environment has none; the reference ingests Avro via the Java avro
library, geomesa-convert-avro).

Supports the OCF layout (magic 'Obj\\x01', metadata map with
avro.schema/avro.codec, sync-marker-delimited blocks; null and deflate
codecs) and the standard binary encoding for: null, boolean, int, long
(zigzag varints), float, double, bytes, string, fixed, enum, array,
map, union, record. Logical types surface as their base type.
"""

from __future__ import annotations

import io
import json
import struct
import zlib
from typing import Any, BinaryIO, Iterator

__all__ = ["AvroFileReader", "read_avro"]

_MAGIC = b"Obj\x01"


class _Decoder:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def read(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) < n:
            raise EOFError("truncated avro data")
        self.pos += n
        return b

    @property
    def eof(self) -> bool:
        return self.pos >= len(self.buf)

    def long(self) -> int:
        shift = 0
        acc = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)  # zigzag

    def value(self, schema) -> Any:
        if isinstance(schema, list):  # union
            idx = self.long()
            return self.value(schema[idx])
        if isinstance(schema, str):
            t = schema
        else:
            t = schema["type"]
        if t == "null":
            return None
        if t == "boolean":
            return self.read(1) != b"\x00"
        if t in ("int", "long"):
            return self.long()
        if t == "float":
            return struct.unpack("<f", self.read(4))[0]
        if t == "double":
            return struct.unpack("<d", self.read(8))[0]
        if t == "bytes":
            return self.read(self.long())
        if t == "string":
            return self.read(self.long()).decode("utf-8")
        if t == "fixed":
            return self.read(schema["size"])
        if t == "enum":
            return schema["symbols"][self.long()]
        if t == "array":
            out = []
            while True:
                n = self.long()
                if n == 0:
                    break
                if n < 0:  # block with byte size
                    self.long()
                    n = -n
                out.extend(self.value(schema["items"]) for _ in range(n))
            return out
        if t == "map":
            out = {}
            while True:
                n = self.long()
                if n == 0:
                    break
                if n < 0:
                    self.long()
                    n = -n
                for _ in range(n):
                    k = self.read(self.long()).decode("utf-8")
                    out[k] = self.value(schema["values"])
            return out
        if t == "record":
            return {f["name"]: self.value(f["type"])
                    for f in schema["fields"]}
        if isinstance(schema, dict) and t not in (
                "record", "array", "map", "fixed", "enum"):
            return self.value(t)  # {"type": "string", "logicalType": ...}
        raise ValueError(f"unsupported avro type {t!r}")


class AvroFileReader:
    """Iterate records of an Avro OCF stream."""

    def __init__(self, source: "BinaryIO | bytes"):
        if isinstance(source, (bytes, bytearray)):
            source = io.BytesIO(source)
        self._fh = source
        if self._fh.read(4) != _MAGIC:
            raise ValueError("not an Avro object container file")
        meta_dec = _Decoder(self._read_all_header())
        self.metadata = {}
        while True:
            n = meta_dec.long()
            if n == 0:
                break
            if n < 0:
                meta_dec.long()
                n = -n
            for _ in range(n):
                k = meta_dec.read(meta_dec.long()).decode()
                self.metadata[k] = meta_dec.read(meta_dec.long())
        self._header_tail = meta_dec.buf[meta_dec.pos:]
        self.schema = json.loads(self.metadata["avro.schema"])
        self.codec = self.metadata.get("avro.codec", b"null").decode()
        if self.codec not in ("null", "deflate"):
            raise ValueError(f"unsupported avro codec {self.codec!r}")
        self.sync = self._header_tail[:16]
        self._body = self._header_tail[16:]

    def _read_all_header(self) -> bytes:
        return self._fh.read()

    def __iter__(self) -> Iterator[dict]:
        dec = _Decoder(self._body)
        while not dec.eof:
            count = dec.long()
            size = dec.long()
            block = dec.read(size)
            if self.codec == "deflate":
                block = zlib.decompress(block, -15)
            bdec = _Decoder(block)
            for _ in range(count):
                yield bdec.value(self.schema)
            if dec.read(16) != self.sync:
                raise ValueError("avro sync marker mismatch")


def read_avro(source) -> tuple[dict, list]:
    """(schema, records) of an OCF file/bytes."""
    r = AvroFileReader(source)
    return r.schema, list(r)


# -- writer (test/export support) ---------------------------------------

def write_avro(schema: dict, records: list, codec: str = "null") -> bytes:
    """Encode records as an OCF byte string (enough of a writer for
    round-trip tests and the CLI avro export)."""
    out = io.BytesIO()
    out.write(_MAGIC)
    meta = {"avro.schema": json.dumps(schema).encode(),
            "avro.codec": codec.encode()}
    _w_long(out, len(meta))
    for k, v in meta.items():
        _w_bytes(out, k.encode())
        _w_bytes(out, v)
    _w_long(out, 0)
    sync = b"0123456789abcdef"
    out.write(sync)
    body = io.BytesIO()
    for r in records:
        _w_value(body, schema, r)
    block = body.getvalue()
    if codec == "deflate":
        comp = zlib.compressobj(wbits=-15)
        block = comp.compress(block) + comp.flush()
    _w_long(out, len(records))
    _w_long(out, len(block))
    out.write(block)
    out.write(sync)
    return out.getvalue()


def _w_long(fh, v: int):
    v = (v << 1) ^ (v >> 63)  # zigzag
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            fh.write(bytes([b | 0x80]))
        else:
            fh.write(bytes([b]))
            break


def _w_bytes(fh, b: bytes):
    _w_long(fh, len(b))
    fh.write(b)


def _w_value(fh, schema, v):
    if isinstance(schema, list):
        for i, s in enumerate(schema):
            t = s if isinstance(s, str) else s["type"]
            if (v is None) == (t == "null"):
                _w_long(fh, i)
                return _w_value(fh, s, v)
        raise ValueError("no union branch")
    t = schema if isinstance(schema, str) else schema["type"]
    if t == "null":
        return
    if t == "boolean":
        fh.write(b"\x01" if v else b"\x00")
    elif t in ("int", "long"):
        _w_long(fh, int(v))
    elif t == "float":
        fh.write(struct.pack("<f", v))
    elif t == "double":
        fh.write(struct.pack("<d", v))
    elif t == "bytes":
        _w_bytes(fh, bytes(v))
    elif t == "string":
        _w_bytes(fh, str(v).encode("utf-8"))
    elif t == "record":
        for f in schema["fields"]:
            _w_value(fh, f["type"], v[f["name"]])
    elif t == "array":
        if v:
            _w_long(fh, len(v))
            for e in v:
                _w_value(fh, schema["items"], e)
        _w_long(fh, 0)
    elif t == "map":
        if v:
            _w_long(fh, len(v))
            for k, e in v.items():
                _w_bytes(fh, str(k).encode())
                _w_value(fh, schema["values"], e)
        _w_long(fh, 0)
    else:
        raise ValueError(f"unsupported write type {t!r}")
