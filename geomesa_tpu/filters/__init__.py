"""L3 filter algebra (SURVEY.md 2.1, geomesa-filter): ECQL parsing,
index-value extraction, and the exact vectorized reference evaluator."""

from . import ast
from .ast import (After, And, BBox, Before, Between, Compare, CompareOp,
                  Contains, Crosses, Disjoint, During, DWithin, Exclude,
                  FidFilter, Filter, Include, InList, Intersects, IsNull,
                  Like, Not, Or, Overlaps, TEquals, Touches, Within)
from .ecql import ECQLError, parse_ecql
from .evaluate import evaluate
from .helper import (Bound, Bounds, FilterValues, distance_degrees,
                     extract_attribute_bounds, extract_geometries,
                     extract_intervals, is_filter_whole_world)

__all__ = [
    "ast", "parse_ecql", "ECQLError", "evaluate",
    "Bound", "Bounds", "FilterValues", "distance_degrees",
    "extract_attribute_bounds", "extract_geometries", "extract_intervals",
    "is_filter_whole_world",
    "After", "And", "BBox", "Before", "Between", "Compare", "CompareOp",
    "Contains", "Crosses", "Disjoint", "During", "DWithin", "Exclude",
    "FidFilter", "Filter", "Include", "InList", "Intersects", "IsNull",
    "Like", "Not", "Or", "Overlaps", "TEquals", "Touches", "Within",
]
