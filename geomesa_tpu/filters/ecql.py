"""ECQL parser: CQL text -> Filter AST.

Hand-rolled recursive-descent parser for the ECQL subset the reference's
query paths exercise (the reference delegates to GeoTools' ECQL parser;
this grammar covers the constructs used across its test suites):

    filter   := or
    or       := and (OR and)*
    and      := not (AND not)*
    not      := NOT not | primary
    primary  := '(' filter ')' | predicate
    predicate:= INCLUDE | EXCLUDE
              | BBOX '(' attr ',' num ',' num ',' num ',' num [',' crs] ')'
              | INTERSECTS|DISJOINT|CONTAINS|WITHIN|TOUCHES|CROSSES|OVERLAPS
                  '(' attr ',' geometry ')'
              | DWITHIN '(' attr ',' geometry ',' num ',' units ')'
              | IN '(' str (',' str)* ')'                  -- fid filter
              | attr IN '(' literal (',' literal)* ')'
              | attr BETWEEN literal AND literal
              | attr [NOT] LIKE str | attr ILIKE str
              | attr IS [NOT] NULL
              | attr DURING instant '/' instant
              | attr BEFORE instant | attr AFTER instant | attr TEQUALS instant
              | attr op literal        (op: = <> != < > <= >=)

Dates parse to epoch millis; geometries parse via the WKT reader.
"""

from __future__ import annotations

import functools
import re

import numpy as np

from ..geometry.wkt import _Scanner, _parse_geom
from . import ast

__all__ = ["parse_ecql", "ECQLError"]


class ECQLError(ValueError):
    pass


_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<lparen>\()
    | (?P<rparen>\))
    | (?P<comma>,)
    | (?P<slash>/)
    | (?P<op><=|>=|<>|!=|=|<|>)
    | (?P<string>'(?:[^']|'')*')
    | (?P<datetime>\d{4}-\d{2}-\d{2}(?:[T ]\d{2}:\d{2}:\d{2}(?:\.\d+)?Z?)?)
    | (?P<number>[-+]?\d*\.?\d+(?:[eE][-+]?\d+)?)
    | (?P<word>[A-Za-z_][A-Za-z0-9_.:-]*)
    )""", re.VERBOSE)

_SPATIAL = {
    "INTERSECTS": ast.Intersects, "DISJOINT": ast.Disjoint,
    "CONTAINS": ast.Contains, "WITHIN": ast.Within,
    "TOUCHES": ast.Touches, "CROSSES": ast.Crosses,
    "OVERLAPS": ast.Overlaps, "EQUALS": ast.GeomEquals,
}

_KEYWORDS = {"AND", "OR", "NOT", "IN", "BETWEEN", "LIKE", "ILIKE", "IS",
             "NULL", "DURING", "BEFORE", "AFTER", "TEQUALS", "INCLUDE",
             "EXCLUDE", "BBOX", "DWITHIN", "TRUE", "FALSE"} | set(_SPATIAL)


def _parse_instant(s: str) -> int:
    """ISO instant -> epoch millis (UTC assumed, trailing Z optional)."""
    s = s.strip().rstrip("Z").replace(" ", "T")
    try:
        return int(np.datetime64(s, "ms").astype(np.int64))
    except ValueError as e:
        raise ECQLError(f"bad instant {s!r}: {e}") from None


class _Tokens:
    def __init__(self, text: str):
        self.text = text
        self.toks: list[tuple[str, str]] = []
        i = 0
        while i < len(text):
            m = _TOKEN_RE.match(text, i)
            if not m or m.end() == i:
                if text[i:].strip():
                    raise ECQLError(f"cannot tokenize at: {text[i:][:40]!r}")
                break
            i = m.end()
            kind = m.lastgroup
            val = m.group(kind)
            self.toks.append((kind, val.strip()))
        self.pos = 0

    def peek(self, k: int = 0):
        if self.pos + k < len(self.toks):
            return self.toks[self.pos + k]
        return ("eof", "")

    def next(self):
        t = self.peek()
        self.pos += 1
        return t

    def expect(self, kind: str, val: str | None = None):
        t = self.next()
        if t[0] != kind or (val is not None and t[1].upper() != val):
            raise ECQLError(f"expected {val or kind}, got {t[1]!r} "
                            f"in {self.text[:80]!r}")
        return t

    def at_word(self, *words: str) -> bool:
        t = self.peek()
        return t[0] == "word" and t[1].upper() in words


def _unquote(s: str) -> str:
    return s[1:-1].replace("''", "'")


def _number(tok: tuple[str, str]) -> float:
    if tok[0] != "number":
        raise ECQLError(f"expected number, got {tok[1]!r}")
    return float(tok[1])


def _literal(tok: tuple[str, str]):
    kind, val = tok
    if kind == "string":
        return _unquote(val)
    if kind == "number":
        f = float(val)
        return int(f) if f.is_integer() and "." not in val and "e" not in val.lower() else f
    if kind == "datetime":
        return _parse_instant(val)
    if kind == "word" and val.upper() in ("TRUE", "FALSE"):
        return val.upper() == "TRUE"
    raise ECQLError(f"expected literal, got {val!r}")


class _Parser:
    def __init__(self, text: str):
        self.t = _Tokens(text)

    def parse(self) -> ast.Filter:
        f = self.or_expr()
        if self.t.peek()[0] != "eof":
            raise ECQLError(f"trailing input: {self.t.peek()[1]!r}")
        return f

    def or_expr(self) -> ast.Filter:
        parts = [self.and_expr()]
        while self.t.at_word("OR"):
            self.t.next()
            parts.append(self.and_expr())
        return parts[0] if len(parts) == 1 else ast.Or(parts)

    def and_expr(self) -> ast.Filter:
        parts = [self.not_expr()]
        while self.t.at_word("AND"):
            self.t.next()
            parts.append(self.not_expr())
        return parts[0] if len(parts) == 1 else ast.And(parts)

    def not_expr(self) -> ast.Filter:
        if self.t.at_word("NOT"):
            self.t.next()
            return ast.Not(self.not_expr())
        return self.primary()

    def primary(self) -> ast.Filter:
        kind, val = self.t.peek()
        if kind == "lparen":
            self.t.next()
            f = self.or_expr()
            self.t.expect("rparen")
            return f
        if kind != "word":
            raise ECQLError(f"unexpected token {val!r}")
        u = val.upper()
        if u == "INCLUDE":
            self.t.next()
            return ast.Include()
        if u == "EXCLUDE":
            self.t.next()
            return ast.Exclude()
        if u == "BBOX":
            return self.bbox()
        if u == "DWITHIN":
            return self.dwithin()
        if u in _SPATIAL:
            return self.spatial(u)
        if u == "IN":
            return self.fid_filter()
        return self.attr_predicate()

    def bbox(self) -> ast.Filter:
        self.t.next()
        self.t.expect("lparen")
        attr = self.t.expect("word")[1]
        vals = []
        for _ in range(4):
            self.t.expect("comma")
            vals.append(_number(self.t.next()))
        if self.t.peek()[0] == "comma":  # optional CRS, ignored (4326 only)
            self.t.next()
            self.t.next()
        self.t.expect("rparen")
        return ast.BBox(attr, *vals)

    def _geometry(self):
        # delegate to the WKT scanner from the current character position
        # (tokens don't model WKT structure)
        start = self._char_pos()
        sc = _Scanner(self.t.text)
        sc.i = start
        g = _parse_geom(sc)
        self._resync(sc.i)
        return g

    def _char_pos(self) -> int:
        """Character offset of the current token in the source text."""
        # recompute by re-tokenizing; positions are monotonic
        i = 0
        for k in range(self.t.pos):
            m = _TOKEN_RE.match(self.t.text, i)
            i = m.end()
        m = _TOKEN_RE.match(self.t.text, i)
        return m.end() - len(m.group(m.lastgroup))

    def _resync(self, char_pos: int):
        """Advance the token stream past char_pos."""
        i = 0
        pos = 0
        while i < char_pos and pos < len(self.t.toks):
            m = _TOKEN_RE.match(self.t.text, i)
            i = m.end()
            pos += 1
        self.t.pos = pos

    def spatial(self, name: str) -> ast.Filter:
        self.t.next()
        self.t.expect("lparen")
        attr = self.t.expect("word")[1]
        self.t.expect("comma")
        g = self._geometry()
        self.t.expect("rparen")
        return _SPATIAL[name](attr, g)

    def dwithin(self) -> ast.Filter:
        self.t.next()
        self.t.expect("lparen")
        attr = self.t.expect("word")[1]
        self.t.expect("comma")
        g = self._geometry()
        self.t.expect("comma")
        dist = _number(self.t.next())
        self.t.expect("comma")
        units = [self.t.next()[1]]
        while self.t.peek()[0] == "word":  # "statute miles" etc.
            units.append(self.t.next()[1])
        self.t.expect("rparen")
        return ast.DWithin(attr, g, dist, " ".join(units).lower())

    def fid_filter(self) -> ast.Filter:
        self.t.next()
        self.t.expect("lparen")
        ids = [_unquote(self.t.expect("string")[1])]
        while self.t.peek()[0] == "comma":
            self.t.next()
            ids.append(_unquote(self.t.expect("string")[1]))
        self.t.expect("rparen")
        return ast.FidFilter(ids)

    def attr_predicate(self) -> ast.Filter:
        attr = self.t.expect("word")[1]
        kind, val = self.t.peek()
        u = val.upper() if kind == "word" else None
        if kind == "op":
            self.t.next()
            lit = _literal(self.t.next())
            op = "<>" if val == "!=" else val
            return ast.Compare(op, attr, lit)
        if u == "BETWEEN":
            self.t.next()
            lo = _literal(self.t.next())
            self.t.expect("word", "AND")
            hi = _literal(self.t.next())
            return ast.Between(attr, lo, hi)
        if u in ("LIKE", "ILIKE"):
            self.t.next()
            pat = _unquote(self.t.expect("string")[1])
            return ast.Like(attr, pat, case_sensitive=(u == "LIKE"))
        if u == "NOT":
            self.t.next()
            if self.t.at_word("LIKE"):
                self.t.next()
                pat = _unquote(self.t.expect("string")[1])
                return ast.Not(ast.Like(attr, pat))
            raise ECQLError("expected LIKE after NOT")
        if u == "IS":
            self.t.next()
            if self.t.at_word("NOT"):
                self.t.next()
                self.t.expect("word", "NULL")
                return ast.Not(ast.IsNull(attr))
            self.t.expect("word", "NULL")
            return ast.IsNull(attr)
        if u == "IN":
            self.t.next()
            self.t.expect("lparen")
            vals = [_literal(self.t.next())]
            while self.t.peek()[0] == "comma":
                self.t.next()
                vals.append(_literal(self.t.next()))
            self.t.expect("rparen")
            return ast.InList(attr, vals)
        if u == "DURING":
            self.t.next()
            start = self._instant()
            self.t.expect("slash")
            end = self._instant()
            return ast.During(attr, start, end)
        if u == "BEFORE":
            self.t.next()
            return ast.Before(attr, self._instant())
        if u == "AFTER":
            self.t.next()
            return ast.After(attr, self._instant())
        if u == "TEQUALS":
            self.t.next()
            return ast.TEquals(attr, self._instant())
        raise ECQLError(f"unexpected predicate on {attr!r}: {val!r}")

    def _instant(self) -> int:
        t = self.t.next()
        if t[0] == "datetime":
            return _parse_instant(t[1])
        if t[0] == "string":
            return _parse_instant(_unquote(t[1]))
        raise ECQLError(f"expected instant, got {t[1]!r}")


@functools.lru_cache(maxsize=512)
def parse_ecql(text: str) -> ast.Filter:
    """Parse an ECQL filter string to a Filter AST.

    Cached: AST nodes are frozen dataclasses, so one shared tree per
    query string is safe — and it makes repeated queries hit the
    stores' plan caches (keyed on filter object identity). The
    reference caches parsed filters the same way on its servers
    (IteratorCache, index/iterators/IteratorCache.scala)."""
    text = text.strip()
    if not text:
        return ast.Include()
    return _Parser(text).parse()
