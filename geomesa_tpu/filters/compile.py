"""Standing-filter compiler: ECQL AST -> device-loadable bound summary.

The continuous-query matcher (scan/standing.py) evaluates the WHOLE
registered filter population against every ingest batch in one fused
``rows x filters`` kernel. That kernel only speaks rectangles: bbox
envelopes over the default point geometry, one inclusive epoch-millis
interval over the default date attribute, and one numeric interval per
tracked attribute. This module walks a parsed filter once at
registration time and projects it onto that vocabulary, mirroring the
conservative-mask + exact-patch split proven in scan/zscan.py:

- the compiled bounds are a SOUND over-approximation — every row the
  filter truly matches falls inside them (extraction helpers treat
  unsupported nodes, ``Not``, and OR'd structure as unconstrained, and
  unions/intersections only widen), so the device mask never drops a
  true match;
- ``residual`` marks filters whose semantics the summary does NOT
  capture exactly (LIKE, string equality, OR trees, polygon predicates,
  IS NULL, fid filters, ...). Their device survivors are re-checked
  with the full ``filters.evaluate`` oracle; non-residual filters need
  only the cheap vectorized f64 recheck in ``exact_match`` (which also
  absorbs the kernel's widened-f32 bound slack);
- ``never`` marks provably-empty filters (EXCLUDE, disjoint ANDed
  boxes/intervals) — matched against nothing, no residual work.

Exactness contract: for any filter and batch,
``hits = candidates[exact_match(...)]`` (non-residual) or
``candidates[evaluate(...)]`` (residual) equals
``np.flatnonzero(evaluate(filter, batch))`` whenever ``candidates`` is
a superset of the true match rows. tests/test_geofence.py enforces it
differentially against random filter populations.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from . import ast
from .evaluate import evaluate
from .helper import (extract_attribute_bounds, extract_geometries,
                     extract_intervals, to_millis)

__all__ = ["CompiledFilter", "compile_filter", "numeric_attrs",
           "exact_match", "NUMERIC_TYPES"]

# attribute types the fused kernel tracks as one f64 interval each
NUMERIC_TYPES = ("Integer", "Long", "Double", "Float")


def numeric_attrs(sft) -> list[str]:
    """Schema attributes the standing kernel carries as device columns,
    in schema order (the kernel's attribute axis layout)."""
    return [a.name for a in sft.attributes if a.type.name in NUMERIC_TYPES]


@dataclasses.dataclass(frozen=True)
class AttrBound:
    """One numeric attribute's interval; None = unbounded side. The
    inclusivity flags matter only on the exact host recheck — the
    device compare is inclusive over widened bounds either way."""
    lo: float | None
    lo_inc: bool
    hi: float | None
    hi_inc: bool


@dataclasses.dataclass(frozen=True)
class CompiledFilter:
    """Device-compilable projection of one standing filter."""
    geom_attr: str | None        # point geometry the boxes apply to
    dtg_attr: str | None         # date attribute the interval applies to
    boxes: tuple                 # ((xmin, ymin, xmax, ymax) f64, ...)
    spatial_any: bool            # no spatial constraint: pass all rows
    interval: tuple | None       # (lo_ms|None, hi_ms|None) inclusive
    attr_bounds: dict            # {attr name: AttrBound}
    residual: bool               # summary is conservative, not exact
    never: bool                  # provably empty: matches nothing

    @property
    def n_boxes(self) -> int:
        return len(self.boxes)


def _is_number(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _bbox_in_world(f: ast.BBox) -> bool:
    """True when extraction is the identity (no IDL split, no world
    clip) so the envelope test equals the evaluator's raw compares."""
    return (-180.0 <= f.xmin <= f.xmax <= 180.0
            and -90.0 <= f.ymin <= f.ymax <= 90.0)


def _expressible(f: ast.Filter, geom_attr, dtg_attr, nums) -> bool:
    """True when the compiled summary reproduces the filter EXACTLY: a
    conjunction of in-world bboxes on the point geometry, temporal
    predicates on the default date, and single-interval numeric bounds.
    Anything else (OR/NOT trees, strings, polygons, ...) is residual."""
    if isinstance(f, ast.Include):
        return True
    if isinstance(f, ast.And):
        return all(_expressible(c, geom_attr, dtg_attr, nums)
                   for c in f.children)
    if isinstance(f, ast.BBox):
        return f.prop == geom_attr and _bbox_in_world(f)
    if isinstance(f, (ast.During, ast.Before, ast.After, ast.TEquals)):
        return f.prop == dtg_attr
    if isinstance(f, ast.Compare):
        return (f.prop in nums and f.op != ast.CompareOp.NE
                and _is_number(f.value))
    if isinstance(f, ast.Between):
        return (f.prop in nums and _is_number(f.lo) and _is_number(f.hi))
    if isinstance(f, ast.InList):
        return (f.prop in nums and len(f.values) == 1
                and _is_number(f.values[0]))
    return False


def _interval_envelope(fv) -> tuple | None:
    """OR'd date Bounds -> one inclusive (lo_ms, hi_ms) envelope (a
    superset — exact only when the extraction was a single interval).
    Exclusive bounds shift by 1 ms, which is exact at millisecond
    resolution."""
    lo_env: int | None = None
    hi_env: int | None = None
    lo_open = hi_open = False
    for b in fv.values:
        if not b.lower.is_bounded:
            lo_open = True
        else:
            lo = to_millis(b.lower.value) + (0 if b.lower.inclusive else 1)
            lo_env = lo if lo_env is None else min(lo_env, lo)
        if not b.upper.is_bounded:
            hi_open = True
        else:
            hi = to_millis(b.upper.value) - (0 if b.upper.inclusive else 1)
            hi_env = hi if hi_env is None else max(hi_env, hi)
    return (None if lo_open else lo_env, None if hi_open else hi_env)


def _attr_envelope(fv) -> AttrBound:
    """OR'd numeric Bounds -> one envelope AttrBound. With multiple
    bounds the inclusivity loosens to True (widening is sound; the
    caller marks the filter residual in that case)."""
    single = len(fv.values) == 1
    lo_env: float | None = None
    hi_env: float | None = None
    lo_inc = hi_inc = True
    lo_open = hi_open = False
    for b in fv.values:
        if not b.lower.is_bounded:
            lo_open = True
        else:
            v = float(b.lower.value)
            if lo_env is None or v < lo_env:
                lo_env = v
                lo_inc = b.lower.inclusive if single else True
        if not b.upper.is_bounded:
            hi_open = True
        else:
            v = float(b.upper.value)
            if hi_env is None or v > hi_env:
                hi_env = v
                hi_inc = b.upper.inclusive if single else True
    return AttrBound(None if lo_open else lo_env, lo_inc,
                     None if hi_open else hi_env, hi_inc)


def compile_filter(f: ast.Filter, sft) -> CompiledFilter:
    """Project one parsed filter onto the standing-kernel vocabulary."""
    geom_attr = sft.geom_field if sft.is_points else None
    dtg_attr = sft.dtg_field
    nums = set(numeric_attrs(sft))
    never = isinstance(f, ast.Exclude)
    exact = never or _expressible(f, geom_attr, dtg_attr, nums)

    # spatial: envelopes of the extracted (OR'd) geometries
    boxes: tuple = ()
    spatial_any = True
    if geom_attr is not None and not never:
        fv = extract_geometries(f, geom_attr)
        if fv.disjoint:
            never = True
        elif fv.values:
            spatial_any = False
            out = []
            for g in fv.values:
                e = g.envelope
                out.append((float(e.xmin), float(e.ymin),
                            float(e.xmax), float(e.ymax)))
            boxes = tuple(out)

    # temporal: one inclusive millis envelope over the dtg attribute
    interval = None
    if dtg_attr is not None and not never:
        fv = extract_intervals(f, dtg_attr)
        if fv.disjoint:
            never = True
        elif fv.values:
            interval = _interval_envelope(fv)
            if len(fv.values) > 1:
                exact = False
            if interval == (None, None):
                interval = None

    # numeric attributes: one envelope interval each
    attr_bounds: dict = {}
    if not never:
        for name in sorted(nums):
            fv = extract_attribute_bounds(f, name)
            if fv.disjoint:
                never = True
                break
            if not fv.values:
                continue
            if any(not (_is_number(b.lower.value) or not b.lower.is_bounded)
                   or not (_is_number(b.upper.value) or not b.upper.is_bounded)
                   for b in fv.values):
                # non-numeric literal leaked into a numeric attribute's
                # bounds: skip the constraint (sound) and force residual
                exact = False
                continue
            ab = _attr_envelope(fv)
            if len(fv.values) > 1:
                exact = False
            if ab.lo is not None or ab.hi is not None:
                attr_bounds[name] = ab

    if never:
        return CompiledFilter(geom_attr, dtg_attr, (), True, None, {},
                              residual=False, never=True)
    return CompiledFilter(geom_attr, dtg_attr, boxes, spatial_any,
                          interval, attr_bounds,
                          residual=not exact, never=False)


def exact_match(cf: CompiledFilter, batch, rows: np.ndarray) -> np.ndarray:
    """Exact f64/i64 verdict of the compiled summary for ``rows``
    (candidate row indices into ``batch``). For non-residual filters
    this IS the filter's semantics; it also strips the widened-bound
    false positives the device mask admits."""
    m = len(rows)
    if cf.never:
        return np.zeros(m, dtype=bool)
    ok = np.ones(m, dtype=bool)
    if not cf.spatial_any and cf.geom_attr is not None:
        col = batch.col(cf.geom_attr)
        x, y = col.x[rows], col.y[rows]
        hit = np.zeros(m, dtype=bool)
        for xmin, ymin, xmax, ymax in cf.boxes:
            hit |= (x >= xmin) & (x <= xmax) & (y >= ymin) & (y <= ymax)
        ok &= hit & col.valid[rows]
    if cf.interval is not None and cf.dtg_attr is not None:
        col = batch.col(cf.dtg_attr)
        ms = col.millis[rows]
        lo, hi = cf.interval
        if lo is not None:
            ok &= ms >= lo
        if hi is not None:
            ok &= ms <= hi
        ok &= col.valid[rows]
    for name, ab in cf.attr_bounds.items():
        col = batch.col(name)
        vals = col.values[rows]
        if ab.lo is not None:
            ok &= (vals >= ab.lo) if ab.lo_inc else (vals > ab.lo)
        if ab.hi is not None:
            ok &= (vals <= ab.hi) if ab.hi_inc else (vals < ab.hi)
        ok &= col.valid[rows]
    return ok


def exact_hits(cf: CompiledFilter, f: ast.Filter, batch,
               candidates: np.ndarray) -> np.ndarray:
    """Candidate rows -> exact hit rows: the one patch-step shared by
    every caller of the standing kernel. Residual filters re-run the
    full evaluator on just the surviving candidate rows; compiled-exact
    filters take the cheap vectorized recheck."""
    if cf.never or not len(candidates):
        return candidates[:0]
    if cf.residual:
        keep = evaluate(f, batch.take(candidates))
    else:
        keep = exact_match(cf, batch, candidates)
    return candidates[keep]
