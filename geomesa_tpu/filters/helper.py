"""Filter analysis: extract index-consumable values from a Filter AST.

Mirrors FilterHelper (geomesa-filter/.../FilterHelper.scala):

- ``extract_geometries`` (:201): AND intersects extracted geometries,
  OR unions them; DWithin buffers by its distance in degrees; BBOXes
  crossing the antimeridian split IDL-safe; results clip to the world.
- ``extract_intervals`` (:267): date bounds with the reference's
  exclusive-bound second-rounding semantics.
- ``extract_attribute_bounds`` (:318): typed bounds lattice for
  attribute-index planning.
- ``is_filter_whole_world`` (:157).

Bounds carry inclusivity; ``FilterValues.disjoint`` marks provably-empty
extractions (e.g. ANDed non-overlapping boxes) so planners can return
empty plans without scanning.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Generic, TypeVar

import numpy as np

from ..geometry import Envelope, Geometry, Polygon
from ..geometry.base import WHOLE_WORLD, _Multi
from . import ast

T = TypeVar("T")

__all__ = ["Bound", "Bounds", "FilterValues", "extract_geometries",
           "extract_intervals", "extract_attribute_bounds",
           "is_filter_whole_world", "distance_degrees", "dwithin_degrees",
           "METERS_MULTIPLIERS"]

# ECQL distance units -> meters (FilterHelper.visitDwithin:93-101)
METERS_MULTIPLIERS = {
    "meters": 1.0,
    "kilometers": 1000.0,
    "feet": 0.3048,
    "statute miles": 1609.347,
    "nautical miles": 1852.0,
}

_WGS84_A = 6378137.0
_WGS84_E2 = 0.00669437999014


def distance_degrees(geom: Geometry, meters: float) -> float:
    """Meters -> degrees: the widest eastward arc at the geometry's
    envelope corners (GeometryUtils.distanceDegrees, GeometryUtils.scala:25-39)."""
    env = geom.envelope
    best = 0.0
    for lat in (env.ymin, env.ymax):
        phi = math.radians(lat)
        # prime-vertical radius of curvature
        n = _WGS84_A / math.sqrt(1 - _WGS84_E2 * math.sin(phi) ** 2)
        circ = n * math.cos(phi)
        if circ <= 0:
            continue
        best = max(best, math.degrees(meters / circ))
    return best if best > 0 else math.degrees(meters / _WGS84_A)


def dwithin_degrees(geom: Geometry, distance: float, units: str) -> float:
    """DWithin distance -> planar degrees. ECQL units convert via
    meters; 'degrees' passes through (the Spark-SQL ST_DWithin
    semantics — CRS units, SQLSpatialFunctions)."""
    if units == "degrees":
        return float(distance)
    return distance_degrees(geom,
                            distance * METERS_MULTIPLIERS.get(units, 1.0))


def to_millis(v) -> int:
    """Interval/bound value -> epoch millis: ECQL quoted date strings
    arrive as raw strings (only bare datetime tokens parse in the lexer)."""
    if isinstance(v, str):
        return int(np.datetime64(v.strip().rstrip("Z").replace(" ", "T"),
                                 "ms").astype(np.int64))
    return int(v)


def like_vocab_mask(pattern: str, case_sensitive: bool,
                    vocab: np.ndarray) -> np.ndarray:
    """SQL LIKE pattern -> bool mask over a string vocab. The single
    source of LIKE semantics for the host evaluator and the device
    residual compiler (their parity is a correctness contract)."""
    import re
    pat = re.escape(pattern).replace("%", ".*").replace("_", ".")
    flags = 0 if case_sensitive else re.IGNORECASE
    rx = re.compile(f"^{pat}$", flags)
    return np.array([bool(rx.match(s)) for s in vocab.astype(str)],
                    dtype=bool)


@dataclasses.dataclass(frozen=True)
class Bound(Generic[T]):
    """One side of an interval; value None = unbounded."""
    value: Any
    inclusive: bool

    @staticmethod
    def unbounded() -> "Bound":
        return Bound(None, True)

    @property
    def is_bounded(self) -> bool:
        return self.value is not None


@dataclasses.dataclass(frozen=True)
class Bounds(Generic[T]):
    lower: Bound
    upper: Bound

    @staticmethod
    def everything() -> "Bounds":
        return Bounds(Bound.unbounded(), Bound.unbounded())

    @property
    def is_equality(self) -> bool:
        return (self.lower.is_bounded and self.lower.value == self.upper.value
                and self.lower.inclusive and self.upper.inclusive)

    @property
    def is_bounded(self) -> bool:
        return self.lower.is_bounded or self.upper.is_bounded

    def intersection(self, other: "Bounds") -> "Bounds | None":
        lo = _lower_max(self.lower, other.lower)
        hi = _upper_min(self.upper, other.upper)
        if lo.is_bounded and hi.is_bounded:
            if lo.value > hi.value:
                return None
            if lo.value == hi.value and not (lo.inclusive and hi.inclusive):
                return None
        return Bounds(lo, hi)

    def union_if_overlapping(self, other: "Bounds") -> "Bounds | None":
        """Merge if the two intervals overlap or touch, else None."""
        if self._disjoint_from(other):
            return None
        return Bounds(_lower_min(self.lower, other.lower),
                      _upper_max(self.upper, other.upper))

    def _disjoint_from(self, other: "Bounds") -> bool:
        for a, b in ((self, other), (other, self)):
            if a.upper.is_bounded and b.lower.is_bounded:
                if a.upper.value < b.lower.value:
                    return True
                if (a.upper.value == b.lower.value
                        and not a.upper.inclusive and not b.lower.inclusive):
                    return True
        return False

    def contains_value(self, v) -> bool:
        if self.lower.is_bounded:
            if v < self.lower.value:
                return False
            if v == self.lower.value and not self.lower.inclusive:
                return False
        if self.upper.is_bounded:
            if v > self.upper.value:
                return False
            if v == self.upper.value and not self.upper.inclusive:
                return False
        return True


# A lower bound of None means -inf; an upper bound of None means +inf.
# Intersections tighten (finite wins, inclusivity ANDs); unions loosen
# (unbounded wins, inclusivity ORs).

def _lower_max(a: Bound, b: Bound) -> Bound:
    if not a.is_bounded:
        return b
    if not b.is_bounded:
        return a
    if a.value != b.value:
        return a if a.value > b.value else b
    return Bound(a.value, a.inclusive and b.inclusive)


def _upper_min(a: Bound, b: Bound) -> Bound:
    if not a.is_bounded:
        return b
    if not b.is_bounded:
        return a
    if a.value != b.value:
        return a if a.value < b.value else b
    return Bound(a.value, a.inclusive and b.inclusive)


def _lower_min(a: Bound, b: Bound) -> Bound:
    if not a.is_bounded or not b.is_bounded:
        return Bound.unbounded()
    if a.value != b.value:
        return a if a.value < b.value else b
    return Bound(a.value, a.inclusive or b.inclusive)


def _upper_max(a: Bound, b: Bound) -> Bound:
    if not a.is_bounded or not b.is_bounded:
        return Bound.unbounded()
    if a.value != b.value:
        return a if a.value > b.value else b
    return Bound(a.value, a.inclusive or b.inclusive)


@dataclasses.dataclass
class FilterValues(Generic[T]):
    """Extraction result: OR'd values + flags (FilterValues.scala)."""
    values: list
    precise: bool = True
    disjoint: bool = False

    @staticmethod
    def empty() -> "FilterValues":
        return FilterValues([])

    @staticmethod
    def make_disjoint() -> "FilterValues":
        return FilterValues([], disjoint=True)

    @property
    def is_empty(self) -> bool:
        return not self.values and not self.disjoint

    def __bool__(self) -> bool:
        return bool(self.values) or self.disjoint

    def __iter__(self):
        return iter(self.values)

    def __len__(self) -> int:
        return len(self.values)


# -- geometry extraction ---------------------------------------------------


def _split_idl(g: Geometry) -> list[Geometry]:
    """Split geometries whose longitudes run past +/-180 into wrapped
    parts (getInternationalDateLineSafeGeometry analog): the x interval
    is treated as an arc on the circle. Only envelope-level splitting is
    needed for planning; exact predicates run downstream."""
    env = g.envelope
    if env.xmin >= -180 and env.xmax <= 180:
        return [g]
    span = env.xmax - env.xmin
    if span >= 360:
        return [Envelope(-180.0, env.ymin, 180.0, env.ymax).to_polygon()]
    # rotate the start into [-180, 180)
    start = ((env.xmin + 180.0) % 360.0) - 180.0
    end = start + span
    if end <= 180:
        return [Envelope(start, env.ymin, end, env.ymax).to_polygon()]
    return [Envelope(start, env.ymin, 180.0, env.ymax).to_polygon(),
            Envelope(-180.0, env.ymin, end - 360.0, env.ymax).to_polygon()]


def _clip_world(g: Geometry) -> Geometry | None:
    env = g.envelope
    world = WHOLE_WORLD.envelope
    if world.contains_env(env):
        return g
    clipped = env.intersection(world)
    if clipped.is_empty:
        return None
    return clipped.to_polygon()


def _geom_intersection(a: Geometry, b: Geometry) -> Geometry | None:
    """AND-combination of two extracted geometries. Exact when either is
    an axis-aligned envelope box (the overwhelmingly common case);
    otherwise conservatively returns the one with the smaller envelope,
    clipped to the other's envelope box (a superset of the true
    intersection — safe for planning, residual filters keep exactness)."""
    if not a.envelope.intersects(b.envelope):
        return None
    ea, eb = a.envelope, b.envelope
    a_is_box = isinstance(a, Polygon) and not a.holes and _is_box(a)
    b_is_box = isinstance(b, Polygon) and not b.holes and _is_box(b)
    if a_is_box and b_is_box:
        e = ea.intersection(eb)
        return None if e.is_empty else e.to_polygon()
    if a_is_box:
        return b if ea.contains_env(eb) else eb.intersection(ea).to_polygon()
    if b_is_box:
        return a if eb.contains_env(ea) else ea.intersection(eb).to_polygon()
    if not a.intersects(b):
        return None
    # both complex: keep the smaller-envelope one (conservative)
    area_a = (ea.xmax - ea.xmin) * (ea.ymax - ea.ymin)
    area_b = (eb.xmax - eb.xmin) * (eb.ymax - eb.ymin)
    return a if area_a <= area_b else b


def _is_box(p: Polygon) -> bool:
    if len(p.shell) != 5:
        return False
    xs = set(p.shell[:, 0].tolist())
    ys = set(p.shell[:, 1].tolist())
    return len(xs) == 2 and len(ys) == 2


def _flatten(g: Geometry) -> list[Geometry]:
    if isinstance(g, _Multi) and g.geom_type == "GeometryCollection":
        return [s for p in g.parts for s in _flatten(p)]
    return [g]


def extract_geometries(f: ast.Filter, attribute: str | None,
                       intersect: bool = True) -> FilterValues:
    """Extract query geometries for `attribute` (FilterHelper.scala:201)."""
    out = _extract_geoms(f, attribute, intersect)
    clipped = []
    for g in out.values:
        c = _clip_world(g)
        if c is not None:
            clipped.append(c)
    if out.values and not clipped:
        return FilterValues.make_disjoint()
    return FilterValues(clipped, out.precise, out.disjoint)


def _extract_geoms(f: ast.Filter, attribute: str | None,
                   intersect: bool) -> FilterValues:
    if isinstance(f, ast.Or):
        vals: list[Geometry] = []
        any_nonempty = False
        for c in f.children:
            child = _extract_geoms(c, attribute, intersect)
            if child.is_empty and not child.disjoint:
                # a child with no spatial constraint matches everywhere:
                # the OR extraction is unbounded
                return FilterValues.empty()
            any_nonempty = True
            vals.extend(child.values)
        if not any_nonempty:
            return FilterValues.empty()
        if not vals:
            return FilterValues.make_disjoint()
        return FilterValues(vals)
    if isinstance(f, ast.And):
        children = [c for c in (
            _extract_geoms(c, attribute, intersect) for c in f.children)
            if not c.is_empty or c.disjoint]
        if not children:
            return FilterValues.empty()
        if any(c.disjoint for c in children):
            return FilterValues.make_disjoint()
        if not intersect:
            return FilterValues([v for c in children for v in c.values])
        acc = children[0].values
        for c in children[1:]:
            new: list[Geometry] = []
            for a in acc:
                for b in c.values:
                    g = _geom_intersection(a, b)
                    if g is not None:
                        new.append(g)
            acc = new
            if not acc:
                return FilterValues.make_disjoint()
        return FilterValues(acc)
    if isinstance(f, ast.BBox):
        if attribute is not None and f.prop != attribute:
            return FilterValues.empty()
        box = Envelope(f.xmin, f.ymin, f.xmax, f.ymax).to_polygon()
        return FilterValues([p for g in _split_idl(box) for p in _flatten(g)])
    if isinstance(f, ast.DWithin):
        if attribute is not None and f.prop != attribute:
            return FilterValues.empty()
        deg = dwithin_degrees(f.geom, f.distance, f.units)
        buffered = f.geom.envelope.buffer(deg).to_polygon()
        return FilterValues([p for g in _split_idl(buffered) for p in _flatten(g)])
    if isinstance(f, (ast.Intersects, ast.Contains, ast.Within,
                      ast.Overlaps, ast.Touches, ast.Crosses,
                      ast.GeomEquals)):
        if attribute is not None and f.prop != attribute:
            return FilterValues.empty()
        return FilterValues([p for g in _split_idl(f.geom) for p in _flatten(g)])
    return FilterValues.empty()


def is_filter_whole_world(f: ast.Filter) -> bool:
    """True if the filter's spatial component covers the whole world
    (FilterHelper.scala:157)."""
    geoms = extract_geometries(f, None)
    if geoms.is_empty:
        return True
    for g in geoms:
        if g.envelope.contains_env(WHOLE_WORLD.envelope):
            return True
    return False


# -- attribute bounds ------------------------------------------------------


def extract_attribute_bounds(f: ast.Filter, attribute: str) -> FilterValues:
    """Typed bounds for one attribute (FilterHelper.scala:318)."""
    if isinstance(f, ast.Or):
        all_bounds: list[Bounds] = []
        for c in f.children:
            child = extract_attribute_bounds(c, attribute)
            if child.is_empty:
                return FilterValues.empty()  # unconstrained child
            all_bounds = _union(all_bounds, child.values)
        return FilterValues(all_bounds) if all_bounds else FilterValues.empty()
    if isinstance(f, ast.And):
        acc: list[Bounds] | None = None
        for c in f.children:
            child = extract_attribute_bounds(c, attribute)
            if child.disjoint:
                return FilterValues.make_disjoint()
            if child.is_empty:
                continue
            if acc is None:
                acc = list(child.values)
            else:
                new = []
                for a in acc:
                    for b in child.values:
                        i = a.intersection(b)
                        if i is not None:
                            new.append(i)
                if not new:
                    return FilterValues.make_disjoint()
                acc = new
        return FilterValues(acc) if acc else FilterValues.empty()
    if isinstance(f, ast.Compare) and f.prop == attribute:
        v = f.value
        if f.op == ast.CompareOp.EQ:
            return FilterValues([Bounds(Bound(v, True), Bound(v, True))])
        if f.op == ast.CompareOp.LT:
            return FilterValues([Bounds(Bound.unbounded(), Bound(v, False))])
        if f.op == ast.CompareOp.LE:
            return FilterValues([Bounds(Bound.unbounded(), Bound(v, True))])
        if f.op == ast.CompareOp.GT:
            return FilterValues([Bounds(Bound(v, False), Bound.unbounded())])
        if f.op == ast.CompareOp.GE:
            return FilterValues([Bounds(Bound(v, True), Bound.unbounded())])
        return FilterValues.empty()  # <> is not index-consumable
    if isinstance(f, ast.Between) and f.prop == attribute:
        return FilterValues([Bounds(Bound(f.lo, True), Bound(f.hi, True))])
    if isinstance(f, ast.InList) and f.prop == attribute:
        return FilterValues([Bounds(Bound(v, True), Bound(v, True))
                             for v in f.values])
    if isinstance(f, ast.During) and f.prop == attribute:
        return FilterValues([Bounds(Bound(f.start, False), Bound(f.end, False))])
    if isinstance(f, ast.Before) and f.prop == attribute:
        return FilterValues([Bounds(Bound.unbounded(), Bound(f.time, False))])
    if isinstance(f, ast.After) and f.prop == attribute:
        return FilterValues([Bounds(Bound(f.time, False), Bound.unbounded())])
    if isinstance(f, ast.TEquals) and f.prop == attribute:
        return FilterValues([Bounds(Bound(f.time, True), Bound(f.time, True))])
    if isinstance(f, ast.Like) and f.prop == attribute and f.case_sensitive:
        # prefix patterns are index-consumable: 'abc%' -> [abc, abd)
        pat = f.pattern
        i = min((pat.index(c) for c in "%_" if c in pat), default=len(pat))
        prefix = pat[:i]
        if prefix and pat[i:] in ("%", ""):
            hi = prefix[:-1] + chr(ord(prefix[-1]) + 1)
            return FilterValues([Bounds(Bound(prefix, True), Bound(hi, False))])
        return FilterValues.empty()
    return FilterValues.empty()


def _union(acc: list[Bounds], more: list[Bounds]) -> list[Bounds]:
    out = list(acc)
    for b in more:
        merged = b
        keep = []
        for a in out:
            u = merged.union_if_overlapping(a)
            if u is None:
                keep.append(a)
            else:
                merged = u
        keep.append(merged)
        out = keep
    return out


# -- interval extraction ---------------------------------------------------


def _round_seconds_up(ms: int) -> int:
    return (ms // 1000 + 1) * 1000


def _round_seconds_down(ms: int) -> int:
    return (ms // 1000 - 1) * 1000 if ms % 1000 == 0 else (ms // 1000) * 1000


def extract_intervals(f: ast.Filter, attribute: str,
                      intersect: bool = True,
                      handle_exclusive: bool = False) -> FilterValues:
    """Date intervals in epoch millis (FilterHelper.extractIntervals:267).

    With ``handle_exclusive``, exclusive bounds round to the next whole
    second and become inclusive (matching the reference's key-range
    construction for second-resolution backends)."""
    bounds = extract_attribute_bounds(f, attribute)
    if not bounds or bounds.disjoint:
        return bounds
    out = []
    for b in bounds.values:
        lower, upper = b.lower, b.upper
        if handle_exclusive and lower.is_bounded and upper.is_bounded \
                and not (lower.inclusive and upper.inclusive):
            margin = 1000 if (lower.inclusive or upper.inclusive) else 2000
            do_round = upper.value - lower.value > margin
            lower = _adjust(lower, _round_seconds_up, do_round)
            upper = _adjust(upper, _round_seconds_down, do_round)
        elif handle_exclusive:
            lower = _adjust(lower, _round_seconds_up, True)
            upper = _adjust(upper, _round_seconds_down, True)
        out.append(Bounds(lower, upper))
    return FilterValues(out, bounds.precise, bounds.disjoint)


def _adjust(bound: Bound, round_fn, do_round: bool) -> Bound:
    if not bound.is_bounded:
        return bound
    if do_round and not bound.inclusive:
        return Bound(round_fn(bound.value), True)
    return bound
