"""Filter AST: the CQL/OpenGIS filter algebra, minus GeoTools.

Node set covers what the reference's planner and evaluators consume
(geomesa-filter/.../FilterHelper.scala, FilterSplitter, the iterator
residual filters): logical ops, comparisons, BETWEEN/LIKE/IN/IS NULL,
spatial predicates over geometry literals, temporal predicates over
date attributes, and feature-ID filters.

All nodes are immutable dataclasses; geometry literals are
geomesa_tpu.geometry objects; temporal literals are epoch millis.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import numpy as _np

from ..geometry import Geometry

__all__ = [
    "Filter", "Include", "Exclude", "And", "Or", "Not", "FidFilter",
    "Compare", "CompareOp", "Between", "Like", "IsNull", "InList",
    "SpatialPredicate", "BBox", "Intersects", "Disjoint", "Contains",
    "Within", "Touches", "Crosses", "Overlaps", "GeomEquals", "DWithin",
    "During", "Before", "After", "TEquals",
]


class Filter:
    """Base class for all filter nodes."""

    def __and__(self, other: "Filter") -> "Filter":
        return And([self, other])

    def __or__(self, other: "Filter") -> "Filter":
        return Or([self, other])

    def __invert__(self) -> "Filter":
        return Not(self)


def _iso(millis: int) -> str:
    """Epoch millis -> ISO-8601 instant (temporal predicates stringify
    to re-parseable ECQL for the wire); numpy always emits the 'T'
    separator."""
    return str(_np.datetime64(int(millis), "ms")) + "Z"


def walk(f: Filter):
    """Yield every node of a filter tree (the one tree traversal —
    property collectors across the stores/SQL layers build on this)."""
    yield f
    for c in getattr(f, "children", ()) or ():
        yield from walk(c)
    child = getattr(f, "child", None)
    if child is not None:
        yield from walk(child)


def props_of(f: Filter) -> set:
    """Attribute names referenced anywhere in a filter tree."""
    return {p for node in walk(f)
            if (p := getattr(node, "prop", None))}


@dataclasses.dataclass(frozen=True)
class Include(Filter):
    """Matches everything (Filter.INCLUDE)."""
    def __str__(self) -> str:
        return "INCLUDE"


@dataclasses.dataclass(frozen=True)
class Exclude(Filter):
    """Matches nothing (Filter.EXCLUDE)."""
    def __str__(self) -> str:
        return "EXCLUDE"


@dataclasses.dataclass(frozen=True)
class And(Filter):
    children: tuple

    def __init__(self, children: Sequence[Filter]):
        flat: list[Filter] = []
        for c in children:
            if isinstance(c, And):
                flat.extend(c.children)
            else:
                flat.append(c)
        object.__setattr__(self, "children", tuple(flat))

    def __str__(self) -> str:
        return "(" + " AND ".join(str(c) for c in self.children) + ")"


@dataclasses.dataclass(frozen=True)
class Or(Filter):
    children: tuple

    def __init__(self, children: Sequence[Filter]):
        flat: list[Filter] = []
        for c in children:
            if isinstance(c, Or):
                flat.extend(c.children)
            else:
                flat.append(c)
        object.__setattr__(self, "children", tuple(flat))

    def __str__(self) -> str:
        return "(" + " OR ".join(str(c) for c in self.children) + ")"


@dataclasses.dataclass(frozen=True)
class Not(Filter):
    child: Filter

    def __str__(self) -> str:
        return f"NOT ({self.child})"


@dataclasses.dataclass(frozen=True)
class FidFilter(Filter):
    """Feature-ID filter (GeoTools Id filter)."""
    ids: tuple

    def __init__(self, ids):
        object.__setattr__(self, "ids", tuple(ids))

    def __str__(self) -> str:
        return "IN (" + ", ".join(f"'{i}'" for i in self.ids) + ")"


class CompareOp:
    EQ = "="
    NE = "<>"
    LT = "<"
    GT = ">"
    LE = "<="
    GE = ">="


@dataclasses.dataclass(frozen=True)
class Compare(Filter):
    op: str
    prop: str
    value: Any

    def __str__(self) -> str:
        v = f"'{self.value}'" if isinstance(self.value, str) else self.value
        return f"{self.prop} {self.op} {v}"


@dataclasses.dataclass(frozen=True)
class Between(Filter):
    prop: str
    lo: Any
    hi: Any

    def __str__(self) -> str:
        return f"{self.prop} BETWEEN {self.lo} AND {self.hi}"


@dataclasses.dataclass(frozen=True)
class Like(Filter):
    prop: str
    pattern: str        # SQL LIKE: % and _ wildcards
    case_sensitive: bool = True

    def __str__(self) -> str:
        op = "LIKE" if self.case_sensitive else "ILIKE"
        return f"{self.prop} {op} '{self.pattern}'"


@dataclasses.dataclass(frozen=True)
class IsNull(Filter):
    prop: str

    def __str__(self) -> str:
        return f"{self.prop} IS NULL"


@dataclasses.dataclass(frozen=True)
class InList(Filter):
    prop: str
    values: tuple

    def __init__(self, prop: str, values):
        object.__setattr__(self, "prop", prop)
        object.__setattr__(self, "values", tuple(values))

    def __str__(self) -> str:
        vals = ", ".join(f"'{v}'" if isinstance(v, str) else str(v)
                         for v in self.values)
        return f"{self.prop} IN ({vals})"


@dataclasses.dataclass(frozen=True)
class SpatialPredicate(Filter):
    prop: str
    geom: Geometry

    op_name = "?"

    def __str__(self) -> str:
        return f"{self.op_name}({self.prop}, {self.geom!r})"


class Intersects(SpatialPredicate):
    op_name = "INTERSECTS"


class Disjoint(SpatialPredicate):
    op_name = "DISJOINT"


class Contains(SpatialPredicate):
    op_name = "CONTAINS"


class Within(SpatialPredicate):
    op_name = "WITHIN"


class Touches(SpatialPredicate):
    op_name = "TOUCHES"


class Crosses(SpatialPredicate):
    op_name = "CROSSES"


class Overlaps(SpatialPredicate):
    op_name = "OVERLAPS"


class GeomEquals(SpatialPredicate):
    """EQUALS / ST_Equals: exact coordinate-sequence equality."""
    op_name = "EQUALS"


@dataclasses.dataclass(frozen=True)
class BBox(Filter):
    prop: str
    xmin: float
    ymin: float
    xmax: float
    ymax: float

    def __str__(self) -> str:
        return (f"BBOX({self.prop}, {self.xmin}, {self.ymin}, "
                f"{self.xmax}, {self.ymax})")


@dataclasses.dataclass(frozen=True)
class DWithin(Filter):
    prop: str
    geom: Geometry
    distance: float
    units: str = "meters"

    def __str__(self) -> str:
        return (f"DWITHIN({self.prop}, {self.geom!r}, "
                f"{self.distance}, {self.units})")


@dataclasses.dataclass(frozen=True)
class During(Filter):
    """dtg DURING start/end — both epoch millis, exclusive bounds per
    ECQL semantics (the reference treats DURING as exclusive)."""
    prop: str
    start: int
    end: int

    def __str__(self) -> str:
        # ISO instants: str(filter) must be re-parseable ECQL (the
        # remote client ships filters over the wire as text)
        return (f"{self.prop} DURING "
                f"{_iso(self.start)}/{_iso(self.end)}")


@dataclasses.dataclass(frozen=True)
class Before(Filter):
    prop: str
    time: int

    def __str__(self) -> str:
        return f"{self.prop} BEFORE {_iso(self.time)}"


@dataclasses.dataclass(frozen=True)
class After(Filter):
    prop: str
    time: int

    def __str__(self) -> str:
        return f"{self.prop} AFTER {_iso(self.time)}"


@dataclasses.dataclass(frozen=True)
class TEquals(Filter):
    prop: str
    time: int

    def __str__(self) -> str:
        return f"{self.prop} TEQUALS {_iso(self.time)}"
