"""Vectorized filter evaluation: Filter AST x FeatureBatch -> bool mask.

This is the exact float64 reference evaluator — the correctness oracle
for TPU kernels (differential testing) and the engine for residual
rechecks and small in-memory scans.  Equivalent in role to the
reference's FastFilterFactory-compiled evaluators running inside
KryoLazyFilterTransformIterator (accumulo/iterators/...:37), but
columnar: each node evaluates against whole columns at once.

String predicates exploit dictionary encoding: the predicate runs over
the (small) vocab, then maps through the code array — the
ArrowFilterOptimizer trick (arrow/filter/ArrowFilterOptimizer.scala:36).
"""

from __future__ import annotations

import fnmatch
import re

import numpy as np

from ..features.batch import (BoolColumn, DateColumn, FeatureBatch,
                              GeometryColumn, NumericColumn, PointColumn,
                              StringColumn)
from ..geometry import Envelope, Point
from . import ast
from .helper import dwithin_degrees, like_vocab_mask, to_millis

__all__ = ["evaluate"]


def evaluate(f: ast.Filter, batch: FeatureBatch) -> np.ndarray:
    """Evaluate filter over a batch; returns bool[n]."""
    return _eval(f, batch)


def _eval(f: ast.Filter, b: FeatureBatch) -> np.ndarray:
    n = b.n
    if isinstance(f, ast.Include):
        return np.ones(n, dtype=bool)
    if isinstance(f, ast.Exclude):
        return np.zeros(n, dtype=bool)
    if isinstance(f, ast.And):
        out = np.ones(n, dtype=bool)
        for c in f.children:
            out &= _eval(c, b)
        return out
    if isinstance(f, ast.Or):
        out = np.zeros(n, dtype=bool)
        for c in f.children:
            out |= _eval(c, b)
        return out
    if isinstance(f, ast.Not):
        return ~_eval(f.child, b)
    if isinstance(f, ast.FidFilter):
        return np.isin(b.ids.astype(str), np.asarray(f.ids, dtype=str))
    if isinstance(f, ast.Compare):
        return _compare(f, b)
    if isinstance(f, ast.Between):
        lo = ast.Compare(ast.CompareOp.GE, f.prop, f.lo)
        hi = ast.Compare(ast.CompareOp.LE, f.prop, f.hi)
        return _compare(lo, b) & _compare(hi, b)
    if isinstance(f, ast.Like):
        return _like(f, b)
    if isinstance(f, ast.IsNull):
        return ~b.col(f.prop).valid
    if isinstance(f, ast.InList):
        col = b.col(f.prop)
        if isinstance(col, StringColumn):
            codes = [col.code_of(str(v)) for v in f.values]
            codes = [c for c in codes if c >= 0]
            return np.isin(col.codes, codes) if codes else np.zeros(n, bool)
        vals = _values(col)
        return np.isin(vals, np.asarray(list(f.values))) & col.valid
    if isinstance(f, ast.BBox):
        return _bbox(f, b)
    if isinstance(f, ast.DWithin):
        return _dwithin(f, b)
    if isinstance(f, ast.SpatialPredicate):
        return _spatial(f, b)
    if isinstance(f, (ast.During, ast.Before, ast.After, ast.TEquals)):
        return _temporal(f, b)
    raise TypeError(f"cannot evaluate {type(f).__name__}")


def _values(col) -> np.ndarray:
    if isinstance(col, NumericColumn):
        return col.values
    if isinstance(col, DateColumn):
        return col.millis
    if isinstance(col, BoolColumn):
        return col.values
    raise TypeError(f"no raw values for {type(col).__name__}")


def _compare(f: ast.Compare, b: FeatureBatch) -> np.ndarray:
    col = b.col(f.prop)
    op = f.op
    if isinstance(col, StringColumn):
        # evaluate on the vocab, then map through codes
        vocab = col.vocab.astype(str)
        v = str(f.value)
        vres = {
            ast.CompareOp.EQ: vocab == v,
            ast.CompareOp.NE: vocab != v,
            ast.CompareOp.LT: vocab < v,
            ast.CompareOp.GT: vocab > v,
            ast.CompareOp.LE: vocab <= v,
            ast.CompareOp.GE: vocab >= v,
        }[op]
        ok = np.zeros(b.n, dtype=bool)
        valid = col.codes >= 0
        ok[valid] = vres[col.codes[valid]]
        return ok
    vals = _values(col)
    v = f.value
    if isinstance(col, DateColumn) and isinstance(v, str):
        v = to_millis(v)
    res = {
        ast.CompareOp.EQ: vals == v,
        ast.CompareOp.NE: vals != v,
        ast.CompareOp.LT: vals < v,
        ast.CompareOp.GT: vals > v,
        ast.CompareOp.LE: vals <= v,
        ast.CompareOp.GE: vals >= v,
    }[op]
    return res & col.valid


def _like(f: ast.Like, b: FeatureBatch) -> np.ndarray:
    col = b.col(f.prop)
    if not isinstance(col, StringColumn):
        raise TypeError("LIKE requires a string attribute")
    vocab_ok = like_vocab_mask(f.pattern, f.case_sensitive, col.vocab)
    ok = np.zeros(b.n, dtype=bool)
    valid = col.codes >= 0
    ok[valid] = vocab_ok[col.codes[valid]]
    return ok


def _geom_xy(b: FeatureBatch, prop: str):
    col = b.col(prop)
    if isinstance(col, PointColumn):
        return col.x, col.y, col.valid, None
    if isinstance(col, GeometryColumn):
        return None, None, col.valid, col
    raise TypeError(f"{prop} is not a geometry column")


def _bbox(f: ast.BBox, b: FeatureBatch) -> np.ndarray:
    x, y, valid, gc = _geom_xy(b, f.prop)
    if gc is None:
        return ((x >= f.xmin) & (x <= f.xmax)
                & (y >= f.ymin) & (y <= f.ymax) & valid)
    # bbox-vs-envelope prefilter, exact intersects per candidate
    env = Envelope(f.xmin, f.ymin, f.xmax, f.ymax)
    bx = gc.bounds
    cand = ((bx[:, 0] <= env.xmax) & (bx[:, 2] >= env.xmin)
            & (bx[:, 1] <= env.ymax) & (bx[:, 3] >= env.ymin))
    out = np.zeros(b.n, dtype=bool)
    box = env.to_polygon()
    for i in np.flatnonzero(cand):
        out[i] = gc.geoms[i] is not None and box.intersects(gc.geoms[i])
    return out


def _spatial(f: ast.SpatialPredicate, b: FeatureBatch) -> np.ndarray:
    x, y, valid, gc = _geom_xy(b, f.prop)
    g = f.geom
    if gc is None:
        # vectorized fast paths for point columns
        if isinstance(f, (ast.Intersects, ast.Within)) and hasattr(g, "contains_points"):
            return g.contains_points(x, y) & valid
        if isinstance(f, ast.Disjoint) and hasattr(g, "contains_points"):
            return ~g.contains_points(x, y) & valid
        env = g.envelope
        cand = (x >= env.xmin) & (x <= env.xmax) & (y >= env.ymin) \
            & (y <= env.ymax) & valid
        if isinstance(f, ast.Disjoint):
            out = np.ones(b.n, dtype=bool) & valid
        else:
            out = np.zeros(b.n, dtype=bool)
        for i in np.flatnonzero(cand):
            p = Point(x[i], y[i])
            out[i] = _apply_pred(f, p, g)
        return out
    out = np.zeros(b.n, dtype=bool)
    env = g.envelope
    bx = gc.bounds
    if isinstance(f, ast.Disjoint):
        cand = np.flatnonzero(valid)
    else:
        cand = np.flatnonzero(
            valid & (bx[:, 0] <= env.xmax) & (bx[:, 2] >= env.xmin)
            & (bx[:, 1] <= env.ymax) & (bx[:, 3] >= env.ymin))
    for i in cand:
        out[i] = _apply_pred(f, gc.geoms[i], g)
    return out


def _apply_pred(f: ast.SpatialPredicate, feature_geom, query_geom) -> bool:
    if isinstance(f, ast.Intersects):
        return feature_geom.intersects(query_geom)
    if isinstance(f, ast.Disjoint):
        return not feature_geom.intersects(query_geom)
    if isinstance(f, ast.Contains):
        # ECQL CONTAINS(attr, g): the feature geometry contains g
        return feature_geom.contains(query_geom)
    if isinstance(f, ast.Within):
        return query_geom.contains(feature_geom)
    # DE-9IM-derived predicates (full JTS semantics; the envelope
    # prefilter above already rejected the cheap negatives)
    from ..geometry import relate as _rel
    if isinstance(f, ast.Touches):
        return _rel.touches(feature_geom, query_geom)
    if isinstance(f, ast.GeomEquals):
        return _rel.topo_equals(feature_geom, query_geom)
    if isinstance(f, ast.Crosses):
        return _rel.crosses(feature_geom, query_geom)
    if isinstance(f, ast.Overlaps):
        return _rel.overlaps(feature_geom, query_geom)
    raise TypeError(type(f).__name__)


def _dwithin(f: ast.DWithin, b: FeatureBatch) -> np.ndarray:
    deg = dwithin_degrees(f.geom, f.distance, f.units)
    x, y, valid, gc = _geom_xy(b, f.prop)
    if gc is None and isinstance(f.geom, Point):
        dx = x - f.geom.x
        dy = y - f.geom.y
        return (dx * dx + dy * dy <= deg * deg) & valid
    env = f.geom.envelope.buffer(deg)
    out = np.zeros(b.n, dtype=bool)
    if gc is None:
        cand = np.flatnonzero((x >= env.xmin) & (x <= env.xmax)
                              & (y >= env.ymin) & (y <= env.ymax) & valid)
        for i in cand:
            out[i] = Point(x[i], y[i]).dwithin(f.geom, deg)
    else:
        bx = gc.bounds
        cand = np.flatnonzero(
            valid & (bx[:, 0] <= env.xmax) & (bx[:, 2] >= env.xmin)
            & (bx[:, 1] <= env.ymax) & (bx[:, 3] >= env.ymin))
        for i in cand:
            out[i] = gc.geoms[i].dwithin(f.geom, deg)
    return out


def _temporal(f, b: FeatureBatch) -> np.ndarray:
    col = b.col(f.prop)
    if not isinstance(col, DateColumn):
        raise TypeError(f"{f.prop} is not a date column")
    ms = col.millis
    if isinstance(f, ast.During):
        return (ms > f.start) & (ms < f.end) & col.valid
    if isinstance(f, ast.Before):
        return (ms < f.time) & col.valid
    if isinstance(f, ast.After):
        return (ms > f.time) & col.valid
    if isinstance(f, ast.TEquals):
        return (ms == f.time) & col.valid
    raise TypeError(type(f).__name__)
