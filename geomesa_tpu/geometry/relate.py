"""DE-9IM topological relationships.

The reference gets `relate` and the derived predicates (touches,
crosses, overlaps, equals, covers) from JTS's full topology graph
(geomesa-spark-sql ST_Relate -> JTS RelateOp). This is an independent
implementation on the split-and-classify scheme:

1. decompose each geometry into *carriers* — points (dim 0), segments
   (dim 1 interiors / dim 2 ring boundaries) — plus the mod-2 boundary
   point set for lines;
2. split every segment of A at its intersections with B's segments (and
   vice versa), so no piece crosses the other geometry's boundary;
3. classify each piece midpoint / split point / boundary point against
   the other geometry (Interior / Boundary / Exterior) and max the
   piece dimension into the matching matrix cell;
4. area-vs-area cells (II / IE / EI for polygons) follow from which
   side of a boundary piece lies where, with a representative interior
   point as the shared-boundary fallback (equal or nested polygons).

Exactness bounds: classification uses the same f64 orientation tests as
the rest of the geometry module — coordinates well below f64 epsilon of
each other can misclassify, the usual non-robust-arithmetic caveat.

Matrix order is JTS's: II IB IE / BI BB BE / EI EB EE over rows = A's
interior/boundary/exterior, columns = B's.
"""

from __future__ import annotations

import numpy as np

from .base import (Geometry, LineString, MultiLineString, MultiPoint,
                   MultiPolygon, Point, Polygon, _on_segment,
                   _ring_contains)

__all__ = ["relate", "relate_matches", "touches", "crosses", "overlaps",
           "topo_equals", "covers", "covered_by", "interior_point"]

_EPS = 1e-12


# -- decomposition ---------------------------------------------------------

def _dim(g: Geometry) -> int:
    if isinstance(g, (Point, MultiPoint)):
        return 0
    if isinstance(g, (LineString, MultiLineString)):
        return 1
    return 2


def _points_of(g) -> list[tuple[float, float]]:
    if isinstance(g, Point):
        return [(float(g.x), float(g.y))]
    return [(float(p.x), float(p.y)) for p in g.parts]


def _lines_of(g) -> list[np.ndarray]:
    if isinstance(g, LineString):
        return [g.coords]
    return [p.coords for p in g.parts]


def _rings_of(g) -> list[np.ndarray]:
    if isinstance(g, Polygon):
        return [g.shell] + list(g.holes)
    out = []
    for p in g.parts:
        out.extend([p.shell] + list(p.holes))
    return out


def _segments(coords_list) -> list[tuple]:
    segs = []
    for c in coords_list:
        for i in range(len(c) - 1):
            a, b = c[i], c[i + 1]
            if a[0] != b[0] or a[1] != b[1]:
                segs.append((float(a[0]), float(a[1]),
                             float(b[0]), float(b[1])))
    return segs


def _line_boundary(g) -> set:
    """Mod-2 rule over ALL parts: endpoints used an odd number of times
    are boundary (a shared junction of two lines is interior)."""
    counts: dict[tuple, int] = {}
    for c in _lines_of(g):
        if len(c) < 2:
            continue
        if (c[0][0], c[0][1]) == (c[-1][0], c[-1][1]):
            continue  # closed ring: no boundary
        for p in ((float(c[0][0]), float(c[0][1])),
                  (float(c[-1][0]), float(c[-1][1]))):
            counts[p] = counts.get(p, 0) + 1
    return {p for p, k in counts.items() if k % 2 == 1}


# -- point location --------------------------------------------------------

def _on_any_segment(segs, x, y) -> bool:
    for (x0, y0, x1, y1) in segs:
        if bool(_on_segment(np.float64(x0), np.float64(y0),
                            np.float64(x1), np.float64(y1),
                            np.float64(x), np.float64(y))):
            return True
    return False


def _locate(g: Geometry, x: float, y: float) -> str:
    """'I' / 'B' / 'E' location of the point in g's topology."""
    if isinstance(g, (Point, MultiPoint)):
        for (px, py) in _points_of(g):
            if px == x and py == y:
                return "I"
        return "E"
    if isinstance(g, (LineString, MultiLineString)):
        if not _on_any_segment(_segments(_lines_of(g)), x, y):
            return "E"
        return "B" if (x, y) in _line_boundary(g) else "I"
    # polygonal
    polys = [g] if isinstance(g, Polygon) else list(g.parts)
    on_ring = False
    for p in polys:
        if _on_any_segment(_segments([p.shell] + list(p.holes)), x, y):
            on_ring = True
            continue
        if bool(p.contains_points(np.float64(x), np.float64(y))):
            return "I"
    return "B" if on_ring else "E"


def interior_point(g) -> tuple[float, float] | None:
    """A point strictly inside a polygonal geometry (scanline between
    crossing pairs; centroid fast path)."""
    polys = [g] if isinstance(g, Polygon) else list(g.parts)
    for p in polys:
        if p.is_empty or p.area == 0:
            continue
        c = p.centroid
        if _locate(p, float(c.x), float(c.y)) == "I":
            return (float(c.x), float(c.y))
        ys = np.unique(np.concatenate(
            [r[:, 1] for r in [p.shell] + list(p.holes)]))
        for j in range(len(ys) - 1):
            ymid = (ys[j] + ys[j + 1]) / 2.0
            xs = []
            for r in [p.shell] + list(p.holes):
                y0, y1 = r[:-1, 1], r[1:, 1]
                x0, x1 = r[:-1, 0], r[1:, 0]
                m = ((y0 <= ymid) & (y1 > ymid)) | ((y1 <= ymid)
                                                    & (y0 > ymid))
                if m.any():
                    t = (ymid - y0[m]) / (y1[m] - y0[m])
                    xs.extend((x0[m] + t * (x1[m] - x0[m])).tolist())
            xs.sort()
            for a, b in zip(xs[::2], xs[1::2]):
                if b - a > _EPS:
                    xm = (a + b) / 2.0
                    if _locate(p, xm, ymid) == "I":
                        return (xm, ymid)
    return None


# -- segment splitting -----------------------------------------------------

def _split_params(ax0, ay0, ax1, ay1, segs_b,
                  pts_b=()) -> list[float]:
    """Parameters t in (0, 1) where segment a meets any segment of b
    (proper crossings, endpoint touches, collinear overlap ends) or
    passes through an isolated point of b."""
    ts: list[float] = []
    adx, ady = ax1 - ax0, ay1 - ay0
    alen2 = adx * adx + ady * ady
    if alen2 == 0:
        return ts
    for (px, py) in pts_b:
        if bool(_on_segment(np.float64(ax0), np.float64(ay0),
                            np.float64(ax1), np.float64(ay1),
                            np.float64(px), np.float64(py))):
            t = ((px - ax0) * adx + (py - ay0) * ady) / alen2
            if _EPS < t < 1 - _EPS:
                ts.append(float(t))
    for (bx0, by0, bx1, by1) in segs_b:
        bdx, bdy = bx1 - bx0, by1 - by0
        denom = adx * bdy - ady * bdx
        if denom != 0:
            # proper / touching intersection of the supporting lines
            t = ((bx0 - ax0) * bdy - (by0 - ay0) * bdx) / denom
            u = ((bx0 - ax0) * ady - (by0 - ay0) * adx) / denom
            if -_EPS <= t <= 1 + _EPS and -_EPS <= u <= 1 + _EPS:
                if _EPS < t < 1 - _EPS:
                    ts.append(float(t))
        else:
            # parallel: collinear overlap contributes b's endpoints
            cross = (bx0 - ax0) * ady - (by0 - ay0) * adx
            if abs(cross) > _EPS * max(1.0, alen2):
                continue
            for (px, py) in ((bx0, by0), (bx1, by1)):
                t = ((px - ax0) * adx + (py - ay0) * ady) / alen2
                if _EPS < t < 1 - _EPS:
                    ts.append(float(t))
    return ts


def _pieces(segs_a, segs_b, pts_b=()):
    """Split A's segments at B intersections (and at B's isolated
    points); yield (midx, midy) per piece, every split point, and the
    original shared vertices (carrier endpoints — a touch exactly at a
    vertex produces no in-segment split, so vertices classify
    separately)."""
    mids, cuts, verts = [], [], []
    for (x0, y0, x1, y1) in segs_a:
        ts = sorted(set([0.0, 1.0] + _split_params(x0, y0, x1, y1,
                                                   segs_b, pts_b)))
        for t0, t1 in zip(ts[:-1], ts[1:]):
            tm = (t0 + t1) / 2.0
            mids.append((x0 + tm * (x1 - x0), y0 + tm * (y1 - y0)))
        for t in ts[1:-1]:
            cuts.append((x0 + t * (x1 - x0), y0 + t * (y1 - y0)))
        verts.append((x0, y0))
        verts.append((x1, y1))
    return mids, cuts, verts


# -- the matrix ------------------------------------------------------------

_IDX = {"I": 0, "B": 1, "E": 2}


class _Matrix:
    def __init__(self):
        self.m = [[-1] * 3 for _ in range(3)]  # -1 = F

    def up(self, row: str, col: str, d: int):
        r, c = _IDX[row], _IDX[col]
        if d > self.m[r][c]:
            self.m[r][c] = d

    def __str__(self):
        return "".join("F" if v < 0 else str(v)
                       for row in self.m for v in row)


def _classify_into(mat: _Matrix, g_other: Geometry, row: str,
                   mids, cuts, dim_piece: int, transpose: bool):
    """Pieces of region `row` of one geometry located against the
    other; `transpose` swaps (row, col) for the B-against-A pass."""
    for (x, y) in mids:
        loc = _locate(g_other, x, y)
        if transpose:
            mat.up(loc, row, dim_piece)
        else:
            mat.up(row, loc, dim_piece)
    for (x, y) in cuts:
        loc = _locate(g_other, x, y)
        if transpose:
            mat.up(loc, row, 0)
        else:
            mat.up(row, loc, 0)


def relate(a: Geometry, b: Geometry) -> str:
    """The DE-9IM matrix of a vs b as a 9-character string."""
    mat = _Matrix()
    mat.up("E", "E", 2)
    da, db = _dim(a), _dim(b)
    a_empty, b_empty = a.is_empty, b.is_empty
    if a_empty or b_empty:
        if not b_empty:
            mat.up("E", "I", db)
            mat.up("E", "B", db - 1 if db else -1)
            if db == 1 and _line_boundary(b):
                mat.up("E", "B", 0)
        if not a_empty:
            mat.up("I", "E", da)
            if da == 1 and _line_boundary(a):
                mat.up("B", "E", 0)
            if da == 2:
                mat.up("B", "E", 1)
        return str(mat)

    segs_a = (_segments(_lines_of(a)) if da == 1
              else _segments(_rings_of(a)) if da == 2 else [])
    segs_b = (_segments(_lines_of(b)) if db == 1
              else _segments(_rings_of(b)) if db == 2 else [])

    pts_a = _points_of(a) if da == 0 else ()
    pts_b = _points_of(b) if db == 0 else ()

    # pass 1: A's carriers against B
    if da == 0:
        for (x, y) in _points_of(a):
            mat.up("I", _locate(b, x, y), 0)
    else:
        mids, cuts, verts = _pieces(segs_a, segs_b, pts_b)
        row = "I" if da == 1 else "B"
        if da == 1:
            bnd = _line_boundary(a)
            cuts = cuts + [v for v in verts if v not in bnd]
            for (x, y) in bnd:
                mat.up("B", _locate(b, x, y), 0)
        else:
            cuts = cuts + verts  # ring vertices are boundary points
        _classify_into(mat, b, row, mids, cuts, 1, transpose=False)

    # pass 2: B's carriers against A (fills columns incl. the E row)
    if db == 0:
        for (x, y) in _points_of(b):
            mat.up(_locate(a, x, y), "I", 0)
    else:
        mids, cuts, verts = _pieces(segs_b, segs_a, pts_a)
        col = "I" if db == 1 else "B"
        if db == 1:
            bnd = _line_boundary(b)
            cuts = cuts + [v for v in verts if v not in bnd]
            for (x, y) in bnd:
                mat.up(_locate(a, x, y), "B", 0)
        else:
            cuts = cuts + verts
        _classify_into(mat, a, col, mids, cuts, 1, transpose=True)

    # area cells: a boundary piece strictly inside the other polygon
    # has that polygon's interior on both of ITS sides
    if da == 2:
        # B's view of A's interior
        if db == 2:
            if mat.m[1][0] > -1:      # B(A) piece met I(B)
                mat.up("I", "I", 2)
                mat.up("E", "I", 2)
            if mat.m[1][2] > -1:      # B(A) piece met E(B)
                mat.up("I", "E", 2)
            if mat.m[0][1] > -1:      # B(B) piece met I(A)
                mat.up("I", "I", 2)
                mat.up("I", "E", 2)
            if mat.m[2][1] > -1:      # B(B) piece met E(A)
                mat.up("E", "I", 2)
            # shared-boundary fallback (equal / nested polygons)
            if mat.m[0][0] < 2:
                ip = interior_point(a)
                if ip is not None:
                    loc = _locate(b, *ip)
                    # Int(A)∩Bnd(B) is a subset of a boundary — at most
                    # 1-dimensional; only the I/E columns can carry 2
                    mat.up("I", loc, 2 if loc != "B" else 1)
                ipb = interior_point(b)
                if ipb is not None:
                    loc = _locate(a, *ipb)
                    if loc == "I":
                        mat.up("I", "I", 2)
                    elif loc == "E":
                        mat.up("E", "I", 2)
        else:
            # lower-dimensional B can never cover a 2-D interior
            mat.up("I", "E", 2)
            if db == 1 and mat.m[0][0] < 0:
                # line piece through I(A) classified in pass 2 already;
                # nothing to do — entry stays as computed
                pass
    if db == 2 and da < 2:
        mat.up("E", "I", 2)
    if da == 2 and db == 2:
        # boundaries always leave SOMETHING exterior on a bounded plane
        pass
    return str(mat)


def relate_matches(matrix: str, pattern: str) -> bool:
    """JTS IntersectionMatrix.matches: 'T' = any non-F, '*' = any,
    'F'/'0'/'1'/'2' exact."""
    for mchar, pchar in zip(matrix, pattern):
        if pchar == "*":
            continue
        if pchar == "T":
            if mchar == "F":
                return False
        elif mchar != pchar:
            return False
    return True


# -- derived predicates (SQLSpatialFunctions semantics via JTS) ------------

def touches(a: Geometry, b: Geometry) -> bool:
    m = relate(a, b)
    return any(relate_matches(m, p)
               for p in ("FT*******", "F**T*****", "F***T****"))


def crosses(a: Geometry, b: Geometry) -> bool:
    m = relate(a, b)
    da, db = _dim(a), _dim(b)
    if da < db:
        return relate_matches(m, "T*T******")
    if da > db:
        return relate_matches(m, "T*****T**")
    if da == 1 and db == 1:
        return m[0] == "0"
    return False


def overlaps(a: Geometry, b: Geometry) -> bool:
    m = relate(a, b)
    da, db = _dim(a), _dim(b)
    if da != db:
        return False
    if da == 1:
        return relate_matches(m, "1*T***T**")
    return relate_matches(m, "T*T***T**")


def topo_equals(a: Geometry, b: Geometry) -> bool:
    return relate_matches(relate(a, b), "T*F**FFF*")


def covers(a: Geometry, b: Geometry) -> bool:
    m = relate(a, b)
    return any(relate_matches(m, p)
               for p in ("T*****FF*", "*T****FF*", "***T**FF*",
                         "****T*FF*"))


def covered_by(a: Geometry, b: Geometry) -> bool:
    return covers(b, a)
