"""Numpy-backed geometry classes with exact float64 predicates.

A lean replacement for the slice of JTS the reference actually uses in
its hot paths (FilterHelper geometry extraction, residual predicate
evaluation, density/knn/tube processes): envelopes, point-in-polygon,
intersects/contains/within/disjoint, distance, centroid, area, length,
convex hull, simple buffering for DWithin.

Coordinates are (n, 2) float64 arrays. Polygons follow the shell+holes
model; no topology validation beyond ring closure (matching lenient JTS
usage in the reference).
"""

from __future__ import annotations

import functools

import numpy as np

__all__ = ["Envelope", "Geometry", "Point", "LineString", "Polygon",
           "MultiPoint", "MultiLineString", "MultiPolygon",
           "GeometryCollection", "WHOLE_WORLD"]


class Envelope:
    """Axis-aligned bounding box [xmin, xmax] x [ymin, ymax]."""

    __slots__ = ("xmin", "ymin", "xmax", "ymax")

    def __init__(self, xmin: float, ymin: float, xmax: float, ymax: float):
        self.xmin = float(xmin)
        self.ymin = float(ymin)
        self.xmax = float(xmax)
        self.ymax = float(ymax)

    @classmethod
    def empty(cls) -> "Envelope":
        return cls(np.inf, np.inf, -np.inf, -np.inf)

    @property
    def is_empty(self) -> bool:
        return self.xmin > self.xmax or self.ymin > self.ymax

    def expand(self, other: "Envelope") -> "Envelope":
        return Envelope(min(self.xmin, other.xmin), min(self.ymin, other.ymin),
                        max(self.xmax, other.xmax), max(self.ymax, other.ymax))

    def intersects(self, other: "Envelope") -> bool:
        return not (self.xmax < other.xmin or other.xmax < self.xmin
                    or self.ymax < other.ymin or other.ymax < self.ymin)

    def contains_env(self, other: "Envelope") -> bool:
        return (self.xmin <= other.xmin and self.xmax >= other.xmax
                and self.ymin <= other.ymin and self.ymax >= other.ymax)

    def contains_point(self, x: float, y: float) -> bool:
        return self.xmin <= x <= self.xmax and self.ymin <= y <= self.ymax

    def intersection(self, other: "Envelope") -> "Envelope":
        return Envelope(max(self.xmin, other.xmin), max(self.ymin, other.ymin),
                        min(self.xmax, other.xmax), min(self.ymax, other.ymax))

    def buffer(self, d: float) -> "Envelope":
        return Envelope(self.xmin - d, self.ymin - d, self.xmax + d, self.ymax + d)

    def to_polygon(self) -> "Polygon":
        return Polygon(np.array([[self.xmin, self.ymin], [self.xmax, self.ymin],
                                 [self.xmax, self.ymax], [self.xmin, self.ymax],
                                 [self.xmin, self.ymin]]))

    def as_tuple(self) -> tuple[float, float, float, float]:
        return (self.xmin, self.ymin, self.xmax, self.ymax)

    def __eq__(self, o) -> bool:
        return (isinstance(o, Envelope) and self.as_tuple() == o.as_tuple())

    def __hash__(self) -> int:
        return hash(self.as_tuple())

    def __repr__(self) -> str:
        return f"Envelope({self.xmin}, {self.ymin}, {self.xmax}, {self.ymax})"


# -- low-level predicates (exact f64, vectorized) --------------------------

def _ring_contains(ring: np.ndarray, x, y):
    """Crossing-number point-in-ring test; boundary counts as inside.
    ring: (n, 2) closed; x/y scalars or arrays."""
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    x0, y0 = ring[:-1, 0], ring[:-1, 1]
    x1, y1 = ring[1:, 0], ring[1:, 1]
    # boundary test: point on any segment
    on = _on_segment(x0, y0, x1, y1, x[..., None], y[..., None]).any(axis=-1)
    cond = (y0 > y[..., None]) != (y1 > y[..., None])
    with np.errstate(divide="ignore", invalid="ignore"):
        xcross = x0 + (y[..., None] - y0) * (x1 - x0) / (y1 - y0)
    inside = (cond & (x[..., None] < xcross)).sum(axis=-1) % 2 == 1
    return inside | on


def _on_segment(x0, y0, x1, y1, px, py):
    """True where (px,py) lies exactly on segment (x0,y0)-(x1,y1)."""
    cross = (x1 - x0) * (py - y0) - (y1 - y0) * (px - x0)
    within_x = (np.minimum(x0, x1) <= px) & (px <= np.maximum(x0, x1))
    within_y = (np.minimum(y0, y1) <= py) & (py <= np.maximum(y0, y1))
    return (cross == 0) & within_x & within_y


def _segments_intersect(a0, a1, b0, b1) -> bool:
    """Exact segment-pair intersection (scalar, orientation-based)."""
    def orient(p, q, r):
        return np.sign((q[0] - p[0]) * (r[1] - p[1]) - (q[1] - p[1]) * (r[0] - p[0]))
    o1, o2 = orient(a0, a1, b0), orient(a0, a1, b1)
    o3, o4 = orient(b0, b1, a0), orient(b0, b1, a1)
    if o1 != o2 and o3 != o4:
        return True
    def between(p, q, r):
        return (min(p[0], q[0]) <= r[0] <= max(p[0], q[0])
                and min(p[1], q[1]) <= r[1] <= max(p[1], q[1]))
    return ((o1 == 0 and between(a0, a1, b0)) or (o2 == 0 and between(a0, a1, b1))
            or (o3 == 0 and between(b0, b1, a0)) or (o4 == 0 and between(b0, b1, a1)))


def _segseg_any_intersection(ca: np.ndarray, cb: np.ndarray) -> bool:
    """Vectorized: does any segment of polyline ca intersect any of cb?"""
    if len(ca) < 2 or len(cb) < 2:
        return False
    a0 = ca[:-1][:, None, :]  # (na, 1, 2)
    a1 = ca[1:][:, None, :]
    b0 = cb[:-1][None, :, :]  # (1, nb, 2)
    b1 = cb[1:][None, :, :]

    def orient(p, q, r):
        return np.sign((q[..., 0] - p[..., 0]) * (r[..., 1] - p[..., 1])
                       - (q[..., 1] - p[..., 1]) * (r[..., 0] - p[..., 0]))
    o1 = orient(a0, a1, b0)
    o2 = orient(a0, a1, b1)
    o3 = orient(b0, b1, a0)
    o4 = orient(b0, b1, a1)
    proper = (o1 != o2) & (o3 != o4)

    def between(p, q, r):
        return ((np.minimum(p[..., 0], q[..., 0]) <= r[..., 0])
                & (r[..., 0] <= np.maximum(p[..., 0], q[..., 0]))
                & (np.minimum(p[..., 1], q[..., 1]) <= r[..., 1])
                & (r[..., 1] <= np.maximum(p[..., 1], q[..., 1])))
    touch = (((o1 == 0) & between(a0, a1, b0)) | ((o2 == 0) & between(a0, a1, b1))
             | ((o3 == 0) & between(b0, b1, a0)) | ((o4 == 0) & between(b0, b1, a1)))
    return bool((proper | touch).any())


def _point_segments_dist2(px, py, coords: np.ndarray):
    """Min squared distance from point(s) to polyline segments."""
    x0, y0 = coords[:-1, 0], coords[:-1, 1]
    dx, dy = np.diff(coords[:, 0]), np.diff(coords[:, 1])
    len2 = dx * dx + dy * dy
    px = np.asarray(px, dtype=np.float64)[..., None]
    py = np.asarray(py, dtype=np.float64)[..., None]
    with np.errstate(divide="ignore", invalid="ignore"):
        t = ((px - x0) * dx + (py - y0) * dy) / len2
    t = np.where(len2 == 0, 0.0, np.clip(t, 0.0, 1.0))
    cx, cy = x0 + t * dx, y0 + t * dy
    d2 = (px - cx) ** 2 + (py - cy) ** 2
    return d2.min(axis=-1)


# -- geometry classes ------------------------------------------------------

class Geometry:
    """Base geometry; subclasses hold numpy coordinate arrays."""

    geom_type: str = "Geometry"

    @property
    def envelope(self) -> Envelope:
        raise NotImplementedError

    @property
    def is_empty(self) -> bool:
        raise NotImplementedError

    def coords_list(self) -> list[np.ndarray]:
        """All constituent coordinate arrays (for packed buffers)."""
        raise NotImplementedError

    # spatial predicates (exact, host f64)
    def intersects(self, other: "Geometry") -> bool:
        if not self.envelope.intersects(other.envelope):
            return False
        return _intersects(self, other)

    def disjoint(self, other: "Geometry") -> bool:
        return not self.intersects(other)

    def contains(self, other: "Geometry") -> bool:
        if not self.envelope.contains_env(other.envelope):
            return False
        return _contains(self, other)

    def within(self, other: "Geometry") -> bool:
        return other.contains(self)

    def distance(self, other: "Geometry") -> float:
        return _distance(self, other)

    def dwithin(self, other: "Geometry", d: float) -> bool:
        if not self.envelope.buffer(d).intersects(other.envelope):
            return False
        return self.distance(other) <= d

    @property
    def area(self) -> float:
        return 0.0

    @property
    def length(self) -> float:
        return 0.0

    @property
    def centroid(self) -> "Point":
        env = self.envelope
        return Point((env.xmin + env.xmax) / 2, (env.ymin + env.ymax) / 2)

    def __eq__(self, other) -> bool:
        if not isinstance(other, Geometry) or self.geom_type != other.geom_type:
            return False
        a, b = self.coords_list(), other.coords_list()
        return (len(a) == len(b)
                and all(x.shape == y.shape and bool(np.all(x == y))
                        for x, y in zip(a, b)))

    def __hash__(self) -> int:
        return hash((self.geom_type,
                     tuple(tuple(map(tuple, c)) for c in self.coords_list())))

    def __repr__(self) -> str:
        from .wkt import to_wkt
        return to_wkt(self)


class Point(Geometry):
    geom_type = "Point"

    def __init__(self, x: float, y: float):
        self.x = float(x)
        self.y = float(y)

    @property
    def envelope(self) -> Envelope:
        return Envelope(self.x, self.y, self.x, self.y)

    @property
    def is_empty(self) -> bool:
        return np.isnan(self.x)

    def coords_list(self) -> list[np.ndarray]:
        return [np.array([[self.x, self.y]])]

    @property
    def centroid(self) -> "Point":
        return self


class LineString(Geometry):
    geom_type = "LineString"

    def __init__(self, coords):
        self.coords = np.asarray(coords, dtype=np.float64).reshape(-1, 2)

    @functools.cached_property
    def envelope(self) -> Envelope:
        # cached: coordinates are treated as immutable
        if len(self.coords) == 0:
            return Envelope.empty()
        return Envelope(self.coords[:, 0].min(), self.coords[:, 1].min(),
                        self.coords[:, 0].max(), self.coords[:, 1].max())

    @property
    def is_empty(self) -> bool:
        return len(self.coords) == 0

    def coords_list(self) -> list[np.ndarray]:
        return [self.coords]

    @property
    def length(self) -> float:
        if len(self.coords) < 2:
            return 0.0
        return float(np.sqrt((np.diff(self.coords, axis=0) ** 2).sum(axis=1)).sum())

    @property
    def centroid(self) -> Point:
        if len(self.coords) == 1:
            return Point(*self.coords[0])
        seg = np.diff(self.coords, axis=0)
        w = np.sqrt((seg ** 2).sum(axis=1))
        mid = (self.coords[:-1] + self.coords[1:]) / 2
        if w.sum() == 0:
            return Point(*self.coords.mean(axis=0))
        c = (mid * w[:, None]).sum(axis=0) / w.sum()
        return Point(*c)


class Polygon(Geometry):
    geom_type = "Polygon"

    def __init__(self, shell, holes=None):
        shell = np.asarray(shell, dtype=np.float64).reshape(-1, 2)
        if len(shell) > 0 and not np.array_equal(shell[0], shell[-1]):
            shell = np.vstack([shell, shell[:1]])  # close the ring
        self.shell = shell
        self.holes = [np.asarray(h, dtype=np.float64).reshape(-1, 2)
                      for h in (holes or [])]
        self.holes = [np.vstack([h, h[:1]]) if len(h) > 0
                      and not np.array_equal(h[0], h[-1]) else h
                      for h in self.holes]

    @functools.cached_property
    def envelope(self) -> Envelope:
        # cached: the shell is treated as immutable
        if len(self.shell) == 0:
            return Envelope.empty()
        return Envelope(self.shell[:, 0].min(), self.shell[:, 1].min(),
                        self.shell[:, 0].max(), self.shell[:, 1].max())

    @property
    def is_empty(self) -> bool:
        return len(self.shell) == 0

    def coords_list(self) -> list[np.ndarray]:
        return [self.shell] + self.holes

    def contains_points(self, x, y):
        """Vectorized point-in-polygon (boundary-inclusive)."""
        inside = _ring_contains(self.shell, x, y)
        for h in self.holes:
            on_boundary = _on_segment(h[:-1, 0], h[:-1, 1], h[1:, 0], h[1:, 1],
                                      np.asarray(x, np.float64)[..., None],
                                      np.asarray(y, np.float64)[..., None]).any(axis=-1)
            inside &= ~(_ring_contains(h, x, y) & ~on_boundary)
        return inside

    @property
    def area(self) -> float:
        def ring_area(r):
            if len(r) < 3:
                return 0.0
            x, y = r[:, 0], r[:, 1]
            return 0.5 * float(np.dot(x[:-1], y[1:]) - np.dot(x[1:], y[:-1]))
        a = abs(ring_area(self.shell))
        for h in self.holes:
            a -= abs(ring_area(h))
        return a

    @property
    def length(self) -> float:
        return float(sum(np.sqrt((np.diff(r, axis=0) ** 2).sum(axis=1)).sum()
                         for r in self.coords_list()))

    @property
    def centroid(self) -> Point:
        r = self.shell
        if len(r) < 4:
            return Point(*r[:max(len(r) - 1, 1)].mean(axis=0))
        x, y = r[:-1, 0], r[:-1, 1]
        x1, y1 = r[1:, 0], r[1:, 1]
        cross = x * y1 - x1 * y
        a = cross.sum() / 2.0
        if a == 0:
            return Point(*r[:-1].mean(axis=0))
        cx = ((x + x1) * cross).sum() / (6 * a)
        cy = ((y + y1) * cross).sum() / (6 * a)
        return Point(cx, cy)


class _Multi(Geometry):
    part_type: type = Geometry

    def __init__(self, parts):
        self.parts = list(parts)

    @functools.cached_property
    def envelope(self) -> Envelope:
        # cached: parts are treated as immutable
        env = Envelope.empty()
        for p in self.parts:
            env = env.expand(p.envelope)
        return env

    @property
    def is_empty(self) -> bool:
        return all(p.is_empty for p in self.parts)

    def coords_list(self) -> list[np.ndarray]:
        return [c for p in self.parts for c in p.coords_list()]

    @property
    def area(self) -> float:
        return float(sum(p.area for p in self.parts))

    @property
    def length(self) -> float:
        return float(sum(p.length for p in self.parts))


class MultiPoint(_Multi):
    geom_type = "MultiPoint"
    part_type = Point


class MultiLineString(_Multi):
    geom_type = "MultiLineString"
    part_type = LineString


class MultiPolygon(_Multi):
    geom_type = "MultiPolygon"
    part_type = Polygon

    def contains_points(self, x, y):
        out = np.zeros(np.shape(np.asarray(x)), dtype=bool)
        for p in self.parts:
            out |= p.contains_points(x, y)
        return out


class GeometryCollection(_Multi):
    geom_type = "GeometryCollection"


WHOLE_WORLD = Polygon(np.array([[-180.0, -90.0], [180.0, -90.0],
                                [180.0, 90.0], [-180.0, 90.0],
                                [-180.0, -90.0]]))


# -- dispatching binary predicates ----------------------------------------

def _parts_of(g: Geometry) -> list[Geometry]:
    """Recursively flatten Multi*/GeometryCollection to simple parts."""
    if isinstance(g, _Multi):
        return [s for p in g.parts for s in _parts_of(p)]
    return [g]


def _intersects(a: Geometry, b: Geometry) -> bool:
    for pa in _parts_of(a):
        for pb in _parts_of(b):
            if pa.envelope.intersects(pb.envelope) and _intersects_simple(pa, pb):
                return True
    return False


def _intersects_simple(a: Geometry, b: Geometry) -> bool:
    # order by complexity: Point < LineString < Polygon
    rank = {"Point": 0, "LineString": 1, "Polygon": 2}
    if rank.get(b.geom_type, 3) < rank.get(a.geom_type, 3):
        a, b = b, a
    if isinstance(a, Point):
        if isinstance(b, Point):
            return a.x == b.x and a.y == b.y
        if isinstance(b, LineString):
            return bool(_point_segments_dist2(a.x, a.y, b.coords) == 0)
        if isinstance(b, Polygon):
            return bool(b.contains_points(a.x, a.y))
    if isinstance(a, LineString):
        if isinstance(b, LineString):
            return _segseg_any_intersection(a.coords, b.coords)
        if isinstance(b, Polygon):
            if bool(b.contains_points(a.coords[:, 0], a.coords[:, 1]).any()):
                return True
            return any(_segseg_any_intersection(a.coords, r)
                       for r in b.coords_list())
    if isinstance(a, Polygon) and isinstance(b, Polygon):
        if bool(a.contains_points(b.shell[:, 0], b.shell[:, 1]).any()):
            return True
        if bool(b.contains_points(a.shell[:, 0], a.shell[:, 1]).any()):
            return True
        # all rings matter: a hole boundary of one can cross the other
        return any(_segseg_any_intersection(ra, rb)
                   for ra in a.coords_list() for rb in b.coords_list())
    raise TypeError(f"unsupported intersects: {a.geom_type}/{b.geom_type}")


def _contains(a: Geometry, b: Geometry) -> bool:
    """a contains b (boundary-inclusive 'covers' semantics for points;
    the residual-filter layer applies strict JTS contains where needed)."""
    if isinstance(a, (Polygon, MultiPolygon)):
        pts = np.vstack(b.coords_list())
        if not bool(a.contains_points(pts[:, 0], pts[:, 1]).all()):
            return False
        # vertices inside; for lines/polys also require no boundary crossing
        if isinstance(b, (Point, MultiPoint)):
            return True
        for ring in ([r for p in _parts_of(a) for r in p.coords_list()]):
            for cb in b.coords_list():
                if _segseg_any_intersection(ring, cb):
                    # touching is allowed only if all of b stays inside;
                    # approximate via midpoint sampling of b's segments
                    mids = (cb[:-1] + cb[1:]) / 2
                    if not bool(a.contains_points(mids[:, 0], mids[:, 1]).all()):
                        return False
        return True
    if isinstance(a, Point):
        return isinstance(b, Point) and a.x == b.x and a.y == b.y
    if isinstance(a, LineString):
        pts = np.vstack(b.coords_list())
        return bool((_point_segments_dist2(pts[:, 0], pts[:, 1], a.coords) == 0).all())
    if isinstance(a, _Multi):
        return all(any(pa.contains(pb) for pa in a.parts) for pb in _parts_of(b))
    raise TypeError(f"unsupported contains: {a.geom_type}/{b.geom_type}")


def _distance(a: Geometry, b: Geometry) -> float:
    if a.intersects(b):
        return 0.0
    best = np.inf
    for pa in _parts_of(a):
        for pb in _parts_of(b):
            best = min(best, _distance_simple(pa, pb))
    return float(best)


def _distance_simple(a: Geometry, b: Geometry) -> float:
    def as_coords(g):
        return np.vstack(g.coords_list())
    if isinstance(a, Point) and isinstance(b, Point):
        return float(np.hypot(a.x - b.x, a.y - b.y))
    if isinstance(a, Point):
        return float(np.sqrt(_point_segments_dist2(a.x, a.y, as_coords(b))))
    if isinstance(b, Point):
        return float(np.sqrt(_point_segments_dist2(b.x, b.y, as_coords(a))))
    ca, cb = as_coords(a), as_coords(b)
    d1 = np.sqrt(_point_segments_dist2(ca[:, 0], ca[:, 1], cb)).min()
    d2 = np.sqrt(_point_segments_dist2(cb[:, 0], cb[:, 1], ca)).min()
    return float(min(d1, d2))
