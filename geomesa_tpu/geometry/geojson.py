"""GeoJSON geometry encoding (RFC 7946) for export surfaces.

The reference exports GeoJSON via GeoTools' FeatureJSON
(geomesa-tools/.../export/formats/); here geometries render directly
from the columnar model.
"""

from __future__ import annotations

import numpy as np

from .base import (Geometry, GeometryCollection, LineString, MultiLineString,
                   MultiPoint, MultiPolygon, Point, Polygon)

__all__ = ["to_geojson", "from_geojson"]


def _pos(c: np.ndarray) -> list:
    return [[float(x), float(y)] for x, y in np.asarray(c).reshape(-1, 2)]


def to_geojson(g: Geometry) -> dict:
    if isinstance(g, Point):
        return {"type": "Point", "coordinates": [float(g.x), float(g.y)]}
    if isinstance(g, LineString):
        return {"type": "LineString", "coordinates": _pos(g.coords)}
    if isinstance(g, Polygon):
        return {"type": "Polygon",
                "coordinates": [_pos(r) for r in g.coords_list()]}
    if isinstance(g, MultiPoint):
        return {"type": "MultiPoint",
                "coordinates": [to_geojson(p)["coordinates"] for p in g.parts]}
    if isinstance(g, MultiLineString):
        return {"type": "MultiLineString",
                "coordinates": [_pos(p.coords) for p in g.parts]}
    if isinstance(g, MultiPolygon):
        return {"type": "MultiPolygon",
                "coordinates": [[_pos(r) for r in p.coords_list()]
                                for p in g.parts]}
    if isinstance(g, GeometryCollection):
        return {"type": "GeometryCollection",
                "geometries": [to_geojson(p) for p in g.parts]}
    raise TypeError(f"cannot GeoJSON-encode {type(g).__name__}")


def from_geojson(obj: dict) -> Geometry:
    t = obj["type"]
    c = obj.get("coordinates")
    if t == "Point":
        return Point(c[0], c[1])
    if t == "LineString":
        return LineString(c)
    if t == "Polygon":
        return Polygon(c[0], c[1:])
    if t == "MultiPoint":
        return MultiPoint([Point(p[0], p[1]) for p in c])
    if t == "MultiLineString":
        return MultiLineString([LineString(l) for l in c])
    if t == "MultiPolygon":
        return MultiPolygon([Polygon(p[0], p[1:]) for p in c])
    if t == "GeometryCollection":
        return GeometryCollection([from_geojson(o)
                                   for o in obj["geometries"]])
    raise ValueError(f"unknown GeoJSON geometry type {t!r}")
