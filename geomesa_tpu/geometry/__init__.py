"""JTS-replacement geometry model.

The reference leans on JTS for all geometry math (vector data model,
predicates, WKT/WKB). Here:

- :mod:`geomesa_tpu.geometry.base` -- numpy-backed geometry classes with
  exact float64 host predicates (planner-time + borderline rechecks)
- :mod:`geomesa_tpu.geometry.wkt` -- WKT parse/format
- :mod:`geomesa_tpu.geometry.packed` -- flat device-friendly buffers
  (vertex arrays + offsets + per-feature bboxes) for scan kernels

Device kernels evaluate predicates in f32 with a conservative error
band; points in the band are re-checked on the host in f64, so final
results match exact double semantics without putting f64 on the TPU.
"""

from .base import (Geometry, Point, LineString, Polygon, MultiPoint,
                   MultiLineString, MultiPolygon, GeometryCollection,
                   Envelope)
from .wkt import parse_wkt, to_wkt

__all__ = ["Geometry", "Point", "LineString", "Polygon", "MultiPoint",
           "MultiLineString", "MultiPolygon", "GeometryCollection",
           "Envelope", "parse_wkt", "to_wkt"]
