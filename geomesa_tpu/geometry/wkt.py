"""WKT (Well-Known Text) parse/format for the geometry model.

Replaces the reference's use of JTS WKTReader/WKTWriter
(geomesa-utils/.../text/WKTUtils). Supports POINT, LINESTRING, POLYGON,
MULTIPOINT, MULTILINESTRING, MULTIPOLYGON, GEOMETRYCOLLECTION and EMPTY.
"""

from __future__ import annotations

import re

import numpy as np

from .base import (Geometry, GeometryCollection, LineString, MultiLineString,
                   MultiPoint, MultiPolygon, Point, Polygon)

__all__ = ["parse_wkt", "to_wkt"]


class _Scanner:
    def __init__(self, s: str):
        self.s = s
        self.i = 0

    def skip_ws(self):
        while self.i < len(self.s) and self.s[self.i].isspace():
            self.i += 1

    def peek(self) -> str:
        self.skip_ws()
        return self.s[self.i] if self.i < len(self.s) else ""

    def expect(self, ch: str):
        self.skip_ws()
        if self.i >= len(self.s) or self.s[self.i] != ch:
            raise ValueError(f"expected {ch!r} at {self.i} in {self.s[:80]!r}")
        self.i += 1

    def word(self) -> str:
        self.skip_ws()
        m = re.match(r"[A-Za-z]+", self.s[self.i:])
        if not m:
            raise ValueError(f"expected word at {self.i} in {self.s[:80]!r}")
        self.i += m.end()
        return m.group(0).upper()

    def number(self) -> float:
        self.skip_ws()
        m = re.match(r"[-+]?\d*\.?\d+(?:[eE][-+]?\d+)?", self.s[self.i:])
        if not m:
            raise ValueError(f"expected number at {self.i} in {self.s[:80]!r}")
        self.i += m.end()
        return float(m.group(0))


def _coords(sc: _Scanner) -> np.ndarray:
    """Parse '(x y, x y, ...)' -> (n, 2). Z/M ordinates are dropped."""
    sc.expect("(")
    pts = []
    while True:
        x = sc.number()
        y = sc.number()
        # optional extra ordinates (z / m)
        while sc.peek() not in ",)":
            sc.number()
        pts.append((x, y))
        if sc.peek() == ",":
            sc.expect(",")
        else:
            break
    sc.expect(")")
    return np.array(pts, dtype=np.float64)


def _maybe_empty(sc: _Scanner) -> bool:
    save = sc.i
    try:
        if sc.word() == "EMPTY":
            return True
    except ValueError:
        pass
    sc.i = save
    return False


def _rings(sc: _Scanner) -> list[np.ndarray]:
    sc.expect("(")
    rings = [_coords(sc)]
    while sc.peek() == ",":
        sc.expect(",")
        rings.append(_coords(sc))
    sc.expect(")")
    return rings


def _parse_geom(sc: _Scanner) -> Geometry:
    tag = sc.word()
    if tag == "POINT":
        if _maybe_empty(sc):
            return Point(np.nan, np.nan)
        c = _coords(sc)
        return Point(c[0, 0], c[0, 1])
    if tag == "LINESTRING":
        if _maybe_empty(sc):
            return LineString(np.empty((0, 2)))
        return LineString(_coords(sc))
    if tag == "POLYGON":
        if _maybe_empty(sc):
            return Polygon(np.empty((0, 2)))
        rings = _rings(sc)
        return Polygon(rings[0], rings[1:])
    if tag == "MULTIPOINT":
        if _maybe_empty(sc):
            return MultiPoint([])
        # both MULTIPOINT(1 2, 3 4) and MULTIPOINT((1 2), (3 4))
        sc.expect("(")
        pts = []
        while True:
            if sc.peek() == "(":
                c = _coords(sc)
                pts.append(Point(c[0, 0], c[0, 1]))
            else:
                x = sc.number()
                y = sc.number()
                pts.append(Point(x, y))
            if sc.peek() == ",":
                sc.expect(",")
            else:
                break
        sc.expect(")")
        return MultiPoint(pts)
    if tag == "MULTILINESTRING":
        if _maybe_empty(sc):
            return MultiLineString([])
        return MultiLineString([LineString(c) for c in _rings(sc)])
    if tag == "MULTIPOLYGON":
        if _maybe_empty(sc):
            return MultiPolygon([])
        sc.expect("(")
        polys = []
        while True:
            rings = _rings(sc)
            polys.append(Polygon(rings[0], rings[1:]))
            if sc.peek() == ",":
                sc.expect(",")
            else:
                break
        sc.expect(")")
        return MultiPolygon(polys)
    if tag == "GEOMETRYCOLLECTION":
        if _maybe_empty(sc):
            return GeometryCollection([])
        sc.expect("(")
        geoms = [_parse_geom(sc)]
        while sc.peek() == ",":
            sc.expect(",")
            geoms.append(_parse_geom(sc))
        sc.expect(")")
        return GeometryCollection(geoms)
    raise ValueError(f"unknown WKT type: {tag}")


def parse_wkt(s: str) -> Geometry:
    sc = _Scanner(s)
    g = _parse_geom(sc)
    sc.skip_ws()
    if sc.i != len(sc.s):
        raise ValueError(f"trailing characters in WKT: {s[sc.i:][:40]!r}")
    return g


def _fmt(v: float) -> str:
    v = float(v)
    if not np.isfinite(v):
        return repr(v)
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _fmt_coords(c: np.ndarray) -> str:
    return "(" + ", ".join(f"{_fmt(x)} {_fmt(y)}" for x, y in c) + ")"


def to_wkt(g: Geometry) -> str:
    t = g.geom_type
    if isinstance(g, Point):
        if g.is_empty:
            return "POINT EMPTY"
        return f"POINT ({_fmt(g.x)} {_fmt(g.y)})"
    if isinstance(g, LineString):
        if g.is_empty:
            return "LINESTRING EMPTY"
        return "LINESTRING " + _fmt_coords(g.coords)
    if isinstance(g, Polygon):
        if g.is_empty:
            return "POLYGON EMPTY"
        rings = ", ".join(_fmt_coords(r) for r in g.coords_list())
        return f"POLYGON ({rings})"
    if isinstance(g, MultiPoint):
        if g.is_empty:
            return "MULTIPOINT EMPTY"
        inner = ", ".join(f"({_fmt(p.x)} {_fmt(p.y)})" for p in g.parts)
        return f"MULTIPOINT ({inner})"
    if isinstance(g, MultiLineString):
        if g.is_empty:
            return "MULTILINESTRING EMPTY"
        inner = ", ".join(_fmt_coords(p.coords) for p in g.parts)
        return f"MULTILINESTRING ({inner})"
    if isinstance(g, MultiPolygon):
        if g.is_empty:
            return "MULTIPOLYGON EMPTY"
        inner = ", ".join("(" + ", ".join(_fmt_coords(r) for r in p.coords_list()) + ")"
                          for p in g.parts)
        return f"MULTIPOLYGON ({inner})"
    if isinstance(g, GeometryCollection):
        if g.is_empty:
            return "GEOMETRYCOLLECTION EMPTY"
        inner = ", ".join(to_wkt(p) for p in g.parts)
        return f"GEOMETRYCOLLECTION ({inner})"
    raise TypeError(f"cannot write WKT for {t}")
