"""OGC Well-Known-Binary geometry serialization.

The reference serializes geometries with a WKB-ish twkb/kryo scheme
(geomesa-features/.../kryo/serialization/KryoGeometrySerialization.scala);
here we use standard little-endian WKB so buffers interoperate with
pyarrow/GDAL tooling directly.

Supported: Point, LineString, Polygon, MultiPoint, MultiLineString,
MultiPolygon, GeometryCollection (2D).
"""

from __future__ import annotations

import struct

import numpy as np

from .base import (Geometry, GeometryCollection, LineString, MultiLineString,
                   MultiPoint, MultiPolygon, Point, Polygon)

__all__ = ["to_wkb", "from_wkb"]

_WKB_POINT = 1
_WKB_LINESTRING = 2
_WKB_POLYGON = 3
_WKB_MULTIPOINT = 4
_WKB_MULTILINESTRING = 5
_WKB_MULTIPOLYGON = 6
_WKB_COLLECTION = 7


def _coords_bytes(coords: np.ndarray) -> bytes:
    c = np.ascontiguousarray(coords, dtype="<f8")
    return struct.pack("<I", len(c)) + c.tobytes()


def _write(g: Geometry, out: list) -> None:
    if isinstance(g, Point):
        out.append(struct.pack("<BI", 1, _WKB_POINT))
        out.append(struct.pack("<dd", g.x, g.y))
    elif isinstance(g, LineString):
        out.append(struct.pack("<BI", 1, _WKB_LINESTRING))
        out.append(_coords_bytes(g.coords))
    elif isinstance(g, Polygon):
        rings = g.coords_list()
        out.append(struct.pack("<BI", 1, _WKB_POLYGON))
        out.append(struct.pack("<I", len(rings)))
        for r in rings:
            out.append(_coords_bytes(r))
    elif isinstance(g, (MultiPoint, MultiLineString, MultiPolygon,
                        GeometryCollection)):
        code = {MultiPoint: _WKB_MULTIPOINT,
                MultiLineString: _WKB_MULTILINESTRING,
                MultiPolygon: _WKB_MULTIPOLYGON,
                GeometryCollection: _WKB_COLLECTION}[type(g)]
        out.append(struct.pack("<BI", 1, code))
        out.append(struct.pack("<I", len(g.parts)))
        for p in g.parts:
            _write(p, out)
    else:  # pragma: no cover
        raise TypeError(f"cannot WKB-encode {type(g).__name__}")


def to_wkb(g: Geometry) -> bytes:
    out: list = []
    _write(g, out)
    return b"".join(out)


class _Reader:
    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def _order(self) -> str:
        b = self.buf[self.pos]
        self.pos += 1
        return "<" if b == 1 else ">"

    def _u32(self, order: str) -> int:
        v = struct.unpack_from(order + "I", self.buf, self.pos)[0]
        self.pos += 4
        return v

    def _coords(self, order: str) -> np.ndarray:
        n = self._u32(order)
        arr = np.frombuffer(self.buf, dtype=order + "f8",
                            count=2 * n, offset=self.pos)
        self.pos += 16 * n
        return arr.reshape(-1, 2).astype(np.float64)

    def read(self) -> Geometry:
        order = self._order()
        code = self._u32(order)
        if code == _WKB_POINT:
            x, y = struct.unpack_from(order + "dd", self.buf, self.pos)
            self.pos += 16
            return Point(x, y)
        if code == _WKB_LINESTRING:
            return LineString(self._coords(order))
        if code == _WKB_POLYGON:
            nr = self._u32(order)
            rings = [self._coords(order) for _ in range(nr)]
            return Polygon(rings[0], rings[1:])
        if code in (_WKB_MULTIPOINT, _WKB_MULTILINESTRING,
                    _WKB_MULTIPOLYGON, _WKB_COLLECTION):
            n = self._u32(order)
            parts = [self.read() for _ in range(n)]
            cls = {_WKB_MULTIPOINT: MultiPoint,
                   _WKB_MULTILINESTRING: MultiLineString,
                   _WKB_MULTIPOLYGON: MultiPolygon,
                   _WKB_COLLECTION: GeometryCollection}[code]
            return cls(parts)
        raise ValueError(f"unsupported WKB geometry code {code}")


def from_wkb(buf: bytes) -> Geometry:
    return _Reader(buf).read()
