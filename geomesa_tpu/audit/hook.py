"""The unified query-audit hook: every query surface (memory / mesh /
remote / replicated / cluster) records its ``QueryEvent`` through
``audit_query`` so the audit plane is complete instead of
store-dependent.

Three pieces make that work:

- **global fallback logger** — a store constructed without an explicit
  ``AuditLogger`` records into the process-wide ring (JSONL path from
  ``geomesa.audit.path``), so ``/rest/audit`` on a server fronting a
  cluster coordinator or remote client still answers;
- **delegation suppression** — a fronting tier (cluster coordinator,
  replica router) records ONE event for the whole query and runs its
  delegate legs under ``delegated_scope()``; the inner stores' hooks
  see the contextvar and skip, so one logical query never
  double-audits. The scope is a contextvar, so it survives the
  coordinator's ``contextvars.copy_context()``-wrapped scatter
  threads;
- **context enrichment** — the hook stamps each event with the current
  trace id, the authenticated principal (web tier sets
  ``principal_scope``), and the cache/hedge flags instrumentation set
  on the trace (obs.set_flag), without any surface having to plumb
  those arguments through.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading

from ..utils.properties import SystemProperty
from .events import AuditLogger

__all__ = ["AUDIT_PATH", "global_audit", "audit_query",
           "delegated_scope", "principal_scope", "current_principal"]

AUDIT_PATH = SystemProperty("geomesa.audit.path", None)

_global: AuditLogger | None = None
_global_lock = threading.Lock()

_suppress: contextvars.ContextVar = contextvars.ContextVar(
    "geomesa_audit_suppress", default=False)
_principal: contextvars.ContextVar = contextvars.ContextVar(
    "geomesa_audit_principal", default=None)


def global_audit() -> AuditLogger:
    """The process-wide fallback logger (lazy; picks up
    ``geomesa.audit.path`` at first use)."""
    global _global
    with _global_lock:
        if _global is None:
            _global = AuditLogger(path=AUDIT_PATH.get())
        return _global


def _reset_global():
    """Test hook: drop the cached global logger so a changed
    ``geomesa.audit.path`` takes effect."""
    global _global
    with _global_lock:
        _global = None


@contextlib.contextmanager
def delegated_scope():
    """Mark the dynamic extent of a fronting tier's delegate calls:
    inner surfaces skip auditing (the tier records the one event)."""
    token = _suppress.set(True)
    try:
        yield
    finally:
        _suppress.reset(token)


@contextlib.contextmanager
def principal_scope(principal: str | None):
    token = _principal.set(principal)
    try:
        yield
    finally:
        _principal.reset(token)


def current_principal() -> str | None:
    return _principal.get()


def audit_query(audit: AuditLogger | None, surface: str,
                type_name: str, filter_str: str, hints: dict | None,
                plan_ms: float, scan_ms: float, hits: int, *,
                index: str | None = None,
                rows_scanned: int | None = None,
                batched: bool = False,
                user: str | None = None) -> bool:
    """Record one query through the shared hook. ``audit`` is the
    surface's own logger (None -> global fallback). Returns False when
    suppressed by an enclosing ``delegated_scope``."""
    if _suppress.get():
        return False
    from ..obs import current_trace_id, get_flag
    from ..tenants import active_tenant
    logger = audit if audit is not None else global_audit()
    logger.record(
        type_name, filter_str, hints or {},
        round(float(plan_ms), 3), round(float(scan_ms), 3), int(hits),
        user=user or current_principal() or "unknown",
        trace_id=current_trace_id(), surface=surface, index=index,
        rows_scanned=rows_scanned,
        cache_hit=bool(get_flag("cache_hit", False)),
        batched=batched or bool(get_flag("batched", False)),
        hedged=bool(get_flag("hedged", False)),
        tenant=active_tenant())
    return True
