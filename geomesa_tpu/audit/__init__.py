"""Query auditing (index/audit/QueryEvent.scala:13 +
AccumuloAuditService analog): every query records an event — type name,
filter, hints, plan/scan timings, hit count — to a pluggable writer
(in-memory ring, JSONL file)."""

from .events import AuditLogger, QueryEvent

__all__ = ["AuditLogger", "QueryEvent"]
