"""Query auditing (index/audit/QueryEvent.scala:13 +
AccumuloAuditService analog): every query surface records an event —
type name, filter, hints, plan/scan timings, hit count, trace id,
index chosen, rows scanned, cache/batch/hedge flags, principal —
through the unified hook in hook.py to a pluggable writer (in-memory
ring, JSONL file)."""

from .events import AuditLogger, QueryEvent
from .hook import (AUDIT_PATH, audit_query, current_principal,
                   delegated_scope, global_audit, principal_scope)

__all__ = ["AuditLogger", "QueryEvent", "AUDIT_PATH", "audit_query",
           "delegated_scope", "principal_scope", "current_principal",
           "global_audit"]
