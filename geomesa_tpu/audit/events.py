"""Audit events + writers."""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Any

__all__ = ["QueryEvent", "AuditLogger"]


@dataclasses.dataclass
class QueryEvent:
    """One audited query (QueryEvent.scala:13 fields)."""
    type_name: str
    user: str
    filter: str
    hints: dict[str, Any]
    date_ms: int
    plan_time_ms: float
    scan_time_ms: float
    hits: int

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), default=str)


class AuditLogger:
    """Keeps a bounded in-memory ring and optionally appends JSONL to a
    file (the async table writer of AccumuloAuditService, minus the
    table)."""

    def __init__(self, path: str | None = None, capacity: int = 10_000):
        import collections
        self.path = path
        self.capacity = capacity
        self.events: "collections.deque[QueryEvent]" = \
            collections.deque(maxlen=capacity)

    def write(self, event: QueryEvent):
        self.events.append(event)
        if self.path:
            with open(self.path, "a") as fh:
                fh.write(event.to_json() + "\n")

    def record(self, type_name: str, filter_str: str, hints: dict,
               plan_time_ms: float, scan_time_ms: float, hits: int,
               user: str = "unknown"):
        self.write(QueryEvent(type_name, user, filter_str, hints,
                              int(time.time() * 1000), plan_time_ms,
                              scan_time_ms, hits))

    def query(self, type_name: str | None = None,
              since_ms: int | None = None) -> list[QueryEvent]:
        out = self.events
        if type_name is not None:
            out = [e for e in out if e.type_name == type_name]
        if since_ms is not None:
            out = [e for e in out if e.date_ms >= since_ms]
        return list(out)
