"""Audit events + writers."""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Any

__all__ = ["QueryEvent", "AuditLogger"]


@dataclasses.dataclass
class QueryEvent:
    """One audited query (QueryEvent.scala:13 fields, enriched with
    the tracing/serving context the unified hook collects)."""
    type_name: str
    user: str
    filter: str
    hints: dict[str, Any]
    date_ms: int
    plan_time_ms: float
    scan_time_ms: float
    hits: int
    # -- enrichment (hook.py fills these; defaults keep old callers
    # and persisted JSONL compatible) --------------------------------
    trace_id: str | None = None
    surface: str | None = None      # memory/mesh/remote/replicated/...
    index: str | None = None        # index chosen by the planner
    rows_scanned: int | None = None  # scanned vs. `hits` returned
    cache_hit: bool = False
    batched: bool = False
    hedged: bool = False
    tenant: str | None = None       # QoS tenant (tenants plane)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), default=str)


class AuditLogger:
    """Keeps a bounded in-memory ring and optionally appends JSONL to a
    file (the async table writer of AccumuloAuditService, minus the
    table).

    Thread-safe: concurrent web workers audit through one logger, so
    ring appends and file writes serialize under a lock, and each event
    is written as one whole line + flush (no interleaved partial
    lines). ``query()`` snapshots the ring under the same lock so
    readers never iterate a deque mid-append."""

    def __init__(self, path: str | None = None, capacity: int = 10_000):
        import collections
        self.path = path
        self.capacity = capacity
        self.events: "collections.deque[QueryEvent]" = \
            collections.deque(maxlen=capacity)
        self._lock = threading.Lock()

    def write(self, event: QueryEvent):
        line = (event.to_json() + "\n") if self.path else None
        with self._lock:
            self.events.append(event)
            if line is not None:
                with open(self.path, "a") as fh:
                    fh.write(line)
                    fh.flush()

    def record(self, type_name: str, filter_str: str, hints: dict,
               plan_time_ms: float, scan_time_ms: float, hits: int,
               user: str = "unknown", **enrich):
        self.write(QueryEvent(type_name, user, filter_str, hints,
                              int(time.time() * 1000), plan_time_ms,
                              scan_time_ms, hits, **enrich))

    def query(self, type_name: str | None = None,
              since_ms: int | None = None) -> list[QueryEvent]:
        with self._lock:
            out = list(self.events)
        if type_name is not None:
            out = [e for e in out if e.type_name == type_name]
        if since_ms is not None:
            out = [e for e in out if e.date_ms >= since_ms]
        return out
