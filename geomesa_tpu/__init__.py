"""geomesa-tpu: a TPU-native framework for large-scale spatio-temporal
indexing and analytics.

A ground-up rebuild of the capabilities of GeoMesa (reference:
/root/reference, surveyed in SURVEY.md) designed for TPU hardware:

- columnar ``FeatureBatch`` arrays sharded over a ``jax.sharding.Mesh``
  replace distributed key-value tables;
- space-filling-curve encoding, range filtering, geometry predicates and
  aggregations are vmapped/jitted JAX kernels;
- "server-side iterators / coprocessors" become fused shard-local scan
  kernels, with ICI collectives (psum / all_gather) replacing the
  client-side reduce.

Layer map (mirrors SURVEY.md section 1):

- :mod:`geomesa_tpu.curves`   -- L0 space-filling curves (Z2/Z3/XZ2/XZ3)
- :mod:`geomesa_tpu.features` -- L1/L2 schema + columnar feature model
- :mod:`geomesa_tpu.filters`  -- L3 CQL filter algebra
- :mod:`geomesa_tpu.geometry` -- JTS-replacement geometry kernels
- :mod:`geomesa_tpu.index`    -- L4 index key spaces + query planner
- :mod:`geomesa_tpu.scan`     -- L6 pushdown scan/aggregation kernels
- :mod:`geomesa_tpu.parallel` -- mesh/sharding + distributed scans
- :mod:`geomesa_tpu.analytics`-- L7 ST_* kernels, joins, KNN, processes
- :mod:`geomesa_tpu.store`    -- L5 datastores (memory / fs / live)
- :mod:`geomesa_tpu.convert`  -- L8 ingest converters
- :mod:`geomesa_tpu.tools`    -- L9 CLI
- :mod:`geomesa_tpu.security` -- LX visibility / authorizations
"""

__version__ = "0.1.0"
