"""geomesa-tpu: a TPU-native framework for large-scale spatio-temporal
indexing and analytics.

A ground-up rebuild of the capabilities of GeoMesa (reference:
/root/reference, surveyed in SURVEY.md) designed for TPU hardware:

- columnar ``FeatureBatch`` arrays sharded over a ``jax.sharding.Mesh``
  replace distributed key-value tables;
- space-filling-curve encoding, range filtering, geometry predicates and
  aggregations are vmapped/jitted JAX kernels;
- "server-side iterators / coprocessors" become fused shard-local scan
  kernels, with ICI collectives (psum / all_gather) replacing the
  client-side reduce.

Layer map (mirrors SURVEY.md section 1):

- :mod:`geomesa_tpu.curves`   -- L0 space-filling curves (Z2/Z3/XZ2/XZ3)
- :mod:`geomesa_tpu.features` -- L1/L2 schema + columnar feature model
- :mod:`geomesa_tpu.filters`  -- L3 CQL filter algebra
- :mod:`geomesa_tpu.geometry` -- JTS-replacement geometry kernels
- :mod:`geomesa_tpu.index`    -- L4 index key spaces + query planner
- :mod:`geomesa_tpu.scan`     -- L6 pushdown scan/aggregation kernels
- :mod:`geomesa_tpu.parallel` -- mesh/sharding + distributed scans
- :mod:`geomesa_tpu.analytics`-- L7 ST_* kernels, joins, KNN, processes
- :mod:`geomesa_tpu.store`    -- L5 datastores (memory / fs / live /
                                 lambda / mesh / stream), DataStore SPI
- :mod:`geomesa_tpu.sql`      -- L7 SQL surface with ST_* pushdown
- :mod:`geomesa_tpu.convert`  -- L8 ingest converters
- :mod:`geomesa_tpu.tools`    -- L9 CLI
- :mod:`geomesa_tpu.security` -- LX visibility / authorizations
- :mod:`geomesa_tpu.native`   -- C++ fast paths (codec, z ranges,
                                 fused z encode, index sort)

Convenience re-exports: the common entry points are importable from the
package root (``geomesa_tpu.InMemoryDataStore`` etc.).
"""

__version__ = "0.2.0"

from .features.sft import parse_spec  # noqa: E402
from .index.api import Query, QueryHints  # noqa: E402

__all__ = ["parse_spec", "Query", "QueryHints", "DataStore",
           "InMemoryDataStore", "FileSystemDataStore", "LiveDataStore",
           "LambdaDataStore", "DistributedDataStore", "StreamDataStore",
           "SqlEngine", "__version__"]


def __getattr__(name):
    # stores/sql import jax and the full stack; keep `import geomesa_tpu`
    # light by resolving the heavyweight exports lazily
    if name in ("DataStore", "InMemoryDataStore", "FileSystemDataStore",
                "LiveDataStore", "LambdaDataStore", "DistributedDataStore",
                "StreamDataStore"):
        from . import store
        return getattr(store, name)
    if name == "SqlEngine":
        from .sql import SqlEngine
        return SqlEngine
    raise AttributeError(f"module 'geomesa_tpu' has no attribute {name!r}")
