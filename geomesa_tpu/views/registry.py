"""Standing materialized views folded on every write-path commit.

``ViewRegistry`` hooks the store's mutation surface (``write`` /
``delete`` — the PR 13 group-commit pipeline and the base
``write_many`` both funnel through ``write``) with instance-attribute
wrappers: each commit's delta batch is WHERE-filtered and folded into
every registered view's per-group state under one fold lock, stamped
with the store's pushdown version/LSN, and the changed groups publish
as deltas on the ``view.<name>`` bus topic.

Reads serve through the store's LSN-keyed ``ResultCache`` at exact
versions, so the web tier gets ETag/304 for free. Durable stores
persist view state on every ``checkpoint()`` (a small O(groups) JSON
sidecar under the journal root, floats hex-encoded for bit exactness)
and restore it on reopen when the WAL LSN matches — a restart recovers
views without a full rebuild.

Kill switch: ``geomesa.views.enabled`` (default false). While off,
``register`` refuses and no hook ever installs — the write path is
bit-identical to a build without this module.
"""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np

from ..filters import ast, evaluate
from ..index.api import Query
from ..metrics import metrics
from ..obs.trace import tracer
from ..utils.properties import SystemProperty
from .state import compile_view
from .subscribe import view_topic

__all__ = ["ViewRegistry", "MaterializedView", "VIEWS_ENABLED",
           "VIEW_RESERVOIR_K"]

VIEWS_ENABLED = SystemProperty("geomesa.views.enabled", "false")
VIEW_RESERVOIR_K = SystemProperty("geomesa.views.reservoir.k", "8")

_STATE_FILE = "views.json"


class MaterializedView:
    """One registered view: compiled state + maintenance counters."""

    def __init__(self, name: str, state):
        self.name = name
        self.state = state
        self.lsn = 0                # store version at the last fold
        self.folds = 0
        self.rows_folded = 0
        self.retraction_fallbacks = 0
        self.replays = 0
        self.pub_seq = 0            # per-view delta sequence (bus)

    def status(self, current_lsn: int | None = None) -> dict:
        out = {"name": self.name, "sql": self.state.sql,
               "table": self.state.table, "groups":
               len(self.state.groups), "lsn": self.lsn,
               "folds": self.folds, "rows_folded": self.rows_folded,
               "retraction_fallbacks": self.retraction_fallbacks,
               "replays": self.replays}
        if current_lsn is not None:
            out["lsn_lag"] = max(0, current_lsn - self.lsn)
        return out


class ViewRegistry:
    """Registry + write-path subscription for materialized views."""

    def __init__(self, store, bus=None, registry=metrics,
                 restore: bool = True):
        self.store = store
        self._explicit_bus = bus
        self._registry = registry
        self._views: dict[str, MaterializedView] = {}
        # one lock orders every fold, materialize and save; it nests
        # OUTSIDE the store's op lock (folds query the store)
        self._fold_lock = threading.RLock()
        self._orig: dict[str, object] = {}
        if restore and VIEWS_ENABLED.as_bool():
            self._restore()

    # -- plumbing -------------------------------------------------------------

    def _bus(self):
        if self._explicit_bus is not None:
            return self._explicit_bus
        bus = getattr(self.store, "bus", None)
        if bus is None:
            live = getattr(self.store, "_live", None)
            bus = getattr(live, "bus", None)
        return bus

    def _lsn(self, type_name: str) -> int:
        fn = getattr(self.store, "pushdown_version", None)
        return int(fn(type_name)) if fn is not None else 0

    def _journal(self):
        return getattr(self.store, "journal", None)

    def _state_path(self) -> str | None:
        j = self._journal()
        root = getattr(j, "root", None)
        return None if root is None else os.path.join(
            root, "views", _STATE_FILE)

    def _views_for(self, type_name: str) -> list[MaterializedView]:
        return [v for v in self._views.values()
                if v.state.table == type_name]

    # -- registration -----------------------------------------------------------

    def register(self, name: str, sql: str) -> MaterializedView:
        """Compile, build (one scan at the current LSN) and subscribe
        a view. Statement errors raise ``ValueError`` (``SqlError``)."""
        if not VIEWS_ENABLED.as_bool():
            raise ValueError("materialized views are disabled "
                             "(geomesa.views.enabled=false)")
        if not name or "/" in name or "." in name:
            raise ValueError(f"invalid view name {name!r}")
        with self._fold_lock:
            if name in self._views:
                raise ValueError(f"materialized view {name!r} exists")
            state = compile_view(
                self.store.get_schema(_table_of(sql)), sql)
            state.reservoir_k = VIEW_RESERVOIR_K.as_int()
            state.build(self.store)
            view = MaterializedView(name, state)
            view.lsn = self._lsn(state.table)
            self._views[name] = view
            self._install_hooks()
        self._registry.gauge("views.registered", len(self._views))
        return view

    def unregister(self, name: str) -> None:
        with self._fold_lock:
            if name not in self._views:
                raise KeyError(f"no such view: {name}")
            del self._views[name]
            if not self._views:
                self._uninstall_hooks()
            self._save_locked()
        self._registry.gauge("views.registered", len(self._views))

    def get(self, name: str) -> MaterializedView:
        v = self._views.get(name)
        if v is None:
            raise KeyError(f"no such view: {name}")
        return v

    def status(self) -> list[dict]:
        with self._fold_lock:
            return [v.status(self._lsn(v.state.table))
                    for _, v in sorted(self._views.items())]

    def refresh(self, name: str) -> dict:
        """Full re-execution (one scan) — the O(table) baseline the
        incremental folds replace; exposed for operators and benches."""
        with self._fold_lock:
            v = self.get(name)
            v.state.build(self.store)
            v.lsn = self._lsn(v.state.table)
            self._invalidate(v)
            return v.status(self._lsn(v.state.table))

    def close(self) -> None:
        with self._fold_lock:
            if self._views and self._state_path():
                self._save_locked()
            self._views.clear()
            self._uninstall_hooks()

    # -- reads ---------------------------------------------------------------------

    def result(self, name: str):
        """Materialize through the store's LSN-keyed result cache: an
        unchanged pushdown version serves the cached finalize (and the
        web tier's exact-version ETag/304)."""
        v = self.get(name)

        def compute():
            with self._fold_lock:
                before = v.replays
                v.replays += v.state.ensure_clean(self.store)
                if v.replays != before:
                    self._registry.counter(
                        "views.replays", v.replays - before)
                return v.state.result(self.store)

        rc = getattr(self.store, "result_cache", None)
        if rc is None:
            return compute()
        return rc.get_or_compute(
            v.state.table, f"view:{name}", compute,
            encode=lambda r: (list(r.names),
                              {k: c.copy() for k, c in r.columns.items()}),
            decode=lambda t: _decode_result(t))

    def _invalidate(self, view: MaterializedView) -> None:
        rc = getattr(self.store, "result_cache", None)
        if rc is not None:
            rc.invalidate(view.state.table)

    # -- write-path hooks -------------------------------------------------------------

    def _install_hooks(self) -> None:
        if self._orig:
            return
        store = self.store

        def hook(meth, wrapper):
            self._orig[meth] = getattr(store, meth)
            setattr(store, meth, wrapper)

        orig_write = store.write

        def write(type_name, batch, *a, **kw):
            with self._fold_lock:
                ret = orig_write(type_name, batch, *a, **kw)
                self._on_write(type_name, batch)
                return ret

        hook("write", write)
        orig_delete = store.delete

        def delete(type_name, ids, *a, **kw):
            with self._fold_lock:
                pre = self._pre_image(type_name, ids)
                ret = orig_delete(type_name, ids, *a, **kw)
                self._on_delete(type_name, pre)
                return ret

        hook("delete", delete)
        from ..store.api import DataStore
        if type(store).write_many is not DataStore.write_many:
            orig_wm = store.write_many

            def write_many(type_name, batches, *a, **kw):
                with self._fold_lock:
                    ret = orig_wm(type_name, batches, *a, **kw)
                    for b in batches:
                        batch = b[0] if isinstance(b, tuple) else b
                        self._on_write(type_name, batch)
                    return ret

            hook("write_many", write_many)
        if hasattr(store, "checkpoint"):
            orig_cp = store.checkpoint

            def checkpoint(*a, **kw):
                ret = orig_cp(*a, **kw)
                # save AFTER the mark: the stamp is the post-mark WAL
                # LSN, which a clean reopen reproduces exactly
                self.save()
                return ret

            hook("checkpoint", checkpoint)

    def _uninstall_hooks(self) -> None:
        for meth, orig in self._orig.items():
            setattr(self.store, meth, orig)
        self._orig = {}

    # -- folds ----------------------------------------------------------------------------

    def _on_write(self, type_name: str, batch) -> None:
        for v in self._views_for(type_name):
            with tracer.span("view-fold", v.name):
                mask = evaluate(v.state.where, batch)
                rows = np.flatnonzero(mask)
                changed = (v.state.fold_insert(batch, batch.ids, rows)
                           if len(rows) else set())
                self._stamp(v, type_name, len(rows))
                self._publish(v, changed, set())

    def _pre_image(self, type_name: str, ids):
        if not self._views_for(type_name):
            return None
        ids = tuple(str(i) for i in ids)
        if not ids:
            return None
        return self.store.query(
            Query(type_name, ast.FidFilter(ids)))

    def _on_delete(self, type_name: str, pre) -> None:
        if pre is None or pre.n == 0 or pre.batch is None:
            return
        for v in self._views_for(type_name):
            with tracer.span("view-fold", v.name):
                mask = evaluate(v.state.where, pre.batch)
                rows = np.flatnonzero(mask)
                if not len(rows):
                    self._stamp(v, type_name, 0)
                    continue
                changed, removed, fb = v.state.fold_delete(
                    pre.batch, pre.ids, rows)
                if fb:
                    v.retraction_fallbacks += fb
                    self._registry.counter(
                        "views.retraction.fallbacks", fb)
                self._stamp(v, type_name, len(rows))
                self._publish(v, changed, removed)

    def _stamp(self, v: MaterializedView, type_name: str,
               nrows: int) -> None:
        v.lsn = self._lsn(type_name)
        v.folds += 1
        v.rows_folded += nrows
        self._registry.counter("views.folds")
        if nrows:
            self._registry.counter("views.rows.folded", nrows)
        self._registry.gauge("views.staleness.lsn_lag", 0)

    # -- delta publishing ---------------------------------------------------------------------

    def _publish(self, v: MaterializedView, changed: set,
                 removed: set) -> None:
        if not changed and not removed:
            return
        bus = self._bus()
        if bus is None:
            return
        replays = v.state.ensure_clean(self.store)
        if replays:
            v.replays += replays
            self._registry.counter("views.replays", replays)
        rows = []
        for kt in sorted(changed & set(v.state.groups),
                         key=lambda k: tuple((x is None, x) for x in k)):
            g = v.state.groups[kt]
            rows.append({"key": [_enc_json(x) for x in kt],
                         "row": {n: _enc_json(x) for n, x
                                 in v.state.group_row(g).items()}})
        gone = [[_enc_json(x) for x in kt]
                for kt in sorted(removed,
                                 key=lambda k: tuple((x is None, x)
                                                     for x in k))]
        payload = {"view": v.name, "lsn": v.lsn, "seq": v.pub_seq,
                   "rows": rows, "removed": gone}
        from ..store.live import GeoMessage
        msg = GeoMessage("view", v.state.table, None,
                         ids=(json.dumps(payload),),
                         timestamp_ms=int(time.time() * 1000))
        try:
            bus.publish(view_topic(v.name), msg)
            v.pub_seq += 1
            self._registry.counter("views.deltas.published")
        except Exception:
            self._registry.counter("views.deltas.publish_errors")

    # -- durability ------------------------------------------------------------------------------

    def save(self) -> str | None:
        with self._fold_lock:
            return self._save_locked()

    def _save_locked(self) -> str | None:
        path = self._state_path()
        if path is None:
            return None
        j = self._journal()
        blobs = []
        for name, v in sorted(self._views.items()):
            v.replays += v.state.ensure_clean(self.store)
            blobs.append({"name": name, "sql": v.state.sql,
                          "lsn": v.lsn,
                          "stamp": int(j.wal.last_lsn),
                          "state": v.state.to_blob()})
        from ..store.filebus import write_json_atomic
        os.makedirs(os.path.dirname(path), exist_ok=True)
        write_json_atomic(path, {"views": blobs})
        return path

    def _restore(self) -> None:
        path = self._state_path()
        if path is None or not os.path.exists(path):
            return
        try:
            with open(path, "rb") as f:
                doc = json.loads(f.read().decode("utf-8"))
        except (OSError, ValueError):
            self._registry.counter("views.recovery.corrupt")
            return
        j = self._journal()
        current = int(j.wal.last_lsn) if j is not None else -1
        with self._fold_lock:
            for blob in doc.get("views", []):
                name = blob["name"]
                try:
                    state = compile_view(
                        self.store.get_schema(_table_of(blob["sql"])),
                        blob["sql"])
                except (KeyError, ValueError):
                    self._registry.counter("views.recovery.dropped")
                    continue
                state.reservoir_k = VIEW_RESERVOIR_K.as_int()
                view = MaterializedView(name, state)
                if int(blob.get("stamp", -2)) == current:
                    state.from_blob(blob["state"])
                    view.lsn = int(blob["lsn"])
                    self._registry.counter("views.recovery.restored")
                else:
                    # writes landed after the last save: the sidecar
                    # is stale, rebuild from the recovered store
                    state.build(self.store)
                    view.lsn = self._lsn(state.table)
                    self._registry.counter("views.recovery.rebuilt")
                self._views[name] = view
            if self._views:
                self._install_hooks()
        self._registry.gauge("views.registered", len(self._views))


def _table_of(sql: str) -> str:
    from ..sql.parser import parse_sql
    return parse_sql(sql).table


def _decode_result(t):
    from ..sql.engine import SqlResult
    names, cols = t
    return SqlResult(list(names), {k: c.copy() for k, c in cols.items()})


def _enc_json(v):
    """JSON-safe scalar: numpy scalars unwrap, geometries go WKT."""
    if isinstance(v, np.generic):
        v = v.item()
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    from ..geometry.base import Geometry
    if isinstance(v, Geometry):
        from ..geometry import to_wkt
        return to_wkt(v)
    return repr(v)
