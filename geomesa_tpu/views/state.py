"""Per-group partial-aggregate state for materialized views.

A view compiles a single-table GROUP BY aggregate statement (the PR 16
mergeable shapes: COUNT/SUM/MIN/MAX natively, AVG as sum+count,
ST_ConvexHull/ST_Extent as geometry folds) into per-group accumulators
that fold each write-path delta in O(delta rows), with a retraction
story for deletes.

The correctness contract is *bit identity*: at any LSN the finalized
view equals re-running the statement from scratch with ``SqlEngine``
at that LSN — same values, same dtypes, same group order. That drives
several non-obvious choices:

- SUM/AVG accumulate in one ``np.float64`` per group, adding deltas in
  row order. ``np.bincount(ginv, weights=...)`` — the engine's reduce —
  is itself a sequential row-order accumulation per group, so the fold
  and the from-scratch reduce perform the *identical* sequence of
  float64 additions (invalid rows contribute the same ``+0.0``).
  Any deletion of a summed row marks the group dirty instead of
  subtracting: float subtraction does not invert the addition order.
- MIN/MAX keep bounded runner-up reservoirs: the K smallest (resp.
  largest) live ``(value, fid)`` pairs. Inserts and most deletes stay
  O(log K); only when a reservoir drains while valid rows remain does
  the group fall back to a targeted recompute (counted as a
  retraction fallback).
- Dirty groups replay with a *single-group* store query (WHERE AND
  key equality). Store scan strategies return row indices in table
  order, so the replayed reduce sees rows in the same order as a full
  re-execution — bit-identical by construction.
- Group keys follow the engine's factorize order: None first, values
  ascending, NaN last (``np.unique`` collapses NaNs). NaN float keys
  are normalized to a singleton sentinel so they can live in a dict.
"""

from __future__ import annotations

import bisect

import numpy as np

from ..features.batch import PointColumn
from ..filters import ast
from ..geometry.base import Envelope
from ..index.api import Query
from ..sql.distributed import _plan_partials
from ..sql.engine import (SqlEngine, SqlResult, _col_floats, _order_limit,
                          _strip_qualifier)
from ..sql.parser import SelectItem, SqlSelect, parse_sql

__all__ = ["ViewState", "compile_view"]


class _NanKey:
    """Singleton stand-in for a NaN group-key float: hashable and
    equal to itself (dict key), and orders AFTER every real value —
    where ``np.unique`` places NaN."""

    def __lt__(self, other):
        return False

    def __gt__(self, other):
        return not isinstance(other, _NanKey)

    def __repr__(self):
        return "NaN"


_NAN_KEY = _NanKey()


def _norm_key(v):
    if isinstance(v, np.generic):
        v = v.item()
    if isinstance(v, float) and v != v:
        return _NAN_KEY
    return v


def _denorm_key(v):
    return float("nan") if v is _NAN_KEY else v


def _sort_key(kt):
    return tuple((x is None, x) for x in kt)


class _NanKeyReplay(Exception):
    """A dirty group keyed by NaN cannot be re-queried with an exact
    Compare (NaN never equals) — the caller rebuilds the whole view."""


# -- slot specs --------------------------------------------------------------

class _SlotSpec:
    """One maintained accumulator: a numeric column's (cnt, nan, sum,
    reservoirs), a convex-hull fold, or an extent fold. Slots are
    deduped by (kind, column) so ``avg(x), max(x)`` share state."""

    __slots__ = ("kind", "col", "need_sum", "need_low", "need_high")

    def __init__(self, kind: str, col: str):
        self.kind = kind            # 'num' | 'hull' | 'extent'
        self.col = col
        self.need_sum = False
        self.need_low = False
        self.need_high = False


class _NumState:
    __slots__ = ("cnt", "nan", "sum", "int_ok", "absum", "low", "high",
                 "low_full", "high_full")

    def __init__(self):
        self.cnt = 0                # valid rows (NaN-valued included)
        self.nan = 0                # valid rows whose value is NaN
        self.sum = np.float64(0.0)
        # while every folded value is an exact-integer float and the
        # magnitude bound keeps all prefix sums < 2^53, the accumulator
        # is exact — retraction can SUBTRACT and stay bit-identical to
        # a from-scratch re-sum. Otherwise a retraction replays.
        self.int_ok = True
        self.absum = 0.0            # sum of |values|: prefix-sum bound
        # ascending (value, fid) lists; `full` means the list covers
        # EVERY live non-NaN value, so trims are loss-recorded. None
        # means drained (group is dirty).
        self.low: list | None = []
        self.high: list | None = []
        self.low_full = True
        self.high_full = True


class _GeoState:
    __slots__ = ("cnt", "geom", "lo", "hi")

    def __init__(self):
        self.cnt = 0                # valid geometry rows
        self.geom = None            # cached hull geometry
        self.lo = None              # extent mins (2,) float64
        self.hi = None              # extent maxs (2,) float64


class _Group:
    __slots__ = ("key", "nrows", "slots", "dirty", "row")

    def __init__(self, key: tuple, slots: list[_SlotSpec]):
        self.key = key
        self.nrows = 0
        self.slots = [(_NumState() if s.kind == "num" else _GeoState())
                      for s in slots]
        self.dirty = False
        self.row = None             # cached finalized values


# -- compile ------------------------------------------------------------------


def compile_view(sft, sql: str) -> "ViewState":
    """Parse + validate a statement into a ``ViewState``. Unsupported
    shapes refuse with a typed ``ValueError`` (parser errors are
    ``SqlError``, itself a ``ValueError``) so surfaces can map them to
    user errors, never shard/server failures."""
    sel = parse_sql(sql)
    if sel.joins:
        raise ValueError("materialized views support single-table "
                         "statements only (no JOIN)")
    if sel.group_by is None:
        raise ValueError(
            "materialized views require GROUP BY: ungrouped aggregates "
            "reduce pairwise and cannot be folded bit-identically")
    plan = _plan_partials(sel, qualified=False)
    if plan is None:
        raise ValueError(
            "statement has no mergeable aggregate form (COUNT/SUM/MIN/"
            "MAX/AVG/ST_ConvexHull/ST_Extent over one table)")
    _, _, comps, keys = plan
    # the same hidden-HAVING extension _plan_partials folded into comps
    ext: list[SelectItem] = list(sel.items)
    sel_names = {it.name for it in sel.items}
    for cond in (sel.having or []):
        if cond.item.agg and cond.item.name not in sel_names:
            ext.append(cond.item)
    by_name = {a.name: a for a in sft.attributes}
    for k in keys:
        if k not in by_name:
            raise ValueError(f"unknown column {k!r} in "
                             f"{sft.type_name!r}")
        if by_name[k].is_geometry:
            raise ValueError(f"cannot GROUP BY geometry column {k!r}")
    slots: list[_SlotSpec] = []
    slot_idx: dict[tuple, int] = {}

    def slot_for(kind: str, col: str) -> int:
        key = (kind, col)
        if key not in slot_idx:
            slot_idx[key] = len(slots)
            slots.append(_SlotSpec(kind, col))
        return slot_idx[key]

    for comp, it in zip(comps, ext):
        if comp["kind"] == "key":
            comp["slot"] = -1
            continue
        col = it.expr.split(".")[-1]
        if col != "*":
            if col not in by_name:
                raise ValueError(f"unknown column {col!r} in "
                                 f"{sft.type_name!r}")
            geo = by_name[col].is_geometry
            if comp["kind"] in ("hull", "extent") and not geo:
                raise ValueError(f"{comp['kind']} requires a geometry "
                                 f"column, got {col!r}")
            if comp["kind"] in ("sum", "avg", "min", "max") and (
                    geo or by_name[col].type.name in
                    ("String", "Bytes", "List", "Map")):
                raise ValueError(f"cannot aggregate column {col!r} "
                                 f"with {comp['kind']}")
        if comp["kind"] == "count" and col == "*":
            comp["slot"] = -1
            continue
        if comp["kind"] in ("count", "sum", "avg", "min", "max"):
            i = slot_for("num", col)
            if comp["kind"] in ("sum", "avg"):
                slots[i].need_sum = True
            elif comp["kind"] == "min":
                slots[i].need_low = True
            elif comp["kind"] == "max":
                slots[i].need_high = True
        else:
            i = slot_for(comp["kind"], col)
        comp["slot"] = i
    order = sel.order_by
    if order is not None:
        stripped = order.split(".", 1)[1] if "." in order else order
        if stripped not in sel_names and order not in sel_names:
            raise ValueError(f"ORDER BY column {order!r} is not in "
                             f"the select list")
    where = (_strip_qualifier(sel.where, sel.alias)
             if sel.where is not None else ast.Include())
    return ViewState(sel, sql, where, keys, comps, slots)


# -- state --------------------------------------------------------------------


class ViewState:
    def __init__(self, sel: SqlSelect, sql: str, where: ast.Filter,
                 keys: list[str], comps: list[dict],
                 slots: list[_SlotSpec], reservoir_k: int = 8):
        self.sel = sel
        self.sql = sql
        self.table = sel.table
        self.where = where
        self.keys = keys
        self.comps = comps
        self.slots = slots
        self.reservoir_k = max(1, int(reservoir_k))
        self.groups: dict[tuple, _Group] = {}

    # -- full (re)build -----------------------------------------------------

    def build(self, store) -> None:
        """Vectorized from-scratch build: one filtered scan, the
        engine's own factorize/segment-reduce shapes."""
        res = store.query(Query(self.table, self.where))
        batch, ids, n = res.batch, res.ids, res.n
        self.groups = {}
        if batch is None or n == 0:
            return
        ginv, rep, ng = self._group_ids(batch, n)
        nrows = np.bincount(ginv, minlength=ng)
        key_cols = [batch.col(k) for k in self.keys]
        kts = [tuple(_norm_key(c.value(int(rep[g]))) for c in key_cols)
               for g in range(ng)]
        groups = [_Group(kts[g], self.slots) for g in range(ng)]
        for g in range(ng):
            groups[g].nrows = int(nrows[g])
        for si, spec in enumerate(self.slots):
            self._build_slot(spec, si, batch, ids, ginv, ng, groups)
        self.groups = {g.key: g for g in groups}

    def _group_ids(self, batch, n):
        """Composite group ids, mirroring ``SqlEngine._grouped``."""
        from ..sql.engine import _factorize
        gid = np.zeros(n, dtype=np.int64)
        bound = 1
        for k in self.keys:
            codes, _ = _factorize(batch.col(k))
            cmax = int(codes.max()) + 1
            if bound > (1 << 60) // max(cmax, 1):
                _, gid = np.unique(gid, return_inverse=True)
                bound = int(gid.max()) + 1
            gid = gid * cmax + codes
            bound *= cmax
        uniq, rep, ginv = np.unique(gid, return_index=True,
                                    return_inverse=True)
        return ginv, rep, len(uniq)

    def _build_slot(self, spec, si, batch, ids, ginv, ng, groups):
        col = batch.col(spec.col)
        valid = np.asarray(col.valid)
        cnt = np.bincount(ginv, weights=valid.astype(np.float64),
                          minlength=ng).astype(np.int64)
        if spec.kind == "hull":
            from ..sql.engine import _group_hull
            hulls = _group_hull(col, None, ginv, ng)
            for g in range(ng):
                st = groups[g].slots[si]
                st.cnt = int(cnt[g])
                st.geom = hulls[g]
            return
        if spec.kind == "extent":
            lo, hi = _extent_bounds(col, valid, ginv, ng)
            for g in range(ng):
                st = groups[g].slots[si]
                st.cnt = int(cnt[g])
                if lo[g] is not None:
                    st.lo, st.hi = lo[g], hi[g]
            return
        floats = _col_floats(col)
        isnan = (np.zeros(len(valid), bool) if floats is None
                 else np.isnan(floats))
        nan = np.bincount(ginv, weights=(valid & isnan).astype(np.float64),
                          minlength=ng).astype(np.int64)
        if spec.need_sum:
            # identical call to the engine's SUM reduce: same row-order
            # float64 accumulation, invalid rows add +0.0
            w = np.where(valid, floats, 0.0)
            s = np.bincount(ginv, weights=w, minlength=ng)
            nonint = valid & (isnan | (floats != np.floor(
                np.where(isnan, 0.0, floats))))
            n_nonint = np.bincount(ginv, weights=nonint.astype(
                np.float64), minlength=ng)
            absum = np.bincount(ginv, weights=np.abs(w), minlength=ng)
        K = self.reservoir_k
        if spec.need_low or spec.need_high:
            vr = np.flatnonzero(valid & ~isnan)
            order = vr[np.lexsort((vr, floats[vr], ginv[vr]))]
            gs = ginv[order]
            grid = np.arange(ng)
            starts = np.searchsorted(gs, grid)
            ends = np.searchsorted(gs, grid, side="right")
        for g in range(ng):
            st = groups[g].slots[si]
            st.cnt = int(cnt[g])
            st.nan = int(nan[g])
            if spec.need_sum:
                st.sum = np.float64(s[g])
                st.absum = float(absum[g])
                st.int_ok = (n_nonint[g] == 0
                             and st.absum <= float(1 << 53))
            if spec.need_low or spec.need_high:
                seg = order[starts[g]:ends[g]]
                full = len(seg) <= K
                if spec.need_low:
                    st.low = [(float(floats[i]), str(ids[i]))
                              for i in seg[:K]]
                    st.low_full = full
                if spec.need_high:
                    st.high = [(float(floats[i]), str(ids[i]))
                               for i in seg[len(seg) - K:]
                               ] if len(seg) > K else \
                        [(float(floats[i]), str(ids[i])) for i in seg]
                    st.high_full = full

    # -- incremental folds ----------------------------------------------------

    def _slot_views(self, batch):
        views = []
        for spec in self.slots:
            col = batch.col(spec.col)
            valid = np.asarray(col.valid)
            if spec.kind == "num":
                floats = _col_floats(col)
                views.append((valid, floats, col))
            elif spec.kind == "extent":
                if isinstance(col, PointColumn):
                    x = np.asarray(col.x, np.float64)
                    y = np.asarray(col.y, np.float64)
                    b = np.stack([x, y, x, y], axis=1)
                else:
                    b = np.asarray(col.bounds, np.float64)
                views.append((valid, b, col))
            else:
                views.append((valid, None, col))
        return views

    def fold_insert(self, batch, ids, rows) -> set:
        """Fold `rows` (WHERE-matching indices of a freshly-written
        batch, in batch order — i.e. table order) into group state."""
        key_cols = [batch.col(k) for k in self.keys]
        views = self._slot_views(batch)
        changed: set = set()
        for i in rows:
            i = int(i)
            kt = tuple(_norm_key(c.value(i)) for c in key_cols)
            g = self.groups.get(kt)
            if g is None:
                g = self.groups[kt] = _Group(kt, self.slots)
            g.nrows += 1
            g.row = None
            changed.add(kt)
            if g.dirty:
                continue            # replay will recompute the slots
            for si, spec in enumerate(self.slots):
                self._insert_row(spec, g.slots[si], views[si], i,
                                 str(ids[i]))
        return changed

    def _insert_row(self, spec, st, view, i, fid):
        valid, data, col = view
        if spec.kind == "num":
            w = np.float64(data[i]) if (data is not None and valid[i]) \
                else np.float64(0.0)
            if spec.need_sum:
                st.sum = st.sum + w     # same op bincount performs
                if st.int_ok:
                    fw = float(w)
                    if fw != fw or fw != np.floor(fw):
                        st.int_ok = False
                    else:
                        st.absum += abs(fw)
                        if st.absum > float(1 << 53):
                            st.int_ok = False
            if not valid[i]:
                return
            st.cnt += 1
            if data is None:
                return
            v = float(data[i])
            if v != v:
                st.nan += 1
                return
            K = self.reservoir_k
            if spec.need_low and st.low is not None:
                # invariant: everything outside `low` >= max(low)
                if st.low_full or not st.low or v < st.low[-1][0]:
                    bisect.insort(st.low, (v, fid))
                    if len(st.low) > K:
                        st.low.pop()
                        st.low_full = False
            if spec.need_high and st.high is not None:
                if st.high_full or not st.high or v > st.high[0][0]:
                    bisect.insort(st.high, (v, fid))
                    if len(st.high) > K:
                        st.high.pop(0)
                        st.high_full = False
            return
        if not valid[i]:
            return
        st.cnt += 1
        if spec.kind == "extent":
            b = data[i]
            if st.lo is None:
                st.lo = b[:2].copy()
                st.hi = b[2:].copy()
            else:
                # same sequential fold reduceat performs in row order
                st.lo = np.minimum(st.lo, b[:2])
                st.hi = np.maximum(st.hi, b[2:])
            return
        # hull: hull-of-hulls is exact — the fold's vertex set has the
        # same convex hull as the full point set
        from ..analytics.st_functions import convex_hull_points
        if isinstance(col, PointColumn):
            pts = np.array([[float(col.x[i]), float(col.y[i])]])
        else:
            pts = np.vstack(col.value(i).coords_list())
        if st.geom is not None:
            pts = np.vstack([np.vstack(st.geom.coords_list()), pts])
        st.geom = convex_hull_points(pts)

    def fold_delete(self, batch, ids, rows):
        """Retract `rows` (WHERE-matching pre-image rows captured
        before the delete applied). Returns (changed keys, removed
        keys, reservoir fallbacks)."""
        key_cols = [batch.col(k) for k in self.keys]
        views = self._slot_views(batch)
        changed: set = set()
        removed: set = set()
        fallbacks = 0
        for i in rows:
            i = int(i)
            kt = tuple(_norm_key(c.value(i)) for c in key_cols)
            g = self.groups.get(kt)
            if g is None:
                continue            # defensive: state never saw the row
            g.nrows -= 1
            g.row = None
            if g.nrows <= 0:
                del self.groups[kt]
                removed.add(kt)
                changed.discard(kt)
                continue
            changed.add(kt)
            if g.dirty:
                continue
            for si, spec in enumerate(self.slots):
                fallbacks += self._retract_row(spec, g, g.slots[si],
                                               views[si], i, str(ids[i]))
        return changed, removed, fallbacks

    def _retract_row(self, spec, g, st, view, i, fid) -> int:
        valid, data, col = view
        if spec.kind == "num":
            if spec.need_sum:
                if not st.int_ok:
                    # float addition is not invertible in sequence
                    # order — a non-integral sum replays on retraction
                    g.dirty = True
                    return 0
                w = float(data[i]) if (data is not None and valid[i]) \
                    else 0.0
                st.sum = st.sum - np.float64(w)   # exact: integer sum
                st.absum -= abs(w)
            if not valid[i]:
                return 0
            st.cnt -= 1
            if data is None:
                return 0
            v = float(data[i])
            if v != v:
                st.nan -= 1
                return 0
            fb = 0
            if spec.need_low and st.low is not None:
                fb += self._reservoir_remove(g, st, "low", v, fid)
            if spec.need_high and st.high is not None and not g.dirty:
                fb += self._reservoir_remove(g, st, "high", v, fid)
            return fb
        if not valid[i]:
            return 0
        st.cnt -= 1
        if st.cnt > 0:
            g.dirty = True          # hull/extent folds cannot retract
        else:
            st.geom = None
            st.lo = st.hi = None
        return 0

    def _reservoir_remove(self, g, st, side, v, fid) -> int:
        res = getattr(st, side)
        full = getattr(st, side + "_full")
        entry = (v, fid)
        j = bisect.bisect_left(res, entry)
        if j < len(res) and res[j] == entry:
            res.pop(j)
        else:
            boundary_ok = (not res) or (
                v >= res[-1][0] if side == "low" else v <= res[0][0])
            if full or not boundary_ok:
                # a value the reservoir should have covered is missing:
                # state can no longer prove the extreme — replay
                g.dirty = True
                setattr(st, side, None)
                return 1
            return 0                # trimmed-away region: no-op
        if not res and not full and st.cnt - st.nan > 0:
            # drained: runner-ups exhausted while values remain
            g.dirty = True
            setattr(st, side, None)
            return 1
        return 0

    # -- replay (dirty groups) -------------------------------------------------

    def _replay(self, store, g) -> bool:
        """Recompute one group with a targeted store query. Scan
        strategies return table-order rows, so the single-group reduce
        is bit-identical to the group's slice of a full re-execution."""
        flt: list = []
        if not isinstance(self.where, ast.Include):
            flt.append(self.where)
        for k, v in zip(self.keys, g.key):
            if v is None:
                flt.append(ast.IsNull(k))
            elif v is _NAN_KEY:
                raise _NanKeyReplay()
            else:
                flt.append(ast.Compare("=", k, v))
        f = (ast.And(flt) if len(flt) > 1
             else (flt[0] if flt else ast.Include()))
        res = store.query(Query(self.table, f))
        n = res.n
        if n == 0 or res.batch is None:
            return False
        fresh = _Group(g.key, self.slots)
        fresh.nrows = n
        ginv = np.zeros(n, dtype=np.int64)
        for si, spec in enumerate(self.slots):
            self._build_slot(spec, si, res.batch, res.ids, ginv, 1,
                             [fresh])
        g.nrows = n
        g.slots = fresh.slots
        g.dirty = False
        g.row = None
        return True

    def ensure_clean(self, store) -> int:
        """Replay every dirty group; returns the number of replays
        (a full rebuild counts as one)."""
        replays = 0
        for kt in [kt for kt, g in self.groups.items() if g.dirty]:
            g = self.groups.get(kt)
            if g is None or not g.dirty:
                continue
            try:
                if not self._replay(store, g):
                    del self.groups[kt]
            except _NanKeyReplay:
                self.build(store)
                return replays + 1
            replays += 1
        return replays

    # -- finalize ----------------------------------------------------------------

    def _comp_value(self, g, comp):
        kind = comp["kind"]
        if kind == "key":
            return _denorm_key(g.key[comp["key"]])
        st = g.slots[comp["slot"]] if comp["slot"] >= 0 else None
        if kind == "count":
            return np.int64(g.nrows if st is None else st.cnt)
        if kind == "sum":
            return None if st.cnt == 0 else np.float64(st.sum)
        if kind == "avg":
            return None if st.cnt == 0 else \
                np.float64(st.sum) / np.float64(st.cnt)
        if kind == "min":
            if st.cnt == 0:
                return None
            if st.nan:
                return np.float64(np.nan)
            return np.float64(st.low[0][0])
        if kind == "max":
            if st.cnt == 0:
                return None
            if st.nan:
                return np.float64(np.nan)
            return np.float64(st.high[-1][0])
        if kind == "hull":
            return None if st.cnt == 0 else st.geom
        # extent
        if st.cnt == 0 or st.lo is None:
            return None
        return Envelope(st.lo[0], st.lo[1],
                        st.hi[0], st.hi[1]).to_polygon()

    def group_row(self, g) -> dict:
        """Finalized {output name: value} for one (clean) group."""
        if g.row is None:
            g.row = {c["name"]: self._comp_value(g, c)
                     for c in self.comps}
        return g.row

    def result(self, store) -> SqlResult:
        """Finalize to the exact single-node SqlEngine output: sorted
        group order, HAVING, hidden-column drop, ORDER BY/LIMIT."""
        self.ensure_clean(store)
        names_all = [c["name"] for c in self.comps]
        kts = sorted(self.groups, key=_sort_key)
        if not kts:
            cols_all = {n: np.empty(0, object) for n in names_all}
        else:
            cols_all = {}
            for c in self.comps:
                vals = [self._comp_value(self.groups[kt], c)
                        for kt in kts]
                if c["kind"] == "count":
                    cols_all[c["name"]] = np.array(vals, dtype=np.int64)
                else:
                    arr = np.empty(len(vals), dtype=object)
                    for i, v in enumerate(vals):
                        arr[i] = v
                    cols_all[c["name"]] = arr
        out_all = SqlResult(names_all, cols_all)

        def compute(it):
            e = it.expr.split(".")[-1]
            if not it.agg and e in self.keys:
                j = self.keys.index(e)
                return np.array([_denorm_key(kt[j]) for kt in kts],
                                dtype=object)
            raise ValueError(f"not an aggregate: {it.name} (HAVING "
                             f"terms must aggregate or be group keys)")

        out_all = SqlEngine._apply_having(out_all, self.sel.having,
                                          compute)
        sel_names = [it.name for it in self.sel.items]
        out = SqlResult(sel_names,
                        {n: out_all.columns[n] for n in sel_names})
        order = self.sel.order_by
        if order and "." in order:
            order = order.split(".", 1)[1]
        if self.sel.order_by is not None and order not in out.columns \
                and self.sel.order_by in out.columns:
            order = self.sel.order_by
        return _order_limit(out, order, self.sel.order_desc,
                            self.sel.limit)

    # -- durable blob --------------------------------------------------------------

    def to_blob(self) -> dict:
        """JSON-safe snapshot. Floats travel as ``float.hex()`` (bit
        exact), geometries as WKT (repr floats round-trip losslessly).
        Callers replay dirty groups first — only clean state is saved."""
        groups = []
        for kt in sorted(self.groups, key=_sort_key):
            g = self.groups[kt]
            gb = {"key": [_enc_key(v) for v in kt], "n": int(g.nrows),
                  "slots": []}
            for spec, st in zip(self.slots, g.slots):
                if spec.kind == "num":
                    gb["slots"].append({
                        "cnt": int(st.cnt), "nan": int(st.nan),
                        "sum": float(st.sum).hex(),
                        "iok": bool(st.int_ok),
                        "ab": float(st.absum).hex(),
                        "low": _enc_res(st.low), "lf": bool(st.low_full),
                        "high": _enc_res(st.high),
                        "hf": bool(st.high_full)})
                elif spec.kind == "hull":
                    from ..geometry import to_wkt
                    gb["slots"].append({
                        "cnt": int(st.cnt),
                        "wkt": None if st.geom is None
                        else to_wkt(st.geom)})
                else:
                    gb["slots"].append({
                        "cnt": int(st.cnt),
                        "lo": None if st.lo is None
                        else [v.hex() for v in st.lo.tolist()],
                        "hi": None if st.hi is None
                        else [v.hex() for v in st.hi.tolist()]})
            groups.append(gb)
        return {"groups": groups}

    def from_blob(self, blob: dict) -> None:
        from ..geometry import parse_wkt
        self.groups = {}
        for gb in blob["groups"]:
            kt = tuple(_dec_key(v) for v in gb["key"])
            g = _Group(kt, self.slots)
            g.nrows = int(gb["n"])
            for spec, st, sb in zip(self.slots, g.slots, gb["slots"]):
                st.cnt = int(sb["cnt"])
                if spec.kind == "num":
                    st.nan = int(sb["nan"])
                    st.sum = np.float64(float.fromhex(sb["sum"]))
                    st.int_ok = bool(sb["iok"])
                    st.absum = float.fromhex(sb["ab"])
                    st.low = _dec_res(sb["low"])
                    st.low_full = bool(sb["lf"])
                    st.high = _dec_res(sb["high"])
                    st.high_full = bool(sb["hf"])
                elif spec.kind == "hull":
                    st.geom = (None if sb["wkt"] is None
                               else parse_wkt(sb["wkt"]))
                else:
                    if sb["lo"] is not None:
                        st.lo = np.array(
                            [float.fromhex(v) for v in sb["lo"]],
                            dtype=np.float64)
                        st.hi = np.array(
                            [float.fromhex(v) for v in sb["hi"]],
                            dtype=np.float64)
            self.groups[kt] = g


def _extent_bounds(col, valid, ginv, ng):
    """Per-group (lo, hi) float64 bound folds, the reduceat shape
    ``_group_extent`` uses (kept as arrays for incremental folding)."""
    if isinstance(col, PointColumn):
        x = np.asarray(col.x, np.float64)
        y = np.asarray(col.y, np.float64)
        bx = np.stack([x, y, x, y], axis=1)
    else:
        bx = np.asarray(col.bounds, np.float64)
    lo_out: list = [None] * ng
    hi_out: list = [None] * ng
    if not valid.any():
        return lo_out, hi_out
    g = ginv[valid]
    vb = bx[valid]
    order = np.argsort(g, kind="stable")
    gs = g[order]
    vb = vb[order]
    starts = np.flatnonzero(np.diff(gs, prepend=gs[0] - 1))
    present = gs[starts]
    lo = np.minimum.reduceat(vb[:, :2], starts, axis=0)
    hi = np.maximum.reduceat(vb[:, 2:], starts, axis=0)
    for i, gi in enumerate(present):
        lo_out[gi] = lo[i].copy()
        hi_out[gi] = hi[i].copy()
    return lo_out, hi_out


def _enc_key(v):
    if v is _NAN_KEY:
        return {"nan": True}
    if isinstance(v, float):
        return {"f": v.hex()}
    if isinstance(v, bool) or v is None or isinstance(v, (int, str)):
        return {"v": v}
    raise ValueError(f"unsupported group key {v!r}")


def _dec_key(b):
    if "nan" in b:
        return _NAN_KEY
    if "f" in b:
        return float.fromhex(b["f"])
    return b["v"]


def _enc_res(res):
    return None if res is None else [[float(v).hex(), fid]
                                     for v, fid in res]


def _dec_res(blob):
    return None if blob is None else \
        [(float.fromhex(v), fid) for v, fid in blob]
