"""Consuming half of a materialized view's delta stream.

Every fold publishes one message on ``view.<name>``: a JSON payload in
the message's ``ids`` header carrying the changed groups' finalized
rows, the removed group keys, the view's LSN and a per-view contiguous
``seq``. ``ViewDeltaSubscriber`` mirrors ``ContinuousQuerySubscriber``:
its own consumer group commits offsets independently, so across a
broker kill/restart (persistent broker ``root=``) delivery is
exactly-once from the last commit — the ``seq`` field lets consumers
assert it.
"""

from __future__ import annotations

import json
from typing import Callable

__all__ = ["ViewDeltaSubscriber", "view_topic"]


def view_topic(name: str) -> str:
    return f"view.{name}"


class ViewDeltaSubscriber:
    def __init__(self, name: str, host: str | None = None,
                 port: int | None = None, group: str = "default",
                 bus=None, timeout_s: float = 30.0):
        self.name = name
        self.topic = view_topic(name)
        if bus is None:
            if host is None or port is None:
                raise ValueError("pass host/port or bus=")
            from ..store.socketbus import SocketBus
            bus = SocketBus(host, port, group=f"view.{name}.{group}",
                            timeout_s=timeout_s)
            self._owns_bus = True
        else:
            self._owns_bus = False
        self.bus = bus
        self._handlers: list[Callable[[dict], None]] = []
        bus.subscribe(self.topic, self._deliver)

    def _deliver(self, msg):
        if not msg.ids:
            return
        delta = json.loads(msg.ids[0])
        for fn in self._handlers:
            fn(delta)

    def on_delta(self, fn: Callable[[dict], None]):
        """fn(delta) per fold; delta = {"view", "lsn", "seq",
        "rows": [{"key", "row"}...], "removed": [key...]}."""
        self._handlers.append(fn)
        return fn

    def poll(self, wait_s: float = 0.0,
             max_messages: int | None = None) -> int:
        poll = getattr(self.bus, "poll", None)
        if poll is None:
            return 0
        return poll(max_messages=max_messages, wait_s=wait_s)

    def offset(self) -> int:
        off = getattr(self.bus, "offset", None)
        return off(self.topic) if callable(off) else 0

    def close(self):
        if self._owns_bus:
            close = getattr(self.bus, "close", None)
            if callable(close):
                close()
