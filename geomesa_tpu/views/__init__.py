"""Incrementally-maintained materialized views.

Standing single-table GROUP BY aggregate statements folded on every
write-path commit: O(delta rows) per group-commit instead of O(table)
per refresh, bit-identical to a from-scratch re-execution at the same
LSN (including under deletes), served through the LSN-keyed result
cache and streamed as group deltas on ``view.<name>`` bus topics.
"""

from .registry import (VIEW_RESERVOIR_K, VIEWS_ENABLED,
                       MaterializedView, ViewRegistry)
from .state import ViewState, compile_view
from .subscribe import ViewDeltaSubscriber, view_topic

__all__ = ["ViewRegistry", "MaterializedView", "ViewState",
           "compile_view", "ViewDeltaSubscriber", "view_topic",
           "VIEWS_ENABLED", "VIEW_RESERVOIR_K"]
