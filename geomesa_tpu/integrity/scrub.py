"""Background scrubber: periodic re-verification + quarantine.

Silent corruption is only caught by reading the data back; the
scrubber walks a durable root on a cadence (``geomesa.scrub.interval.s``)
and re-verifies WAL segment CRCs and checkpoint digests. Corrupt
checkpoints are quarantined (renamed ``*.corrupt`` so recovery falls
back to the next intact snapshot) when ``geomesa.integrity.quarantine``
is on; corrupt mid-history WAL segments are reported and counted but
NEVER renamed — pulling a segment out of the log would silently turn a
detected gap into an undetected one (replay must stop at the corrupt
frame, not skip past it).

On a replica the scrubber doubles as anti-entropy (Dynamo's Merkle
sweep, one level simpler): it asks the primary for a per-type
row-count + content digest (the shipper's ``digest`` op), compares the
replica's own state, and triggers a re-bootstrap on divergence — but
only when both sides agree the replica is fully caught up, so a
legitimate streaming lag is never misread as corruption.
"""

from __future__ import annotations

import threading
import time

from ..metrics import metrics
from ..utils.properties import SystemProperty
from .verify import ids_digest, quarantine, verify_checkpoint, verify_wal

__all__ = ["Scrubber", "integrity_report", "SCRUB_INTERVAL_S",
           "INTEGRITY_QUARANTINE"]

# scrub cadence (seconds) for the background loop
SCRUB_INTERVAL_S = SystemProperty("geomesa.scrub.interval.s", "60")
# rename corrupt artifacts to *.corrupt (off: detect + report only)
INTEGRITY_QUARANTINE = SystemProperty("geomesa.integrity.quarantine",
                                      "true")


def integrity_report(root: str) -> dict:
    """Read-only verification sweep over a durable root (``log/`` +
    ``snapshots/``): the GET /rest/integrity and ``tools integrity
    verify`` payload. Never quarantines."""
    import os

    from ..wal.snapshot import checkpoint_dirs
    wal = verify_wal(os.path.join(root, "log"))
    ckpts = []
    for lsn, path in checkpoint_dirs(root):
        rep = verify_checkpoint(path)
        rep["dir"] = os.path.basename(path)
        ckpts.append(rep)
    return {"root": root, "ok": wal["ok"] and all(c["ok"] for c in ckpts),
            "wal": wal, "checkpoints": ckpts}


class Scrubber:
    """Periodic integrity verifier for a durable root and/or a replica.

    ``Scrubber(journal=ds.journal).start()`` scrubs a primary's WAL +
    checkpoints; ``Scrubber(replica=r)`` adds the anti-entropy digest
    comparison against ``r``'s primary. ``run_once()`` is the
    synchronous unit (the CLI and POST /rest/integrity/scrub call it
    directly)."""

    def __init__(self, journal=None, replica=None,
                 interval_s: float | None = None,
                 quarantine_corrupt: bool | None = None,
                 registry=metrics):
        if journal is None and replica is None:
            raise ValueError("scrubber needs a journal and/or a replica")
        self.journal = journal
        self.replica = replica
        self.interval_s = float(
            interval_s if interval_s is not None
            else (SCRUB_INTERVAL_S.as_float() or 60.0))
        self.quarantine_corrupt = bool(
            quarantine_corrupt if quarantine_corrupt is not None
            else INTEGRITY_QUARANTINE.as_bool())
        self.registry = registry
        self.runs = 0
        self.last_report: dict | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Scrubber":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="integrity-scrubber")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.run_once()
            except Exception:
                # a scrub pass must never take the process down
                self.registry.counter("integrity.scrub.crashes")

    # -- one pass ----------------------------------------------------------

    def run_once(self) -> dict:
        t0 = time.perf_counter()
        out: dict = {"ok": True, "quarantined": [], "unreferenced": []}
        if self.journal is not None:
            self._scrub_root(out)
        if self.replica is not None:
            self._scrub_replica(out)
        out["seconds"] = round(time.perf_counter() - t0, 4)
        self.runs += 1
        self.last_report = out
        self.registry.counter("integrity.scrub.runs")
        self.registry.gauge("integrity.scrub.seconds", out["seconds"])
        if not out["ok"]:
            self.registry.counter("integrity.scrub.errors")
        return out

    def _scrub_root(self, out: dict):
        import os

        from ..wal.snapshot import checkpoint_dirs
        root = self.journal.root
        wal = verify_wal(os.path.join(root, "log"))
        out["wal"] = wal
        if not wal["ok"]:
            out["ok"] = False
            self.registry.counter("integrity.corrupt.wal.segments",
                                  len(wal["corrupt_segments"]))
        ckpts = []
        for lsn, path in checkpoint_dirs(root):
            rep = verify_checkpoint(path)
            rep["dir"] = os.path.basename(path)
            ckpts.append(rep)
            if rep["unreferenced"]:
                # crashed-attempt debris inside the dir: flag only
                out["unreferenced"].extend(
                    os.path.join(os.path.basename(path), f)
                    for f in rep["unreferenced"])
            if not rep["ok"]:
                out["ok"] = False
                self.registry.counter("integrity.corrupt.checkpoints")
                if self.quarantine_corrupt:
                    moved = quarantine(path, self.registry)
                    if moved is not None:
                        rep["quarantined_to"] = os.path.basename(moved)
                        out["quarantined"].append(os.path.basename(moved))
        # abandoned .tmp staging dirs (crash mid-checkpoint): flag too
        snapdir = os.path.join(root, "snapshots")
        try:
            for d in sorted(os.listdir(snapdir)):
                if d.endswith(".tmp"):
                    out["unreferenced"].append(d)
        except OSError:
            pass
        if out["unreferenced"]:
            self.registry.counter("integrity.unreferenced",
                                  len(out["unreferenced"]))
        out["checkpoints"] = ckpts

    def _scrub_replica(self, out: dict):
        from ..replication.sync import ReplClient
        rep = self.replica
        anti: dict = {"checked": False, "mismatch": []}
        out["anti_entropy"] = anti
        if not rep.attached:
            return
        try:
            client = ReplClient(rep.host, rep.port,
                                timeout_s=rep.timeout_s)
            try:
                remote = client.digest()
            finally:
                client.close()
        except (ConnectionError, TimeoutError, OSError) as e:
            anti["error"] = repr(e)
            return
        if remote.get("error"):
            anti["error"] = remote["error"]
            return
        # only a quiescent, fully caught-up replica is comparable: the
        # primary must not have advanced while computing, and the
        # replica must have applied everything shipped
        if not (remote.get("last_lsn_pre") == remote.get("last_lsn")
                == rep.applied_lsn and rep.applied_lsn > 0):
            anti["skipped"] = "replica lagging or primary in flux"
            return
        anti["checked"] = True
        for name, want in remote.get("types", {}).items():
            try:
                rows, digest = ids_digest(rep, name)
            except KeyError:
                rows, digest = -1, ""
            if rows != int(want["rows"]) or digest != want["digest"]:
                anti["mismatch"].append(name)
        missing = set(t for t in rep.get_type_names()
                      if t not in remote.get("types", {}))
        anti["mismatch"].extend(sorted(missing))
        if anti["mismatch"]:
            out["ok"] = False
            self.registry.counter("integrity.antientropy.mismatches")
            self.registry.counter("integrity.antientropy.rebootstraps")
            anti["rebootstrap"] = True
            rep.request_rebootstrap()

    # -- status ------------------------------------------------------------

    def status(self) -> dict:
        return {"runs": self.runs, "interval_s": self.interval_s,
                "quarantine": self.quarantine_corrupt,
                "running": (self._thread is not None
                            and self._thread.is_alive()),
                "last_report": self.last_report}
