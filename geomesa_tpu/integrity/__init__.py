"""Storage fault tolerance: fault injection, end-to-end verification,
scrub + quarantine, crash-consistency harness.

Only :mod:`.faultfs` is imported eagerly — the WAL and filebus route
their writes/fsyncs through it on every call, so it must be cheap and
dependency-free. Everything else (verify / scrub / harness) pulls in
the wal and store packages and is exposed lazily to keep this package
importable from inside them without a cycle.
"""

from . import faultfs
from .faultfs import CrashPoint, Fault, FaultDisk, flip_bit

__all__ = [
    "faultfs", "FaultDisk", "Fault", "CrashPoint", "flip_bit",
    # lazy (PEP 562):
    "sha256_hex", "file_sha256", "verify_checkpoint", "verify_wal",
    "ids_digest", "quarantine",
    "Scrubber", "integrity_report",
    "CrashHarness", "run_crash_workload",
]

_LAZY = {
    "sha256_hex": "verify", "file_sha256": "verify",
    "verify_checkpoint": "verify", "verify_wal": "verify",
    "ids_digest": "verify", "quarantine": "verify",
    "Scrubber": "scrub", "integrity_report": "scrub",
    "CrashHarness": "harness", "run_crash_workload": "harness",
}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib
    return getattr(importlib.import_module(f".{mod}", __name__), name)
