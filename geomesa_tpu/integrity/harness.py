"""Crash-consistency harness: randomized kill-points over real ingest.

The ALICE methodology (Pillai et al., OSDI '14) distilled to this
stack: run an acked-write workload against a durable store, kill it at
a randomly chosen storage operation (torn write / EIO / fsync failure
via :mod:`.faultfs`), reopen from disk, and check the durability
contract rather than any particular execution:

- **no acked loss** — every write acked before the kill is present
  after recovery (``wal_fsync="always"`` makes ack durable);
- **no garbage** — nothing the workload never wrote appears;
- **no duplicates** — replay idempotence holds across re-application;
- **at-most-once tail** — the single in-flight unacked write may
  survive (frame hit the file before the cut) or vanish, never
  partially.

A poisoned WAL (injected fsync failure) is part of the contract too:
the store must keep serving reads, refuse writes with
``DurabilityError``, and a fresh store on the same root must recover
everything acked before the poison.
"""

from __future__ import annotations

import random

from .faultfs import CrashPoint, FaultDisk

__all__ = ["CrashHarness", "run_crash_workload"]

_SPEC = "name:String,dtg:Date,*geom:Point:srid=4326"


def _make_batch(sft, ids, seed=7):
    import numpy as np

    from ..features.batch import FeatureBatch
    rng = np.random.default_rng(seed)
    n = len(ids)
    return FeatureBatch.from_dict(sft, ids, {
        "name": [f"n{i % 5}" for i in range(n)],
        "dtg": rng.integers(0, 10**12, n),
        "geom": (rng.uniform(-100, -60, n), rng.uniform(25, 50, n))})


class CrashHarness:
    """Randomized kill-point runner over one durable root.

    Each round opens a fresh store on ``root`` (recovery), checks the
    invariants against everything acked so far, then ingests
    single-feature writes with one randomly armed storage fault. The
    fault either unwinds the workload (simulated crash — the store is
    abandoned via ``journal.abort()``, never closed cleanly) or
    poisons the WAL (fsync), in which case read-only degradation is
    asserted in place. Violations accumulate in ``self.violations``;
    an empty list after ``run()`` is the pass condition."""

    _KINDS = ("torn", "eio", "fsync", "enospc")

    def __init__(self, root: str, seed: int = 0, type_name: str = "crash",
                 checkpoint_every: int = 3):
        self.root = str(root)
        self.rng = random.Random(seed)
        self.type_name = type_name
        self.checkpoint_every = int(checkpoint_every)
        self.acked: list[str] = []
        self.issued: set[str] = set()
        self.violations: list[str] = []
        self.faults: list[tuple[str, str, str]] = []
        self.rounds_run = 0

    # -- pieces ------------------------------------------------------------

    def _open(self):
        from ..features.sft import parse_spec
        from ..store.memory import InMemoryDataStore
        ds = InMemoryDataStore(durable_dir=self.root, wal_fsync="always")
        if self.type_name not in ds.get_type_names():
            ds.create_schema(parse_spec(self.type_name, _SPEC))
        return ds

    def _surviving_ids(self, ds) -> list[str]:
        res = ds.query("INCLUDE", self.type_name)
        return [] if res.batch is None else list(map(str, res.ids))

    def check_invariants(self, ds, where: str):
        ids = self._surviving_ids(ds)
        got = set(ids)
        if len(ids) != len(got):
            self.violations.append(f"{where}: duplicate rows after recovery")
        lost = [i for i in self.acked if i not in got]
        if lost:
            self.violations.append(
                f"{where}: {len(lost)} acked write(s) lost, e.g. {lost[:3]}")
        garbage = got - self.issued
        if garbage:
            self.violations.append(
                f"{where}: {len(garbage)} garbage row(s), "
                f"e.g. {sorted(garbage)[:3]}")

    def _arm(self, disk: FaultDisk):
        """One random fault at a random kill-point: skip 0..N matching
        storage ops before firing, so the cut lands anywhere in the
        round's write sequence."""
        kind = self.rng.choice(self._KINDS)
        op = "fsync" if kind == "fsync" else "write"
        disk.add(op, match="log", kind=kind,
                 skip=self.rng.randrange(0, 12))

    # -- driver ------------------------------------------------------------

    def run(self, rounds: int = 5, writes_per_round: int = 20) -> dict:
        from ..features.sft import parse_spec  # noqa: F401 (fail fast)
        from ..wal import DurabilityError
        for rnd in range(rounds):
            self.rounds_run += 1
            ds = self._open()
            try:
                self.check_invariants(ds, f"round {rnd} reopen")
                sft = ds.get_schema(self.type_name)
                disk = FaultDisk()
                self._arm(disk)
                poisoned = False
                with disk:
                    for i in range(writes_per_round):
                        fid = f"r{rnd}-{i}"
                        batch = _make_batch(sft, [fid],
                                            seed=rnd * 1000 + i)
                        self.issued.add(fid)
                        try:
                            ds.write(self.type_name, batch)
                        except (CrashPoint, DurabilityError, OSError):
                            poisoned = ds.journal.poisoned
                            break
                        self.acked.append(fid)
                        if (self.checkpoint_every
                                and i % self.checkpoint_every == 2
                                and self.rng.random() < 0.3):
                            try:
                                ds.checkpoint()
                            except (CrashPoint, DurabilityError,
                                    OSError):
                                poisoned = ds.journal.poisoned
                                break
                self.faults.extend(disk.injected)
                if poisoned:
                    # degraded mode: reads fine, writes typed-refused
                    self.check_invariants(ds, f"round {rnd} poisoned reads")
                    try:
                        ds.write(self.type_name,
                                 _make_batch(sft, [f"r{rnd}-poisoned"]))
                        self.violations.append(
                            f"round {rnd}: poisoned store accepted a write")
                    except DurabilityError:
                        pass
            finally:
                # simulated crash: drop the store without clean close
                ds.journal.abort()
        # final reopen with no faults armed
        ds = self._open()
        try:
            self.check_invariants(ds, "final reopen")
        finally:
            ds.close()
        return self.report()

    def report(self) -> dict:
        return {"ok": not self.violations, "rounds": self.rounds_run,
                "acked": len(self.acked), "issued": len(self.issued),
                "faults_injected": len(self.faults),
                "violations": list(self.violations)}


def run_crash_workload(root: str, rounds: int = 5,
                       writes_per_round: int = 20, seed: int = 0) -> dict:
    """One-call wrapper: build a harness, run it, return the report."""
    h = CrashHarness(root, seed=seed)
    return h.run(rounds=rounds, writes_per_round=writes_per_round)
