"""Injectable fault disk: the storage tier's chaos proxy.

The network resilience layer earns its guarantees against a
``ChaosProxy``; this module gives the durability/replication stack the
same adversary for the disk. Production code routes its storage-side
writes and fsyncs through the two module functions below (``write`` /
``fsync``) — zero-cost pass-throughs until a ``FaultDisk`` is
installed, at which point chosen operations fail the way real disks
fail (Pillai et al., OSDI '14; Rebello et al., ATC '20):

- ``eio`` / ``enospc``  — the syscall raises (I/O error, disk full);
- ``fsync``             — fsync raises EIO *without* syncing: the page
  cache state is unknowable afterwards (fsyncgate), which is why the
  WAL poisons itself rather than retrying;
- ``torn``              — only a prefix of the buffer reaches the file,
  then ``CrashPoint`` unwinds the caller like a power cut mid-write;
- ``bitflip``           — one bit of the buffer inverts and the write
  *succeeds silently* (firmware/cable corruption; only end-to-end
  checksums catch it).

Faults are matched by operation + path substring and are one-shot by
default (``count=1``), with ``skip=N`` to arm on the (N+1)-th matching
call — the randomized kill-point knob the crash-consistency harness
turns. ``flip_bit`` corrupts a file already at rest (bit rot), for
scrubber and recovery tests.

Install is process-global but explicitly scoped::

    disk = FaultDisk()
    disk.add("fsync", match="log", kind="fsync")   # one-shot
    with disk:
        ...workload...
    assert disk.injected  # [(op, path, kind), ...]
"""

from __future__ import annotations

import errno
import os
import threading

from ..metrics import metrics

__all__ = ["FaultDisk", "Fault", "CrashPoint", "install", "uninstall",
           "active", "write", "fsync", "flip_bit"]

_KINDS = ("eio", "enospc", "torn", "bitflip", "fsync")


class CrashPoint(OSError):
    """A torn write's unwind: the simulated machine lost power with
    only a prefix of the buffer on disk. Harnesses catch it and treat
    the store as dead (reopen, never close cleanly)."""


class Fault:
    """One armed fault: fires on matching (op, path) calls."""

    __slots__ = ("op", "match", "kind", "count", "skip")

    def __init__(self, op: str, match: str = "", kind: str = "eio",
                 count: int = 1, skip: int = 0):
        if kind not in _KINDS:
            raise ValueError(f"unknown fault kind {kind!r}")
        if op not in ("write", "fsync"):
            raise ValueError(f"unknown fault op {op!r}")
        self.op = op
        self.match = match
        self.kind = kind
        self.count = int(count)   # firings left (<=0 = spent)
        self.skip = int(skip)     # matching calls to let through first

    def __repr__(self):
        return (f"Fault({self.op!r}, match={self.match!r}, "
                f"kind={self.kind!r}, count={self.count}, "
                f"skip={self.skip})")


class FaultDisk:
    """A programmable plan of storage faults; a context manager that
    installs itself as the process's active injector."""

    def __init__(self):
        self._lock = threading.Lock()
        self._faults: list[Fault] = []
        self.injected: list[tuple[str, str, str]] = []

    def add(self, op: str, match: str = "", kind: str = "eio",
            count: int = 1, skip: int = 0) -> "FaultDisk":
        with self._lock:
            self._faults.append(Fault(op, match, kind, count, skip))
        return self

    def _take(self, op: str, path: str) -> Fault | None:
        with self._lock:
            for f in self._faults:
                if f.op != op or f.count <= 0 or f.match not in path:
                    continue
                if f.skip > 0:
                    f.skip -= 1
                    continue
                f.count -= 1
                self.injected.append((op, path, f.kind))
                return f
        return None

    def pending(self) -> int:
        """Armed (unfired) faults left in the plan."""
        with self._lock:
            return sum(1 for f in self._faults if f.count > 0)

    def __enter__(self) -> "FaultDisk":
        install(self)
        return self

    def __exit__(self, *exc):
        uninstall(self)


_active: FaultDisk | None = None


def install(disk: FaultDisk):
    global _active
    _active = disk


def uninstall(disk: FaultDisk | None = None):
    global _active
    if disk is None or _active is disk:
        _active = None


def active() -> FaultDisk | None:
    return _active


def _flip(data: bytes) -> bytes:
    """One inverted bit mid-buffer: past any header, inside payload."""
    buf = bytearray(data)
    buf[len(buf) // 2] ^= 0x01
    return bytes(buf)


def write(f, data: bytes, path: str):
    """Write ``data`` to the open file object ``f`` whose destination
    is ``path`` (the logical target, not a tmp name), applying any
    armed write fault."""
    disk = _active
    if disk is None:
        f.write(data)
        return
    fault = disk._take("write", path)
    if fault is None:
        f.write(data)
        return
    metrics.counter("integrity.faults.injected")
    if fault.kind == "eio":
        raise OSError(errno.EIO, f"injected I/O error: {path}")
    if fault.kind == "enospc":
        raise OSError(errno.ENOSPC, f"injected disk full: {path}")
    if fault.kind == "torn":
        f.write(data[:max(len(data) // 2, 1)])
        f.flush()
        raise CrashPoint(errno.EIO, f"injected torn write: {path}")
    if fault.kind == "bitflip":
        f.write(_flip(data))  # succeeds silently — checksums must catch
        return
    raise OSError(errno.EIO, f"injected {fault.kind}: {path}")


def fsync(fd: int, path: str = ""):
    """fsync ``fd`` (whose file is ``path``), applying any armed fsync
    fault. An injected failure raises WITHOUT syncing — afterwards the
    kernel may have dropped the dirty pages (fsyncgate), so callers
    must treat the data as possibly lost."""
    disk = _active
    if disk is not None and disk._take("fsync", path) is not None:
        metrics.counter("integrity.faults.injected")
        raise OSError(errno.EIO, f"injected fsync failure: {path}")
    os.fsync(fd)


def flip_bit(path: str, offset: int | None = None):
    """Corrupt one bit of a file at rest (silent media bit rot). The
    default offset lands mid-file — inside frame payloads / column
    bytes, past headers."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot flip a bit in empty file {path}")
    off = size // 2 if offset is None else int(offset)
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0x01]))
