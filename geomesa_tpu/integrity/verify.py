"""End-to-end integrity checks over the durable artifacts.

The WAL already CRC-frames every record; this module closes the other
gaps: checkpoint ``.bin`` payloads get a per-file SHA-256 + length in
the manifest (written by ``wal.snapshot.write_checkpoint``), and the
functions here re-verify both artifact families — at load time
(``load_checkpoint`` falls back past a corrupt snapshot), over the wire
(replica bootstrap), and periodically (the scrubber).

``quarantine`` renames a corrupt artifact to ``*.corrupt`` so loaders
stop selecting it while the evidence survives for forensics — the
disposition Ext4/ZFS scrubs apply to unrecoverable blocks.
"""

from __future__ import annotations

import hashlib
import json
import os

from ..metrics import metrics

__all__ = ["sha256_hex", "file_sha256", "verify_checkpoint",
           "verify_wal", "ids_digest", "quarantine"]

_QUARANTINE_SUFFIX = ".corrupt"


def sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def file_sha256(path: str) -> tuple[str, int]:
    """(hex digest, byte length) of a file, streamed."""
    h = hashlib.sha256()
    n = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            h.update(chunk)
            n += len(chunk)
    return h.hexdigest(), n


def verify_checkpoint(path: str) -> dict:
    """Re-verify one checkpoint directory against its manifest.

    Returns ``{"ok", "lsn", "errors": [...], "files_checked",
    "unreferenced": [...]}``. Manifests from before digests were
    recorded verify by existence only (never retroactively condemned).
    ``unreferenced`` lists files the manifest doesn't claim — debris
    from a crashed earlier checkpoint attempt at the same LSN; they are
    flagged, not errors (the referenced state is intact)."""
    out = {"ok": True, "lsn": 0, "errors": [], "files_checked": 0,
           "unreferenced": []}

    def fail(msg):
        out["ok"] = False
        out["errors"].append(msg)

    mpath = os.path.join(path, "MANIFEST.json")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except FileNotFoundError:
        fail("missing MANIFEST.json")
        return out
    except (ValueError, OSError) as e:
        fail(f"unreadable MANIFEST.json: {e!r}")
        return out
    out["lsn"] = int(manifest.get("lsn", 0))
    referenced = {"MANIFEST.json"}
    for t in manifest.get("types", []):
        fname = t.get("file")
        if not fname:
            continue
        referenced.add(fname)
        fpath = os.path.join(path, fname)
        try:
            digest, size = file_sha256(fpath)
        except FileNotFoundError:
            fail(f"{fname}: missing")
            continue
        except OSError as e:
            fail(f"{fname}: unreadable ({e!r})")
            continue
        out["files_checked"] += 1
        want_bytes = t.get("bytes")
        if want_bytes is not None and int(want_bytes) != size:
            fail(f"{fname}: length {size} != manifest {want_bytes}")
            continue
        want_sha = t.get("sha256")
        if want_sha is not None and want_sha != digest:
            fail(f"{fname}: sha256 mismatch")
    try:
        for fname in sorted(os.listdir(path)):
            if fname in referenced or fname.endswith(_QUARANTINE_SUFFIX):
                continue
            out["unreferenced"].append(fname)
    except OSError:
        pass
    return out


def verify_wal(logdir: str) -> dict:
    """Re-scan every WAL segment's CRC frames.

    A torn/invalid frame in the *tail* segment is the normal crash
    residue the next open truncates (reported, not an error); the same
    thing mid-history means silent corruption of records that were once
    valid — those segments are reported in ``corrupt_segments`` and
    fail the check."""
    from ..wal.log import _scan_segment, list_segments
    out = {"ok": True, "segments": 0, "records": 0, "corrupt_segments": [],
           "tail_torn_records": 0, "errors": []}
    segs = list_segments(logdir)
    out["segments"] = len(segs)
    for i, (first_lsn, path) in enumerate(segs):
        n = 0

        def count(rec):
            nonlocal n
            n += 1
        try:
            _good_end, torn = _scan_segment(path, on_record=count)
        except (OSError, ValueError) as e:
            out["ok"] = False
            out["errors"].append(f"{os.path.basename(path)}: {e!r}")
            out["corrupt_segments"].append(os.path.basename(path))
            continue
        out["records"] += n
        if torn:
            if i == len(segs) - 1:
                out["tail_torn_records"] += torn
            else:
                out["ok"] = False
                out["corrupt_segments"].append(os.path.basename(path))
                out["errors"].append(
                    f"{os.path.basename(path)}: {torn} invalid frame(s) "
                    f"mid-history")
    return out


def ids_digest(store, type_name: str) -> tuple[int, str]:
    """(row count, order-independent content digest) for one type — the
    anti-entropy comparison unit: two stores holding the same feature
    ids produce the same digest regardless of insertion order."""
    res = store.query("INCLUDE", type_name)
    ids = sorted(map(str, res.ids)) if res.batch is not None else []
    h = hashlib.sha256()
    for i in ids:
        h.update(i.encode())
        h.update(b"\x00")
    return len(ids), h.hexdigest()


def quarantine(path: str, registry=metrics) -> str | None:
    """Rename a corrupt artifact (file or checkpoint directory) to
    ``<path>.corrupt`` so loaders skip it. Returns the new path, or
    None when the rename failed (already quarantined / races)."""
    from ..store.filebus import fsync_dir
    target = path + _QUARANTINE_SUFFIX
    n = 1
    while os.path.exists(target):
        target = f"{path}{_QUARANTINE_SUFFIX}.{n}"
        n += 1
    try:
        os.rename(path, target)
    except OSError:
        return None
    fsync_dir(os.path.dirname(path) or ".")
    registry.counter("integrity.quarantined")
    return target
