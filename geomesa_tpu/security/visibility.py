"""Accumulo-style visibility expression parser/evaluator
(security/VisibilityEvaluator.scala:21).

Grammar: term | '(' expr ')' with '&' (and) and '|' (or); '&' and '|'
cannot mix without parens (Accumulo's rule). Terms are alphanumeric
(plus _ - : . /) or arbitrary strings in double quotes.
"""

from __future__ import annotations

import dataclasses
import re

import numpy as np

__all__ = ["parse_visibility", "VisibilityExpression",
           "evaluate_visibilities", "validate_labels"]


def validate_labels(sft, labels) -> None:
    """Validate distinct visibility labels against a schema: ONE shared
    check for every backend's write path (memory, fs, live, ...), so
    arity and grammar rules cannot drift. Attribute-level schemas need
    exactly one comma-separated part per attribute; every non-empty
    part (or whole label) must parse."""
    if sft.visibility_level == "attribute":
        n_attr = len(sft.attributes)
        for e in labels:
            parts = str(e).split(",")
            if len(parts) != n_attr:
                raise ValueError(
                    f"attribute-level visibility needs {n_attr} "
                    f"comma-separated labels, got {e!r}")
            for p in parts:
                if p:
                    parse_visibility(p)
    else:
        for e in labels:
            parse_visibility(str(e))

_TERM_RE = re.compile(r'[A-Za-z0-9_\-:./]+|"(?:[^"\\]|\\.)*"')


@dataclasses.dataclass(frozen=True)
class VisibilityExpression:
    """op: 'term' | 'and' | 'or'."""
    op: str
    term: str | None = None
    children: tuple = ()

    def evaluate(self, auths: set[str]) -> bool:
        if self.op == "term":
            return self.term in auths
        if self.op == "and":
            return all(c.evaluate(auths) for c in self.children)
        return any(c.evaluate(auths) for c in self.children)


class _P:
    def __init__(self, s: str):
        self.s = s
        self.i = 0

    def peek(self) -> str:
        return self.s[self.i] if self.i < len(self.s) else ""

    def parse(self) -> VisibilityExpression:
        e = self._expr()
        if self.i != len(self.s):
            raise ValueError(f"trailing input in visibility: {self.s[self.i:]!r}")
        return e

    def _expr(self) -> VisibilityExpression:
        parts = [self._primary()]
        op = None
        while self.peek() in ("&", "|"):
            ch = self.s[self.i]
            if op is None:
                op = ch
            elif ch != op:
                raise ValueError(
                    f"cannot mix & and | without parens: {self.s!r}")
            self.i += 1
            parts.append(self._primary())
        if len(parts) == 1:
            return parts[0]
        return VisibilityExpression("and" if op == "&" else "or",
                                    children=tuple(parts))

    def _primary(self) -> VisibilityExpression:
        if self.peek() == "(":
            self.i += 1
            e = self._expr()
            if self.peek() != ")":
                raise ValueError(f"unbalanced parens in {self.s!r}")
            self.i += 1
            return e
        m = _TERM_RE.match(self.s, self.i)
        if not m:
            raise ValueError(f"bad visibility term at {self.i} in {self.s!r}")
        self.i = m.end()
        term = m.group(0)
        if term.startswith('"'):
            term = term[1:-1].replace('\\"', '"').replace("\\\\", "\\")
        return VisibilityExpression("term", term)


_CACHE: dict[str, VisibilityExpression] = {}


def parse_visibility(expr: str) -> VisibilityExpression:
    if expr not in _CACHE:
        if len(_CACHE) > 10_000:
            _CACHE.clear()
        _CACHE[expr] = _P(expr.strip()).parse()
    return _CACHE[expr]


def evaluate_visibilities(expressions, auths) -> np.ndarray:
    """Vectorized-ish: bool mask of rows whose visibility passes the
    auth set. Empty/None visibility is world-readable (reference
    semantics)."""
    auth_set = set(auths)
    uniq: dict[str, bool] = {}
    out = np.empty(len(expressions), dtype=bool)
    for i, e in enumerate(expressions):
        if e is None or e == "":
            out[i] = True
            continue
        if e not in uniq:
            uniq[e] = parse_visibility(e).evaluate(auth_set)
        out[i] = uniq[e]
    return out
