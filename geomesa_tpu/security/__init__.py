"""LX security: cell-level visibility (geomesa-security analog).

VisibilityEvaluator (security/VisibilityEvaluator.scala:21) parses
Accumulo-style boolean visibility expressions — ``A&B|(C&D)``, quoted
terms — and evaluates them against a user's authorization set, enabling
row-level security on stores without native cell visibility.
"""

from .visibility import (VisibilityExpression, evaluate_visibilities,
                         parse_visibility, validate_labels)

__all__ = ["VisibilityExpression", "evaluate_visibilities",
           "parse_visibility", "validate_labels"]
