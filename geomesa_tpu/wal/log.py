"""Append-only segmented write-ahead log with CRC-framed records.

The journal half of the durability subsystem (the redo log of ARIES;
Kafka's per-partition segment log has the same on-disk shape). Records
carry monotonically increasing LSNs and append through a group-commit
path whose fsync policy is configurable:

- ``always``      — every ``append`` returns only after the record is
  fsynced; concurrent appenders coalesce into one fsync (the leader
  syncs for everyone appended so far — group commit);
- ``interval_ms`` — a daemon flusher fsyncs at most every N ms; an
  acknowledged write can lose at most that window on a crash;
- ``never``       — no explicit fsync (the OS decides); fastest, for
  workloads whose durability floor is the periodic checkpoint.

Segment files are named by the first LSN they contain
(``wal-<lsn:020d>.log``) and rotate at a size threshold, so retention
after a checkpoint is just "unlink whole segments below the checkpoint
LSN". Each segment starts with a small header naming the frame version
and the checksum algorithm in use; each record frame is::

    u32 crc   — over the 13 header bytes after it + the payload
    u32 len   — payload length
    u64 lsn
    u8  kind
    payload

On open, the tail segment is scanned and truncated at the last valid
frame (torn-tail discipline: a crash mid-append must not wedge the log
or replay garbage). CRC-32C (Castagnoli) is used when a native
implementation is importable; otherwise the frame falls back to zlib's
CRC-32 and the segment header records which one, so readers always
validate with the writer's algorithm.
"""

from __future__ import annotations

import io
import json
import os
import struct
import threading
import time
import zlib

from ..integrity import faultfs
from ..metrics import metrics
from ..utils.properties import SystemProperty

__all__ = ["WriteAheadLog", "DurabilityError", "WRITE", "DELETE",
           "CREATE_SCHEMA", "DROP_SCHEMA", "CHECKPOINT_MARK",
           "inspect_dir",
           "WAL_FSYNC", "WAL_SEGMENT_BYTES", "WAL_INTERVAL_MS",
           "encode_write", "decode_write", "encode_delete",
           "decode_delete", "encode_schema", "decode_schema",
           "encode_drop_schema"]


class DurabilityError(OSError):
    """The WAL can no longer promise durability and has poisoned
    itself. Raised by every subsequent append/sync; the owning store
    degrades to read-only.

    The trigger is a failed storage-side write or fsync. After a
    failed fsync in particular the kernel may have already dropped the
    dirty pages while keeping the file marked clean, so retrying the
    same fsync can falsely succeed without the data being on disk
    (fsyncgate — Rebello et al., ATC '20). The only honest move is to
    refuse further writes on this log handle; recovery is a fresh
    process re-reading what the disk actually holds."""

# record kinds
WRITE = 1
DELETE = 2
CREATE_SCHEMA = 3
DROP_SCHEMA = 4
CHECKPOINT_MARK = 5

KIND_NAMES = {WRITE: "write", DELETE: "delete",
              CREATE_SCHEMA: "create_schema", DROP_SCHEMA: "drop_schema",
              CHECKPOINT_MARK: "checkpoint"}

# fsync policy: "always" | "interval" | "never"
WAL_FSYNC = SystemProperty("geomesa.wal.fsync", "always")
# segment rotation threshold (bytes)
WAL_SEGMENT_BYTES = SystemProperty("geomesa.wal.segment.bytes",
                                   str(64 * 1024 * 1024))
# flush cadence for the "interval" policy
WAL_INTERVAL_MS = SystemProperty("geomesa.wal.interval.ms", "50")

_MAGIC = b"GMTPUWAL"
_SEG_VERSION = 1
_HEADER = struct.Struct("<8sBB")      # magic, version, checksum algo
_FRAME = struct.Struct("<IIQB")       # crc, len, lsn, kind
_CKSUM_CRC32C = 1
_CKSUM_CRC32 = 2


def _resolve_checksum():
    """(algo id, fn) — native CRC-32C when available, zlib CRC-32
    otherwise. The algo id is persisted in each segment header so the
    reader always validates with the writer's algorithm."""
    try:
        from crc32c import crc32c as f  # type: ignore[import-not-found]
        return _CKSUM_CRC32C, lambda b: f(b) & 0xFFFFFFFF
    except ImportError:
        pass
    try:
        import google_crc32c  # type: ignore[import-not-found]
        return _CKSUM_CRC32C, lambda b: google_crc32c.value(b)
    except ImportError:
        pass
    return _CKSUM_CRC32, lambda b: zlib.crc32(b) & 0xFFFFFFFF


def _checksum_for(algo: int):
    if algo == _CKSUM_CRC32:
        return lambda b: zlib.crc32(b) & 0xFFFFFFFF
    got, fn = _resolve_checksum()
    if got != algo:
        raise ValueError("segment written with CRC-32C but no native "
                         "crc32c implementation is importable")
    return fn


def segment_file(first_lsn: int) -> str:
    return f"wal-{first_lsn:020d}.log"


def list_segments(root: str) -> list[tuple[int, str]]:
    """Sorted (first_lsn, path) for every segment under ``root``."""
    out = []
    try:
        names = os.listdir(root)
    except FileNotFoundError:
        return out
    for f in names:
        if f.startswith("wal-") and f.endswith(".log"):
            try:
                out.append((int(f[4:-4]), os.path.join(root, f)))
            except ValueError:
                continue
    return sorted(out)


def inspect_dir(root: str) -> dict:
    """Read-only scan of a WAL directory (the CLI's ``wal inspect``):
    the ``scan_stats()`` shape without opening the log for append — no
    torn-tail truncation, no lock, safe against a live writer."""
    segs = []
    counts: dict[str, int] = {}
    total_bytes = 0
    torn = 0
    last_lsn = 0
    last_checkpoint = None
    for first_lsn, path in list_segments(root):
        n = 0
        lo = hi = None

        def note(rec):
            nonlocal n, lo, hi, last_lsn, last_checkpoint
            lsn, kind, payload = rec
            n += 1
            lo = lsn if lo is None else lo
            hi = lsn
            last_lsn = max(last_lsn, lsn)
            name = KIND_NAMES.get(kind, str(kind))
            counts[name] = counts.get(name, 0) + 1
            if kind == CHECKPOINT_MARK:
                try:
                    last_checkpoint = json.loads(payload.decode())
                except (ValueError, UnicodeDecodeError):
                    pass
        good_end, t = _scan_segment(path, on_record=note)
        torn += t
        size = os.path.getsize(path)
        total_bytes += size
        segs.append({"file": os.path.basename(path),
                     "first_lsn": first_lsn, "records": n,
                     "lsn_range": [lo, hi], "bytes": size,
                     "valid_bytes": good_end})
    return {"segments": segs, "records_by_kind": counts,
            "bytes": total_bytes, "last_lsn": last_lsn,
            "torn_records": torn, "last_checkpoint": last_checkpoint}


# -- record payload codecs -------------------------------------------------
# WRITE/DELETE reuse the filebus GeoMessage wire format (JSON header +
# Arrow IPC batch): self-describing, so replay needs no out-of-band
# schema exchange and the two durable logs stay mutually readable.

def encode_write(type_name: str, batch, visibilities=None) -> bytes:
    from ..store.filebus import _encode
    from ..store.live import GeoMessage
    vis = (None if visibilities is None
           else tuple(None if v is None else str(v) for v in visibilities))
    return _encode(GeoMessage("create", type_name, batch,
                              timestamp_ms=int(time.time() * 1000),
                              visibilities=vis))


def decode_write(payload: bytes):
    """-> (type_name, FeatureBatch, visibilities tuple | None)"""
    from ..store.filebus import _decode
    msg = _decode(payload)
    return msg.type_name, msg.batch, msg.visibilities


def encode_delete(type_name: str, ids) -> bytes:
    from ..store.filebus import _encode
    from ..store.live import GeoMessage
    return _encode(GeoMessage("delete", type_name,
                              ids=tuple(map(str, ids)),
                              timestamp_ms=int(time.time() * 1000)))


def decode_delete(payload: bytes):
    """-> (type_name, ids tuple)"""
    from ..store.filebus import _decode
    msg = _decode(payload)
    return msg.type_name, msg.ids


def encode_schema(sft) -> bytes:
    from ..features.sft import encode_spec
    return json.dumps({"type_name": sft.type_name,
                       "spec": encode_spec(sft)}).encode()


def decode_schema(payload: bytes):
    """-> (type_name, spec string | None)"""
    obj = json.loads(payload.decode())
    return obj["type_name"], obj.get("spec")


def encode_drop_schema(type_name: str) -> bytes:
    return json.dumps({"type_name": type_name}).encode()


class WriteAheadLog:
    """Segmented append-only log; thread-safe.

    ``append`` frames the payload, writes it to the current segment and
    applies the fsync policy before returning; ``records`` iterates
    every valid frame at or past a starting LSN; ``truncate_below``
    unlinks segments wholly below a retention LSN (checkpoint
    compaction).
    """

    def __init__(self, root: str, fsync: str | None = None,
                 segment_bytes: int | None = None,
                 interval_ms: float | None = None, registry=metrics):
        self.root = root
        self.fsync_policy = str(fsync if fsync is not None
                                else WAL_FSYNC.get())
        if self.fsync_policy not in ("always", "interval", "never"):
            raise ValueError(
                f"unknown fsync policy {self.fsync_policy!r}")
        self.segment_bytes = int(segment_bytes if segment_bytes is not None
                                 else WAL_SEGMENT_BYTES.get())
        self.interval_ms = float(interval_ms if interval_ms is not None
                                 else WAL_INTERVAL_MS.get())
        self.registry = registry
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()          # append path
        self._sync_cond = threading.Condition()  # group-commit path
        self._sync_in_progress = False
        self._fd: io.BufferedWriter | None = None
        self._seg_start_lsn = 0
        self._seg_bytes = 0
        self._seg_path = ""
        self._closed = False
        self._poisoned: OSError | None = None
        self.torn_tail_records = 0  # dropped by open-time truncation
        self._cksum_algo, self._cksum = _resolve_checksum()
        self._recover_tail()
        self._flusher: threading.Thread | None = None
        self._flusher_stop = threading.Event()
        if self.fsync_policy == "interval":
            self._flusher = threading.Thread(
                target=self._flush_loop, daemon=True,
                name="wal-interval-flusher")
            self._flusher.start()

    # -- open-time tail recovery ------------------------------------------

    def _segments(self) -> list[tuple[int, str]]:
        """Sorted (first_lsn, path) for every segment on disk."""
        return list_segments(self.root)

    def _recover_tail(self):
        """Find the last LSN by scanning the tail segment, truncating a
        torn final record (crash mid-append) at the last valid frame."""
        segs = self._segments()
        last_lsn = 0
        if segs:
            first_lsn, path = segs[-1]
            last_lsn = first_lsn - 1
            good_end, torn = _scan_segment(path, on_record=lambda rec: None)
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
            if good_end < size:
                with open(path, "r+b") as f:
                    f.truncate(good_end)
                self.torn_tail_records += torn
            # last valid lsn in the tail segment
            def note(rec):
                nonlocal last_lsn
                last_lsn = rec[0]
            _scan_segment(path, on_record=note)
        self._next_lsn = last_lsn + 1
        self._appended_lsn = last_lsn
        self._synced_lsn = last_lsn
        self._open_segment(self._next_lsn)

    def _open_segment(self, first_lsn: int):
        path = os.path.join(self.root, segment_file(first_lsn))
        exists = os.path.exists(path)
        self._fd = open(path, "ab")
        self._seg_start_lsn = first_lsn
        self._seg_path = path
        self._seg_bytes = self._fd.tell()
        if not exists or self._seg_bytes == 0:
            faultfs.write(self._fd, _HEADER.pack(_MAGIC, _SEG_VERSION,
                                                 self._cksum_algo), path)
            self._fd.flush()
            self._seg_bytes = _HEADER.size

    # -- append / group commit --------------------------------------------

    @property
    def next_lsn(self) -> int:
        return self._next_lsn

    @property
    def last_lsn(self) -> int:
        return self._appended_lsn

    @property
    def durable_lsn(self) -> int:
        """Highest LSN known to be fsynced (== last_lsn under the
        ``always`` policy once append returns)."""
        return self._synced_lsn

    @property
    def poisoned(self) -> bool:
        return self._poisoned is not None

    @property
    def poison_cause(self) -> OSError | None:
        return self._poisoned

    def _poison(self, cause: OSError):
        """Mark the log permanently unusable for writes and raise the
        typed refusal. Idempotent; wakes blocked group-committers so
        they observe the poison instead of retrying the fsync."""
        with self._sync_cond:
            if self._poisoned is None:
                self._poisoned = cause
                self.registry.counter("wal.poisoned")
            self._sync_cond.notify_all()
        raise DurabilityError(
            f"write-ahead log poisoned: {cause}") from cause

    def _raise_if_poisoned(self):
        if self._poisoned is not None:
            raise DurabilityError(
                f"write-ahead log poisoned: {self._poisoned}")

    def append(self, kind: int, payload: bytes) -> int:
        """Frame and write one record; returns its LSN after the fsync
        policy is satisfied. A storage failure along the way (frame
        write, rotation, fsync) poisons the log: the tail position is
        no longer trustworthy, so every later append raises
        ``DurabilityError`` rather than risk stranding valid frames
        behind a torn one."""
        if self._closed:
            raise ValueError("log is closed")
        self._raise_if_poisoned()
        err: OSError | None = None
        with self._lock:
            lsn = self._next_lsn
            self._next_lsn += 1
            rest = struct.pack("<IQB", len(payload), lsn, kind)
            crc = self._cksum(rest + payload)
            frame = struct.pack("<I", crc) + rest + payload
            try:
                if (self._seg_bytes + len(frame) > self.segment_bytes
                        and self._seg_bytes > _HEADER.size):
                    self._rotate(lsn)
                faultfs.write(self._fd, frame, self._seg_path)
                self._fd.flush()  # to the OS; fsync is the policy's call
                self._seg_bytes += len(frame)
                self._appended_lsn = lsn
            except OSError as e:
                err = e
        if err is not None:
            self._poison(err)
        reg = self.registry
        reg.counter("wal.appended.records")
        reg.counter("wal.appended.bytes", len(frame))
        if self.fsync_policy == "always":
            self._commit(lsn)
        return lsn

    def _rotate(self, first_lsn: int):
        """Seal the current segment (fsync so earlier records stay
        durable regardless of policy timing) and start the next."""
        self._fd.flush()
        faultfs.fsync(self._fd.fileno(), self._seg_path)
        self._fd.close()
        self._open_segment(first_lsn)
        self.registry.counter("wal.segments.rotated")

    def _commit(self, lsn: int):
        """Group commit: one fsync covers every record appended so far;
        concurrent committers wait for the in-flight sync and return
        without a second fsync when it already covered their LSN. A
        failed fsync poisons the log — the kernel may have dropped the
        dirty pages, so neither this committer nor a waiter may retry
        (fsyncgate)."""
        with self._sync_cond:
            while self._sync_in_progress and self._synced_lsn < lsn:
                self._sync_cond.wait()
            self._raise_if_poisoned()
            if self._synced_lsn >= lsn:
                return
            self._sync_in_progress = True
        pending: int | None = None
        err: OSError | None = None
        t0 = time.perf_counter()
        try:
            from ..obs import tracer
            from ..obs.prof import watchdog
            cur = tracer.current()
            with self._lock, \
                    watchdog.watch("wal.fsync",
                                   span=cur[1] if cur else None):
                fd, path = self._fd, self._seg_path
                fd.flush()
                faultfs.fsync(fd.fileno(), path)
                pending = self._appended_lsn
        except OSError as e:
            err = e
        finally:
            with self._sync_cond:
                batch = 0
                if pending is not None:
                    batch = pending - self._synced_lsn
                    self._synced_lsn = max(self._synced_lsn, pending)
                self._sync_in_progress = False
                self._sync_cond.notify_all()
        if err is not None:
            self._poison(err)
        self.registry.counter("wal.fsyncs")
        from ..obs import annotate
        annotate("wal.fsync", lsn=lsn, batch=batch,
                 ms=round((time.perf_counter() - t0) * 1000, 3))
        if batch > 0:
            self.registry.gauge("wal.group_commit.batch", batch)

    def sync(self):
        """Force-fsync everything appended so far (any policy)."""
        self._raise_if_poisoned()
        if self._appended_lsn > self._synced_lsn:
            self._commit(self._appended_lsn)

    def _flush_loop(self):
        while not self._flusher_stop.wait(self.interval_ms / 1e3):
            try:
                self.sync()
            except (OSError, ValueError):
                return  # closed under us

    # -- read / replay -----------------------------------------------------

    def records(self, from_lsn: int = 1, on_torn=None):
        """Yield (lsn, kind, payload) for every valid record with
        ``lsn >= from_lsn``, in LSN order. Iteration ends ENTIRELY at
        the first invalid frame — in the tail segment that is the
        normal crash residue, but mid-history it means silent
        corruption, and continuing into later segments would replay
        across a hole (records applied out of prefix order; deletes or
        overwrites before the hole replayed, their predecessors lost).
        ``on_torn(path, frames)`` fires when iteration stops early so
        recovery can report exactly where.

        Segments wholly below ``from_lsn`` are skipped without being
        opened — segment file names carry their first LSN, so a segment
        whose successor starts at or below ``from_lsn`` cannot contain
        anything to yield. Replication shippers tail this call in a
        loop from an advancing cursor; without the skip every tail
        iteration would rescan the whole log."""
        segs = self._segments()
        for i, (first_lsn, path) in enumerate(segs):
            nxt = segs[i + 1][0] if i + 1 < len(segs) else None
            if nxt is not None and nxt <= from_lsn:
                continue  # every record in [first_lsn, nxt) < from_lsn
            out: list = []
            try:
                _good_end, torn = _scan_segment(path, on_record=out.append,
                                                min_lsn=from_lsn)
            except FileNotFoundError:
                # checkpoint truncation unlinked it between the listing
                # and the open: it was wholly below the checkpoint LSN,
                # so a reader positioned at/above the checkpoint loses
                # nothing by skipping it
                continue
            for rec in out:
                yield rec
            if torn:
                self.registry.counter("wal.replay.stopped")
                if on_torn is not None:
                    on_torn(path, torn)
                return

    def scan_stats(self) -> dict:
        """Inspection summary over the whole log (CLI surface)."""
        segs = []
        counts: dict[str, int] = {}
        total_bytes = 0
        last_checkpoint = None
        for first_lsn, path in self._segments():
            n = 0
            lo = hi = None

            def note(rec):
                nonlocal n, lo, hi, last_checkpoint
                lsn, kind, payload = rec
                n += 1
                lo = lsn if lo is None else lo
                hi = lsn
                counts[KIND_NAMES.get(kind, str(kind))] = \
                    counts.get(KIND_NAMES.get(kind, str(kind)), 0) + 1
                if kind == CHECKPOINT_MARK:
                    try:
                        last_checkpoint = json.loads(payload.decode())
                    except (ValueError, UnicodeDecodeError):
                        pass
            good_end, _ = _scan_segment(path, on_record=note)
            size = os.path.getsize(path)
            total_bytes += size
            segs.append({"file": os.path.basename(path),
                         "first_lsn": first_lsn, "records": n,
                         "lsn_range": [lo, hi], "bytes": size,
                         "valid_bytes": good_end})
        return {"segments": segs, "records_by_kind": counts,
                "bytes": total_bytes, "last_lsn": self.last_lsn,
                "durable_lsn": self.durable_lsn,
                "torn_tail_records": self.torn_tail_records,
                "last_checkpoint": last_checkpoint,
                "checksum": ("crc32c" if self._cksum_algo == _CKSUM_CRC32C
                             else "crc32"),
                "fsync_policy": self.fsync_policy}

    # -- retention ---------------------------------------------------------

    def truncate_below(self, lsn: int) -> int:
        """Unlink segments whose every record is below ``lsn`` (the
        last durable checkpoint). The segment containing ``lsn`` and
        everything after it stay. Returns segments dropped."""
        dropped = 0
        with self._lock:
            segs = self._segments()
            for i, (first, path) in enumerate(segs):
                nxt = segs[i + 1][0] if i + 1 < len(segs) else None
                # a segment is wholly below lsn iff the next segment
                # starts at or below it (its records end at nxt-1);
                # never drop the active tail segment
                if nxt is None or nxt > lsn:
                    break
                os.unlink(path)
                dropped += 1
        if dropped:
            self.registry.counter("wal.segments.dropped", dropped)
            _fsync_dir(self.root)
        return dropped

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._flusher_stop.set()
        if self._flusher is not None:
            self._flusher.join(timeout=2.0)
        try:
            if self.fsync_policy != "never" and self._poisoned is None:
                self.sync()
        finally:
            with self._lock:
                if self._fd is not None:
                    self._fd.close()
                    self._fd = None

    def abort(self):
        """Drop the log handle without flushing or syncing — the
        simulated-crash close. The crash harness uses this so an
        injected failure's aftermath reaches the next open exactly as
        the disk holds it; also the right disposal for a poisoned log,
        where a clean close would imply durability it can't promise."""
        self._closed = True
        self._flusher_stop.set()
        with self._lock:
            if self._fd is not None:
                try:
                    self._fd.close()
                except OSError:
                    pass
                self._fd = None


def _scan_segment(path: str, on_record, min_lsn: int = 0):
    """Scan one segment file, invoking ``on_record((lsn, kind,
    payload))`` for each valid frame with lsn >= min_lsn. Returns
    (offset of the end of the last valid frame, frames dropped after
    it). Stops at the first invalid frame."""
    with open(path, "rb") as f:
        raw = f.read()
    if len(raw) < _HEADER.size:
        return 0, (1 if raw else 0)
    magic, version, algo = _HEADER.unpack_from(raw, 0)
    if magic != _MAGIC or version != _SEG_VERSION:
        raise ValueError(f"not a WAL segment: {path}")
    cksum = _checksum_for(algo)
    off = _HEADER.size
    good_end = off
    torn = 0
    n = len(raw)
    while off < n:
        if off + _FRAME.size > n:
            torn += 1
            break
        crc, length, lsn, kind = _FRAME.unpack_from(raw, off)
        end = off + _FRAME.size + length
        if end > n:
            torn += 1
            break
        body = raw[off + 4:end]
        if cksum(body) != crc:
            torn += 1
            break
        if lsn >= min_lsn:
            on_record((lsn, kind, raw[off + _FRAME.size:end]))
        off = end
        good_end = off
    return good_end, torn


def _fsync_dir(path: str):
    """Make directory-entry changes (rename/unlink) durable."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass  # some filesystems refuse directory fsync; best effort
    finally:
        os.close(fd)
