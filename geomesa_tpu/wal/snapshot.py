"""Checkpoint/restore: atomic snapshots of a store's host-side state.

A checkpoint bounds recovery time and enables log compaction: once a
snapshot at LSN ``L`` is durable, replay starts from the snapshot and
only redoes records past ``L``, and segments wholly below ``L`` can be
unlinked (``log.truncate_below``).

Layout (under the durable root)::

    snapshots/ckpt-<lsn:020d>/
        <type>.bin      — filebus wire format: JSON header (spec, vis)
                          + Arrow IPC column batch
        MANIFEST.json   — {lsn, types: [{name, rows, index_version,
                          file}], created_ms}

Every file goes through ``filebus.write_bytes_atomic`` /
``write_json_atomic`` (tmp + fsync + rename + directory fsync), and the
manifest is written LAST — a crash mid-checkpoint leaves either a fully
valid snapshot or a manifest-less directory that loaders ignore.
"""

from __future__ import annotations

import os
import json
import shutil
import time

from ..metrics import metrics
from ..store.filebus import fsync_dir, write_bytes_atomic, write_json_atomic

__all__ = ["write_checkpoint", "load_checkpoint", "latest_checkpoint_lsn",
           "iter_store_states", "drop_stale_checkpoints"]

_DIR_PREFIX = "ckpt-"


def _snap_root(root: str) -> str:
    return os.path.join(root, "snapshots")


def checkpoint_dirs(root: str) -> list[tuple[int, str]]:
    """Sorted (lsn, path) of checkpoint dirs that have a manifest."""
    base = _snap_root(root)
    out = []
    if not os.path.isdir(base):
        return out
    for d in os.listdir(base):
        if not d.startswith(_DIR_PREFIX):
            continue
        path = os.path.join(base, d)
        if not os.path.exists(os.path.join(path, "MANIFEST.json")):
            continue  # crash mid-checkpoint: ignore, never load
        try:
            out.append((int(d[len(_DIR_PREFIX):]), path))
        except ValueError:
            continue
    return sorted(out)


def latest_checkpoint_lsn(root: str) -> int:
    """LSN of the newest durable checkpoint, 0 when none exists."""
    dirs = checkpoint_dirs(root)
    return dirs[-1][0] if dirs else 0


def iter_store_states(store):
    """Yield (sft, host batch | None, vis array | None) for every type
    in a store, reaching through the wrapper layers the durable knob
    composes (live -> memory, lambda -> transient live, DurableStore ->
    inner)."""
    if hasattr(store, "_types"):          # InMemoryDataStore family
        for st in store._types.values():
            st.flush()
            yield st.sft, st._batch, (st.vis if st.has_vis else None)
        return
    if hasattr(store, "_mem"):            # LiveDataStore
        yield from iter_store_states(store._mem)
        return
    if hasattr(store, "transient"):       # LambdaDataStore
        yield from iter_store_states(store.transient)
        return
    if hasattr(store, "inner"):           # DurableStore wrapper
        yield from iter_store_states(store.inner)
        return
    raise TypeError(f"cannot snapshot a {type(store).__name__}")


def write_checkpoint(root: str, states, lsn: int,
                     registry=metrics) -> str:
    """Write a snapshot of ``states`` (an ``iter_store_states``-shaped
    iterable) tagged with the log position ``lsn`` it covers. Returns
    the checkpoint directory path.

    The whole directory is staged as a ``.tmp`` sibling and renamed
    into place once complete — re-using the final directory would let a
    crashed earlier attempt at the same LSN leave stale ``.bin`` files
    the new manifest doesn't reference. Within the staged dir the
    manifest is still written last and carries each payload's SHA-256 +
    length, so loaders and replicas can verify end-to-end."""
    from ..integrity.verify import sha256_hex
    from .log import encode_write
    base = _snap_root(root)
    path = os.path.join(base, f"{_DIR_PREFIX}{lsn:020d}")
    tmp = path + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)  # debris from a crashed earlier attempt
    os.makedirs(tmp)
    types = []
    total_bytes = 0
    for sft, batch, vis in states:
        fname = f"{sft.type_name}.bin"
        n = 0 if batch is None else batch.n
        if batch is not None:
            raw = encode_write(sft.type_name, batch, vis)
        else:
            # schema-only type: persist the spec so recovery recreates
            # the (empty) schema without a CREATE_SCHEMA log record
            raw = b""
        from ..features.sft import encode_spec
        entry = {"name": sft.type_name, "rows": int(n),
                 "index_version": sft.index_version,
                 "spec": encode_spec(sft),
                 "file": fname if raw else None}
        if raw:
            entry["sha256"] = sha256_hex(raw)
            entry["bytes"] = len(raw)
            write_bytes_atomic(os.path.join(tmp, fname), raw)
            total_bytes += len(raw)
        types.append(entry)
    write_json_atomic(os.path.join(tmp, "MANIFEST.json"),
                      {"lsn": int(lsn), "types": types,
                       "created_ms": int(time.time() * 1000)})
    if os.path.exists(path):
        shutil.rmtree(path)  # same-LSN predecessor being replaced
    os.rename(tmp, path)
    fsync_dir(base)
    registry.counter("wal.checkpoints")
    registry.counter("wal.checkpoint.bytes", total_bytes)
    return path


def load_checkpoint(root: str, on_skip=None):
    """Load the newest durable checkpoint that VERIFIES.

    Each candidate (newest first) is digest-checked against its
    manifest before a byte of it is trusted; a corrupt one is reported
    via ``on_skip(path, why)``, quarantined (renamed ``*.corrupt``)
    when ``geomesa.integrity.quarantine`` is on, and the next-newest
    tried — degrading to ``None`` (full WAL replay) when none survive.
    Returns ``(lsn, [(sft, batch | None, vis | None)])`` or ``None``."""
    from ..features.sft import parse_spec
    from ..integrity.scrub import INTEGRITY_QUARANTINE
    from ..integrity.verify import quarantine, verify_checkpoint
    from .log import decode_write
    for lsn, path in reversed(checkpoint_dirs(root)):
        rep = verify_checkpoint(path)
        if not rep["ok"]:
            metrics.counter("integrity.load.fallbacks")
            if on_skip is not None:
                on_skip(path, "; ".join(rep["errors"]) or "corrupt")
            if INTEGRITY_QUARANTINE.as_bool():
                quarantine(path)
            continue
        with open(os.path.join(path, "MANIFEST.json")) as f:
            manifest = json.load(f)
        out = []
        for t in manifest["types"]:
            sft = parse_spec(t["name"], t.get("spec") or "")
            if t.get("file"):
                with open(os.path.join(path, t["file"]), "rb") as f:
                    _tn, batch, vis = decode_write(f.read())
                out.append((sft, batch, vis))
            else:
                out.append((sft, None, None))
        return int(manifest["lsn"]), out
    return None


def drop_stale_checkpoints(root: str, keep: int = 1) -> int:
    """Remove all but the ``keep`` newest checkpoints (retention after
    a successful new checkpoint). Returns directories removed.

    The manifest is deleted first and the deletion fsynced before the
    rest of the tree goes: a crash mid-``rmtree`` then leaves a
    manifest-less directory that ``checkpoint_dirs`` already ignores,
    never a manifest-bearing husk that ``load_checkpoint`` would select
    and crash on."""
    dirs = checkpoint_dirs(root)
    removed = 0
    for _lsn, path in dirs[:-keep] if keep else dirs:
        manifest = os.path.join(path, "MANIFEST.json")
        try:
            os.unlink(manifest)
        except FileNotFoundError:
            pass
        fsync_dir(path)
        shutil.rmtree(path, ignore_errors=True)
        removed += 1
    return removed
