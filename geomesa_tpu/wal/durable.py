"""The ``Journal`` façade stores embed, and a generic ``DurableStore``.

Two ways to opt in:

- constructor knob — ``InMemoryDataStore(durable_dir=...)`` (and the
  same knob on the live/lambda stores) embeds a ``Journal`` and follows
  the validate → journal → apply discipline natively;
- wrapper — ``DurableStore(inner, root)`` journals every mutation
  before delegating to any ``DataStore`` implementation, and replays
  the log into it on open.

Both journal BEFORE apply (write-ahead rule): a crash after the journal
fsync but before the in-memory apply is repaired by replay; a crash
before the fsync loses only what was never acknowledged durable.
"""

from __future__ import annotations

import json
import os

from ..features.batch import FeatureBatch
from ..features.sft import SimpleFeatureType, parse_spec
from ..index.api import Query
from ..metrics import metrics
from ..store.api import DataStore
from .log import (CHECKPOINT_MARK, WriteAheadLog, encode_delete,
                  encode_drop_schema, encode_schema, encode_write,
                  CREATE_SCHEMA, DELETE, DROP_SCHEMA, WRITE)
from .recovery import RecoveryReport, recover
from .snapshot import (drop_stale_checkpoints, iter_store_states,
                       latest_checkpoint_lsn, write_checkpoint)

__all__ = ["Journal", "DurableStore"]


class Journal:
    """One durable root = one WAL (``<root>/log``) + its checkpoints
    (``<root>/snapshots``). The ``log_*`` methods are no-ops while
    ``replaying`` — recovery drives the store's normal mutation surface
    and must not re-journal what it reads from the log."""

    def __init__(self, root: str, fsync: str | None = None,
                 segment_bytes: int | None = None,
                 interval_ms: float | None = None, registry=metrics):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.registry = registry
        self.wal = WriteAheadLog(os.path.join(root, "log"), fsync=fsync,
                                 segment_bytes=segment_bytes,
                                 interval_ms=interval_ms, registry=registry)
        self.replaying = False
        self.last_report: RecoveryReport | None = None

    # -- journaling (called by stores BEFORE they apply) -------------------

    def log_write(self, type_name: str, batch, visibilities=None):
        if self.replaying:
            return None
        return self.wal.append(WRITE,
                               encode_write(type_name, batch, visibilities))

    def log_delete(self, type_name: str, ids):
        if self.replaying:
            return None
        return self.wal.append(DELETE, encode_delete(type_name, ids))

    def log_create_schema(self, sft):
        if self.replaying:
            return None
        return self.wal.append(CREATE_SCHEMA, encode_schema(sft))

    def log_drop_schema(self, type_name: str):
        if self.replaying:
            return None
        return self.wal.append(DROP_SCHEMA, encode_drop_schema(type_name))

    # -- recovery / checkpoint ---------------------------------------------

    def recover(self, store) -> RecoveryReport:
        """Replay checkpoint + log into ``store`` (journaling
        suppressed for the duration)."""
        self.replaying = True
        try:
            self.last_report = recover(store, self.wal, self.root,
                                       self.registry)
        finally:
            self.replaying = False
        return self.last_report

    def checkpoint(self, store, keep: int = 2) -> dict:
        """Snapshot ``store`` and compact the log.

        The covered LSN is captured BEFORE the snapshot: rows appended
        while the snapshot runs may land in both the snapshot and the
        replayed tail, which idempotent redo collapses — so appenders
        are never blocked.

        Two integrity couplings gate the compaction. First, the fresh
        snapshot is read back and digest-verified before any log
        truncation — a checkpoint silently corrupted on the way down
        (bit flip) must never become the excuse for deleting the
        records that could rebuild it. Second, the log is truncated
        below the OLDEST checkpoint still retained (``keep`` defaults
        to 2), so if the newest snapshot later rots, replaying from the
        prior one is id-exact rather than lossy."""
        from ..integrity.verify import quarantine, verify_checkpoint
        from .snapshot import checkpoint_dirs
        lsn = self.wal.last_lsn
        self.wal.sync()  # records <= lsn must be durable before the
        #                  checkpoint claims to cover them
        path = write_checkpoint(self.root, iter_store_states(store), lsn,
                                self.registry)
        rep = verify_checkpoint(path)
        if not rep["ok"]:
            self.registry.counter("integrity.checkpoint.writeback_failures")
            quarantine(path, self.registry)
            raise OSError("checkpoint failed read-back verification "
                          "(log NOT truncated): "
                          + "; ".join(rep["errors"]))
        self.wal.append(CHECKPOINT_MARK,
                        json.dumps({"lsn": lsn}).encode())
        stale = drop_stale_checkpoints(self.root, keep=keep)
        dirs = checkpoint_dirs(self.root)
        floor = dirs[0][0] if dirs else lsn
        dropped = self.wal.truncate_below(floor)
        return {"lsn": lsn, "path": path, "segments_dropped": dropped,
                "checkpoints_dropped": stale}

    # -- inspection ---------------------------------------------------------

    @property
    def poisoned(self) -> bool:
        """True once the WAL refused durability (failed fsync/write):
        journal-before-apply then makes the owning store read-only —
        every mutation raises ``DurabilityError`` at the journal step,
        before any in-memory state changes."""
        return self.wal.poisoned

    def stats(self) -> dict:
        out = self.wal.scan_stats()
        out["root"] = self.root
        out["checkpoint_lsn"] = latest_checkpoint_lsn(self.root)
        out["poisoned"] = self.wal.poisoned
        if self.wal.poison_cause is not None:
            out["poison_cause"] = repr(self.wal.poison_cause)
        if self.last_report is not None:
            out["recovery"] = self.last_report.to_json_object()
        return out

    def close(self):
        self.wal.close()

    def abort(self):
        """Simulated-crash disposal: drop the WAL handle without
        flushing (see ``WriteAheadLog.abort``)."""
        self.wal.abort()


class DurableStore(DataStore):
    """Journal-before-apply wrapper over any DataStore. On open it
    replays the durable root into ``inner`` (pass a FRESH inner store —
    replay assumes it holds nothing the log doesn't know about).

    Don't stack it on a store that already journals natively
    (``durable_dir=`` knob) — every mutation would be logged twice."""

    def __init__(self, inner: DataStore, root: str,
                 fsync: str | None = None,
                 segment_bytes: int | None = None,
                 interval_ms: float | None = None,
                 recover_on_open: bool = True, registry=metrics):
        self.inner = inner
        self.journal = Journal(root, fsync=fsync,
                               segment_bytes=segment_bytes,
                               interval_ms=interval_ms, registry=registry)
        self.recovery: RecoveryReport | None = (
            self.journal.recover(inner) if recover_on_open else None)

    # -- schema -------------------------------------------------------------

    def create_schema(self, sft: SimpleFeatureType | str,
                      spec: str | None = None):
        if isinstance(sft, str):
            sft = parse_spec(sft, spec or "")
        if sft.type_name in self.inner.get_type_names():
            raise ValueError(f"schema {sft.type_name!r} already exists")
        self.journal.log_create_schema(sft)
        self.inner.create_schema(sft)

    def get_schema(self, type_name: str) -> SimpleFeatureType:
        return self.inner.get_schema(type_name)

    def get_type_names(self) -> list[str]:
        return self.inner.get_type_names()

    def remove_schema(self, type_name: str):
        if type_name in self.inner.get_type_names():
            self.journal.log_drop_schema(type_name)
        self.inner.remove_schema(type_name)

    # -- mutations (journal, then apply) ------------------------------------

    def write(self, type_name: str, batch: FeatureBatch, **kwargs):
        vis = kwargs.get("visibilities")
        self.journal.log_write(type_name, batch, vis)
        self.inner.write(type_name, batch, **kwargs)

    def delete(self, type_name: str, ids):
        ids = [str(i) for i in ids]
        self.journal.log_delete(type_name, ids)
        self.inner.delete(type_name, ids)

    # -- queries (pure delegation) -------------------------------------------

    def query(self, q: Query | str, type_name: str | None = None,
              explain_out=None):
        return self.inner.query(q, type_name, explain_out=explain_out)

    def count(self, type_name: str) -> int:
        return self.inner.count(type_name)

    # -- durability surface ---------------------------------------------------

    def checkpoint(self, keep: int = 2) -> dict:
        return self.journal.checkpoint(self.inner, keep=keep)

    def close(self):
        self.journal.close()
        close = getattr(self.inner, "close", None)
        if close is not None:
            close()

    def __getattr__(self, name):
        # everything else (query_batched, density, stats_query, audit,
        # ...) rides through to the wrapped store
        return getattr(self.inner, name)
