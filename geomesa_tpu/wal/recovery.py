"""Open-time crash recovery: snapshot load + redo replay.

ARIES redo, restricted to the logical-record level: restore the newest
durable checkpoint, then re-apply every log record past the checkpoint
LSN. Replay is idempotent — a WRITE deletes its ids before re-inserting
(so a record applied both by the snapshot and the log, or replayed
twice, lands exactly once) — which lets the checkpoint capture state
concurrently with appenders: the snapshot may already contain rows
whose records sit above the checkpoint LSN, and redo simply re-applies
them.

Per-record apply failures are tolerated and counted (a generic
``DurableStore`` wrapper can journal a record whose apply then fails;
recovery must not wedge on it), and the torn-tail records dropped by
``WriteAheadLog`` open are surfaced here so one ``RecoveryReport``
describes the whole reopen.
"""

from __future__ import annotations

import logging
import time
from dataclasses import asdict, dataclass, field

from ..metrics import metrics
from .log import (CHECKPOINT_MARK, CREATE_SCHEMA, DELETE, DROP_SCHEMA,
                  WRITE, _FRAME, decode_delete, decode_schema, decode_write)
from .snapshot import load_checkpoint

__all__ = ["RecoveryReport", "recover", "replay_into"]

_log = logging.getLogger("geomesa_tpu.wal")


@dataclass
class RecoveryReport:
    """What a reopen did: how much state came from the snapshot, how
    much was redone from the log, and what the log scan cost."""

    checkpoint_lsn: int = 0
    snapshot_types: int = 0
    snapshot_rows: int = 0
    records_replayed: int = 0
    records_failed: int = 0          # apply raised; tolerated + counted
    rows_replayed: int = 0
    bytes_scanned: int = 0
    torn_records_dropped: int = 0
    corrupt_frames: int = 0          # invalid frames that ENDED replay
    replay_stopped_lsn: int = 0      # last LSN applied before the stop
    checkpoints_skipped: int = 0     # corrupt snapshots fallen past
    last_lsn: int = 0
    wall_time_s: float = 0.0
    errors: list = field(default_factory=list)  # first few, for the CLI

    def to_json_object(self) -> dict:
        return asdict(self)


def _ensure_schema(store, sft):
    if sft.type_name in store.get_type_names():
        return
    try:
        store.create_schema(sft)
    except TypeError:
        from ..features.sft import encode_spec
        store.create_schema(sft.type_name, encode_spec(sft))


def _apply(store, kind: int, payload: bytes, report: RecoveryReport):
    if kind == WRITE:
        tn, batch, vis = decode_write(payload)
        if batch is None or batch.n == 0:
            return
        _ensure_schema(store, batch.sft)
        # idempotence: a redo of rows the snapshot (or an earlier
        # replayed record) already holds must not duplicate them
        store.delete(tn, batch.ids)
        store.write(tn, batch,
                    visibilities=None if vis is None else list(vis))
        report.rows_replayed += batch.n
    elif kind == DELETE:
        tn, ids = decode_delete(payload)
        if tn in store.get_type_names():
            store.delete(tn, ids)
    elif kind == CREATE_SCHEMA:
        tn, spec = decode_schema(payload)
        if tn not in store.get_type_names():
            store.create_schema(tn, spec or "")
    elif kind == DROP_SCHEMA:
        tn, _spec = decode_schema(payload)
        if tn in store.get_type_names():
            store.remove_schema(tn)
    elif kind == CHECKPOINT_MARK:
        pass  # position marker only; the snapshot is the state
    else:
        raise ValueError(f"unknown record kind {kind}")


def replay_into(store, records, report: RecoveryReport | None = None
                ) -> RecoveryReport:
    """Redo an iterable of ``(lsn, kind, payload)`` records against a
    store (journaling suppressed by the caller)."""
    report = report if report is not None else RecoveryReport()
    for lsn, kind, payload in records:
        report.bytes_scanned += _FRAME.size + len(payload)
        try:
            _apply(store, kind, payload, report)
        except Exception as e:
            report.records_failed += 1
            if len(report.errors) < 5:
                report.errors.append(f"lsn {lsn}: {e!r}")
            _log.warning("WAL replay: record lsn=%s kind=%s failed",
                         lsn, kind, exc_info=True)
        else:
            report.records_replayed += 1
        report.replay_stopped_lsn = lsn
    return report


def recover(store, wal, root: str, registry=metrics) -> RecoveryReport:
    """Full reopen sequence: load the newest checkpoint under ``root``
    into ``store``, then redo every log record past its LSN. ``wal`` is
    an already-open WriteAheadLog (its open truncated any torn tail)."""
    t0 = time.perf_counter()
    report = RecoveryReport()
    report.torn_records_dropped = getattr(wal, "torn_tail_records", 0)
    from_lsn = 1

    def skipped(path, why):
        report.checkpoints_skipped += 1
        if len(report.errors) < 5:
            report.errors.append(f"checkpoint skipped: {path}: {why}")
        _log.warning("recovery: skipping corrupt checkpoint %s (%s)",
                     path, why)

    ckpt = load_checkpoint(root, on_skip=skipped)
    if ckpt is not None:
        lsn0, states = ckpt
        report.checkpoint_lsn = lsn0
        from_lsn = lsn0 + 1
        for sft, batch, vis in states:
            _ensure_schema(store, sft)
            if batch is not None and batch.n:
                store.write(sft.type_name, batch,
                            visibilities=None if vis is None else list(vis))
                report.snapshot_rows += int(batch.n)
            report.snapshot_types += 1

    def torn(path, frames):
        report.corrupt_frames += frames
        if len(report.errors) < 5:
            report.errors.append(
                f"replay stopped at lsn {report.replay_stopped_lsn}: "
                f"{frames} invalid frame(s) in {path}")

    replay_into(store, wal.records(from_lsn, on_torn=torn), report)
    report.last_lsn = wal.last_lsn
    report.wall_time_s = time.perf_counter() - t0
    registry.gauge("wal.recovery.seconds", report.wall_time_s)
    registry.counter("wal.recovery.records", report.records_replayed)
    if report.checkpoints_skipped:
        registry.counter("integrity.recovery.checkpoints_skipped",
                         report.checkpoints_skipped)
    return report
