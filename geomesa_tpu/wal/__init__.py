"""Durability subsystem: write-ahead log, checkpoints, crash recovery.

The reference gets durability for its near-real-time tier by layering
on Kafka (the Lambda store merges transient state with long-term
persistence); the TPU rebuild's hot stores hold device-resident columns
with no persistence at all — a process crash loses every write since
startup. This package closes that gap with the classic ARIES-style
journal/checkpoint/replay discipline:

- ``log.py``      — append-only segmented log, CRC-framed records,
  monotonic LSNs, group-commit with a configurable fsync policy,
  torn-tail truncation on open;
- ``snapshot.py`` — atomic checkpoint of a store's host-side column
  state + schema + index-version metadata, with retention that drops
  log segments wholly below the last durable checkpoint;
- ``recovery.py`` — open-time replay (snapshot load + redo past the
  checkpoint LSN, idempotent on reapplied ids) with a RecoveryReport;
- ``durable.py``  — the ``Journal`` façade the stores embed, and a
  generic ``DurableStore`` wrapper for any DataStore.
"""

from .log import (CHECKPOINT_MARK, CREATE_SCHEMA, DELETE, DROP_SCHEMA,
                  WRITE, DurabilityError, WriteAheadLog, decode_delete,
                  decode_schema, decode_write, encode_delete,
                  encode_drop_schema, encode_schema, encode_write)
from .snapshot import (latest_checkpoint_lsn, load_checkpoint,
                       write_checkpoint)
from .recovery import RecoveryReport, recover, replay_into
from .durable import DurableStore, Journal

__all__ = [
    "WriteAheadLog", "DurabilityError", "WRITE", "DELETE",
    "CREATE_SCHEMA", "DROP_SCHEMA", "CHECKPOINT_MARK",
    "encode_write", "decode_write", "encode_delete", "decode_delete",
    "encode_schema", "decode_schema", "encode_drop_schema",
    "write_checkpoint", "load_checkpoint", "latest_checkpoint_lsn",
    "RecoveryReport", "recover", "replay_into",
    "Journal", "DurableStore",
]
