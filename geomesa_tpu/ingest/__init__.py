"""Ingest firehose: group-commit batched writes + admission control.

``IngestPipeline`` stages converter output (or any caller's batches)
through a bounded in-flight-rows queue into coalesced ``write_many``
store calls — one WAL append / fsync decision and one state append per
fused group. ``IngestGovernor`` is the admission-control half: a
token bucket over in-flight rows (blocking put for embedded callers,
429 + Retry-After on the web tier) plus a shed signal derived from the
read batchers' queue depth so bulk ingest cannot starve query
dispatches.
"""

from .pipeline import (INGEST_GROUP_ROWS, INGEST_LATENCY_BUDGET_MS,
                       INGEST_MAX_INFLIGHT_ROWS, INGEST_SHED_QUEUE_DEPTH,
                       IngestAck, IngestGovernor, IngestPipeline)

__all__ = ["IngestPipeline", "IngestGovernor", "IngestAck",
           "INGEST_MAX_INFLIGHT_ROWS", "INGEST_GROUP_ROWS",
           "INGEST_LATENCY_BUDGET_MS", "INGEST_SHED_QUEUE_DEPTH"]
