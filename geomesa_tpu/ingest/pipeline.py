"""The group-commit write plane.

Write amplification in the seed path is per-caller: every
``store.write`` pays its own journal append (a WAL frame + an fsync
decision), its own ``_TypeState.append`` (plan-cache clear, lazy-result
detach), and on the cluster store its own owner slicing. The pipeline
inverts that: callers stage batches into a bounded queue and a single
writer thread drains it in fused groups, so N staged batches cost
⌈N·rows/group⌉ store writes instead of N.

Group sizing reuses the PR 10 latency-derived cap idea from
``scan/batcher.py``: an EWMA of observed per-row write cost turns
``geomesa.ingest.latency.budget.ms`` into a row cap, so groups grow on
fast stores and shrink under slow fsyncs to keep commit latency
bounded.

Admission control is row-denominated: ``geomesa.ingest.max.inflight.
rows`` tokens cover everything staged but not yet committed. Embedded
callers block (backpressure); the web tier asks non-blocking and maps
refusal to 429 + Retry-After. Independently, the writer pauses briefly
while the read batchers' queues are deep (``geomesa.ingest.shed.queue.
depth``) — sustained ingest yields to query dispatches instead of
starving them.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any

from ..features.batch import FeatureBatch
from ..metrics import metrics
from ..utils.properties import SystemProperty

__all__ = ["IngestPipeline", "IngestGovernor", "IngestAck",
           "INGEST_MAX_INFLIGHT_ROWS", "INGEST_GROUP_ROWS",
           "INGEST_LATENCY_BUDGET_MS", "INGEST_SHED_QUEUE_DEPTH"]

INGEST_MAX_INFLIGHT_ROWS = SystemProperty(
    "geomesa.ingest.max.inflight.rows", "262144")
INGEST_GROUP_ROWS = SystemProperty("geomesa.ingest.group.rows", "131072")
INGEST_LATENCY_BUDGET_MS = SystemProperty(
    "geomesa.ingest.latency.budget.ms", "500")
INGEST_SHED_QUEUE_DEPTH = SystemProperty(
    "geomesa.ingest.shed.queue.depth", "64")
INGEST_SHED_PAUSE_MS = SystemProperty("geomesa.ingest.shed.pause.ms", "5")

_EWMA_ALPHA = 0.2  # matches scan/batcher.py cost smoothing
_MIN_GROUP_ROWS = 1024  # latency cap floor: groups never degenerate to 1


class IngestAck:
    """Per-staged-batch commit handle: set once its fused group's store
    write returns (or fails). An acked batch has been journaled — the
    zero-loss recovery contract covers exactly the acked rows."""

    __slots__ = ("_event", "result", "error")

    def __init__(self):
        self._event = threading.Event()
        self.result = None
        self.error: BaseException | None = None

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: float | None = None):
        if not self._event.wait(timeout):
            raise TimeoutError("ingest ack timeout")
        if self.error is not None:
            raise self.error
        return self.result

    def _complete(self, result=None, error: BaseException | None = None):
        self.result = result
        self.error = error
        self._event.set()


class IngestGovernor:
    """Token bucket over in-flight (staged, uncommitted) rows plus the
    read-starvation shed signal."""

    def __init__(self, max_inflight_rows: int | None = None):
        self.max_inflight_rows = int(
            max_inflight_rows
            if max_inflight_rows is not None
            else INGEST_MAX_INFLIGHT_ROWS.as_int())
        self._cv = threading.Condition()
        self._inflight = 0

    @property
    def inflight_rows(self) -> int:
        return self._inflight

    def acquire(self, rows: int, block: bool = True,
                timeout: float | None = None) -> bool:
        """Admit ``rows``; blocks while the bucket is full. An oversize
        batch (> the whole bucket) is admitted alone once the bucket
        drains — refusing it forever would deadlock callers."""
        waited = False
        with self._cv:
            while (self._inflight > 0
                   and self._inflight + rows > self.max_inflight_rows):
                if not block:
                    metrics.counter("ingest.backpressure.refused")
                    return False
                if not waited:
                    waited = True
                    metrics.counter("ingest.backpressure.waits")
                if not self._cv.wait(timeout=timeout):
                    metrics.counter("ingest.backpressure.refused")
                    return False
            self._inflight += rows
            metrics.gauge("ingest.queue.rows", self._inflight)
        return True

    def release(self, rows: int):
        with self._cv:
            self._inflight = max(0, self._inflight - rows)
            metrics.gauge("ingest.queue.rows", self._inflight)
            self._cv.notify_all()

    def wait_idle(self, timeout: float | None = None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._inflight > 0:
                left = (None if deadline is None
                        else deadline - time.monotonic())
                if left is not None and left <= 0:
                    return False
                if not self._cv.wait(timeout=left):
                    return False
        return True

    # -- read-starvation shed signal --------------------------------------

    @staticmethod
    def read_queue_depth() -> int:
        from ..scan.registry import batcher_registry
        return sum(batcher_registry.queue_depths().values())

    def should_shed(self) -> bool:
        """True while admitting more ingest would starve readers: the
        process-wide read batchers have a deep backlog."""
        threshold = INGEST_SHED_QUEUE_DEPTH.as_int()
        if threshold is None or threshold <= 0:
            return False
        return self.read_queue_depth() > threshold


class IngestPipeline:
    """Bounded-queue group-commit writer over any ``DataStore``.

    Callers ``write()`` staged batches and get an ``IngestAck``; one
    writer thread coalesces same-type runs up to the effective group
    cap and commits them with a single ``store.write_many`` — one
    journal append + one state append per group on durable stores, one
    owner slicing per group on the cluster store.
    """

    def __init__(self, store, group_rows: int | None = None,
                 governor: IngestGovernor | None = None,
                 max_inflight_rows: int | None = None):
        self.store = store
        self.governor = governor or IngestGovernor(max_inflight_rows)
        self._group_rows = int(group_rows if group_rows is not None
                               else INGEST_GROUP_ROWS.as_int())
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._closed = False
        self._cost_ewma: float | None = None  # seconds per row
        self._rate_ewma: float | None = None  # rows per second
        self._writer = threading.Thread(target=self._run, daemon=True,
                                        name="ingest-pipeline")
        self._writer.start()

    # -- staging -----------------------------------------------------------

    def write(self, type_name: str, batch: FeatureBatch,
              visibilities=None, block: bool = True,
              timeout: float | None = None) -> IngestAck | None:
        """Stage a batch. Blocks on the governor while the in-flight
        bucket is full; with ``block=False`` returns None instead (the
        web tier's 429 path). Empty batches ack immediately."""
        if self._closed:
            raise RuntimeError("ingest pipeline is closed")
        ack = IngestAck()
        if batch.n == 0:
            ack._complete()
            return ack
        if not self.governor.acquire(batch.n, block=block, timeout=timeout):
            return None
        # per-tenant row bucket (tenants plane): the staging caller's
        # tenant is charged here and credited by the writer thread when
        # the group commits — the writer has no caller context, so the
        # identity rides the queue entry. QoS off -> tenant is None and
        # the global governor is the only gate, as before.
        from ..tenants import active_tenant, tenant_registry
        tenant = active_tenant()
        if tenant is not None and not tenant_registry.acquire_rows(
                tenant, batch.n, block=block, timeout=timeout):
            self.governor.release(batch.n)
            return None
        with self._cv:
            if self._closed:
                self.governor.release(batch.n)
                if tenant is not None:
                    tenant_registry.release_rows(tenant, batch.n)
                raise RuntimeError("ingest pipeline is closed")
            from ..obs import tracer
            self._q.append((type_name, batch, visibilities, ack,
                            tracer.current(), tenant))
            self._cv.notify()
        return ack

    def flush(self, timeout: float | None = None) -> bool:
        """Block until everything staged so far has committed."""
        return self.governor.wait_idle(timeout=timeout)

    def close(self, timeout: float | None = None):
        self.flush(timeout=timeout)
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._writer.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- group sizing ------------------------------------------------------

    def effective_group_rows(self) -> int:
        """Static cap, shrunk to what the latency budget affords at the
        EWMA per-row write cost (scan/batcher.py's effective_max_batch
        shape, row-denominated)."""
        cap = self._group_rows
        budget_ms = INGEST_LATENCY_BUDGET_MS.as_float()
        if budget_ms and budget_ms > 0 and self._cost_ewma:
            cap = min(cap, max(_MIN_GROUP_ROWS,
                               int((budget_ms / 1000.0) / self._cost_ewma)))
        return max(1, cap)

    def _observe(self, rows: int, elapsed_s: float):
        if rows <= 0:
            return
        per_row = elapsed_s / rows
        self._cost_ewma = (per_row if self._cost_ewma is None
                           else _EWMA_ALPHA * per_row
                           + (1.0 - _EWMA_ALPHA) * self._cost_ewma)
        if elapsed_s > 0:
            rate = rows / elapsed_s
            self._rate_ewma = (rate if self._rate_ewma is None
                               else _EWMA_ALPHA * rate
                               + (1.0 - _EWMA_ALPHA) * self._rate_ewma)
            metrics.gauge("ingest.rows.per.s", int(self._rate_ewma))

    def observe_context(self, ctx) -> dict[str, int]:
        """Publish converter counters into ingest metrics (the
        EvaluationContext merge point)."""
        counts = ctx.counters()
        metrics.gauge("ingest.convert.success", counts["success"])
        metrics.gauge("ingest.convert.failure", counts["failure"])
        metrics.gauge("ingest.convert.lines", counts["line"])
        return counts

    # -- writer thread -----------------------------------------------------

    def _next_group(self) -> list | None:
        """Pop a same-type run from the queue head, capped at the
        effective group rows. Returns None once closed and drained."""
        with self._cv:
            while not self._q:
                if self._closed:
                    return None
                self._cv.wait()
            cap = self.effective_group_rows()
            type_name = self._q[0][0]
            group = [self._q.popleft()]
            rows = group[0][1].n
            while (self._q and self._q[0][0] == type_name
                   and rows + self._q[0][1].n <= cap):
                entry = self._q.popleft()
                rows += entry[1].n
                group.append(entry)
            return group

    def _shed_pause(self):
        """Yield to deep read queues, briefly and boundedly: commit
        latency stays finite even under a permanently-saturated read
        tier."""
        if self.governor.should_shed():
            metrics.counter("ingest.shed.pauses")
            pause_ms = INGEST_SHED_PAUSE_MS.as_float() or 0.0
            if pause_ms > 0:
                time.sleep(pause_ms / 1000.0)

    def _run(self):
        while True:
            group = self._next_group()
            if group is None:
                return
            type_name = group[0][0]
            rows = sum(e[1].n for e in group)
            self._shed_pause()
            t0 = time.perf_counter()
            from ..obs import tracer
            gsp = tracer.span("group-commit", type_name, root=True)
            if gsp.span_id is not None:
                # link the commit span to every staged caller's trace so
                # a write's trace resolves to the fsync that durably
                # committed it (and vice versa)
                for e in group:
                    ctx = e[4]
                    if ctx is None:
                        continue
                    state, wsp = ctx
                    gsp.link(state.trace_id, wsp.span_id)
                    wsp.link(gsp.trace_id, gsp.span_id)
            try:
                with gsp:
                    gsp.set_attr(rows=rows, staged=len(group))
                    from ..obs.prof import watchdog
                    with watchdog.watch(f"ingest.commit.{type_name}",
                                        span=gsp):
                        result = self.store.write_many(
                            type_name, [(e[1], e[2]) for e in group])
            except BaseException as exc:  # noqa: BLE001 — acks carry it
                metrics.counter("ingest.errors")
                for e in group:
                    e[3]._complete(error=exc)
            else:
                elapsed = time.perf_counter() - t0
                self._observe(rows, elapsed)
                metrics.counter("ingest.rows", rows)
                metrics.counter("ingest.groups")
                metrics.counter("ingest.staged.batches", len(group))
                metrics.gauge("ingest.group.rows", rows)
                metrics.gauge("ingest.group.cap",
                                  self.effective_group_rows())
                for e in group:
                    e[3]._complete(result=result)
            finally:
                self.governor.release(rows)
                for e in group:
                    if len(e) > 5 and e[5] is not None:
                        from ..tenants import tenant_registry
                        tenant_registry.release_rows(e[5], e[1].n)
