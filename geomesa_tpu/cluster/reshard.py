"""Online Z-shard split/migration: move a prefix range between groups
with zero acked-write loss while the cluster keeps serving.

The reference's elastic-scale story is tablet splitting on the
key-value store — ranges split and migrate between region servers as
key density shifts, without a restart. This module is that operation
for the cluster tier: reassign a z-prefix range ``[lo, hi)`` from a
source shard group to a destination group *online*, against live
reads and writes.

Protocol (the snapshot + WAL-tail + atomic-flip shape PR 4/6/8 built
the pieces for):

1. **Install** (brief exclusive gate): a ``_Migration`` is attached to
   the coordinator. For a non-durable source the coordinator starts
   double-routing — every write/delete that lands in the moving range
   is also applied to the migration's private *staging* store. A
   durable source needs no double-routing: its WAL already carries
   every acked mutation, and the tail IS the stream.
2. **Snapshot**: the moving range's rows are captured through the
   checkpoint path (force a checkpoint, load and verify it, filter to
   the moving range) and staged. Staging is always delete-then-write
   under one lock — the recovery.py idempotent-redo idiom — so a row
   present in both the snapshot and the tail lands exactly once.
3. **Catch-up**: the WAL tail past the snapshot LSN replays into the
   staging store in bounded rounds until the remaining tail is small.
4. **Flip** (exclusive gate, ``geomesa.reshard.flip.timeout.s``): the
   final tail replays up to the barrier LSN (``wal.last_lsn`` with all
   writers drained), the migration is CUT — any straggler staged apply
   now fails typed ``StaleTopologyError``, the `_promote_cutoff`
   zombie-fencing pattern pointed at topology instead of promotion —
   then the staged rows bulk-write to the destination
   (delete-then-write: idempotent on resume), the source deletes them,
   and the coordinator swaps in the successor topology (epoch + 1) and
   clears the prune cache. The LSN vector bumps for both groups, so
   read-your-writes holds across the flip.
5. **Crash mid-flip**: the migration is left ``broken`` and every
   cluster op fails typed until ``resume()`` (re-runs the idempotent
   flip steps) or ``abort()`` (restores the staged rows to the source,
   removes them from the destination, keeps the old topology) —
   exact-or-typed, never silently duplicated or lost.

``geomesa.reshard.enabled=false`` refuses every reshard verb, leaving
the uniform epoch-0 topology — routing bit-identical to the
pre-reshard cluster.
"""

from __future__ import annotations

import contextlib
import threading
import time

import numpy as np

from ..metrics import metrics
from ..utils.properties import SystemProperty
from .partition import _N_PREFIXES, ZPrefixPartitioner

__all__ = ["Resharder", "ReshardError", "StaleTopologyError",
           "RESHARD_ENABLED", "RESHARD_COOLDOWN_S",
           "RESHARD_MAX_CONCURRENT", "RESHARD_FLIP_TIMEOUT_S"]

# kill switch: "false" refuses split/migrate/auto entirely — the
# topology stays uniform epoch-0, bit-identical to the pre-reshard
# cluster (reload-only scaling)
RESHARD_ENABLED = SystemProperty("geomesa.reshard.enabled", "true")
# minimum seconds between AUTO-triggered reshards (rate guard on the
# control loop; manual operator verbs are not throttled)
RESHARD_COOLDOWN_S = SystemProperty("geomesa.reshard.cooldown.s", "300")
# concurrent migrations allowed (the flip serializes on the op gate
# regardless; >1 is for future multi-range moves)
RESHARD_MAX_CONCURRENT = SystemProperty("geomesa.reshard.max.concurrent",
                                        "1")
# how long the flip may wait to drain in-flight ops before failing
# typed (the migration stays resumable)
RESHARD_FLIP_TIMEOUT_S = SystemProperty("geomesa.reshard.flip.timeout.s",
                                        "30")


class ReshardError(RuntimeError):
    """A reshard verb could not run (disabled, already in flight,
    cooldown, bad range) or a migration is in a state that needs
    ``resume()``/``abort()``. NOT retryable blindly — the message says
    which."""

    retryable = False


class StaleTopologyError(ReshardError):
    """A write carried a topology epoch the cluster has already moved
    past (or a staged apply raced the flip's cut) — the zombie-write
    fence. The client must refresh its topology and re-route."""

    def __init__(self, detail: str, epoch=None, current=None):
        self.epoch = epoch
        self.current = current
        super().__init__(detail)


class _OpGate:
    """Shared/exclusive gate over cluster ops: every read/write takes
    the shared side (concurrent among themselves), the flip takes the
    exclusive side — draining in-flight ops and blocking new ones for
    the flip's brief critical section."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def acquire_shared(self):
        # writer-preferring: new shared entrants queue behind a waiting
        # flip, or a steady stream of scatter reads would starve the
        # exclusive drain past its timeout
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def release_shared(self):
        with self._cond:
            self._readers -= 1
            if self._readers <= 0:
                self._cond.notify_all()

    @contextlib.contextmanager
    def shared(self):
        self.acquire_shared()
        try:
            yield
        finally:
            self.release_shared()

    @contextlib.contextmanager
    def exclusive(self, timeout_s: float):
        deadline = time.monotonic() + timeout_s
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._readers or self._writer:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise ReshardError(
                            f"could not drain in-flight cluster ops "
                            f"inside {timeout_s:g}s "
                            f"(geomesa.reshard.flip.timeout.s)")
                    self._cond.wait(remaining)
                self._writer = True
            finally:
                self._writers_waiting -= 1
                if not self._writers_waiting:
                    self._cond.notify_all()
        try:
            yield
        finally:
            with self._cond:
                self._writer = False
                self._cond.notify_all()


class _Migration:
    """One in-flight range move: the moving range, the successor
    topology it will flip to, and the private staging store the moving
    rows accumulate in. Staged rows are invisible to reads until the
    flip — scatter legs keep merging disjoint partitions, so queries
    during migration stay exact."""

    def __init__(self, coord, src_idx: int, dst_idx: int,
                 prefix_lo: int, prefix_hi: int,
                 probe: ZPrefixPartitioner, reason: str,
                 forward: bool, registry=metrics):
        from ..store.memory import InMemoryDataStore
        self.src_idx, self.dst_idx = int(src_idx), int(dst_idx)
        self.src_name = coord._names[src_idx]
        self.dst_name = coord._names[dst_idx]
        self.prefix_lo, self.prefix_hi = int(prefix_lo), int(prefix_hi)
        self.base = coord._part                # topology being left
        self.probe = probe                     # topology being entered
        self.reason = reason
        self.forward = forward                 # double-route (no WAL)
        self.phase = "install"
        self.lock = threading.RLock()
        self.pending = InMemoryDataStore()
        self.cursor = 0                        # last WAL lsn staged
        self.barrier_lsn = None
        self.rows_staged = 0
        self.rows_moved = 0
        self.moved_ids: dict[str, list] = {}
        self.started_ms = int(time.time() * 1000)
        self.error = None
        self._registry = registry

    @property
    def blocking(self) -> bool:
        """True once the flip has begun mutating group state — cluster
        ops must fail typed until resume/abort restores a consistent
        topology."""
        return self.phase in ("cut", "broken")

    def describe(self) -> dict:
        return {"src": self.src_name, "dst": self.dst_name,
                "prefix_lo": self.prefix_lo, "prefix_hi": self.prefix_hi,
                "phase": self.phase, "reason": self.reason,
                "rows_staged": self.rows_staged,
                "cursor_lsn": self.cursor,
                "barrier_lsn": self.barrier_lsn,
                "started_ms": self.started_ms,
                "error": self.error}

    # -- staging -----------------------------------------------------------

    def moving_rows(self, sft, batch) -> np.ndarray:
        """Row indices whose ownership this migration changes: routed
        to src under the old topology AND to dst under the successor.
        Id-hash-routed rows never qualify (same owner in both)."""
        o0 = self.base.owners_for_batch(sft, batch)
        o1 = self.probe.owners_for_batch(sft, batch)
        return np.flatnonzero((o0 == self.src_idx) & (o1 == self.dst_idx))

    def _ensure_schema(self, sft):
        if sft.type_name not in self.pending.get_type_names():
            self.pending.create_schema(sft)

    def stage_write(self, sft, batch, visibilities=None) -> int:
        """Stage the moving slice of a batch: delete-then-write under
        the staging lock (exactly one copy per id, idempotent on
        re-apply — the recovery.py redo idiom)."""
        rows = self.moving_rows(sft, batch)
        if not len(rows):
            return 0
        sub = batch if len(rows) == batch.n else batch.take(rows)
        vis = None
        if visibilities is not None:
            vis = list(np.asarray(visibilities, dtype=object)[rows])
        with self.lock:
            if self.blocking or self.phase in ("done", "aborted"):
                self._registry.counter("cluster.reshard.zombie.rejects")
                raise StaleTopologyError(
                    f"staged write raced the topology flip "
                    f"(migration {self.phase})")
            self._ensure_schema(sft)
            self.pending.delete(sft.type_name, list(sub.ids))
            self.pending.write(sft.type_name, sub, visibilities=vis)
            self.rows_staged = sum(self.pending.count(t)
                                   for t in self.pending.get_type_names())
        return int(len(rows))

    def stage_delete(self, type_name: str, ids) -> None:
        with self.lock:
            if self.blocking or self.phase in ("done", "aborted"):
                self._registry.counter("cluster.reshard.zombie.rejects")
                raise StaleTopologyError(
                    f"staged delete raced the topology flip "
                    f"(migration {self.phase})")
            if type_name in self.pending.get_type_names():
                self.pending.delete(type_name, ids)


def _journal_of(group):
    """The source group's journal, reaching through the replication
    router (``.primary``) and the DurableStore wrapper. None for a
    non-durable group — the live-snapshot + double-route path."""
    j = getattr(group, "journal", None)
    if j is not None:
        return j
    primary = getattr(group, "primary", None)
    if primary is not None:
        return getattr(primary, "journal", None)
    return None


class Resharder:
    """Executes split/migrate verbs against one ``ClusterDataStore``
    and records the topology epoch history. ``fault_hook(tag)`` is the
    kill-point seam the crash-safety tests arm (the CrashHarness
    shape): raising from it simulates a crash at that point in the
    protocol."""

    #: kill-point tags fault_hook can fire at, in protocol order
    PHASES = ("snapshot.start", "snapshot.done", "catchup.done",
              "flip.enter", "flip.barrier", "flip.copy", "flip.copied",
              "flip.delete_src", "flip.swap")

    def __init__(self, coord, registry=metrics):
        self._coord = coord
        self._registry = registry
        self._lock = threading.Lock()
        self._active: _Migration | None = None
        self._last_done: float | None = None   # monotonic, cooldown
        self.history: list[dict] = []
        self.fault_hook = None

    # -- plumbing ----------------------------------------------------------

    def _fault(self, tag: str):
        if self.fault_hook is not None:
            self.fault_hook(tag)

    def _check_enabled(self):
        if not RESHARD_ENABLED.as_bool():
            raise ReshardError(
                "resharding disabled (geomesa.reshard.enabled=false); "
                "topology is fixed at the uniform epoch-0 split")

    def _gidx(self, group) -> int:
        names = self._coord._names
        if isinstance(group, (int, np.integer)):
            if not 0 <= int(group) < len(names):
                raise ReshardError(f"group index {group} out of range")
            return int(group)
        if group in names:
            return names.index(group)
        raise ReshardError(f"no such group {group!r}; have: "
                           + ", ".join(names))

    def _flip_timeout(self) -> float:
        return RESHARD_FLIP_TIMEOUT_S.as_float() or 30.0

    def cooldown_remaining(self) -> float:
        """Seconds until the next AUTO reshard may fire (0 when clear)."""
        if self._last_done is None:
            return 0.0
        cd = RESHARD_COOLDOWN_S.as_float() or 0.0
        return max(0.0, cd - (time.monotonic() - self._last_done))

    def status(self) -> dict:
        mig = self._active
        return {"enabled": bool(RESHARD_ENABLED.as_bool()),
                "epoch": self._coord._part.epoch,
                "active": mig.describe() if mig is not None else None,
                "cooldown_remaining_s": round(self.cooldown_remaining(), 3),
                "history": list(self.history)}

    # -- verbs -------------------------------------------------------------

    def split(self, src, dst=None, at=None, reason: str = "manual"
              ) -> dict:
        """Split the source group's (widest) owned range at a
        key-density-chosen point and migrate the upper side to ``dst``
        (default: the least-loaded other group)."""
        self._check_enabled()
        src_idx = self._gidx(src)
        ranges = self._coord._part.owned_prefix_ranges(src_idx)
        if not ranges:
            raise ReshardError(
                f"group {self._coord._names[src_idx]!r} owns no range")
        lo, hi = max(ranges, key=lambda r: r[1] - r[0])
        if hi - lo < 2:
            raise ReshardError("owned range too narrow to split")
        if at is None:
            at = self._pick_split_point(src_idx, lo, hi)
        at = int(at)
        if not lo < at < hi:
            raise ReshardError(
                f"split point {at} outside the splittable range "
                f"({lo}, {hi})")
        dst_idx = self._pick_dst(src_idx) if dst is None else self._gidx(dst)
        return self.migrate(at, hi, src_idx, dst_idx, reason=reason)

    def migrate(self, prefix_lo: int, prefix_hi: int, src, dst,
                reason: str = "manual") -> dict:
        """Move prefixes ``[prefix_lo, prefix_hi)`` from ``src`` to
        ``dst`` online. Returns the completed epoch-history entry."""
        self._check_enabled()
        coord = self._coord
        src_idx, dst_idx = self._gidx(src), self._gidx(dst)
        if src_idx == dst_idx:
            raise ReshardError("src and dst are the same group")
        if not 0 <= prefix_lo < prefix_hi <= _N_PREFIXES:
            raise ReshardError(
                f"bad prefix range [{prefix_lo}, {prefix_hi})")
        with self._lock:
            limit = max(RESHARD_MAX_CONCURRENT.as_int() or 1, 1)
            if self._active is not None and limit <= 1:
                raise ReshardError(
                    f"migration already in flight "
                    f"({self._active.src_name}->{self._active.dst_name} "
                    f"{self._active.phase}); resume or abort it first")
            if reason == "auto" and self.cooldown_remaining() > 0:
                raise ReshardError(
                    f"auto reshard in cooldown: "
                    f"{self.cooldown_remaining():.0f}s remaining "
                    f"(geomesa.reshard.cooldown.s)")
            part = coord._part
            for seg in part.segments():
                if (seg["prefix_lo"] < prefix_hi
                        and seg["prefix_hi"] > prefix_lo
                        and seg["group"] != src_idx):
                    raise ReshardError(
                        f"prefixes [{prefix_lo}, {prefix_hi}) are not "
                        f"all owned by {coord._names[src_idx]!r} "
                        f"(segment {seg} intersects)")
            probe = part.with_move(prefix_lo, prefix_hi, dst_idx)
            src_group = coord._groups[src_idx]
            journal = _journal_of(src_group)
            mig = _Migration(coord, src_idx, dst_idx, prefix_lo,
                             prefix_hi, probe, reason,
                             forward=journal is None,
                             registry=self._registry)
            # mirror the schemas so staged applies always land
            for tn in coord.get_type_names():
                mig.pending.create_schema(coord.get_schema(tn))
            self._active = mig
        # install under a brief exclusive section: drains in-flight
        # writes, so every later mutation is either WAL-tailed
        # (durable) or double-routed (non-durable)
        try:
            with coord._gate.exclusive(self._flip_timeout()):
                coord._migration = mig
                mig.phase = "snapshot"
        except BaseException:
            with self._lock:
                self._active = None
            raise
        return self._drive(mig, src_group, journal)

    def resume(self) -> dict:
        """Re-drive an interrupted migration to completion. Safe after
        a crash at any kill point: staging and the flip are both
        delete-then-write idempotent."""
        self._check_enabled()
        mig = self._active
        if mig is None:
            raise ReshardError("no migration to resume")
        coord = self._coord
        src_group = coord._groups[mig.src_idx]
        journal = _journal_of(src_group)
        if mig.phase in ("cut", "broken"):
            # the flip already cut: redo only the flip body
            t0 = time.perf_counter()
            with coord._gate.exclusive(self._flip_timeout()):
                with mig.lock:
                    mig.phase = "cut"
                self._finish_flip(mig)
            return self._record(mig, (time.perf_counter() - t0) * 1e3)
        mig.error = None
        mig.phase = "snapshot"
        return self._drive(mig, src_group, journal)

    def abort(self) -> dict:
        """Cancel the active migration and restore the pre-migration
        state: staged rows return to the source (delete-then-write),
        any copies already flipped into the destination are removed,
        and the old topology stays."""
        mig = self._active
        if mig is None:
            raise ReshardError("no migration to abort")
        coord = self._coord
        src = coord._groups[mig.src_idx]
        dst = coord._groups[mig.dst_idx]
        from ..wal.snapshot import iter_store_states
        with coord._gate.exclusive(self._flip_timeout()):
            if mig.blocking:
                # the flip may have part-copied into dst and
                # part-deleted from src: the staging store holds the
                # authoritative barrier-time state of the moving range
                for sft, batch, vis in list(iter_store_states(mig.pending)):
                    if batch is None or not batch.n:
                        continue
                    ids = list(batch.ids)
                    dst.delete(sft.type_name, ids)
                    src.delete(sft.type_name, ids)
                    src.write(sft.type_name, batch,
                              visibilities=None if vis is None
                              else list(vis))
            with mig.lock:
                mig.phase = "aborted"
            coord._migration = None
        with self._lock:
            self._active = None
        self._registry.counter("cluster.reshard.aborts")
        entry = {"epoch": coord._part.epoch, "op": "abort",
                 "src": mig.src_name, "dst": mig.dst_name,
                 "prefix_lo": mig.prefix_lo, "prefix_hi": mig.prefix_hi,
                 "reason": mig.reason, "ts_ms": int(time.time() * 1000)}
        self.history.append(entry)
        return entry

    # -- protocol ----------------------------------------------------------

    def _drive(self, mig: _Migration, src_group, journal) -> dict:
        t0 = time.perf_counter()
        try:
            self._fault("snapshot.start")
            if journal is not None:
                self._snapshot_durable(mig, src_group, journal)
            else:
                self._snapshot_live(mig, src_group)
            self._fault("snapshot.done")
            mig.phase = "catchup"
            if journal is not None:
                self._catchup(mig, journal)
            self._fault("catchup.done")
            flip_ms = self._flip(mig, journal)
        except ReshardError:
            raise
        except BaseException as e:
            mig.error = f"{type(e).__name__}: {e}"
            with mig.lock:
                if mig.phase == "cut":
                    mig.phase = "broken"
            self._registry.counter("cluster.reshard.failures")
            raise
        return self._record(mig, flip_ms)

    def _snapshot_durable(self, mig, group, journal):
        """Snapshot via the checkpoint path: force a checkpoint (the
        write is atomic + digest-verified by snapshot.py), load it
        back, stage the moving slice. The WAL tail past the checkpoint
        LSN is replayed by catch-up."""
        from ..wal.snapshot import load_checkpoint
        ckpt = getattr(group, "checkpoint", None)
        if not callable(ckpt):
            primary = getattr(group, "primary", None)
            ckpt = getattr(primary, "checkpoint", None)
        if callable(ckpt):
            ckpt()
        loaded = load_checkpoint(journal.root)
        if loaded is None:
            # no loadable snapshot (all corrupt): fall back to a live
            # read; the WAL tail still converges the staging store
            self._snapshot_live(mig, group)
            return
        lsn, states = loaded
        mig.cursor = int(lsn)
        for sft, batch, vis in states:
            if batch is None or not batch.n:
                continue
            mig.stage_write(sft, batch, visibilities=vis)

    def _snapshot_live(self, mig, group):
        """Non-durable source: read the group's state directly, under
        the exclusive gate so the point-in-time read cannot interleave
        with double-routed applies (which would re-order a delete
        against its row)."""
        from ..wal.snapshot import iter_store_states
        with self._coord._gate.exclusive(self._flip_timeout()):
            try:
                states = list(iter_store_states(group))
            except TypeError:
                # remote or otherwise opaque group: full query per type
                from ..index.api import Query
                states = []
                for tn in self._coord.get_type_names():
                    sft = self._coord.get_schema(tn)
                    res = group.query(Query(tn, "INCLUDE"))
                    states.append((sft, res.batch, None))
            for sft, batch, vis in states:
                if batch is None or not batch.n:
                    continue
                mig.stage_write(sft, batch, visibilities=vis)

    def _replay_tail(self, mig, journal, upto=None) -> int:
        """Stage the WAL records past the cursor (WRITE filtered to the
        moving range, DELETE verbatim — LSN order is authoritative, so
        this converges regardless of interleaving)."""
        from ..wal.log import DELETE, WRITE, decode_delete, decode_write
        n = 0
        for lsn, kind, payload in journal.wal.records(mig.cursor + 1):
            if upto is not None and lsn > upto:
                break
            if kind == WRITE:
                tn, batch, vis = decode_write(payload)
                if batch is not None and batch.n:
                    mig.stage_write(batch.sft, batch, visibilities=vis)
            elif kind == DELETE:
                tn, ids = decode_delete(payload)
                mig.stage_delete(tn, ids)
            mig.cursor = int(lsn)
            n += 1
        return n

    def _catchup(self, mig, journal, rounds: int = 8, settle: int = 64):
        """Bounded catch-up rounds: replay the tail while writers keep
        appending; once a round stages few enough records the final
        (exclusive-gated) barrier replay is short."""
        for _ in range(rounds):
            if self._replay_tail(mig, journal) <= settle:
                return

    def _flip(self, mig, journal) -> float:
        coord = self._coord
        t0 = time.perf_counter()
        with coord._gate.exclusive(self._flip_timeout()):
            self._fault("flip.enter")
            if journal is not None:
                mig.barrier_lsn = int(journal.wal.last_lsn)
                self._replay_tail(mig, journal, upto=mig.barrier_lsn)
            self._fault("flip.barrier")
            with mig.lock:
                mig.phase = "cut"      # zombie fence: staged applies
            self._finish_flip(mig)     # past this point fail typed
        return (time.perf_counter() - t0) * 1e3

    def _finish_flip(self, mig):
        """The flip body — idempotent end to end (delete-then-write
        into dst, delete-by-id from src, reference-swap the topology)
        so ``resume()`` can re-run it after a crash at any point."""
        coord = self._coord
        src = coord._groups[mig.src_idx]
        dst = coord._groups[mig.dst_idx]
        from ..wal.snapshot import iter_store_states
        moved: dict[str, list] = {}
        rows = 0
        self._fault("flip.copy")
        for sft, batch, vis in list(iter_store_states(mig.pending)):
            if batch is None or not batch.n:
                continue
            ids = list(batch.ids)
            dst.delete(sft.type_name, ids)
            ret = dst.write(sft.type_name, batch,
                            visibilities=None if vis is None
                            else list(vis))
            coord._bump_lsn(mig.dst_name, dst, ret)
            moved[sft.type_name] = ids
            rows += int(batch.n)
            self._fault("flip.copied")
        mig.moved_ids = moved
        self._fault("flip.delete_src")
        for tn, ids in moved.items():
            ret = src.delete(tn, ids)
            coord._bump_lsn(mig.src_name, src, ret)
        self._fault("flip.swap")
        coord._part = mig.probe
        coord._prune_cache.clear()
        coord._migration = None
        with mig.lock:
            mig.phase = "done"
        mig.rows_moved = rows
        with self._lock:
            self._active = None
            self._last_done = time.monotonic()

    def _record(self, mig, flip_ms: float) -> dict:
        coord = self._coord
        entry = {"epoch": coord._part.epoch,
                 "op": "migrate", "reason": mig.reason,
                 "src": mig.src_name, "dst": mig.dst_name,
                 "prefix_lo": mig.prefix_lo, "prefix_hi": mig.prefix_hi,
                 "rows_moved": mig.rows_moved,
                 "barrier_lsn": mig.barrier_lsn,
                 "flip_ms": round(flip_ms, 3),
                 "ts_ms": int(time.time() * 1000)}
        self.history.append(entry)
        self._registry.counter("cluster.reshard.migrations")
        self._registry.counter("cluster.reshard.rows.moved",
                               mig.rows_moved)
        self._registry.gauge("cluster.reshard.flip.ms", flip_ms)
        self._registry.gauge("cluster.topology.epoch", coord._part.epoch)
        return entry

    # -- placement helpers -------------------------------------------------

    def _pick_split_point(self, src_idx: int, lo: int, hi: int) -> int:
        """Key-density split point: histogram the source group's rows
        over its owned prefixes and take the weighted median — half
        the keys (not half the keyspace) on each side. Midpoint when
        the group is empty or unreadable."""
        from ..index.splitter import pick_split_prefix, prefix_histogram
        coord = self._coord
        group = coord._groups[src_idx]
        total = None
        for tn in coord.get_type_names():
            try:
                h = prefix_histogram(group, tn, lo, hi)
            except Exception:  # noqa: BLE001 — placement is advisory
                continue
            total = h if total is None else total + h
        return pick_split_prefix(total, lo, hi)

    def _pick_dst(self, src_idx: int) -> int:
        """Least-loaded destination: lowest observed leg p99 (a group
        with no samples is idle — best of all)."""
        coord = self._coord
        best, best_p99 = None, None
        for i, name in enumerate(coord._names):
            if i == src_idx:
                continue
            p99 = coord._breakers.latency_p99_s(name) or 0.0
            if best is None or p99 < best_p99:
                best, best_p99 = i, p99
        if best is None:
            raise ReshardError("no destination group available")
        return best
