"""Partition-tolerant cluster serving: Z-sharded scatter-gather.

``ClusterDataStore`` fronts N shard groups that own disjoint z-prefix
ranges of the keyspace (the tablet-split shape of the reference, one
level up): writes route to the owning group, reads scatter to all
groups under per-leg deadlines/hedges/breakers and merge exactly.
``cluster://h1:p1,h2:p2`` opens the federation form over web servers.
"""

from .autoscale import RESHARD_AUTO, Autoscaler
from .coordinator import (CLUSTER_ALLOW_PARTIAL, CLUSTER_HEDGE_MS,
                          CLUSTER_LEG_DEADLINE_S, ClusterDataStore,
                          ClusterQueryResult, PartialCount,
                          ShardUnavailableError)
from .partition import PREFIX_BITS, ZPrefixPartitioner
from .reshard import (RESHARD_ENABLED, Resharder, ReshardError,
                      StaleTopologyError)

__all__ = ["ClusterDataStore", "ClusterQueryResult",
           "ShardUnavailableError", "PartialCount", "ZPrefixPartitioner",
           "PREFIX_BITS", "CLUSTER_LEG_DEADLINE_S", "CLUSTER_HEDGE_MS",
           "CLUSTER_ALLOW_PARTIAL", "Resharder", "ReshardError",
           "StaleTopologyError", "Autoscaler", "RESHARD_ENABLED",
           "RESHARD_AUTO"]
