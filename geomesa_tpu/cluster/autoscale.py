"""SLO-driven autoscaler: close the loop from hot-shard signal to
online split.

The health plane (PR 15) detects an error-budget burn; the breaker
board already tracks per-group leg latency EWMAs. This module
subscribes to both and turns a *sustained* hot-shard signal into a
``Resharder.split`` of the hot group's range — the reference's
"operator watches the dashboard and splits the tablet" loop with the
operator removed.

Decision rule per tick (``run_once``):

- a group is HOT when its leg p99 is at least
  ``geomesa.reshard.hot.factor`` x the median of the other groups'
  p99s (relative, so a uniformly slow cluster never splits — a split
  cannot help symmetric load);
- the signal must SUSTAIN for ``geomesa.reshard.hot.sustain.s``
  before acting (a single slow scatter is noise) — unless the SLO
  engine's fast burn is already firing, in which case the budget is
  actively draining and the sustain window is waived;
- execution is guarded: ``geomesa.reshard.enabled`` (kill switch),
  ``geomesa.reshard.auto`` (default FALSE — the loop only *proposes*
  until an operator opts in), the resharder's cooldown
  (``geomesa.reshard.cooldown.s``) and in-flight limit.

Every tick returns (and stores) a decision dict, so tests and the
bench drive the loop with an injected clock and assert on what it
decided, not on wall time.
"""

from __future__ import annotations

import threading
import time

from ..metrics import metrics
from ..utils.properties import SystemProperty
from .reshard import RESHARD_ENABLED, ReshardError

__all__ = ["Autoscaler", "RESHARD_AUTO", "RESHARD_HOT_FACTOR",
           "RESHARD_HOT_SUSTAIN_S", "RESHARD_INTERVAL_S"]

# act on decisions (default: observe + propose only)
RESHARD_AUTO = SystemProperty("geomesa.reshard.auto", "false")
# hot threshold: group p99 >= factor x median(other groups' p99)
RESHARD_HOT_FACTOR = SystemProperty("geomesa.reshard.hot.factor", "3.0")
# how long the hot signal must persist before acting
RESHARD_HOT_SUSTAIN_S = SystemProperty("geomesa.reshard.hot.sustain.s",
                                       "10")
# background loop tick interval
RESHARD_INTERVAL_S = SystemProperty("geomesa.reshard.interval.s", "5")
# absolute p99 floor below which a group is never "hot" (relative
# skew between two sub-millisecond groups is noise, not load)
RESHARD_HOT_MIN_MS = SystemProperty("geomesa.reshard.hot.min.ms", "5")


class Autoscaler:
    """Watches one cluster's per-group latency plane (+ the SLO burn
    engine) and proposes/executes splits through its ``Resharder``.
    ``clock`` is injectable; tests drive ``run_once(now=...)``."""

    def __init__(self, coord, resharder=None, slo=None,
                 clock=time.monotonic, registry=metrics):
        self._coord = coord
        self._resharder = resharder
        self._slo = slo
        self._clock = clock
        self._registry = registry
        self._hot_since: dict[str, float] = {}
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.last_decision: dict | None = None

    def _get_resharder(self):
        if self._resharder is None:
            self._resharder = self._coord.resharder
        return self._resharder

    def _slo_fast_burning(self, now: float) -> bool:
        """True when any route's fast burn is firing — the error
        budget is draining NOW, so the sustain window is waived."""
        engine = self._slo
        if engine is None:
            try:
                from ..obs.slo import slo_engine
                engine = slo_engine
            except Exception:  # noqa: BLE001 — advisory signal
                return False
        try:
            routes = engine.evaluate()
        except Exception:  # noqa: BLE001 — advisory signal
            return False
        return any(st.get("fast_firing") for st in (routes or {}).values())

    # -- the control loop --------------------------------------------------

    def observe(self) -> dict[str, float | None]:
        """Per-group leg p99 seconds (None before any observation)."""
        b = self._coord._breakers
        return {name: b.latency_p99_s(name) for name in self._coord._names}

    def run_once(self, now: float | None = None) -> dict:
        """One control tick: observe, detect, guard, (maybe) act.
        Returns the decision record."""
        now = self._clock() if now is None else float(now)
        decision: dict = {"ts": now, "action": "none", "executed": False}
        if not RESHARD_ENABLED.as_bool():
            decision["blocked"] = "geomesa.reshard.enabled=false"
            self.last_decision = decision
            return decision
        lat = self.observe()
        decision["p99_s"] = {k: (round(v, 6) if v is not None else None)
                             for k, v in lat.items()}
        hot = self._detect_hot(lat, now)
        if hot is None:
            self.last_decision = decision
            return decision
        name, p99, sustained_s = hot
        burning = self._slo_fast_burning(now)
        sustain_need = RESHARD_HOT_SUSTAIN_S.as_float() or 0.0
        decision.update({"action": "split", "group": name,
                         "hot_p99_s": round(p99, 6),
                         "sustained_s": round(sustained_s, 3),
                         "slo_fast_burning": burning})
        if sustained_s < sustain_need and not burning:
            decision["blocked"] = (f"sustain {sustained_s:.1f}s < "
                                   f"{sustain_need:g}s")
            self.last_decision = decision
            return decision
        self._registry.counter("cluster.reshard.auto.proposed")
        if not RESHARD_AUTO.as_bool():
            decision["blocked"] = "geomesa.reshard.auto=false (propose-only)"
            self.last_decision = decision
            return decision
        try:
            entry = self._get_resharder().split(name, reason="auto")
        except ReshardError as e:
            decision["blocked"] = str(e)
        else:
            decision["executed"] = True
            decision["result"] = entry
            self._hot_since.pop(name, None)
            self._registry.counter("cluster.reshard.auto.fired")
        self.last_decision = decision
        return decision

    def _detect_hot(self, lat: dict, now: float):
        """The hottest sustained group, or None. Tracks first-seen
        timestamps per group so sustain survives across ticks."""
        import statistics
        sampled = {k: v for k, v in lat.items() if v is not None}
        floor_s = (RESHARD_HOT_MIN_MS.as_float() or 0.0) / 1e3
        factor = RESHARD_HOT_FACTOR.as_float() or 3.0
        hot_name, hot_p99 = None, 0.0
        if len(sampled) >= 2:
            for name, p99 in sampled.items():
                others = [v for k, v in sampled.items() if k != name]
                med = statistics.median(others)
                if (p99 >= floor_s and med >= 0.0
                        and p99 >= factor * max(med, 1e-9)
                        and p99 > hot_p99):
                    hot_name, hot_p99 = name, p99
        # sustain bookkeeping: groups that cooled off reset
        for name in list(self._hot_since):
            if name != hot_name:
                del self._hot_since[name]
        if hot_name is None:
            return None
        since = self._hot_since.setdefault(hot_name, now)
        return hot_name, hot_p99, now - since

    # -- background loop ---------------------------------------------------

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.run_once()
                except Exception:  # noqa: BLE001 — the loop survives
                    self._registry.counter("cluster.reshard.auto.errors")
                self._stop.wait(RESHARD_INTERVAL_S.as_float() or 5.0)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="cluster-autoscaler")
        self._thread.start()

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(5.0)
        self._thread = None

    def status(self) -> dict:
        return {"enabled": bool(RESHARD_ENABLED.as_bool()),
                "auto": bool(RESHARD_AUTO.as_bool()),
                "running": self._thread is not None
                and self._thread.is_alive(),
                "hot_factor": RESHARD_HOT_FACTOR.as_float(),
                "hot_sustain_s": RESHARD_HOT_SUSTAIN_S.as_float(),
                "p99_s": self.observe(),
                "last_decision": self.last_decision}
