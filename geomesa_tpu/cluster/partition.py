"""Z-prefix range partitioning: which shard group owns a feature.

The reference scales by splitting the z-ordered keyspace into tablets
and assigning tablet ranges to region servers (PAPER.md L4 splitter;
``GeoMesaFeatureIndex.getSplits`` precomputes the split points from the
curve). The cluster tier does the same thing one level up: the 62-bit
z2 keyspace is range-partitioned by its top ``PREFIX_BITS`` bits into
a sorted list of *segments* — half-open prefix ranges, each owned by
one group — and a feature belongs to the group whose segment covers
its z-prefix.

Properties the coordinator relies on:

- **deterministic**: ownership is a pure function of (geometry,
  topology) — any client holding the same epoch computes the same
  routing with no metadata service.
- **disjoint + covering**: every prefix has exactly one owner, so
  scatter-gather merges are exact set unions (no dedup pass).
- **range-shaped**: a group's ownership is a short list of contiguous
  z ranges, so a down group's *missing data* is describable to callers
  as explicit z-ranges (the partial-results contract) and shard
  split/migration is a range handoff.
- **versioned**: the boundary list is stamped with an ``epoch``;
  instances are immutable, and a reshard builds the successor topology
  with ``with_move`` (epoch + 1) so the coordinator's flip is a single
  reference swap and a plan or result can name the topology it was
  computed under.

The default topology (epoch 0) is the uniform ceil-div split — group
``g`` owns ``[ceil(g*P/n), ceil((g+1)*P/n))`` — which routes
bit-identically to the pre-reshard partitioner, so the
``geomesa.reshard.enabled=false`` kill switch restores old behavior
exactly.

Features without a usable geometry (no geom field, or a null geometry,
which normalizes to bin 0 deterministically) route by a stable hash of
the feature id — NOT ``hash()``, which is per-process salted. Id-hash
routing depends only on ``n_groups`` (fixed across resharding), so
geometry-less rows never move in a boundary flip.
"""

from __future__ import annotations

import zlib

import numpy as np

from ..curves import zorder
from ..curves.sfc import Z2SFC

__all__ = ["ZPrefixPartitioner", "PREFIX_BITS"]

# top bits of the z2 key that drive ownership: 16 bits = 65536 split
# points, plenty of resolution for any realistic group count while
# keeping range descriptions human-readable
PREFIX_BITS = 16

_Z2_BITS = 2 * zorder.Z2_BITS          # 62-bit z2 keys
_SHIFT = np.uint64(_Z2_BITS - PREFIX_BITS)
_N_PREFIXES = 1 << PREFIX_BITS


def _uniform_segments(n_groups: int) -> tuple[list[int], list[int]]:
    """The epoch-0 ceil-div boundary list: group ``g`` starts at
    ``ceil(g*P/n)`` (zero-width groups dropped — only possible when
    ``n_groups`` exceeds the prefix space)."""
    starts, owners = [], []
    for g in range(n_groups):
        lo = -(-g * _N_PREFIXES // n_groups)          # ceil div
        hi = -(-(g + 1) * _N_PREFIXES // n_groups)
        if hi > lo:
            starts.append(lo)
            owners.append(g)
    return starts, owners


class ZPrefixPartitioner:
    """Range-partition the z2 prefix space across ``n_groups``.

    ``ZPrefixPartitioner(n)`` builds the uniform epoch-0 topology;
    ``with_move`` derives a successor with an arbitrary prefix range
    reassigned (epoch + 1). Instances are immutable — the coordinator
    flips topology by swapping the partitioner reference.
    """

    def __init__(self, n_groups: int, starts=None, owners=None,
                 epoch: int = 0):
        if n_groups < 1:
            raise ValueError("n_groups must be >= 1")
        self.n_groups = int(n_groups)
        self._sfc = Z2SFC()
        if starts is None:
            starts, owners = _uniform_segments(self.n_groups)
        s = np.asarray(list(starts), dtype=np.int64)
        o = np.asarray(list(owners), dtype=np.int64)
        if len(s) != len(o) or not len(s):
            raise ValueError("starts/owners must be same nonzero length")
        if s[0] != 0:
            raise ValueError("first segment must start at prefix 0")
        if len(s) > 1 and not bool(np.all(np.diff(s) > 0)):
            raise ValueError("segment starts must strictly increase")
        if bool(np.any((o < 0) | (o >= self.n_groups))):
            raise ValueError("segment owner out of range")
        if s[-1] >= _N_PREFIXES:
            raise ValueError("segment start beyond prefix space")
        self._starts = s
        self._owners = o
        self.epoch = int(epoch)
        u_starts, u_owners = _uniform_segments(self.n_groups)
        self._uniform = (len(s) == len(u_starts)
                         and bool(np.array_equal(s, u_starts))
                         and bool(np.array_equal(o, u_owners)))

    # -- ownership ---------------------------------------------------------

    def _owners_of_prefixes(self, prefix: np.ndarray) -> np.ndarray:
        if self._uniform:
            # the closed form IS the ceil-div segment lookup
            # (floor(p*n/P) == g iff p in [ceil(gP/n), ceil((g+1)P/n)))
            return (prefix * self.n_groups) >> PREFIX_BITS
        idx = np.searchsorted(self._starts, prefix, side="right") - 1
        return self._owners[idx]

    def owner_of(self, prefix: int) -> int:
        """Owning group of one z prefix."""
        if not 0 <= prefix < _N_PREFIXES:
            raise ValueError(f"prefix {prefix} out of range")
        return int(self._owners_of_prefixes(
            np.asarray([prefix], dtype=np.int64))[0])

    def owners_xy(self, x, y) -> np.ndarray:
        """Owning group index per coordinate pair (vectorized)."""
        z = np.asarray(self._sfc.index(x, y, lenient=True)).astype(np.uint64)
        prefix = (z >> _SHIFT).astype(np.int64)
        return self._owners_of_prefixes(prefix)

    def owners_ids(self, ids) -> np.ndarray:
        """Stable id-hash routing for features without a geometry
        (crc32, not the per-process-salted ``hash()``). Depends only on
        ``n_groups``, never on the boundary list — geometry-less rows
        stay put across reshards."""
        return np.fromiter(
            (zlib.crc32(str(i).encode()) % self.n_groups for i in ids),
            dtype=np.int64, count=len(ids))

    def owners_for_batch(self, sft, batch) -> np.ndarray:
        """Owning group per row of a feature batch: point geometries by
        their coordinates, extent geometries by their bbox centroid,
        geometry-less schemas by id hash."""
        geom = sft.geom_field
        if geom is None:
            return self.owners_ids(batch.ids)
        col = batch.col(geom)
        if hasattr(col, "x"):                      # PointColumn
            return self.owners_xy(np.asarray(col.x, np.float64),
                                  np.asarray(col.y, np.float64))
        bounds = np.asarray(col.bounds, np.float64)  # GeometryColumn
        cx = (bounds[:, 0] + bounds[:, 2]) * 0.5
        cy = (bounds[:, 1] + bounds[:, 3]) * 0.5
        bad = ~np.isfinite(cx) | ~np.isfinite(cy)
        owners = self.owners_xy(np.where(bad, 0.0, cx),
                                np.where(bad, 0.0, cy))
        if bad.any():                               # null geometries
            owners[bad] = self.owners_ids(batch.ids[bad])
        return owners

    # -- topology ----------------------------------------------------------

    def segments(self) -> list[dict]:
        """The full boundary list, in prefix order: one entry per
        contiguous owned range."""
        out = []
        for i in range(len(self._starts)):
            lo = int(self._starts[i])
            hi = (int(self._starts[i + 1]) if i + 1 < len(self._starts)
                  else _N_PREFIXES)
            out.append({"group": int(self._owners[i]),
                        "prefix_lo": lo, "prefix_hi": hi,
                        "z_lo": lo << (_Z2_BITS - PREFIX_BITS),
                        "z_hi": hi << (_Z2_BITS - PREFIX_BITS)})
        return out

    def owned_prefix_ranges(self, group: int) -> list[tuple[int, int]]:
        """Every ``[lo, hi)`` prefix range ``group`` owns (possibly
        empty after its whole range migrated away, possibly several
        after fragmented moves)."""
        if not 0 <= group < self.n_groups:
            raise ValueError(f"group {group} out of range")
        return [(s["prefix_lo"], s["prefix_hi"])
                for s in self.segments() if s["group"] == group]

    def with_move(self, prefix_lo: int, prefix_hi: int,
                  dst_group: int) -> "ZPrefixPartitioner":
        """The successor topology (epoch + 1) with prefixes
        ``[prefix_lo, prefix_hi)`` reassigned to ``dst_group`` —
        adjacent same-owner segments coalesced."""
        if not 0 <= prefix_lo < prefix_hi <= _N_PREFIXES:
            raise ValueError(f"bad prefix range [{prefix_lo}, "
                             f"{prefix_hi})")
        if not 0 <= dst_group < self.n_groups:
            raise ValueError(f"dst group {dst_group} out of range")
        pts = sorted({int(p) for p in self._starts}
                     | {int(prefix_lo), int(prefix_hi)})
        starts, owners = [], []
        for p in pts:
            if p >= _N_PREFIXES:
                continue
            o = (int(dst_group) if prefix_lo <= p < prefix_hi
                 else self.owner_of(p))
            if owners and owners[-1] == o:
                continue                            # coalesce
            starts.append(p)
            owners.append(o)
        return ZPrefixPartitioner(self.n_groups, starts=starts,
                                  owners=owners, epoch=self.epoch + 1)

    # -- range descriptions ------------------------------------------------

    def prefix_range(self, group: int) -> tuple[int, int]:
        """The half-open prefix range ``[lo, hi)`` covering everything
        ``group`` owns — exact when the ownership is one contiguous
        segment (always true at epoch 0), the convex hull when a
        reshard fragmented it, ``(0, 0)`` when the group owns
        nothing."""
        ranges = self.owned_prefix_ranges(group)
        if not ranges:
            return 0, 0
        return ranges[0][0], ranges[-1][1]

    def z_range(self, group: int) -> dict:
        """Human/JSON-facing description of a group's owned z range —
        what a partial result reports as *missing* when the group is
        unreachable. ``prefix_lo``/``prefix_hi`` are the hull (see
        ``prefix_range``); ``ranges`` lists each owned segment exactly
        when the ownership is fragmented."""
        lo, hi = self.prefix_range(group)
        out = {"group": group,
               "prefix_lo": lo, "prefix_hi": hi,
               "z_lo": lo << (_Z2_BITS - PREFIX_BITS),
               "z_hi": hi << (_Z2_BITS - PREFIX_BITS)}
        ranges = self.owned_prefix_ranges(group)
        if len(ranges) != 1:
            out["ranges"] = [
                {"z_lo": a << (_Z2_BITS - PREFIX_BITS),
                 "z_hi": b << (_Z2_BITS - PREFIX_BITS)}
                for a, b in ranges]
        return out

    def describe(self) -> list[dict]:
        return [self.z_range(g) for g in range(self.n_groups)]

    # -- leg pruning ---------------------------------------------------------

    def covering_ranges(self, boxes) -> np.ndarray:
        """Inclusive ``[z_lo, z_hi]`` z2 ranges covering the bbox union
        at prefix granularity (``precision=PREFIX_BITS`` stops the
        covering BFS exactly at the ownership cell size — finer ranges
        cannot change which groups intersect). Boxes clamp to world
        bounds first: the normalizers treat out-of-range lows as caller
        error, and an over-wide query box must still cover."""
        clamped = []
        for (xmin, ymin, xmax, ymax) in boxes:
            clamped.append((max(float(xmin), -180.0),
                            max(float(ymin), -90.0),
                            min(float(xmax), 180.0),
                            min(float(ymax), 90.0)))
        return self._sfc.ranges(clamped, precision=PREFIX_BITS)

    def groups_for_ranges(self, ranges) -> list[int]:
        """Group indices whose owned segments can intersect any of the
        inclusive covering ranges — the legs a scatter must contact;
        every other group provably holds no matching rows (point
        schemas route by the same curve the ranges cover). Intersection
        is per-segment, never against the hull, so a fragmented group
        prunes exactly."""
        r = np.asarray(ranges, dtype=np.int64).reshape(-1, 2)
        out: set[int] = set()
        if not len(r):
            return []
        for seg in self.segments():
            if seg["group"] in out:
                continue
            if bool(np.any((r[:, 0] < seg["z_hi"])
                           & (r[:, 1] >= seg["z_lo"]))):
                out.add(seg["group"])
        return sorted(out)
