"""Z-prefix range partitioning: which shard group owns a feature.

The reference scales by splitting the z-ordered keyspace into tablets
and assigning tablet ranges to region servers (PAPER.md L4 splitter;
``GeoMesaFeatureIndex.getSplits`` precomputes the split points from the
curve). The cluster tier does the same thing one level up: the 62-bit
z2 keyspace is range-partitioned by its top ``PREFIX_BITS`` bits into
``n_groups`` contiguous prefix ranges, and a feature belongs to the
group whose range covers its z-prefix.

Properties the coordinator relies on:

- **deterministic**: ownership is a pure function of (geometry,
  n_groups) — any client computes the same routing with no metadata
  service.
- **disjoint + covering**: every prefix has exactly one owner, so
  scatter-gather merges are exact set unions (no dedup pass).
- **range-shaped**: a group's ownership is one contiguous z range, so
  a down group's *missing data* is describable to callers as explicit
  z-ranges (the partial-results contract) and, later, shard
  split/migration is a range handoff.

Features without a usable geometry (no geom field, or a null geometry,
which normalizes to bin 0 deterministically) route by a stable hash of
the feature id — NOT ``hash()``, which is per-process salted.
"""

from __future__ import annotations

import zlib

import numpy as np

from ..curves import zorder
from ..curves.sfc import Z2SFC

__all__ = ["ZPrefixPartitioner", "PREFIX_BITS"]

# top bits of the z2 key that drive ownership: 16 bits = 65536 split
# points, plenty of resolution for any realistic group count while
# keeping range descriptions human-readable
PREFIX_BITS = 16

_Z2_BITS = 2 * zorder.Z2_BITS          # 62-bit z2 keys
_SHIFT = np.uint64(_Z2_BITS - PREFIX_BITS)
_N_PREFIXES = 1 << PREFIX_BITS


class ZPrefixPartitioner:
    """Range-partition the z2 prefix space across ``n_groups``.

    Group ``g`` owns prefixes ``[ceil(g*P/n), ceil((g+1)*P/n))`` where
    ``P = 2**PREFIX_BITS`` — the proportional range split, so group
    sizes differ by at most one prefix.
    """

    def __init__(self, n_groups: int):
        if n_groups < 1:
            raise ValueError("n_groups must be >= 1")
        self.n_groups = int(n_groups)
        self._sfc = Z2SFC()

    # -- ownership ---------------------------------------------------------

    def owners_xy(self, x, y) -> np.ndarray:
        """Owning group index per coordinate pair (vectorized)."""
        z = np.asarray(self._sfc.index(x, y, lenient=True)).astype(np.uint64)
        prefix = (z >> _SHIFT).astype(np.int64)
        return (prefix * self.n_groups) >> PREFIX_BITS

    def owners_ids(self, ids) -> np.ndarray:
        """Stable id-hash routing for features without a geometry
        (crc32, not the per-process-salted ``hash()``)."""
        return np.fromiter(
            (zlib.crc32(str(i).encode()) % self.n_groups for i in ids),
            dtype=np.int64, count=len(ids))

    def owners_for_batch(self, sft, batch) -> np.ndarray:
        """Owning group per row of a feature batch: point geometries by
        their coordinates, extent geometries by their bbox centroid,
        geometry-less schemas by id hash."""
        geom = sft.geom_field
        if geom is None:
            return self.owners_ids(batch.ids)
        col = batch.col(geom)
        if hasattr(col, "x"):                      # PointColumn
            return self.owners_xy(np.asarray(col.x, np.float64),
                                  np.asarray(col.y, np.float64))
        bounds = np.asarray(col.bounds, np.float64)  # GeometryColumn
        cx = (bounds[:, 0] + bounds[:, 2]) * 0.5
        cy = (bounds[:, 1] + bounds[:, 3]) * 0.5
        bad = ~np.isfinite(cx) | ~np.isfinite(cy)
        owners = self.owners_xy(np.where(bad, 0.0, cx),
                                np.where(bad, 0.0, cy))
        if bad.any():                               # null geometries
            owners[bad] = self.owners_ids(batch.ids[bad])
        return owners

    # -- range descriptions ------------------------------------------------

    def prefix_range(self, group: int) -> tuple[int, int]:
        """The half-open prefix range ``[lo, hi)`` group ``group`` owns."""
        if not 0 <= group < self.n_groups:
            raise ValueError(f"group {group} out of range")
        lo = -(-group * _N_PREFIXES // self.n_groups)        # ceil div
        hi = -(-(group + 1) * _N_PREFIXES // self.n_groups)
        return lo, hi

    def z_range(self, group: int) -> dict:
        """Human/JSON-facing description of a group's owned z range —
        what a partial result reports as *missing* when the group is
        unreachable."""
        lo, hi = self.prefix_range(group)
        return {"group": group,
                "prefix_lo": lo, "prefix_hi": hi,
                "z_lo": lo << (_Z2_BITS - PREFIX_BITS),
                "z_hi": hi << (_Z2_BITS - PREFIX_BITS)}

    def describe(self) -> list[dict]:
        return [self.z_range(g) for g in range(self.n_groups)]

    # -- leg pruning ---------------------------------------------------------

    def covering_ranges(self, boxes) -> np.ndarray:
        """Inclusive ``[z_lo, z_hi]`` z2 ranges covering the bbox union
        at prefix granularity (``precision=PREFIX_BITS`` stops the
        covering BFS exactly at the ownership cell size — finer ranges
        cannot change which groups intersect). Boxes clamp to world
        bounds first: the normalizers treat out-of-range lows as caller
        error, and an over-wide query box must still cover."""
        clamped = []
        for (xmin, ymin, xmax, ymax) in boxes:
            clamped.append((max(float(xmin), -180.0),
                            max(float(ymin), -90.0),
                            min(float(xmax), 180.0),
                            min(float(ymax), 90.0)))
        return self._sfc.ranges(clamped, precision=PREFIX_BITS)

    def groups_for_ranges(self, ranges) -> list[int]:
        """Group indices whose owned ``[z_lo, z_hi)`` can intersect any
        of the inclusive covering ranges — the legs a scatter must
        contact; every other group provably holds no matching rows
        (point schemas route by the same curve the ranges cover)."""
        r = np.asarray(ranges, dtype=np.int64).reshape(-1, 2)
        out = []
        for g in range(self.n_groups):
            zr = self.z_range(g)
            if len(r) and bool(np.any((r[:, 0] < zr["z_hi"])
                                      & (r[:, 1] >= zr["z_lo"]))):
                out.append(g)
        return out
