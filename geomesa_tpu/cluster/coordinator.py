"""ClusterDataStore: Z-sharded scatter-gather over shard groups.

The reference scales horizontally by splitting z-ordered tables into
tablets and fanning queries across region servers in the coprocessor
scatter-gather shape (GeoMesaCoprocessor.scala:105-123): each server
computes a partial (ids, counts, bin chunks, stat sketches, arrow
batches) over its tablet ranges and the client merges. This module is
that shape one level up the stack: N *shard groups* — each typically a
primary + WAL-shipped replicas behind ``ReplicatedDataStore`` — own
disjoint z-prefix ranges (partition.py), writes route to the owning
group, and reads scatter to every group and merge exactly (the
partition is disjoint, so unions/sums/sketch-merges are exact, never
deduped or estimated).

Failure semantics are the point (a cluster that hangs or silently
drops a shard's rows is worse than a single store):

- every scatter leg runs under ``geomesa.cluster.leg.deadline.s`` with
  a hedged second attempt through the shared ``HedgePolicy``
  (resilience/hedge.py): after the group's observed p99-ish latency
  once the EWMA has samples, else the static
  ``geomesa.cluster.hedge.ms`` (for a
  replicated group the hedge naturally lands on a different replica —
  the router round-robins), and a per-group breaker
  (resilience/breaker.py) fast-fails legs into a known-dead group;
- a group losing its primary auto-promotes internally (PR 4 probe +
  most-caught-up election, zero acked-write loss) — the cluster keeps
  routing to the group object, which now fronts the promoted replica;
- cross-shard read-your-writes: every acked write bumps a per-group
  **LSN vector** (returned from ``write``/``delete`` and surfaced in
  ``cluster_status``); scatter legs against replicated groups carry a
  min-LSN gate — the staleness bound tightens to ``primary_estimate -
  acked_lsn`` so no replica that has not applied this client's writes
  can serve the leg (the PR 4 bounded-staleness contract, pointed at
  consistency instead of freshness);
- when a whole group stays down past its deadline the query fails
  **typed** (``ShardUnavailableError`` naming the group and its owned
  z-ranges) — or, behind ``geomesa.cluster.allow.partial``, returns a
  result flagged ``complete=False`` with the missing z-ranges attached
  (``missing_z_ranges``). Silent wrong answers are structurally
  impossible: a merge only runs over legs that succeeded, and any
  missing leg either raises or flags.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time

import numpy as np

from ..features.batch import FeatureBatch
from ..features.sft import parse_spec
from ..index.api import Explainer, FilterStrategy, Query
from ..metrics import metrics
from ..resilience.breaker import BreakerBoard, CircuitOpenError
from ..resilience.hedge import HedgePolicy
from ..resilience.policy import RetryBudget
from ..store.api import DataStore
from ..store.memory import QueryResult
from ..utils.properties import SystemProperty
from .partition import PREFIX_BITS, ZPrefixPartitioner
from .reshard import _OpGate, ReshardError, StaleTopologyError

__all__ = ["ClusterDataStore", "ClusterQueryResult",
           "ShardUnavailableError", "PartialCount",
           "ReshardError", "StaleTopologyError",
           "CLUSTER_LEG_DEADLINE_S", "CLUSTER_HEDGE_MS",
           "CLUSTER_ALLOW_PARTIAL", "CLUSTER_PRUNE"]

# per-scatter-leg deadline: a group that cannot answer inside this is
# treated as down for THIS query (typed failure or flagged partial)
CLUSTER_LEG_DEADLINE_S = SystemProperty("geomesa.cluster.leg.deadline.s",
                                        "5")
# tail-latency hedge: when a leg's first attempt has not answered
# after this long, a second attempt launches against the same group
# (a replicated group round-robins it to a different replica)
CLUSTER_HEDGE_MS = SystemProperty("geomesa.cluster.hedge.ms", "75")
# partial-results mode: False (default) -> a down group fails the
# query typed; True -> merge the live legs and flag the result
# complete=False with the missing z-ranges
CLUSTER_ALLOW_PARTIAL = SystemProperty("geomesa.cluster.allow.partial",
                                       "false")
# Z-range leg pruning kill switch: "false" scatters every read to
# every group (today's pre-planner behavior, bit-identical)
CLUSTER_PRUNE = SystemProperty("geomesa.cluster.prune", "true")


def _gated(fn):
    """Run a cluster read under the shared side of the op gate (see
    ``ClusterDataStore._op``): concurrent with other ops, drained by
    the reshard flip's exclusive section, typed-failed while a crashed
    flip leaves the topology inconsistent."""
    def wrapper(self, *args, **kwargs):
        with self._op():
            return fn(self, *args, **kwargs)
    wrapper.__name__ = fn.__name__
    wrapper.__doc__ = fn.__doc__
    return wrapper


class ShardUnavailableError(ConnectionError):
    """One or more shard groups could not serve their scatter leg
    inside the deadline. Carries which groups and which z-ranges of
    the keyspace are therefore unreadable. NOT retryable as-is: the
    breaker holds the group out until it half-opens."""

    retryable = False

    def __init__(self, groups, z_ranges, detail: str = ""):
        self.groups = list(groups)
        self.z_ranges = list(z_ranges)
        msg = (f"shard group(s) unavailable: {', '.join(self.groups)}"
               f" (missing z-ranges: "
               f"{[(r['z_lo'], r['z_hi']) for r in self.z_ranges]})")
        super().__init__(msg + (f": {detail}" if detail else ""))


class PartialCount(int):
    """An int count flagged incomplete — plain ints cannot carry the
    partial-results metadata, and a count missing a shard must never
    look like a complete one."""

    complete = False
    missing_groups: list = []
    missing_z_ranges: list = []


class _PartialGrid(np.ndarray):
    """Density grid flagged incomplete (view-cast ndarray)."""

    complete = False


class _PartialBytes(bytes):
    """bin/arrow payload flagged incomplete."""

    complete = False


class _ClusterStream:
    """Iterator of merged FeatureBatches from streamed scatter legs,
    carrying the partial-results contract. ``complete`` /
    ``missing_groups`` / ``missing_z_ranges`` are final once the
    stream is exhausted (a leg can only drop out while it runs)."""

    def __init__(self):
        self._gen = iter(())
        self.complete = True
        self.missing_groups: list[str] = []
        self.missing_z_ranges: list[dict] = []
        self._on_close = None

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._gen)

    def close(self):
        # closing a never-started generator skips its finally, so the
        # op-gate release hooks here too (idempotent)
        self._gen.close()
        if self._on_close is not None:
            self._on_close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — finalizer
            pass


class ClusterQueryResult(QueryResult):
    """QueryResult plus the cluster contract: ``complete`` /
    ``missing_groups`` / ``missing_z_ranges`` (partial-results mode)
    and ``lsn_vector`` (the per-group acked-LSN snapshot this result
    is consistent with)."""

    def __init__(self, ids, batch, explain, plan, n=None):
        super().__init__(ids, batch, explain, plan, n=n)
        self.complete = True
        self.missing_groups: list[str] = []
        self.missing_z_ranges: list[dict] = []
        self.lsn_vector: dict[str, int] = {}


class ClusterDataStore(DataStore):
    """One DataStore façade over N z-partitioned shard groups.

    ``groups`` is a list of DataStores — typically
    ``ReplicatedDataStore`` (primary + replicas; gives the cluster
    intra-group failover and hedge-to-replica) or ``RemoteDataStore``
    (a federation of web servers; ``cluster://h1:p1,h2:p2`` builds
    this shape). ``names`` labels them for status/metrics/errors
    (default ``shard0..shardN-1``).

    Ctor overrides beat the system-property knobs; a ``None`` override
    re-reads the knob per call so tests and operators can flip
    ``geomesa.cluster.allow.partial`` on a live cluster.
    """

    def __init__(self, groups, names=None, leg_deadline_s=None,
                 hedge_ms=None, allow_partial=None, registry=metrics,
                 audit=None):
        if not groups:
            raise ValueError("at least one shard group required")
        self.audit = audit  # AuditLogger or None (global fallback)
        self._groups = list(groups)
        self._names = (list(names) if names is not None
                       else [f"shard{i}" for i in range(len(groups))])
        if len(self._names) != len(self._groups):
            raise ValueError("names/groups length mismatch")
        if len(set(self._names)) != len(self._names):
            raise ValueError("duplicate group names")
        self._part = ZPrefixPartitioner(len(self._groups))
        self._leg_deadline_override = leg_deadline_s
        self._hedge_override = hedge_ms
        self._allow_partial_override = allow_partial
        self._registry = registry
        self._breakers = BreakerBoard(registry=registry)
        # shared hedging helper (resilience/hedge.py): scatter legs
        # launch their backup attempt through it, charged to a
        # cluster-wide retry budget
        self._hedge = HedgePolicy(budget=RetryBudget(), registry=registry)
        self._lock = threading.Lock()
        self._lsn_vector: dict[str, int] = {}
        self._sfts: dict = {}
        # scatter-plan surface: per-thread latest (concurrent queries
        # must not clobber each other's plan reads) plus a global
        # latest for the admin/status view
        self._plan_tls = threading.local()
        self._last_plan: dict | None = None
        # (type, filter-text) -> prune decision: real query mixes
        # repeat filter shapes, and the covering-range derivation is
        # pure in (schema, filter, topology) — invalidated on schema
        # change (see create_schema/remove_schema) and on topology
        # epoch change (the reshard flip)
        self._prune_cache: dict[tuple[str, str], tuple] = {}
        # elastic topology: every op takes the shared side of the gate,
        # the reshard flip takes the exclusive side; _migration is the
        # in-flight range move (double-routing + staging), installed
        # and cleared by the Resharder under the exclusive gate
        self._gate = _OpGate()
        self._migration = None
        self._resharder = None
        self._autoscaler = None
        registry.gauge("cluster.groups", len(self._groups))

    # -- elastic topology --------------------------------------------------

    @property
    def resharder(self):
        """The split/migrate executor for this cluster (lazy)."""
        if self._resharder is None:
            from .reshard import Resharder
            self._resharder = Resharder(self, registry=self._registry)
        return self._resharder

    @property
    def autoscaler(self):
        """The SLO/latency-driven control loop for this cluster (lazy;
        propose-only until ``geomesa.reshard.auto`` is set)."""
        if self._autoscaler is None:
            from .autoscale import Autoscaler
            self._autoscaler = Autoscaler(self, self.resharder,
                                          registry=self._registry)
        return self._autoscaler

    @contextlib.contextmanager
    def _op(self):
        """Every read/write runs under the shared side of the op gate
        (the flip drains them via the exclusive side), and fails typed
        while a crashed flip leaves the topology inconsistent —
        exact-or-typed, never a silently duplicated merge."""
        with self._gate.shared():
            mig = self._migration
            if mig is not None and mig.blocking:
                raise ReshardError(
                    f"topology flip incomplete (migration "
                    f"{mig.src_name}->{mig.dst_name}, phase "
                    f"{mig.phase}); resume or abort the reshard")
            yield

    def _check_epoch(self, topology_epoch):
        """Zombie-write fence: a client that routed against a topology
        the cluster has flipped past must fail typed and re-route (the
        PR 8 promote-cutoff pattern pointed at topology)."""
        if topology_epoch is None:
            return
        if int(topology_epoch) != self._part.epoch:
            self._registry.counter("cluster.reshard.zombie.rejects")
            raise StaleTopologyError(
                f"write routed under topology epoch {topology_epoch} "
                f"but the cluster is at epoch {self._part.epoch}",
                epoch=int(topology_epoch), current=self._part.epoch)

    def topology(self, include_counts: bool = True) -> dict:
        """The versioned topology document (``GET /rest/topology``):
        epoch, boundary segments, per-group owned ranges + row counts
        (the key-density summary), the active migration, and the epoch
        history."""
        part = self._part
        mig = self._migration
        from .autoscale import RESHARD_AUTO
        from .reshard import RESHARD_ENABLED
        groups = []
        for i, (name, g) in enumerate(zip(self._names, self._groups)):
            ent: dict = {"name": name,
                         "ranges": [{"prefix_lo": lo, "prefix_hi": hi}
                                    for lo, hi
                                    in part.owned_prefix_ranges(i)]}
            if include_counts:
                rows: int | None = 0
                for tn in self.get_type_names():
                    try:
                        rows += int(g.count(tn))
                    except Exception:  # noqa: BLE001 — status surface
                        rows = None
                        break
                ent["rows"] = rows
            groups.append(ent)
        self._registry.gauge("cluster.topology.epoch", part.epoch)
        return {"epoch": part.epoch,
                "prefix_bits": PREFIX_BITS,
                "n_groups": len(self._groups),
                "enabled": bool(RESHARD_ENABLED.as_bool()),
                "auto": bool(RESHARD_AUTO.as_bool()),
                "segments": [dict(s, name=self._names[s["group"]])
                             for s in part.segments()],
                "groups": groups,
                "migration": mig.describe() if mig is not None else None,
                "history": list(self.resharder.history)}

    # -- knobs -------------------------------------------------------------

    def _leg_deadline_s(self) -> float:
        if self._leg_deadline_override is not None:
            return float(self._leg_deadline_override)
        return CLUSTER_LEG_DEADLINE_S.as_float() or 5.0

    def _hedge_s(self) -> float:
        if self._hedge_override is not None:
            return float(self._hedge_override) / 1e3
        return (CLUSTER_HEDGE_MS.as_float() or 75.0) / 1e3

    def _allow_partial(self) -> bool:
        if self._allow_partial_override is not None:
            return bool(self._allow_partial_override)
        return bool(CLUSTER_ALLOW_PARTIAL.as_bool())

    # -- uri ---------------------------------------------------------------

    @classmethod
    def from_uri(cls, uri: str, auth_token: str | None = None,
                 **kwargs) -> "ClusterDataStore":
        """``cluster://host1:port1,host2:port2,...`` — one
        RemoteDataStore shard group per endpoint (the two-process
        federation shape)."""
        if not uri.startswith("cluster://"):
            raise ValueError(f"not a cluster uri: {uri!r}")
        endpoints = [e.strip() for e in uri[len("cluster://"):].split(",")
                     if e.strip()]
        if not endpoints:
            raise ValueError("cluster:// uri names no endpoints")
        from ..store.remote import RemoteDataStore
        groups = []
        for ep in endpoints:
            host, _, port = ep.rpartition(":")
            if not port.isdigit():
                raise ValueError(f"bad cluster endpoint {ep!r} "
                                 "(want host:port)")
            groups.append(RemoteDataStore(host or "127.0.0.1", int(port),
                                          auth_token=auth_token))
        return cls(groups, names=endpoints, **kwargs)

    # -- scatter machinery -------------------------------------------------

    def _leg(self, name: str, fn, deadline: float, hedge_s: float,
             results: dict, failures: dict):
        """Run one scatter leg: breaker-gated, deadline-bounded, with
        one hedged backup through the shared ``HedgePolicy``
        (resilience/hedge.py). The speculative delay prefers the
        group's observed p99-ish latency over the static
        ``geomesa.cluster.hedge.ms`` once the EWMA has samples — a
        fast group hedges sooner, a slow one stops hedging on every
        call — and hedges are charged to the cluster's retry budget so
        a cluster-wide brownout can't double its own load."""
        from ..obs import tracer
        with tracer.span("scatter-leg", name) as sp:
            breaker = self._breakers.get(name)
            try:
                breaker.acquire()
            except CircuitOpenError as e:
                self._registry.counter("cluster.leg.fastfails")
                sp.annotate("breaker.fastfail")
                failures[name] = e
                return
            t0 = time.perf_counter()
            delay = self._hedge.delay_s(
                self._breakers.latency_p99_s(name))
            if delay is None:
                delay = hedge_s  # no estimate yet: the static knob
            if self._hedge.budget is not None:
                self._hedge.budget.deposit()  # first attempts earn
            try:
                from ..obs.prof import watchdog
                with watchdog.watch(f"scatter-leg.{name}", span=sp):
                    v = self._hedge.call(
                        fn, delay, deadline_s=deadline,
                        name=f"cluster.{name}",
                        on_hedge=lambda: self._registry.counter(
                            "cluster.leg.hedges"))
            except TimeoutError:
                breaker.failure()
                self._registry.counter("cluster.leg.failures")
                self._registry.counter("cluster.leg.timeouts")
                sp.annotate("leg.timeout", deadline_s=deadline)
                failures[name] = TimeoutError(
                    f"shard leg {name!r} exceeded its {deadline:g}s "
                    "deadline")
            except Exception as e:  # noqa: BLE001 — leg boundary
                breaker.failure()
                self._registry.counter("cluster.leg.failures")
                sp.annotate("leg.failed", error=type(e).__name__)
                failures[name] = e
            else:
                breaker.success()
                self._breakers.observe(name, time.perf_counter() - t0)
                results[name] = v

    def _scatter(self, make_fn, legs=None) -> tuple[dict, dict]:
        """Fan one read out to every group — or, with ``legs``, only
        the named subset the planner proved can hold matching rows (a
        Z-pruned leg is never contacted, so it can never fail and can
        never be reported missing: pruned != unavailable).
        ``make_fn(name, group)`` returns the zero-arg leg callable.
        Returns ``(results_by_name, failures_by_name)``."""
        self._registry.counter("cluster.scatter.calls")
        pairs = list(zip(self._names, self._groups))
        if legs is not None:
            want = set(legs)
            pairs = [(n, g) for n, g in pairs if n in want]
        deadline, hedge_s = self._leg_deadline_s(), self._hedge_s()
        results: dict = {}
        failures: dict = {}
        if len(pairs) == 1:
            # single-leg scatter (a fully-pruned selective read): run
            # inline — a thread buys no parallelism and its spawn/join
            # cost dominates a selective leg
            name, group = pairs[0]
            self._leg(name, make_fn(name, group), deadline, hedge_s,
                      results, failures)
            return results, failures
        threads = []
        for name, group in pairs:
            # each leg thread runs under a copy of the caller's
            # context: trace spans parent correctly and the audit
            # hook's delegation suppression reaches the inner stores
            ctx = contextvars.copy_context()
            t = threading.Thread(
                target=ctx.run,
                args=(self._leg, name, make_fn(name, group), deadline,
                      hedge_s, results, failures),
                daemon=True, name=f"cluster-scatter-{name}")
            threads.append(t)
            t.start()
        for t in threads:
            t.join(deadline + 5.0)
        return results, failures

    def _missing(self, failures: dict) -> dict | None:
        """Enforce the partial-results contract for a scatter with
        failed legs: raise typed by default, or describe what is
        missing for the caller to attach when the knob allows it."""
        if not failures:
            return None
        names = sorted(failures)
        z_ranges = [self._part.z_range(self._names.index(n))
                    for n in names]
        if not self._allow_partial():
            self._registry.counter("cluster.scatter.failed")
            raise ShardUnavailableError(
                names, z_ranges,
                detail="; ".join(f"{n}: {type(failures[n]).__name__}: "
                                 f"{failures[n]}" for n in names)
            ) from failures[names[0]]
        self._registry.counter("cluster.scatter.partial")
        return {"groups": names, "z_ranges": z_ranges}

    # -- cost-based planning: leg pruning + cardinality estimates ----------

    def prune_for(self, type_name: str, flt) -> tuple[list[str] | None,
                                                      dict | None]:
        """Z-range leg pruning: the group names whose owned z range can
        intersect the filter's covering Z2 ranges, or ``(None, info)``
        when pruning does not apply (knob off, non-point schema, no
        spatial bound — routing and filtering only provably coincide
        for point schemas, where the routed coordinate IS the filtered
        geometry). ``info`` is the plan fragment explaining the
        decision; None exactly when the knob is off, so a disabled
        cluster's plans stay bit-identical to the pre-planner ones."""
        if not CLUSTER_PRUNE.as_bool():
            return None, None
        key = (type_name, str(flt))
        hit = self._prune_cache.get(key)
        if hit is not None:
            return hit
        out = self._prune_uncached(type_name, flt)
        if len(self._prune_cache) >= 256:
            self._prune_cache.pop(next(iter(self._prune_cache)))
        self._prune_cache[key] = out
        return out

    def _prune_uncached(self, type_name: str, flt):
        try:
            from ..filters import parse_ecql
            from ..filters.helper import extract_geometries
            sft = self.get_schema(type_name)
            if sft.geom_field is None or not sft.is_points:
                return None, {"pruning": "non-point-schema"}
            if flt is None:
                return None, {"pruning": "no-spatial-bound"}
            if isinstance(flt, str):
                flt = parse_ecql(flt)
            geoms = extract_geometries(flt, sft.geom_field)
            if geoms.disjoint:
                # provably-empty spatial constraint: contact no leg
                return [], {"pruning": "empty", "covering_ranges": 0}
            if geoms.is_empty:
                return None, {"pruning": "no-spatial-bound"}
            boxes = [(g.envelope.xmin, g.envelope.ymin,
                      g.envelope.xmax, g.envelope.ymax) for g in geoms]
            ranges = self._part.covering_ranges(boxes)
            keep = self._part.groups_for_ranges(ranges)
            names = [self._names[g] for g in keep]
            return names, {"pruning": "z-range",
                           "covering_ranges": int(len(ranges))}
        except Exception as e:  # noqa: BLE001 — pruning is advisory
            return None, {"pruning": f"error: {type(e).__name__}"}

    def _account_legs(self, op: str, type_name: str, legs,
                      info: dict | None = None) -> dict:
        """Record which legs a scatter will contact vs pruned, on the
        metrics plane and the cluster-level plan surface."""
        contacted = (list(self._names) if legs is None
                     else [n for n in self._names if n in set(legs)])
        pruned = [n for n in self._names if n not in contacted]
        plan = {"op": op, "type": type_name,
                "topology_epoch": self._part.epoch,
                "contacted": contacted, "pruned": pruned}
        if info:
            plan.update(info)
        self._registry.counter("cluster.legs.contacted", len(contacted))
        if pruned:
            self._registry.counter("cluster.legs.pruned", len(pruned))
        self._plan_tls.plan = plan
        self._last_plan = plan
        return plan

    def last_plan(self) -> dict | None:
        """The most recent scatter plan (contacted/pruned legs): this
        thread's if it has issued one, else the cluster-wide latest —
        the plan surface tests and operators assert pruning against."""
        return getattr(self._plan_tls, "plan", None) or self._last_plan

    def estimate_count(self, type_name: str, flt) -> int | None:
        """Cluster-merged cardinality estimate: each shard group
        estimates its own slice (O(cells) sketch math, no scan) and
        the coordinator sums — exact composition because the z-prefix
        partition is disjoint. None as soon as any group cannot
        estimate (cold type, cleared stats, unsupported filter): the
        SQL planner then falls back to static thresholds."""
        from ..sql.planner import estimate_for_store
        total = 0
        for group in self._groups:
            est = estimate_for_store(group, type_name, flt)
            if est is None:
                return None
            total += int(est)
        return total

    def _ryw_kwargs(self, name: str, group) -> dict:
        """Cross-shard read-your-writes: translate 'this leg must see
        everything we have acked on this group' (min LSN) into the
        replication router's max-lag bound — a replica is only
        eligible when primary_estimate - applied <= bound, i.e. when
        applied >= our acked LSN."""
        from ..replication.router import ReplicatedDataStore
        if not isinstance(group, ReplicatedDataStore):
            return {}
        with self._lock:
            acked = self._lsn_vector.get(name, 0)
        if not acked:
            return {}
        bound = max(group._primary_lsn_estimate() - acked, 0)
        if group.max_lag_lsn is not None:
            bound = min(bound, group.max_lag_lsn)
        return {"max_lag_lsn": bound}

    # -- schema management -------------------------------------------------

    def create_schema(self, sft, spec=None):
        if isinstance(sft, str):
            sft = parse_spec(sft, spec or "")
        for name, group in zip(self._names, self._groups):
            ret = group.create_schema(sft)
            self._bump_lsn(name, group, ret)
        self._sfts[sft.type_name] = sft
        self._prune_cache.clear()
        mig = self._migration
        if mig is not None:
            # keep the staging store's schema view current so later
            # staged applies of the new type always land
            mig.pending.create_schema(sft)

    def get_schema(self, type_name: str):
        sft = self._sfts.get(type_name)
        if sft is not None:
            return sft
        err = None
        for group in self._groups:
            try:
                sft = group.get_schema(type_name)
            except KeyError:
                raise
            except Exception as e:  # noqa: BLE001 — try next group
                err = e
                continue
            self._sfts[type_name] = sft
            return sft
        raise err if err is not None else KeyError(type_name)

    def get_type_names(self) -> list[str]:
        err = None
        for group in self._groups:
            try:
                return group.get_type_names()
            except Exception as e:  # noqa: BLE001 — try next group
                err = e
        raise err if err is not None else RuntimeError("no groups")

    def remove_schema(self, type_name: str):
        for name, group in zip(self._names, self._groups):
            ret = group.remove_schema(type_name)
            self._bump_lsn(name, group, ret)
        self._sfts.pop(type_name, None)
        self._prune_cache.clear()
        mig = self._migration
        if mig is not None and type_name in mig.pending.get_type_names():
            mig.pending.remove_schema(type_name)

    # -- write path --------------------------------------------------------

    def _bump_lsn(self, name: str, group, returned):
        """Record the group's acked WAL position after a mutation —
        the component of the LSN vector later reads gate on."""
        lsn = None
        if isinstance(returned, (int, np.integer)):
            lsn = int(returned)
        elif isinstance(returned, dict):
            lsn = returned.get("lsn")
        if lsn is None:
            est = getattr(group, "_primary_lsn_estimate", None)
            if callable(est):
                lsn = est()
        if lsn is None:
            journal = getattr(group, "journal", None)
            if journal is not None:
                lsn = journal.wal.last_lsn
        if lsn:
            with self._lock:
                if lsn > self._lsn_vector.get(name, 0):
                    self._lsn_vector[name] = int(lsn)

    def lsn_vector(self) -> dict[str, int]:
        """Per-group acked LSNs: results consistent with this vector
        include every write this store instance has acknowledged."""
        with self._lock:
            return dict(self._lsn_vector)

    def write(self, type_name: str, batch: FeatureBatch,
              visibilities=None, topology_epoch=None, **kwargs):
        """Partition the batch by z-prefix owner and write each slice
        to its owning group. Returns the updated LSN vector. Groups
        are written in order; a failing group raises after earlier
        groups applied their slices (at-least-once on retry — the
        failed slice was never acked, so the zero-acked-loss contract
        holds). ``topology_epoch`` (optional) asserts the topology the
        caller routed against — a stale epoch fails typed before any
        slice lands."""
        with self._op():
            self._check_epoch(topology_epoch)
            sft = self.get_schema(type_name)
            owners = self._part.owners_for_batch(sft, batch)
            vis_arr = (np.asarray(visibilities, dtype=object)
                       if visibilities is not None else None)
            routed = 0
            for gi, (name, group) in enumerate(zip(self._names,
                                                   self._groups)):
                rows = np.flatnonzero(owners == gi)
                if not len(rows):
                    continue
                sub = batch if len(rows) == batch.n else batch.take(rows)
                vis = None if vis_arr is None else list(vis_arr[rows])
                ret = group.write(type_name, sub, visibilities=vis,
                                  **kwargs)
                self._bump_lsn(name, group, ret)
                routed += len(rows)
            mig = self._migration
            if mig is not None and mig.forward:
                # non-durable source: double-route the moving slice to
                # the migration's staging store (a durable source's
                # WAL tail carries it instead)
                mig.stage_write(sft, batch, visibilities=visibilities)
            self._registry.counter("cluster.writes.routed", routed)
            return self.lsn_vector()

    def write_many(self, type_name: str,
                   pairs: list[tuple[FeatureBatch, list | None]]):
        """Routed group commit: coalesce every staged batch's slices
        per owning group BEFORE writing, so a fused ingest group costs
        each group ONE ``write_many`` (one journal/fsync decision, one
        admission pass) instead of one write per caller batch."""
        pairs = [(b, v) for b, v in pairs if b is not None and b.n]
        if not pairs:
            return None
        with self._op():
            sft = self.get_schema(type_name)
            per_group: list[list] = [[] for _ in self._groups]
            routed = 0
            for batch, vis in pairs:
                owners = self._part.owners_for_batch(sft, batch)
                vis_arr = (np.asarray(vis, dtype=object)
                           if vis is not None else None)
                for gi in np.unique(owners):
                    rows = np.flatnonzero(owners == gi)
                    sub = (batch if len(rows) == batch.n
                           else batch.take(rows))
                    sv = None if vis_arr is None else list(vis_arr[rows])
                    per_group[int(gi)].append((sub, sv))
                    routed += len(rows)
            for gi, (name, group) in enumerate(zip(self._names,
                                                   self._groups)):
                if not per_group[gi]:
                    continue
                ret = group.write_many(type_name, per_group[gi])
                self._bump_lsn(name, group, ret)
            mig = self._migration
            if mig is not None and mig.forward:
                for batch, vis in pairs:
                    mig.stage_write(sft, batch, visibilities=vis)
            self._registry.counter("cluster.writes.routed", routed)
            return self.lsn_vector()

    def delete(self, type_name: str, ids):
        """Broadcast: geometry-routed rows cannot be re-owned from ids
        alone, and deleting absent ids is a no-op everywhere."""
        with self._op():
            for name, group in zip(self._names, self._groups):
                ret = group.delete(type_name, ids)
                self._bump_lsn(name, group, ret)
            mig = self._migration
            if mig is not None and mig.forward:
                mig.stage_delete(type_name, ids)
            return self.lsn_vector()

    # -- read path ---------------------------------------------------------

    def _as_query(self, q, type_name) -> Query:
        if isinstance(q, str):
            if type_name is None:
                raise ValueError("type_name required with a filter string")
            q = Query(type_name, q)
        return q

    @_gated
    def query(self, q, type_name=None, explain_out=None):
        q = self._as_query(q, type_name)

        def make_fn(name, group):
            def leg():
                res = group.query(q, **self._ryw_kwargs(name, group))
                # materialize lazy ids/batch INSIDE the leg, before
                # slower sibling legs land: a replica apply between
                # scatter and merge must not invalidate row indices
                _ = res.ids
                _ = res.batch
                return res
            return leg

        from ..audit import audit_query, delegated_scope
        t0 = time.perf_counter()
        legs, prune_info = self.prune_for(q.type_name, q.filter)
        self._account_legs("query", q.type_name, legs, prune_info)
        with delegated_scope():
            results, failures = self._scatter(make_fn, legs=legs)
        missing = self._missing(failures)
        ids_parts, batch_parts = [], []
        for name in self._names:
            res = results.get(name)
            if res is None or res.n == 0:
                continue
            ids_parts.append(np.asarray(res.ids, dtype=object))
            batch_parts.append(res.batch)
        ids = (np.concatenate(ids_parts) if ids_parts
               else np.empty(0, dtype=object))
        batch = None
        if batch_parts:
            batch = (batch_parts[0] if len(batch_parts) == 1
                     else FeatureBatch.concat_all(batch_parts))
        if q.sort_by is not None and batch is not None and batch.n:
            from ..store.common import sort_order
            order = sort_order(batch, q.sort_by, q.sort_desc)
            batch = batch.take(order)
            ids = ids[order]
        if q.max_features is not None and len(ids) > q.max_features:
            ids = ids[:q.max_features]
            if batch is not None:
                batch = batch.take(np.arange(q.max_features))
        explain = Explainer(explain_out)
        explain(lambda: f"Cluster scatter over {len(self._groups)} "
                        f"groups ({len(failures)} missing)")
        out = ClusterQueryResult(
            ids, batch, explain,
            FilterStrategy("cluster", q.filter, None), n=len(ids))
        out.lsn_vector = self.lsn_vector()
        out.topology_epoch = self._part.epoch
        if missing:
            out.complete = False
            out.missing_groups = missing["groups"]
            out.missing_z_ranges = missing["z_ranges"]
        audit_query(self.audit, "cluster", q.type_name, str(q.filter),
                    q.hints, 0.0, (time.perf_counter() - t0) * 1000,
                    len(ids), index="cluster")
        return out

    @_gated
    def query_count(self, q, type_name=None) -> int:
        q = self._as_query(q, type_name)
        from ..audit import audit_query, delegated_scope
        t0 = time.perf_counter()
        legs, prune_info = self.prune_for(q.type_name, q.filter)
        self._account_legs("query_count", q.type_name, legs, prune_info)
        with delegated_scope():
            results, failures = self._scatter(
                lambda name, group:
                lambda: group.query_count(q, **self._ryw_kwargs(name,
                                                                group)),
                legs=legs)
        missing = self._missing(failures)
        total = int(sum(results.values()))
        if q.max_features is not None:
            total = min(total, q.max_features)
        audit_query(self.audit, "cluster", q.type_name, str(q.filter),
                    q.hints, 0.0, (time.perf_counter() - t0) * 1000,
                    total, index="cluster")
        if missing:
            out = PartialCount(total)
            out.missing_groups = missing["groups"]
            out.missing_z_ranges = missing["z_ranges"]
            out.topology_epoch = self._part.epoch
            return out
        return total

    # -- distributed SQL legs ----------------------------------------------

    @_gated
    def sql_partial(self, stmt: str, type_name: str = "",
                    legs: list[str] | None = None) \
            -> tuple[dict, dict | None]:
        """Scatter one partial-aggregate SQL leg per shard group (the
        sql/distributed.py decomposition): remote groups evaluate via
        their own ``sql_partial`` endpoint, in-process groups run the
        leg directly. ``legs`` (from the SQL planner's Z-range pruning
        of the pushed WHERE) restricts the scatter to the named
        groups. Returns ``(partials_by_group, missing)`` under the
        standard partial-results contract."""
        from ..audit import audit_query, delegated_scope
        from ..sql.distributed import partial_aggregate
        t0 = time.perf_counter()
        self._account_legs("sql_partial", type_name, legs)

        def make_fn(name, group):
            def leg():
                fn = getattr(group, "sql_partial", None)
                if callable(fn):
                    return fn(stmt)
                return partial_aggregate(
                    group, stmt,
                    query_kwargs=self._ryw_kwargs(name, group))
            return leg

        with delegated_scope():
            results, failures = self._scatter(make_fn, legs=legs)
        missing = self._missing(failures)
        audit_query(self.audit, "cluster", type_name, stmt, None, 0.0,
                    (time.perf_counter() - t0) * 1000,
                    int(sum(r.get("n", 0) for r in results.values())),
                    index="sql-partial")
        return results, missing

    @_gated
    def sql_join_partial(self, spec: dict, type_name: str = "",
                         legs: list[str] | None = None) \
            -> tuple[dict, dict | None]:
        """Scatter one broadcast-join leg per shard group: each group
        joins the shipped small side against its local slice of the
        big side. ``legs`` restricts the scatter to the groups whose
        owned z range can hold local-side matches. Same contract as
        ``sql_partial``."""
        from ..audit import audit_query, delegated_scope
        from ..sql.distributed import join_partial_leg
        t0 = time.perf_counter()
        self._account_legs("sql_join_partial", type_name, legs)

        def make_fn(name, group):
            def leg():
                fn = getattr(group, "sql_join_partial", None)
                if callable(fn):
                    return fn(spec)
                return join_partial_leg(
                    group, spec,
                    query_kwargs=self._ryw_kwargs(name, group))
            return leg

        with delegated_scope():
            results, failures = self._scatter(make_fn, legs=legs)
        missing = self._missing(failures)
        audit_query(self.audit, "cluster", type_name,
                    spec.get("sql", ""), None, 0.0,
                    (time.perf_counter() - t0) * 1000,
                    int(sum(r.get("n", r.get("count", 0))
                            for r in results.values())),
                    index="sql-join-partial")
        return results, missing

    @_gated
    def count(self, type_name: str) -> int:
        results, failures = self._scatter(
            lambda name, group:
            lambda: group.count(type_name,
                                **self._ryw_kwargs(name, group)))
        missing = self._missing(failures)
        total = int(sum(results.values()))
        if missing:
            out = PartialCount(total)
            out.missing_groups = missing["groups"]
            out.missing_z_ranges = missing["z_ranges"]
            return out
        return total

    # -- mergeable aggregates ----------------------------------------------

    @_gated
    def stats_query(self, type_name: str, stat_spec: str, ecql=None):
        """Scatter the sketch, merge exactly (Stat.merge — every
        sketch in stats/sketches.py is a commutative monoid over
        disjoint row sets, the StatsScan client reduce)."""
        legs, prune_info = self.prune_for(type_name, ecql)
        self._account_legs("stats_query", type_name, legs, prune_info)
        results, failures = self._scatter(
            lambda name, group:
            lambda: group.stats_query(type_name, stat_spec, ecql,
                                      **self._ryw_kwargs(name, group)),
            legs=legs)
        missing = self._missing(failures)
        merged = None
        for name in self._names:
            s = results.get(name)
            if s is None:
                continue
            if isinstance(s, dict):
                raise NotImplementedError(
                    "cluster stats merge needs Stat-returning groups "
                    "(in-process or replicated); a RemoteDataStore "
                    "group returned a JSON summary")
            merged = s if merged is None else merged.merge(s)
        if merged is None:
            from ..stats import parse_stat
            merged = parse_stat(stat_spec)
        merged.complete = missing is None
        if missing:
            merged.missing_groups = missing["groups"]
            merged.missing_z_ranges = missing["z_ranges"]
        return merged

    @_gated
    def density(self, type_name: str, ecql, bbox, width: int, height: int,
                weight_attr: str | None = None) -> np.ndarray:
        """Scatter the heatmap; grids over disjoint partitions sum
        exactly (the DensityScan client reduce)."""
        kwargs = {} if weight_attr is None else {"weight_attr": weight_attr}
        legs, prune_info = self.prune_for(
            type_name, self._density_filter(type_name, ecql, bbox))
        self._account_legs("density", type_name, legs, prune_info)
        results, failures = self._scatter(
            lambda name, group:
            lambda: group.density(type_name, ecql, bbox, width, height,
                                  **kwargs,
                                  **self._ryw_kwargs(name, group)),
            legs=legs)
        missing = self._missing(failures)
        grid = np.zeros((height, width), dtype=np.float32)
        for g in results.values():
            grid += np.asarray(g, dtype=np.float32)
        if missing:
            grid = grid.view(_PartialGrid)
            grid.missing_groups = missing["groups"]
            grid.missing_z_ranges = missing["z_ranges"]
        return grid

    def _density_filter(self, type_name: str, ecql, bbox):
        """The effective spatial constraint of a density scan: the
        ecql AND the grid bbox (rows outside the rendered extent
        contribute no weight, so their legs can prune)."""
        try:
            from ..filters import ast as _ast
            from ..filters import parse_ecql
            sft = self.get_schema(type_name)
            if sft.geom_field is None:
                return ecql
            box = _ast.BBox(sft.geom_field, float(bbox[0]), float(bbox[1]),
                            float(bbox[2]), float(bbox[3]))
            f = parse_ecql(ecql) if isinstance(ecql, str) else ecql
            if f is None or isinstance(f, _ast.Include):
                return box
            return _ast.And([f, box])
        except Exception:  # noqa: BLE001 — pruning input is advisory
            return ecql

    @_gated
    def bin_query(self, type_name: str, ecql, track: str | None = None,
                  label: str | None = None, sort: bool = False) -> bytes:
        """Scatter BIN encoding; sorted chunks k-way merge via
        merge_sorted_bin_chunks (the BinSorter client reduce)."""
        legs, prune_info = self.prune_for(type_name, ecql)
        self._account_legs("bin_query", type_name, legs, prune_info)
        results, failures = self._scatter(
            lambda name, group:
            lambda: group.bin_query(type_name, ecql, track=track,
                                    label=label, sort=sort,
                                    **self._ryw_kwargs(name, group)),
            legs=legs)
        missing = self._missing(failures)
        chunks = [results[n] for n in self._names
                  if results.get(n)]
        if sort:
            from ..scan.aggregations import merge_sorted_bin_chunks
            data = merge_sorted_bin_chunks(chunks,
                                           labeled=label is not None)
        else:
            data = b"".join(chunks)
        if missing:
            data = _PartialBytes(data)
            data.missing_groups = missing["groups"]
            data.missing_z_ranges = missing["z_ranges"]
        return data

    @_gated
    def arrow_ipc(self, type_name: str, ecql="INCLUDE",
                  sort_by: str | None = None) -> bytes:
        """Scatter arrow encoding (each leg sorts shard-locally), then
        reduce the per-group IPC payloads as *streams*: the k-way merge
        of arrow/delta.merge_sorted_streams holds one in-flight record
        batch per leg instead of decoding and concatenating the union
        before sorting."""
        results, failures = self._scatter(
            lambda name, group:
            lambda: group.arrow_ipc(type_name, ecql, sort_by=sort_by,
                                    **self._ryw_kwargs(name, group)))
        missing = self._missing(failures)
        sft = self.get_schema(type_name)
        import io as _io
        from ..arrow.delta import iter_ipc, merge_sorted_streams
        from ..arrow.io import FeatureArrowFileWriter, write_ipc
        sources = [iter_ipc(results[name], sft)[1]
                   for name in self._names if results.get(name)]
        sink = _io.BytesIO()
        wrote = False
        with FeatureArrowFileWriter(sink, sft) as w:
            for b in merge_sorted_streams(sources, sort_by):
                w.write(b)
                wrote = True
        data = (sink.getvalue() if wrote
                else write_ipc(sft, _empty_batch(sft)))
        if missing:
            data = _PartialBytes(data)
            data.missing_groups = missing["groups"]
            data.missing_z_ranges = missing["z_ranges"]
        return data

    # -- streamed scatter-gather -------------------------------------------

    def query_stream(self, q, type_name=None, batch_rows=None):
        """Streamed scatter-gather: one producer thread per group feeds
        a bounded queue (depth ``geomesa.stream.max.inflight.batches``
        — a slow consumer backpressures the legs instead of buffering
        them), and the consumer runs the k-way sort-merge over the
        queues, so cluster results stream in bounded memory end to end.

        Streaming legs are never hedged — a duplicate leg would
        double-deliver rows. The per-leg deadline bounds the wait for a
        group's *next* batch: a stalled group fails the stream typed
        (``ShardUnavailableError``) mid-iteration, or under
        ``geomesa.cluster.allow.partial`` drops out with its z-ranges
        flagged on the returned handle (final once exhausted)."""
        import queue as _queue
        from ..arrow.delta import (STREAM_MAX_INFLIGHT,
                                   merge_sorted_streams, slice_batches)
        q = self._as_query(q, type_name)
        # a stream holds the shared op gate for its whole lifetime
        # (releases in the merge's finally): the reshard flip cannot
        # swap topology under a half-consumed merge
        self._gate.acquire_shared()
        gate_released = threading.Event()

        def release_gate():
            if not gate_released.is_set():
                gate_released.set()
                self._gate.release_shared()

        mig = self._migration
        if mig is not None and mig.blocking:
            release_gate()
            raise ReshardError(
                f"topology flip incomplete (migration "
                f"{mig.src_name}->{mig.dst_name}, phase {mig.phase}); "
                "resume or abort the reshard")
        deadline = self._leg_deadline_s()
        depth = max(STREAM_MAX_INFLIGHT.as_int() or 4, 1)
        self._registry.counter("cluster.scatter.calls")
        stop = threading.Event()
        failures: dict = {}
        _BATCH, _DONE, _ERR = "batch", "done", "err"

        def put(qq, item) -> bool:
            # bounded put that gives up when the consumer walked away
            while not stop.is_set():
                try:
                    qq.put(item, timeout=0.1)
                    return True
                except _queue.Full:
                    continue
            return False

        def producer(name, group, qq):
            breaker = self._breakers.get(name)
            try:
                breaker.acquire()
            except CircuitOpenError as e:
                self._registry.counter("cluster.leg.fastfails")
                put(qq, (_ERR, e))
                return
            t0 = time.perf_counter()
            try:
                fn = getattr(group, "query_stream", None)
                it = fn(q, batch_rows=batch_rows,
                        **self._ryw_kwargs(name, group)) \
                    if callable(fn) else slice_batches(
                        group.query(
                            q, **self._ryw_kwargs(name, group)).batch,
                        batch_rows)
                for b in it:
                    if not put(qq, (_BATCH, b)):
                        return
            except Exception as e:  # noqa: BLE001 — leg boundary
                breaker.failure()
                self._registry.counter("cluster.leg.failures")
                put(qq, (_ERR, e))
            else:
                breaker.success()
                self._breakers.observe(name, time.perf_counter() - t0)
                put(qq, (_DONE, None))

        queues = []
        for name, group in zip(self._names, self._groups):
            qq = _queue.Queue(maxsize=depth)
            threading.Thread(target=producer, args=(name, group, qq),
                             daemon=True,
                             name=f"cluster-stream-{name}").start()
            queues.append((name, qq))

        def leg_source(name, qq):
            while True:
                try:
                    kind, val = qq.get(timeout=deadline + 5.0)
                except _queue.Empty:
                    self._registry.counter("cluster.leg.failures")
                    self._registry.counter("cluster.leg.timeouts")
                    failures[name] = TimeoutError(
                        f"shard leg {name!r} produced no batch inside "
                        f"its {deadline:g}s deadline")
                    self._missing({name: failures[name]})
                    return
                if kind == _DONE:
                    return
                if kind == _ERR:
                    failures[name] = val
                    self._missing({name: val})  # raises typed unless
                    return                      # partials are allowed
                yield val

        handle = _ClusterStream()
        handle._on_close = release_gate

        def merged():
            try:
                remaining = q.max_features
                for b in merge_sorted_streams(
                        [leg_source(name, qq) for name, qq in queues],
                        q.sort_by, reverse=q.sort_desc,
                        batch_rows=batch_rows):
                    if remaining is not None:
                        if remaining <= 0:
                            return
                        if b.n > remaining:
                            b = b.take(np.arange(remaining))
                        remaining -= b.n
                    yield b
            finally:
                # runs on every exit path — exhaustion, max_features
                # truncation, consumer close — so a truncated stream
                # still reports legs that failed before the cut. Only
                # the partial-allowed branch of _missing is reachable
                # here (strict mode raised typed during iteration);
                # gating on it keeps the finally from raising anew.
                stop.set()
                release_gate()
                if failures and self._allow_partial():
                    missing = self._missing(failures)
                    handle.complete = False
                    handle.missing_groups = missing["groups"]
                    handle.missing_z_ranges = missing["z_ranges"]

        handle._gen = merged()
        return handle

    # -- admin -------------------------------------------------------------

    def cluster_status(self) -> dict:
        vec = self.lsn_vector()
        groups = []
        for i, (name, g) in enumerate(zip(self._names, self._groups)):
            ent = {"name": name, "type": type(g).__name__,
                   "acked_lsn": vec.get(name, 0),
                   "breaker": self._breakers.get(name).state}
            ent.update({k: v for k, v in self._part.z_range(i).items()
                        if k != "group"})
            rs = getattr(g, "replication_status", None)
            if callable(rs):
                try:
                    ent["replication"] = rs()
                except Exception as e:  # noqa: BLE001 — status, not control
                    ent["replication_error"] = f"{type(e).__name__}: {e}"
            groups.append(ent)
        self._registry.gauge("cluster.groups", len(self._groups))
        return {"role": "cluster",
                "n_groups": len(self._groups),
                "prefix_bits": PREFIX_BITS,
                "topology_epoch": self._part.epoch,
                "allow_partial": self._allow_partial(),
                "prune": bool(CLUSTER_PRUNE.as_bool()),
                "leg_deadline_s": self._leg_deadline_s(),
                "hedge_ms": self._hedge_s() * 1e3,
                "lsn_vector": vec,
                "groups": groups,
                "last_plan": self.last_plan(),
                "leg_latency": self._breakers.latencies()}

    def cache_status(self) -> dict:
        """Per-leg materialized-cache view: each shard group's cache is
        keyed by that group's own LSN, so a write routed to one shard
        only invalidates that leg's tiles."""
        groups: dict[str, dict] = {}
        for name, g in zip(self._names, self._groups):
            cs = getattr(g, "cache_status", None)
            if not callable(cs):
                continue
            try:
                groups[name] = cs()
            except Exception as e:  # noqa: BLE001 — status, not control
                groups[name] = {"error": f"{type(e).__name__}: {e}"}
        return {"role": "cluster", "lsn_vector": self.lsn_vector(),
                "groups": groups}

    def invalidate_cache(self, type_name: str | None = None) -> int:
        n = 0
        for g in self._groups:
            inv = getattr(g, "invalidate_cache", None)
            if not callable(inv):
                continue
            try:
                n += int(inv(type_name))
            except Exception:  # noqa: BLE001 — best-effort fan-out
                pass
        return n

    def promote_group(self, name: str | None = None) -> dict:
        """Manually promote inside one shard group (the group must be
        replicated, or a remote fronting a replicated store)."""
        if name is None:
            if len(self._groups) != 1:
                raise ValueError(
                    "group name required; have: " + ", ".join(self._names))
            name = self._names[0]
        if name not in self._names:
            raise ValueError(f"no such group {name!r}; have: "
                             + ", ".join(self._names))
        group = self._groups[self._names.index(name)]
        fn = getattr(group, "promote", None)
        if not callable(fn):
            raise ValueError(f"group {name!r} ({type(group).__name__}) "
                             "does not support promotion")
        out = dict(fn() or {})
        out["group"] = name
        self._registry.counter("cluster.promotions")
        return out

    def close(self):
        for group in self._groups:
            close = getattr(group, "close", None)
            if callable(close):
                close()


def _empty_batch(sft) -> FeatureBatch:
    return FeatureBatch.from_dict(
        sft, np.empty(0, dtype=object),
        {a.name: ((np.empty(0), np.empty(0)) if a.type.name == "Point"
                  else []) for a in sft.attributes})
