"""Index/query API core: Query, FilterStrategy, QueryPlan, Explainer.

Mirrors the reference's geomesa-index-api surface (SURVEY.md section 1):
``GeoMesaFeatureIndex.getFilterStrategy/getQueryPlan``
(index/api/GeoMesaFeatureIndex.scala:140-156), ``FilterStrategy`` /
``FilterPlan`` (index/api/FilterPlan.scala:19-34), and the tree-style
``Explainer`` (index/utils/Explainer.scala:16-56).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable

from ..filters import ast
from ..filters.ecql import parse_ecql

__all__ = ["Query", "FilterStrategy", "QueryPlan", "Explainer", "QueryHints"]


class QueryHints:
    """Per-query hint keys (index/conf/QueryHints.scala:22-68)."""
    DENSITY_BBOX = "DENSITY_BBOX"
    DENSITY_WIDTH = "DENSITY_WIDTH"
    DENSITY_HEIGHT = "DENSITY_HEIGHT"
    DENSITY_WEIGHT = "DENSITY_WEIGHT"
    STATS_STRING = "STATS_STRING"
    ENCODE_STATS = "ENCODE_STATS"
    BIN_TRACK = "BIN_TRACK"
    BIN_GEOM = "BIN_GEOM"
    BIN_DTG = "BIN_DTG"
    BIN_LABEL = "BIN_LABEL"
    BIN_SORT = "BIN_SORT"
    BIN_BATCH_SIZE = "BIN_BATCH_SIZE"
    ARROW_ENCODE = "ARROW_ENCODE"
    ARROW_DICTIONARY_FIELDS = "ARROW_DICTIONARY_FIELDS"
    SAMPLING = "SAMPLING"
    SAMPLE_BY = "SAMPLE_BY"
    QUERY_INDEX = "QUERY_INDEX"
    COST_EVALUATION = "COST_EVALUATION"
    EXACT_COUNT = "EXACT_COUNT"
    LOOSE_BBOX = "LOOSE_BBOX"


@dataclasses.dataclass
class Query:
    """A query against one feature type (GeoTools Query analog)."""
    type_name: str
    filter: ast.Filter = dataclasses.field(default_factory=ast.Include)
    properties: list[str] | None = None      # projection; None = all
    max_features: int | None = None
    sort_by: str | None = None
    sort_desc: bool = False
    hints: dict[str, Any] = dataclasses.field(default_factory=dict)
    auths: list[str] | None = None   # visibility authorizations

    def __post_init__(self):
        if isinstance(self.filter, str):
            self.filter = parse_ecql(self.filter)


@dataclasses.dataclass
class FilterStrategy:
    """A possible way to run a query against one index: the primary
    (index-consumable) part and the secondary (residual) part
    (index/api/FilterPlan.scala:19)."""
    index: str
    primary: ast.Filter | None
    secondary: ast.Filter | None
    cost: float = 0.0

    def __str__(self) -> str:
        p = str(self.primary) if self.primary else "INCLUDE"
        s = str(self.secondary) if self.secondary else "None"
        return f"{self.index}[primary={p}, secondary={s}, cost={self.cost:g}]"


@dataclasses.dataclass
class QueryPlan:
    """An executable plan: strategy + the executor closure that runs it.

    ``execute(hints) -> result``; the store wires concrete executors.
    Mirrors QueryPlan (index/api/QueryPlan.scala:27) minus the
    byte-range machinery, which has no TPU analog.
    """
    strategy: FilterStrategy
    execute: Callable[..., Any]
    details: dict[str, Any] = dataclasses.field(default_factory=dict)


class Explainer:
    """Tree-structured explain output (index/utils/Explainer.scala)."""

    def __init__(self, out: Callable[[str], None] | None = None):
        self._depth = 0
        self._lines: list = []
        self._out = out

    def __call__(self, msg) -> "Explainer":
        """``msg`` may be a zero-arg callable: hot query paths pass
        lambdas so plan traces that nobody reads never pay the string
        formatting (filters stringify recursively — WKT and all)."""
        if self._out is None and callable(msg):
            self._lines.append(("  " * self._depth, msg))
            return self
        if callable(msg):
            msg = msg()
        line = "  " * self._depth + str(msg)
        self._lines.append(line)
        if self._out:
            self._out(line)
        return self

    def push(self, msg=None) -> "Explainer":
        if msg is not None:
            self(msg)
        self._depth += 1
        return self

    def pop(self) -> "Explainer":
        self._depth = max(0, self._depth - 1)
        return self

    @property
    def text(self) -> str:
        # resolve any deferred messages on first read
        out = []
        for ln in self._lines:
            if isinstance(ln, tuple):
                indent, fn = ln
                out.append(indent + str(fn()))
            else:
                out.append(ln)
        return "\n".join(out)


class Timing:
    """Inline timer (MethodProfiling/Timings analog)."""

    def __init__(self):
        self.times: dict[str, float] = {}

    def time(self, key: str):
        timing = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.perf_counter()

            def __exit__(self, *exc):
                timing.times[key] = timing.times.get(key, 0.0) + (
                    time.perf_counter() - self.t0)

        return _Ctx()
