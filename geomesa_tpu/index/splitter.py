"""FilterSplitter: decompose a filter into per-index strategy options.

Mirrors the reference's FilterSplitter (index/planning/FilterSplitter.scala:25)
+ per-index ``getFilterStrategy``: for each available index, split the
query filter into a *primary* part the index can turn into ranges and a
*secondary* residual evaluated on scan results.

Index applicability (reference key spaces):
- z3/xz3:  spatial values on the default geometry AND intervals on the
           default date (Z3IndexKeySpace.scala:63-119)
- z2/xz2:  spatial values on the default geometry (Z2IndexKeySpace)
- attr:    bounds on an indexed attribute (AttributeIndex)
- id:      FidFilter (RecordIndex/IdIndex)
- fullscan: anything (the in-memory fallback; no reference analog needed
            because tables always have at least one index)
"""

from __future__ import annotations

from ..features.sft import SimpleFeatureType
from ..filters import ast
from ..filters.helper import (FilterValues, extract_attribute_bounds,
                              extract_geometries, extract_intervals)
from .api import FilterStrategy

__all__ = ["split_filter", "spatial_part", "temporal_part"]


def _is_spatial(f: ast.Filter, geom: str) -> bool:
    return (isinstance(f, (ast.BBox, ast.DWithin, ast.SpatialPredicate))
            and f.prop == geom)


def _is_temporal(f: ast.Filter, dtg: str | None) -> bool:
    return (dtg is not None
            and isinstance(f, (ast.During, ast.Before, ast.After, ast.TEquals,
                               ast.Compare, ast.Between))
            and getattr(f, "prop", None) == dtg)


def _partition(f: ast.Filter, pred) -> tuple[ast.Filter | None, ast.Filter | None]:
    """Split an AND tree into (matching, rest). Non-AND filters are all
    or nothing. Returns (None, f) when nothing matches."""
    if isinstance(f, ast.Include):
        return None, None
    if pred(f):
        return f, None
    if isinstance(f, ast.And):
        hit = [c for c in f.children if pred(c)]
        rest = [c for c in f.children if not pred(c)]
        hit_f = None if not hit else (hit[0] if len(hit) == 1 else ast.And(hit))
        rest_f = None if not rest else (rest[0] if len(rest) == 1 else ast.And(rest))
        return hit_f, rest_f
    return None, f


def _or_primary(f: ast.Filter, pred) -> ast.Filter | None:
    """A homogeneous OR (every child matches pred) is usable as a primary
    (FilterSplitter's same-dimension OR rule)."""
    if isinstance(f, ast.Or) and all(pred(c) for c in f.children):
        return f
    return None


def _with_or(pred):
    """Extend a node predicate so a homogeneous OR counts as matching —
    both at the top level and as a conjunct inside an AND."""
    def p(c):
        return pred(c) or _or_primary(c, pred) is not None
    return p


def spatial_part(f: ast.Filter, geom: str):
    return _partition(f, _with_or(lambda c: _is_spatial(c, geom)))


def temporal_part(f: ast.Filter, dtg: str | None):
    return _partition(f, _with_or(lambda c: _is_temporal(c, dtg)))


def _and_opt(a: ast.Filter | None, b: ast.Filter | None) -> ast.Filter | None:
    if a is None:
        return b
    if b is None:
        return a
    return ast.And([a, b])


def split_filter(sft: SimpleFeatureType, f: ast.Filter,
                 indices: list[str]) -> list[FilterStrategy]:
    """All viable FilterStrategy options for the filter.

    OR filters at the top level are handled as in the reference: if every
    OR child constrains the same dimension the whole OR is usable as a
    primary; otherwise only fullscan applies (FilterSplitter's
    'cannot split an OR across indices' rule, simplified).
    """
    geom = sft.geom_field
    dtg = sft.dtg_field
    options: list[FilterStrategy] = []

    if isinstance(f, ast.Exclude):
        return [FilterStrategy("empty", None, None, cost=0)]

    for index in indices:
        if index in ("z3", "xz3") and geom is not None and dtg is not None:
            geoms = extract_geometries(f, geom)
            intervals = extract_intervals(f, dtg)
            if geoms.disjoint or intervals.disjoint:
                return [FilterStrategy("empty", None, None, cost=0)]
            # z3 needs a bounded time interval (Z3IndexKeySpace requires
            # intervals; unbounded falls through to z2/fullscan)
            bounded = bool(intervals) and all(
                b.lower.is_bounded and b.upper.is_bounded for b in intervals)
            if bounded:
                spatial, rest1 = spatial_part(f, geom)
                temporal, rest2 = temporal_part(rest1, dtg) if rest1 else (None, None)
                primary = _and_opt(spatial, temporal)
                if primary is not None:
                    options.append(FilterStrategy(index, primary, rest2))
        elif index in ("z2", "xz2") and geom is not None:
            geoms = extract_geometries(f, geom)
            if geoms.disjoint:
                return [FilterStrategy("empty", None, None, cost=0)]
            if geoms:
                spatial, rest = spatial_part(f, geom)
                if spatial is not None:
                    options.append(FilterStrategy(index, spatial, rest))
        elif index == "id":
            if isinstance(f, ast.FidFilter):
                options.append(FilterStrategy("id", f, None))
            elif isinstance(f, ast.And):
                fids = [c for c in f.children if isinstance(c, ast.FidFilter)]
                if fids:
                    # multiple fid filters AND together: intersect the sets
                    ids = set(fids[0].ids)
                    for extra in fids[1:]:
                        ids &= set(extra.ids)
                    rest = [c for c in f.children if c not in fids]
                    rest_f = None if not rest else (
                        rest[0] if len(rest) == 1 else ast.And(rest))
                    options.append(FilterStrategy(
                        "id", ast.FidFilter(sorted(ids)), rest_f))
        elif index.startswith("attr:"):
            attr = index.split(":", 1)[1]
            bounds = extract_attribute_bounds(f, attr)
            if bounds.disjoint:
                return [FilterStrategy("empty", None, None, cost=0)]
            if bounds and any(b.is_bounded for b in bounds):
                def _attr_pred(c, attr=attr):
                    return (getattr(c, "prop", None) == attr
                            and isinstance(c, (ast.Compare, ast.Between,
                                               ast.InList, ast.Like,
                                               ast.During, ast.Before,
                                               ast.After, ast.TEquals)))
                primary, rest = _partition(f, _with_or(_attr_pred))
                if primary is not None:
                    options.append(FilterStrategy(index, primary, rest))

    # fullscan is always viable
    residual = None if isinstance(f, ast.Include) else f
    options.append(FilterStrategy("fullscan", None, residual))
    return options
