"""FilterSplitter: decompose a filter into per-index strategy options.

Mirrors the reference's FilterSplitter (index/planning/FilterSplitter.scala:25)
+ per-index ``getFilterStrategy``: for each available index, split the
query filter into a *primary* part the index can turn into ranges and a
*secondary* residual evaluated on scan results.

Index applicability (reference key spaces):
- z3/xz3:  spatial values on the default geometry AND intervals on the
           default date (Z3IndexKeySpace.scala:63-119)
- z2/xz2:  spatial values on the default geometry (Z2IndexKeySpace)
- attr:    bounds on an indexed attribute (AttributeIndex)
- id:      FidFilter (RecordIndex/IdIndex)
- fullscan: anything (the in-memory fallback; no reference analog needed
            because tables always have at least one index)
"""

from __future__ import annotations

import numpy as np

from ..features.sft import SimpleFeatureType
from ..filters import ast
from ..filters.helper import (FilterValues, extract_attribute_bounds,
                              extract_geometries, extract_intervals)
from .api import FilterStrategy

__all__ = ["split_filter", "spatial_part", "temporal_part",
           "prefix_histogram", "pick_split_prefix"]


def _is_spatial(f: ast.Filter, geom: str) -> bool:
    return (isinstance(f, (ast.BBox, ast.DWithin, ast.SpatialPredicate))
            and f.prop == geom)


def _is_temporal(f: ast.Filter, dtg: str | None) -> bool:
    return (dtg is not None
            and isinstance(f, (ast.During, ast.Before, ast.After, ast.TEquals,
                               ast.Compare, ast.Between))
            and getattr(f, "prop", None) == dtg)


def _partition(f: ast.Filter, pred) -> tuple[ast.Filter | None, ast.Filter | None]:
    """Split an AND tree into (matching, rest). Non-AND filters are all
    or nothing. Returns (None, f) when nothing matches."""
    if isinstance(f, ast.Include):
        return None, None
    if pred(f):
        return f, None
    if isinstance(f, ast.And):
        hit = [c for c in f.children if pred(c)]
        rest = [c for c in f.children if not pred(c)]
        hit_f = None if not hit else (hit[0] if len(hit) == 1 else ast.And(hit))
        rest_f = None if not rest else (rest[0] if len(rest) == 1 else ast.And(rest))
        return hit_f, rest_f
    return None, f


def _or_primary(f: ast.Filter, pred) -> ast.Filter | None:
    """A homogeneous OR (every child matches pred) is usable as a primary
    (FilterSplitter's same-dimension OR rule)."""
    if isinstance(f, ast.Or) and all(pred(c) for c in f.children):
        return f
    return None


def _with_or(pred):
    """Extend a node predicate so a homogeneous OR counts as matching —
    both at the top level and as a conjunct inside an AND."""
    def p(c):
        return pred(c) or _or_primary(c, pred) is not None
    return p


def spatial_part(f: ast.Filter, geom: str):
    return _partition(f, _with_or(lambda c: _is_spatial(c, geom)))


def temporal_part(f: ast.Filter, dtg: str | None):
    return _partition(f, _with_or(lambda c: _is_temporal(c, dtg)))


def _and_opt(a: ast.Filter | None, b: ast.Filter | None) -> ast.Filter | None:
    if a is None:
        return b
    if b is None:
        return a
    return ast.And([a, b])


def split_filter(sft: SimpleFeatureType, f: ast.Filter,
                 indices: list[str]) -> list[FilterStrategy]:
    """All viable FilterStrategy options for the filter.

    OR filters at the top level are handled as in the reference: if every
    OR child constrains the same dimension the whole OR is usable as a
    primary; otherwise only fullscan applies (FilterSplitter's
    'cannot split an OR across indices' rule, simplified).
    """
    geom = sft.geom_field
    dtg = sft.dtg_field
    options: list[FilterStrategy] = []

    if isinstance(f, ast.Exclude):
        return [FilterStrategy("empty", None, None, cost=0)]

    for index in indices:
        if index in ("z3", "xz3") and geom is not None and dtg is not None:
            geoms = extract_geometries(f, geom)
            intervals = extract_intervals(f, dtg)
            if geoms.disjoint or intervals.disjoint:
                return [FilterStrategy("empty", None, None, cost=0)]
            # z3 needs a bounded time interval (Z3IndexKeySpace requires
            # intervals; unbounded falls through to z2/fullscan)
            bounded = bool(intervals) and all(
                b.lower.is_bounded and b.upper.is_bounded for b in intervals)
            if bounded:
                spatial, rest1 = spatial_part(f, geom)
                temporal, rest2 = temporal_part(rest1, dtg) if rest1 else (None, None)
                primary = _and_opt(spatial, temporal)
                if primary is not None:
                    options.append(FilterStrategy(index, primary, rest2))
        elif index in ("z2", "xz2") and geom is not None:
            geoms = extract_geometries(f, geom)
            if geoms.disjoint:
                return [FilterStrategy("empty", None, None, cost=0)]
            if geoms:
                spatial, rest = spatial_part(f, geom)
                if spatial is not None:
                    options.append(FilterStrategy(index, spatial, rest))
        elif index == "id":
            if isinstance(f, ast.FidFilter):
                options.append(FilterStrategy("id", f, None))
            elif isinstance(f, ast.And):
                fids = [c for c in f.children if isinstance(c, ast.FidFilter)]
                if fids:
                    # multiple fid filters AND together: intersect the sets
                    ids = set(fids[0].ids)
                    for extra in fids[1:]:
                        ids &= set(extra.ids)
                    rest = [c for c in f.children if c not in fids]
                    rest_f = None if not rest else (
                        rest[0] if len(rest) == 1 else ast.And(rest))
                    options.append(FilterStrategy(
                        "id", ast.FidFilter(sorted(ids)), rest_f))
        elif index.startswith("attr:"):
            attr = index.split(":", 1)[1]
            bounds = extract_attribute_bounds(f, attr)
            if bounds.disjoint:
                return [FilterStrategy("empty", None, None, cost=0)]
            if bounds and any(b.is_bounded for b in bounds):
                def _attr_pred(c, attr=attr):
                    return (getattr(c, "prop", None) == attr
                            and isinstance(c, (ast.Compare, ast.Between,
                                               ast.InList, ast.Like,
                                               ast.During, ast.Before,
                                               ast.After, ast.TEquals)))
                primary, rest = _partition(f, _with_or(_attr_pred))
                if primary is not None:
                    options.append(FilterStrategy(index, primary, rest))

    # fullscan is always viable
    residual = None if isinstance(f, ast.Include) else f
    options.append(FilterStrategy("fullscan", None, residual))
    return options


# -- key-density histograms (reshard split-point selection) ---------------
#
# The reference's tablet splitter picks split points from the observed
# key distribution, not the keyspace midpoint (``getSplits`` over the
# curve). Same idea here: histogram a store's rows by their z-key
# prefix and split at the weighted median, so a hot range splits into
# halves of equal ROW count even when the keys are badly skewed.

def _batch_prefixes(sft, batch, prefix_bits: int) -> np.ndarray | None:
    """Z-key prefix per row (the same routing key the cluster
    partitioner derives): point coords directly, extent geometries by
    bbox centroid; None for a geometry-less schema (id-hash routed —
    no spatial key to histogram)."""
    from ..curves import zorder
    from ..curves.sfc import Z2SFC
    geom = sft.geom_field
    if geom is None or batch is None or not batch.n:
        return None
    col = batch.col(geom)
    if hasattr(col, "x"):                          # PointColumn
        x = np.asarray(col.x, np.float64)
        y = np.asarray(col.y, np.float64)
    else:                                          # GeometryColumn
        bounds = np.asarray(col.bounds, np.float64)
        x = (bounds[:, 0] + bounds[:, 2]) * 0.5
        y = (bounds[:, 1] + bounds[:, 3]) * 0.5
        bad = ~np.isfinite(x) | ~np.isfinite(y)
        x = np.where(bad, 0.0, x)
        y = np.where(bad, 0.0, y)
    z = np.asarray(Z2SFC().index(x, y, lenient=True)).astype(np.uint64)
    shift = np.uint64(2 * zorder.Z2_BITS - prefix_bits)
    return (z >> shift).astype(np.int64)


def prefix_histogram(store, type_name: str, prefix_lo: int,
                     prefix_hi: int, prefix_bits: int = 16) -> np.ndarray:
    """Row count per z prefix over ``[prefix_lo, prefix_hi)`` for one
    type — the key-density profile a reshard split point is chosen
    from (and the ``GET /rest/topology`` density summary)."""
    from .api import Query
    sft = store.get_schema(type_name)
    out = np.zeros(max(int(prefix_hi) - int(prefix_lo), 0),
                   dtype=np.int64)
    if not len(out):
        return out
    res = store.query(Query(type_name, "INCLUDE"))
    prefixes = _batch_prefixes(sft, res.batch, prefix_bits)
    if prefixes is None:
        return out
    in_range = prefixes[(prefixes >= prefix_lo) & (prefixes < prefix_hi)]
    if len(in_range):
        np.add.at(out, in_range - prefix_lo, 1)
    return out


def pick_split_prefix(counts: np.ndarray | None, prefix_lo: int,
                      prefix_hi: int) -> int:
    """The weighted-median split point for a density profile over
    ``[prefix_lo, prefix_hi)``: the smallest prefix with at least half
    the rows strictly below it, clamped inside the open interval so
    both sides stay non-empty. Falls back to the keyspace midpoint for
    an empty (or absent) profile."""
    mid = (int(prefix_lo) + int(prefix_hi)) // 2
    if counts is None:
        return mid
    counts = np.asarray(counts, dtype=np.int64)
    total = int(counts.sum())
    if total <= 0 or len(counts) != prefix_hi - prefix_lo:
        return mid
    cum = np.cumsum(counts)
    at = int(prefix_lo) + int(np.searchsorted(cum, total / 2.0)) + 1
    return int(min(max(at, prefix_lo + 1), prefix_hi - 1))
