"""L4 index & query-planning core (SURVEY.md section 1, geomesa-index-api)."""

from .api import Explainer, FilterStrategy, Query, QueryHints, QueryPlan
from .planner import decide_strategy, heuristic_cost
from .splitter import split_filter
from .splitters import (AlphaNumericSplitter, DigitSplitter, HexSplitter,
                        NoSplitter, splitter_for)

__all__ = ["Explainer", "FilterStrategy", "Query", "QueryHints", "QueryPlan",
           "decide_strategy", "heuristic_cost", "split_filter",
           "AlphaNumericSplitter", "DigitSplitter", "HexSplitter",
           "NoSplitter", "splitter_for"]
