"""Query planner: strategy selection + explain.

Mirrors QueryPlanner (index/planning/QueryPlanner.scala:43) and
StrategyDecider/CostBasedStrategyDecider (index/planning/StrategyDecider.scala:47-64):
enumerate strategy options via the splitter, cost each (stats-based when
stats exist, index-priority heuristics otherwise), pick the cheapest,
honoring the QUERY_INDEX hint override.
"""

from __future__ import annotations

from ..features.sft import SimpleFeatureType
from ..filters import ast
from ..filters.helper import extract_geometries, extract_intervals
from .api import Explainer, FilterStrategy, Query, QueryHints
from .splitter import split_filter

__all__ = ["decide_strategy", "heuristic_cost"]

# index-priority costs when no stats are available, mirroring the
# reference's fixed-cost fallback ordering (id < attr-eq < z3 < z2 < scan)
_BASE_COST = {
    "empty": 0.0,
    "id": 1.0,
    "z3": 200.0,
    "xz3": 201.0,
    "z2": 400.0,
    "xz2": 401.0,
}

# Full-table scans are a last resort, never a tie-winner: the reference's
# CostBasedStrategyDecider only falls back to a full scan when no index
# applies (StrategyDecider.scala:47-64). Without the penalty, a store
# loaded through parquet pushdown (holding only ~matching rows) costs
# every strategy at ~n and fullscan won the tie, so no index was ever
# built — and the fs sidecar persistence never fired (round-3 red tests).
_FULLSCAN_PENALTY = 1e9


def heuristic_cost(sft: SimpleFeatureType, s: FilterStrategy,
                   n_features: int) -> float:
    if s.index.startswith("attr:"):
        # equality cheaper than range (AttributeIndex cost heuristics)
        base = 10.0
        if isinstance(s.primary, (ast.Compare,)) and s.primary.op == "=":
            return base
        return base * 10
    base = _BASE_COST.get(s.index, 1e9)
    if s.index == "fullscan":
        return _FULLSCAN_PENALTY + float(max(n_features, 1))
    return base


def decide_strategy(sft: SimpleFeatureType, query: Query,
                    indices: list[str], n_features: int,
                    stats=None, explain: Explainer | None = None
                    ) -> FilterStrategy:
    """Pick the best strategy (StrategyDecider.getFilterPlan analog)."""
    explain = explain or Explainer()
    options = split_filter(sft, query.filter, indices)
    explain.push(lambda: f"Strategy options for '{query.filter}':")

    forced = query.hints.get(QueryHints.QUERY_INDEX)
    if forced:
        for s in options:
            if s.index == forced or s.index.startswith(f"{forced}:"):
                explain(f"Forced via QUERY_INDEX hint: {s}")
                explain.pop()
                return s
        explain(f"QUERY_INDEX={forced} requested but not viable; ignoring")

    best = None
    for s in options:
        if stats is not None:
            s.cost = _stats_cost(sft, s, stats, n_features)
        else:
            s.cost = heuristic_cost(sft, s, n_features)
        explain(lambda s=s: f"option: {s}")
        if best is None or s.cost < best.cost:
            best = s
    explain(lambda: f"Selected: {best}")
    explain.pop()
    return best


def _stats_cost(sft: SimpleFeatureType, s: FilterStrategy, stats,
                n_features: int) -> float:
    """Stats-based cost: estimated matching count for the primary
    (StatsBasedEstimator analog); falls back to heuristics."""
    if s.index == "empty":
        return 0.0
    if s.index == "fullscan":
        return _FULLSCAN_PENALTY + float(max(n_features, 1))
    if s.primary is None:
        return float(max(n_features, 1))
    if s.index.startswith("attr:"):
        cost = heuristic_cost(sft, s, n_features)
        # histogram/sketch-backed equality selectivity: a predicate on
        # a value covering most of the table must LOSE to a selective
        # z strategy (the skewed-data failure the flat heuristic had;
        # StatsBasedEstimator.scala:27)
        if (isinstance(s.primary, ast.Compare)
                and s.primary.op == ast.CompareOp.EQ):
            est = stats.attr_equality_estimate(
                s.index.split(":", 1)[1], s.primary.value)
            if est is not None:
                cost = float(est)
        # secondary (value, date) tiering: an equality scan narrowed by
        # the residual's date bounds touches only the matching time
        # bins, so its cost scales with the temporal selectivity
        # (AttributeIndex.scala:124-158 secondary key-space tightening)
        if (sft.dtg_field is not None and s.secondary is not None
                and isinstance(s.primary, ast.Compare)
                and s.primary.op == ast.CompareOp.EQ):
            iv = extract_intervals(s.secondary, sft.dtg_field)
            frac = stats.temporal_fraction(iv)
            if frac is not None:
                cost *= max(frac, 1e-3)
        return cost
    try:
        est = stats.estimate_count(s.primary)
    except Exception:
        est = None
    if est is None:
        return heuristic_cost(sft, s, n_features)
    # small bias keeps z3 preferred over z2 at equal selectivity
    return est + _BASE_COST.get(s.index, 500.0) / 1e6
