"""Host-side sorted z-key index: query ranges -> candidate rows.

The TPU analog of the reference's key-range pruning: the reference sorts
rows by ``[2-byte time bin][8-byte z3]`` in the backing table and turns a
query into covering key ranges (Z3IndexKeySpace.getRanges,
geomesa-index-api/.../index/z3/Z3IndexKeySpace.scala:121-136, delegating
to Z3SFC.ranges / sfcurve zranges), so scans touch only intersecting
tablets.  Here the *device* columns stay in insertion order (a gather is
order-agnostic on TPU); what is sorted is a **host-side key array +
permutation**.  Planning a query:

    boxes + time intervals
      -> per-bin z ranges (curves/zranges.py divide-and-conquer)
      -> binary search into the sorted keys (np.searchsorted)
      -> candidate row positions -> original row ids via the permutation

The candidate set is a strict over-approximation of the true matches
(range decomposition over-covers, exactly like the reference, which
re-checks every row server-side with Z3Filter); the fused device kernel
then evaluates the exact predicate on just the gathered candidates.
When the candidate set is a large fraction of the table the store falls
back to the full-batch scan — a gather of most rows costs more than a
dense scan (the cost crossover the reference handles with
``QueryProperties.SCAN_RANGES_TARGET`` coarsening).

Index build is lazy per curve (z3 and z2 orders are built on first use,
the two "tables" of the reference's Z3Index/Z2Index).
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

from ..curves import timebin
from ..curves.sfc import z2sfc, z3sfc
from ..curves.timebin import TimePeriod
from ..utils.properties import SystemProperty

__all__ = ["ZKeyIndex", "multi_arange", "prune_candidates",
           "SCAN_BLOCK_THRESHOLD"]

# candidate-fraction above which an indexed scan falls back to the dense
# full-batch kernel (gather cost crossover)
SCAN_BLOCK_THRESHOLD = SystemProperty("geomesa.scan.index.threshold", "0.4")


def multi_arange(starts: np.ndarray, stops: np.ndarray) -> np.ndarray:
    """Concatenate ``arange(starts[i], stops[i])`` without a Python loop.

    Standard cumsum trick: one output cell per emitted integer, seeded
    with jumps at segment starts.
    """
    starts = np.asarray(starts, dtype=np.int64)
    stops = np.asarray(stops, dtype=np.int64)
    counts = stops - starts
    keep = counts > 0
    starts, counts = starts[keep], counts[keep]
    if len(starts) == 0:
        return np.empty(0, dtype=np.int64)
    total = int(counts.sum())
    out = np.ones(total, dtype=np.int64)
    ends = np.cumsum(counts)
    out[0] = starts[0]
    out[ends[:-1]] = starts[1:] - (starts[:-1] + counts[:-1] - 1)
    return np.cumsum(out)


_native_sort = None  # None = unprobed, False = unavailable


def _native_sort_lib():
    """ctypes handle to the native sort (native/src/zsort.cpp): counting
    sort by bin + per-segment pair sort, replacing two indirect
    O(N log N) argsorts in np.lexsort. Tie order matches lexsort."""
    global _native_sort
    if _native_sort is False:
        return None
    if _native_sort is None:
        import ctypes
        from ..native import symbols
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        dp = ctypes.POINTER(ctypes.c_double)
        lib = symbols({
            "geomesa_sort_bin_z": (
                ctypes.c_int64,
                [i32p, i64p, ctypes.c_int64, ctypes.c_int64, i32p, i64p,
                 i64p]),
            "geomesa_sort_z": (
                ctypes.c_int64, [i64p, ctypes.c_int64, i32p, i64p]),
            "geomesa_gather_xyz": (
                ctypes.c_int64,
                [dp, dp, i64p, i32p, ctypes.c_int64, dp, dp, i64p]),
        })
        _native_sort = lib if lib is not None else False
    return _native_sort or None


def _i32p(a):
    import ctypes
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _i64p(a):
    import ctypes
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _native_sort_bin_z(bins: np.ndarray, z: np.ndarray):
    """(z_sorted, perm, ubins, seg_offsets) or None. The counting sort
    exports its per-bin prefix sums, so segment boundaries come back
    for free — no bins gather / np.unique pass afterwards."""
    lib = _native_sort_lib()
    if lib is None or not len(bins):
        return None
    bins = np.ascontiguousarray(bins, dtype=np.int32)
    z = np.ascontiguousarray(z, dtype=np.int64)
    max_bin = int(bins.max())
    perm = np.empty(len(z), dtype=np.int32)
    z_sorted = np.empty(len(z), dtype=np.int64)
    offsets = np.empty(max_bin + 2, dtype=np.int64)
    rc = lib.geomesa_sort_bin_z(_i32p(bins), _i64p(z), len(z),
                                max_bin, _i32p(perm), _i64p(z_sorted),
                                _i64p(offsets))
    if rc != 0:
        return None
    counts = np.diff(offsets)
    present = counts > 0
    ubins = np.flatnonzero(present).astype(bins.dtype)
    seg_offsets = np.append(offsets[:-1][present], len(z))
    return z_sorted, perm, ubins, seg_offsets


def _native_sort_z(z: np.ndarray):
    lib = _native_sort_lib()
    if lib is None or not len(z):
        return None
    z = np.ascontiguousarray(z, dtype=np.int64)
    perm = np.empty(len(z), dtype=np.int32)
    z_sorted = np.empty(len(z), dtype=np.int64)
    rc = lib.geomesa_sort_z(_i64p(z), len(z), _i32p(perm),
                            _i64p(z_sorted))
    return None if rc != 0 else (z_sorted, perm)


_native_build = None  # None = unprobed, False = unavailable
_PERIOD_CODE = {timebin.TimePeriod.DAY: 0, timebin.TimePeriod.WEEK: 1}
_EDGE_CACHE: dict = {}  # period -> int64 bin-edge epoch millis


def _bin_edges(period) -> np.ndarray:
    """Epoch millis of every calendar bin boundary (MONTH/YEAR), one
    past the last indexable bin included — computed once, 262KB."""
    period = timebin.TimePeriod.parse(period)
    if period not in _EDGE_CACHE:
        unit = "M" if period is timebin.TimePeriod.MONTH else "Y"
        grid = np.arange(0, 32769).astype(f"datetime64[{unit}]")
        _EDGE_CACHE[period] = grid.astype("datetime64[ms]") \
            .astype(np.int64)
    return _EDGE_CACHE[period]


def _native_encode_binned_z3(x, y, millis, period):
    """(bins:int32, z:int64) from the fused native clamp+bin+encode
    pass (native/src/zbuild.cpp), or None when the native library is
    absent. DAY/WEEK use constant-divisor bin splits; MONTH/YEAR pass
    a precomputed calendar bin-edge table and binary-search it fused
    with the encode."""
    global _native_build
    period = timebin.TimePeriod.parse(period)
    if _native_build is False or not len(x):
        return None
    import ctypes
    if _native_build is None:
        from ..native import symbols
        dp = ctypes.POINTER(ctypes.c_double)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib = symbols({
            "geomesa_encode_binned_z3": (
                ctypes.c_int64,
                [dp, dp, i64p, ctypes.c_int64, ctypes.c_int32,
                 ctypes.c_double, i32p, i64p]),
            "geomesa_encode_binned_z3_edges": (
                ctypes.c_int64,
                [dp, dp, i64p, ctypes.c_int64, i64p, ctypes.c_int64,
                 ctypes.c_int64, ctypes.c_double, i32p, i64p]),
        })
        _native_build = lib if lib is not None else False
        if _native_build is False:
            return None
    x = np.ascontiguousarray(x, dtype=np.float64)
    y = np.ascontiguousarray(y, dtype=np.float64)
    millis = np.ascontiguousarray(millis, dtype=np.int64)
    n = len(x)
    if len(y) != n or len(millis) != n:
        return None
    bins = np.empty(n, dtype=np.int32)
    z = np.empty(n, dtype=np.int64)
    dptr = ctypes.POINTER(ctypes.c_double)
    t_max = float(z3sfc(period).time.max)
    code = _PERIOD_CODE.get(period)
    if code is not None:
        rc = _native_build.geomesa_encode_binned_z3(
            x.ctypes.data_as(dptr), y.ctypes.data_as(dptr),
            _i64p(millis), n, code, t_max, _i32p(bins), _i64p(z))
    else:
        edges = _bin_edges(period)
        off_div = 1000 if period is timebin.TimePeriod.MONTH else 60_000
        rc = _native_build.geomesa_encode_binned_z3_edges(
            x.ctypes.data_as(dptr), y.ctypes.data_as(dptr),
            _i64p(millis), n, _i64p(edges), len(edges) - 1, off_div,
            t_max, _i32p(bins), _i64p(z))
    return None if rc != 0 else (bins, z)


def binned_candidate_positions(ubins, seg_offsets, keys_sorted,
                               intervals_ms, period, range_fn,
                               max_rows: int | None,
                               base_total: int = 0) -> np.ndarray | None:
    """Shared per-time-bin fan-out (Z3IndexKeySpace.getRanges:100-136):
    clamp intervals into the indexable range (monotone, matching the
    lenient keys), union per-bin offset hulls, and binary-search each
    bin's covering ranges (``range_fn((lo_off, hi_off))``) inside its
    sorted segment. Returns positions into the sorted order, an empty
    array when nothing matches, or None when the interval set is empty
    or the candidate count (plus ``base_total``) exceeds ``max_rows``.
    Used by both the z3 point index and the xz3 extent index."""
    cap = timebin.max_date_millis(period) - 1
    by_bin: dict[int, list[int]] = {}
    for lo_ms, hi_ms in intervals_ms:
        if hi_ms < lo_ms:
            continue
        lo_ms = min(max(int(lo_ms), 0), cap)
        hi_ms = min(max(int(hi_ms), 0), cap)
        bs, los, his = timebin.bins_of_interval(lo_ms, hi_ms, period)
        for b, lo, hi in zip(bs.tolist(), los.tolist(), his.tolist()):
            cur = by_bin.get(b)
            if cur is None:
                by_bin[b] = [lo, hi]
            else:
                # over-approximate disjoint unions with the hull; the
                # exact re-check downstream handles every candidate
                cur[0] = min(cur[0], lo)
                cur[1] = max(cur[1], hi)
    if not by_bin:
        return None
    if max_rows is not None and base_total > max_rows:
        return None
    range_cache: dict[tuple, np.ndarray] = {}
    pieces: list[np.ndarray] = []
    total = base_total
    for b in sorted(by_bin):
        i = int(np.searchsorted(ubins, b))
        if i >= len(ubins) or int(ubins[i]) != b:
            continue
        s, e = int(seg_offsets[i]), int(seg_offsets[i + 1])
        key = tuple(by_bin[b])
        ranges = range_cache.get(key)
        if ranges is None:
            ranges = range_fn(key)
            range_cache[key] = ranges
        if len(ranges) == 0:
            continue
        seg = keys_sorted[s:e]
        los = s + np.searchsorted(seg, ranges[:, 0], side="left")
        his = s + np.searchsorted(seg, ranges[:, 1], side="right")
        total += int(np.sum(his - los))
        if max_rows is not None and total > max_rows:
            return None
        pos = multi_arange(los, his)
        if len(pos):
            pieces.append(pos)
    if not pieces:
        return np.empty(0, dtype=np.int64)
    return np.concatenate(pieces)


def search_rows(zindex, index_name: str, boxes, intervals,
                host_cap: int | None, block_cap: int | None,
                cache: bool = True):
    """THE store-level fast-path policy (single copy for every store):
    whole-world gate, then one range decomposition via
    ``zindex.query_rows`` serving both tiers — ("exact", rows) under
    ``host_cap``, ("candidates", rows) under ``block_cap``,
    (None, None) for the dense path. Indexes without query_rows (the XZ
    extent family runs its own exact stage) fall back to
    prune_candidates. ``cache=False`` skips the decomposition cache —
    probe loops with never-repeating boxes (KNN ring expansion) must
    not flush entries that repeated store queries rely on."""
    whole_world = list(boxes) == [(-180.0, -90.0, 180.0, 90.0)]
    if zindex is None or (whole_world
                          and not (index_name == "z3" and intervals)):
        return None, None
    qr = getattr(zindex, "query_rows", None)
    if qr is None:
        rows = prune_candidates(zindex, index_name, boxes, intervals,
                                block_cap)
        return ("candidates", rows) if rows is not None else (None, None)
    return qr(index_name, boxes, intervals, host_cap, block_cap,
              cache=cache)


def prune_candidates(zindex, index_name: str, boxes, intervals,
                     max_rows: int | None) -> np.ndarray | None:
    """THE pruning policy, shared by every store and index family
    (z2/z3 point orders, xz2/xz3 extent orders): pick the
    spatio-temporal or spatial-only order for the strategy, skip
    pruning for unconstrained (whole-world, no-time) queries, and bail
    to a dense scan when the candidate set exceeds ``max_rows``.
    Returns candidate row indices or None (caller runs the dense path)."""
    whole_world = list(boxes) == [(-180.0, -90.0, 180.0, 90.0)]
    if zindex is None or (whole_world and not intervals):
        return None
    if index_name in ("z3", "xz3") and intervals:
        fn = getattr(zindex, f"candidates_{index_name}", None)
        return None if fn is None else fn(boxes, intervals,
                                          max_rows=max_rows)
    if not whole_world:
        spatial = "xz2" if index_name.startswith("xz") else "z2"
        fn = getattr(zindex, f"candidates_{spatial}", None)
        return None if fn is None else fn(boxes, max_rows=max_rows)
    return None


# cache-miss sentinel for ZKeyIndex._qcache (a stored None means "the
# decomposition chose the dense path", which is itself worth caching)
_QMISS = object()


class ZKeyIndex:
    """Sorted (bin, z3) and z2 key orders over point columns.

    Parameters are host arrays in insertion order; ``millis`` may be
    None for a time-less schema (z2 only).
    """

    def __init__(self, x: np.ndarray, y: np.ndarray,
                 millis: np.ndarray | None,
                 period: TimePeriod | str = TimePeriod.WEEK,
                 version: int = 2):
        self._x = np.asarray(x, dtype=np.float64)
        self._y = np.asarray(y, dtype=np.float64)
        self._millis = (None if millis is None
                        else np.asarray(millis, dtype=np.int64))
        self.period = TimePeriod.parse(period)
        # index layout version: 1 = legacy semi-normalized z3 curve
        # (curves/legacy.py), 2 = current. Sort orders and query ranges
        # must use the SAME curve or pruning silently drops rows.
        self.version = int(version)
        self.n = len(self._x)
        self._z3 = None  # (ubins, seg_offsets, z_sorted, perm)
        self._z2 = None  # (z_sorted, perm)
        # sorted-order coordinate copies, built on first search_*: the
        # candidate positions from range decomposition are CONTIGUOUS
        # runs in sorted order, so evaluating on x[perm]/y[perm] copies
        # turns the hot candidate pass from random gathers over the
        # full columns into sequential slices
        self._z3_coords = None  # (xs, ys, ms) in z3 order
        self._z2_coords = None  # (xs, ys) in z2 order
        self._z3_uses = 0       # exact-tier queries served per curve;
        self._z2_uses = 0       # gates the sorted-copy amortization
        # (boxes, intervals, caps) -> candidate positions: repeated
        # queries skip the range decomposition + seek (extend() returns
        # a NEW index, so entries never outlive the data they describe)
        self._qcache: "OrderedDict" = OrderedDict()
        self._qcache_n = 0  # total cached positions (byte bound)

    # -- build -------------------------------------------------------------

    # exact-tier queries per curve before the sorted-order coordinate
    # copies are worth their full-table build cost: the FIRST query
    # answers off the cheap per-candidate gather (cold start never pays
    # the full-table copies), any repeat usage amortizes them at once
    _COORDS_AFTER = 1

    def _perm_dtype(self):
        # XLA TPU gathers address with 32-bit indices, and a >=2^31-row
        # column set exceeds single-chip HBM anyway: larger tables must
        # shard over the mesh (store/mesh_store.py), which keeps every
        # per-device shard far below this cap.
        if self.n >= 2**31:
            raise ValueError(
                "single-shard table exceeds 2^31 rows; shard it over "
                "the mesh-distributed store instead")
        return np.int32

    def _sfc3(self):
        """The z3 curve for this index's layout version."""
        if self.version == 1:
            from ..curves.legacy import legacy_z3sfc
            return legacy_z3sfc(self.period)
        return z3sfc(self.period)

    def _build_z3(self):
        if self._z3 is not None or self._millis is None:
            return self._z3
        # the fused native encode implements only the CURRENT curve
        fused = (_native_encode_binned_z3(self._x, self._y, self._millis,
                                          self.period)
                 if self.version != 1 else None)
        if fused is not None:
            bins, z = fused
        else:
            sfc = self._sfc3()
            bins, offs = timebin.to_binned(self._millis, self.period,
                                           lenient=True)
            z = sfc.index(self._x, self._y, offs.astype(np.float64),
                          lenient=True).astype(np.int64)
        self._perm_dtype()  # enforce the row cap
        sorted_nat = _native_sort_bin_z(bins, z)
        if sorted_nat is not None:
            z_sorted, perm, ubins, seg_offsets = sorted_nat
        else:
            perm = np.lexsort((z, bins)).astype(np.int32)
            bins_sorted = bins[perm]
            z_sorted = z[perm]
            # per-bin contiguous segments in the sorted order
            ubins, seg_starts = np.unique(bins_sorted, return_index=True)
            seg_offsets = np.append(seg_starts, self.n)
        self._z3 = (ubins, seg_offsets, z_sorted, perm)
        return self._z3

    def _build_z2(self):
        if self._z2 is not None:
            return self._z2
        z = z2sfc().index(self._x, self._y, lenient=True).astype(np.int64)
        self._perm_dtype()  # enforce the row cap
        sorted_nat = _native_sort_z(z)
        if sorted_nat is not None:
            self._z2 = sorted_nat  # (z_sorted, perm)
        else:
            perm = np.argsort(z, kind="stable").astype(np.int32)
            self._z2 = (z[perm], perm)
        return self._z2

    # -- persistence (fs-store index sidecars) -----------------------------

    def state_dict(self) -> dict:
        """Built sort orders as plain arrays, for persistence next to
        the backing data (the fs store's index sidecars — the analog of
        the reference keeping its index *tables* durable while this
        design keeps device columns in insertion order plus a sorted
        host permutation). Only materialized orders are exported; the
        coordinate copies are cheap gathers and are rebuilt on demand."""
        out: dict = {}
        if self._z3 is not None:
            ubins, seg_offsets, z_sorted, perm = self._z3
            out.update(z3_ubins=ubins, z3_seg_offsets=seg_offsets,
                       z3_zsorted=z_sorted, z3_perm=perm)
        if self._z2 is not None:
            z_sorted, perm = self._z2
            out.update(z2_zsorted=z_sorted, z2_perm=perm)
        if out:
            out["index_version"] = np.array([self.version],
                                            dtype=np.int64)
        return out

    def warm(self) -> None:
        """Build the curve sort orders now (ingest-time indexing — the
        reference writes z-keys with every mutation, write path 3.2).
        Queries that arrive later find a ready index. The sorted-order
        coordinate copies stay deferred (see _COORDS_AFTER)."""
        if self._millis is not None:
            self._build_z3()
        self._build_z2()

    def load_state(self, state: dict) -> bool:
        """Install persisted sort orders (possibly memory-mapped).
        Returns False — installing nothing — when the arrays don't
        cover this table's rows (stale sidecar after writes) or were
        built under a different index layout version (a reindexed
        table must not adopt its pre-migration sort orders)."""
        persisted_v = int(np.asarray(
            state.get("index_version", [2]))[0])
        if persisted_v != self.version:
            return False
        self._qcache.clear()  # positions are per sort-order build
        self._qcache_n = 0
        ok = False
        if "z3_zsorted" in state and self._millis is not None:
            z_sorted, perm = state["z3_zsorted"], state["z3_perm"]
            if len(z_sorted) == self.n and len(perm) == self.n:
                self._z3 = (state["z3_ubins"], state["z3_seg_offsets"],
                            z_sorted, perm)
                ok = True
        if "z2_zsorted" in state:
            z_sorted, perm = state["z2_zsorted"], state["z2_perm"]
            if len(z_sorted) == self.n and len(perm) == self.n:
                self._z2 = (z_sorted, perm)
                ok = True
        return ok

    # -- incremental maintenance -------------------------------------------

    def extend(self, x: np.ndarray, y: np.ndarray,
               millis: np.ndarray | None) -> "ZKeyIndex":
        """New index covering the existing rows plus appended rows, with
        already-built sort orders MERGED (sorted-run merge: O(N) memcpy
        + O(D log D) delta sort) instead of re-sorted from scratch — the
        LSM-style write path the reference gets from its backing stores'
        minor compactions (BatchWriter mutations merging into tablets).
        """
        if (self._millis is None) != (millis is None):
            raise ValueError("time column presence must match")
        out = ZKeyIndex.__new__(ZKeyIndex)
        out._x = np.concatenate([self._x, np.asarray(x, dtype=np.float64)])
        out._y = np.concatenate([self._y, np.asarray(y, dtype=np.float64)])
        out._millis = (None if millis is None else np.concatenate(
            [self._millis, np.asarray(millis, dtype=np.int64)]))
        out.period = self.period
        out.version = self.version
        out.n = len(out._x)
        out._qcache = OrderedDict()
        out._qcache_n = 0
        out._z3_uses = self._z3_uses
        out._z2_uses = self._z2_uses
        out._perm_dtype()  # enforce the row cap before any merge work
        # built coord copies merge via the same inserts (delta-sized
        # sort + O(N) memcpy); unbuilt ones stay lazy
        out._z3, out._z3_coords = (self._merged_z3(x, y, millis)
                                   if self._z3 else (None, None))
        out._z2, out._z2_coords = (self._merged_z2(x, y)
                                   if self._z2 else (None, None))
        return out

    def _merged_z2(self, x, y):
        """Returns ((z_sorted, perm), coords_or_None)."""
        z_sorted, perm = self._z2
        x = np.asarray(x, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64)
        dz = z2sfc().index(x, y, lenient=True).astype(np.int64)
        dorder = np.argsort(dz, kind="stable")
        dzs = dz[dorder]
        # side="right": appended rows land after equal existing keys,
        # preserving stable insertion order
        pos = np.searchsorted(z_sorted, dzs, side="right")
        new_z = np.insert(z_sorted, pos, dzs)
        new_perm = np.insert(perm, pos,
                             (dorder + self.n).astype(perm.dtype))
        coords = None
        if self._z2_coords is not None:
            xs, ys = self._z2_coords
            coords = (np.insert(xs, pos, x[dorder]),
                      np.insert(ys, pos, y[dorder]))
        return (new_z, new_perm), coords

    def _merged_z3(self, x, y, millis):
        """Returns ((ubins, seg_offsets, z_sorted, perm), coords)."""
        ubins, seg_offsets, z_sorted, perm = self._z3
        sfc = self._sfc3()
        x = np.asarray(x, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64)
        millis = np.asarray(millis, dtype=np.int64)
        dbins, doffs = timebin.to_binned(millis, self.period, lenient=True)
        dz = sfc.index(x, y, doffs.astype(np.float64),
                       lenient=True).astype(np.int64)
        dorder = np.lexsort((dz, dbins))
        dbs, dzs = dbins[dorder], dz[dorder]
        pos = np.empty(len(dbs), dtype=np.int64)
        # per-unique-delta-bin: binary search within the bin's segment
        # (few distinct bins per write burst)
        for b in np.unique(dbs):
            m = dbs == b
            i = int(np.searchsorted(ubins, b))
            if i < len(ubins) and int(ubins[i]) == b:
                s, e = int(seg_offsets[i]), int(seg_offsets[i + 1])
                pos[m] = s + np.searchsorted(z_sorted[s:e], dzs[m],
                                             side="right")
            else:
                # a bin the table has not seen: insert at the boundary
                pos[m] = int(seg_offsets[i])
        counts = np.diff(seg_offsets)
        bins_sorted = np.repeat(ubins, counts)
        new_z = np.insert(z_sorted, pos, dzs)
        new_bins = np.insert(bins_sorted, pos, dbs)
        new_perm = np.insert(perm, pos,
                             (dorder + self.n).astype(perm.dtype))
        # new_bins is sorted: segment bounds from value changes, no sort
        steps = np.flatnonzero(new_bins[1:] != new_bins[:-1]) + 1
        seg_starts = np.concatenate([[0], steps])
        ubins2 = new_bins[seg_starts]
        seg_offsets2 = np.append(seg_starts, len(new_bins))
        coords = None
        if self._z3_coords is not None:
            xs, ys, ms = self._z3_coords
            coords = (
                np.insert(xs, pos, x[dorder]),
                np.insert(ys, pos, y[dorder]),
                None if ms is None else np.insert(ms, pos,
                                                  millis[dorder]))
        return (ubins2, seg_offsets2, new_z, new_perm), coords

    def _gather_coords(self, perm: np.ndarray, with_ms: bool):
        """Sorted-order coordinate copies — the native fused gather
        reads ``perm`` once per row and fills every output with
        sequential writes across threads; numpy fallback pays one
        single-threaded random gather per column (the difference is
        seconds of first-query latency at 100M rows)."""
        ms = self._millis if with_ms else None
        lib = _native_sort_lib()
        import os
        # single-core hosts: numpy's tuned per-array take beats the
        # fused interleaved loop (3 random streams thrash one cache);
        # the fused pass wins only when threads split the row range
        if (os.cpu_count() or 1) > 1 and lib is not None and len(perm) \
                and perm.dtype == np.int32 \
                and hasattr(lib, "geomesa_gather_xyz"):
            import ctypes
            n = len(perm)
            x = np.ascontiguousarray(self._x)
            y = np.ascontiguousarray(self._y)
            p = np.ascontiguousarray(perm)
            xo = np.empty(n, dtype=np.float64)
            yo = np.empty(n, dtype=np.float64)
            dp = ctypes.POINTER(ctypes.c_double)
            mo = None
            msp = ctypes.cast(None, ctypes.POINTER(ctypes.c_int64))
            mop = msp
            if ms is not None:
                mo = np.empty(n, dtype=np.int64)
                msp = _i64p(np.ascontiguousarray(ms))
                mop = _i64p(mo)
            rc = lib.geomesa_gather_xyz(
                x.ctypes.data_as(dp), y.ctypes.data_as(dp), msp,
                _i32p(p), n, xo.ctypes.data_as(dp),
                yo.ctypes.data_as(dp), mop)
            if rc == 0:
                return (xo, yo, mo) if with_ms else (xo, yo)
        if with_ms:
            return (self._x[perm], self._y[perm],
                    None if ms is None else ms[perm])
        return (self._x[perm], self._y[perm])

    # -- exact search (host fast path) -------------------------------------

    @staticmethod
    def _eval_sorted(xs, ys, ms, pos, boxes, intervals_ms) -> np.ndarray:
        """Exact f64 evaluation over sorted-order positions; identical
        semantics to zscan.exact_patch (inclusive box bounds, inclusive
        [lo, hi] millis intervals). Returns keep mask over pos."""
        x = xs[pos]
        y = ys[pos]
        keep = np.zeros(len(pos), dtype=bool)
        for xmin, ymin, xmax, ymax in boxes:
            keep |= ((x >= xmin) & (x <= xmax)
                     & (y >= ymin) & (y <= ymax))
        if intervals_ms and ms is not None:
            m = ms[pos]
            tk = np.zeros(len(pos), dtype=bool)
            for lo, hi in intervals_ms:
                tk |= (m >= lo) & (m <= hi)
            keep &= tk
        return keep

    def query_rows(self, index_name: str, boxes, intervals_ms,
                   host_cap: int | None, block_cap: int | None,
                   max_ranges: int | None = None, cache: bool = True):
        """ONE range decomposition serving both tiers: returns
        ("exact", rows) when the candidate positions fit ``host_cap``
        (exact evaluation over sorted-order coordinate copies —
        sequential access), ("candidates", rows) when they fit only
        ``block_cap`` (caller runs the gathered device scan), or
        (None, None) for the dense path. ``cache=False`` neither reads
        nor writes the decomposition cache (one-shot probe boxes)."""
        use_z3 = index_name == "z3" and bool(intervals_ms)
        # the z2 order cannot evaluate time: with intervals present but
        # no z3 order in play, results may only be CANDIDATES (the
        # caller's scan re-checks time), never "exact"
        exact_ok = use_z3 or not intervals_ms
        # decomposition + seek cache: the candidate POSITIONS (not the
        # final rows) are deterministic per sort-order snapshot, so a
        # repeated query skips the z-range decomposition and the
        # searchsorted seeks; the exact evaluation below still runs —
        # the cache holds the plan's ranges, the scan stays a scan
        if max_ranges is None:
            # the host tiers re-check every candidate exactly, so a
            # coarse cover only grows the (small) candidate set while
            # the range decomposition is a PER-QUERY cost — a deep
            # 2000-range BFS spends more than the extra candidates save
            # on selective query streams (the coarsening knob the
            # reference turns with SCAN_RANGES_TARGET)
            from ..utils.properties import HOST_RANGES_TARGET
            max_ranges = int(HOST_RANGES_TARGET.get())
        qkey = (use_z3, tuple(boxes),
                tuple(tuple(i) for i in intervals_ms),
                block_cap, max_ranges)
        hit = self._qcache.get(qkey, _QMISS) if cache else _QMISS
        if hit is not _QMISS:
            pos = hit
            if use_z3:
                _, _, _, perm = self._build_z3()
            else:
                _, perm = self._build_z2()
        elif use_z3:
            built = self._build_z3()
            if built is None:
                return None, None
            ubins, seg_offsets, z_sorted, perm = built
            sfc = self._sfc3()
            pos = binned_candidate_positions(
                ubins, seg_offsets, z_sorted, intervals_ms, self.period,
                lambda key: sfc.ranges(boxes, [key],
                                       max_ranges=max_ranges),
                block_cap)
        else:
            z_sorted, perm = self._build_z2()
            ranges = z2sfc().ranges(boxes, max_ranges=max_ranges)
            los = np.searchsorted(z_sorted, ranges[:, 0], side="left")
            his = np.searchsorted(z_sorted, ranges[:, 1], side="right")
            if block_cap is not None \
                    and int(np.sum(his - los)) > block_cap:
                pos = None
            else:
                pos = multi_arange(los, his)
        if cache and hit is _QMISS and (pos is None
                                        or len(pos) <= 262_144):
            # bounded in BYTES, not just entries: evict oldest until the
            # retained position arrays fit ~16MB (2M int64 positions)
            self._qcache_n += 0 if pos is None else len(pos)
            while (len(self._qcache) >= 64
                   or self._qcache_n > 2_097_152):
                _, old = self._qcache.popitem(last=False)
                if old is not None:
                    self._qcache_n -= len(old)
            self._qcache[qkey] = pos
        if pos is None:
            return None, None
        if not exact_ok:
            return "candidates", perm[pos].astype(np.int64)
        if not len(pos):
            return "exact", np.empty(0, dtype=np.int64)
        if host_cap is not None and len(pos) > host_cap:
            return "candidates", perm[pos].astype(np.int64)
        # sorted-order coordinate copies turn the candidate pass into
        # sequential slices, but building them costs full-table gathers
        # (~10s at 100M rows) — far more than a first query needs. Early
        # queries evaluate on a per-candidate gather (O(|pos|)); the
        # copies build only once the curve has served enough queries to
        # amortize them.
        if use_z3:
            coords, ivals = self._z3_coords, intervals_ms
            self._z3_uses += 1 if cache else 0
            if coords is None and self._z3_uses > self._COORDS_AFTER:
                coords = self._z3_coords = self._gather_coords(perm, True)
        else:
            coords, ivals = self._z2_coords, []
            # one-shot probe loops (cache=False, e.g. KNN rings) must
            # not trip the amortization gate: their boxes never repeat
            self._z2_uses += 1 if cache else 0
            if coords is None and self._z2_uses > self._COORDS_AFTER:
                coords = self._z2_coords = self._gather_coords(perm, False)
        if coords is not None:
            xs, ys = coords[0], coords[1]
            ms = coords[2] if use_z3 else None
            keep = self._eval_sorted(xs, ys, ms, pos, boxes, ivals)
            return "exact", np.sort(perm[pos[keep]].astype(np.int64))
        rows = perm[pos]
        keep = self._eval_sorted(self._x, self._y,
                                 self._millis if use_z3 else None,
                                 rows, boxes, ivals)
        return "exact", np.sort(rows[keep].astype(np.int64))


    # -- candidates --------------------------------------------------------

    def candidates_z3(self, boxes, intervals_ms, *,
                      max_rows: int | None = None,
                      max_ranges: int | None = None) -> np.ndarray | None:
        """Candidate original-order row indices for boxes + intervals, or
        None when the z3 order is unavailable / the set exceeds max_rows.

        Mirrors the per-bin fan-out of Z3IndexKeySpace.getRanges
        (:100-136): interior bins use whole-period ranges (computed
        once), edge bins their partial-offset ranges.
        """
        built = self._build_z3()
        if built is None:
            return None
        ubins, seg_offsets, z_sorted, perm = built
        sfc = self._sfc3()
        pos = binned_candidate_positions(
            ubins, seg_offsets, z_sorted, intervals_ms, self.period,
            lambda key: sfc.ranges(boxes, [key], max_ranges=max_ranges),
            max_rows)
        if pos is None:
            return None
        if not len(pos):
            return np.empty(0, dtype=np.int64)
        return perm[pos].astype(np.int64)

    def candidates_z2(self, boxes, *, max_rows: int | None = None,
                      max_ranges: int | None = None) -> np.ndarray | None:
        """Candidate rows for a pure-spatial query via the z2 order."""
        z_sorted, perm = self._build_z2()
        ranges = z2sfc().ranges(boxes, max_ranges=max_ranges)
        if len(ranges) == 0:
            return np.empty(0, dtype=np.int64)
        los = np.searchsorted(z_sorted, ranges[:, 0], side="left")
        his = np.searchsorted(z_sorted, ranges[:, 1], side="right")
        if max_rows is not None and int(np.sum(his - los)) > max_rows:
            return None
        pos = multi_arange(los, his)
        if len(pos) == 0:
            return np.empty(0, dtype=np.int64)
        return perm[pos].astype(np.int64)
