"""Host-side sorted XZ-key index: extent-geometry range pruning.

The analog of the reference's XZ2/XZ3 index key spaces
(/root/reference/geomesa-index-api/src/main/scala/org/locationtech/
geomesa/index/index/z2/XZ2IndexKeySpace.scala,
.../z3/XZ3IndexKeySpace.scala; curve math XZ2SFC.scala:146-252): extent
geometries key by their XZ sequence code (from the bounding box), the
table sorts by [time bin][code], and a query decomposes into covering
code ranges so scans touch only intersecting candidates.

Same architecture as the point-geometry ZKeyIndex (index/zkeys.py):
device/host columns stay in insertion order; the sorted thing is a
host key array + permutation; candidate sets over-approximate and an
exact predicate always re-checks them.
"""

from __future__ import annotations

import numpy as np

from ..curves import timebin
from ..curves.timebin import TimePeriod
from ..curves.xz import xz2sfc, xz3sfc
from .zkeys import binned_candidate_positions, multi_arange

__all__ = ["XZKeyIndex"]


class XZKeyIndex:
    """Sorted xz2 / (bin, xz3) code orders over extent bounds.

    ``bounds`` is the (n, 4) xmin/ymin/xmax/ymax array (nan rows =
    null geometries, never candidates); ``millis`` may be None for a
    time-less schema (xz2 only).
    """

    def __init__(self, bounds: np.ndarray, millis: np.ndarray | None,
                 period: TimePeriod | str = TimePeriod.WEEK):
        self._bounds = np.asarray(bounds, dtype=np.float64)
        self._millis = (None if millis is None
                        else np.asarray(millis, dtype=np.int64))
        self.period = TimePeriod.parse(period)
        self.n = len(self._bounds)
        self._valid = ~np.isnan(self._bounds[:, 0])
        # lenient indexing clamps out-of-domain bounds, so such rows
        # could land outside a query's covering ranges: they stay
        # unconditional candidates instead
        b = self._bounds
        esc = self._valid & ((b[:, 0] < -180) | (b[:, 1] < -90)
                             | (b[:, 2] > 180) | (b[:, 3] > 90))
        self._escape = np.flatnonzero(esc).astype(np.int64)
        self._valid = self._valid & ~esc
        self._xz2 = None  # (codes_sorted, perm)
        self._xz3 = None  # (ubins, seg_offsets, codes_sorted, perm)

    # -- build -------------------------------------------------------------

    def _build_xz2(self):
        if self._xz2 is None:
            rows = np.flatnonzero(self._valid)
            b = self._bounds[rows]
            codes = xz2sfc().index_boxes(b[:, 0], b[:, 1], b[:, 2],
                                         b[:, 3], lenient=True)
            order = np.argsort(codes, kind="stable")
            self._xz2 = (codes[order], rows[order].astype(np.int64))
        return self._xz2

    def _build_xz3(self):
        if self._xz3 is None and self._millis is not None:
            rows = np.flatnonzero(self._valid)
            b = self._bounds[rows]
            bins, offs = timebin.to_binned(self._millis[rows], self.period,
                                           lenient=True)
            off = offs.astype(np.float64)
            sfc = xz3sfc(period=self.period)
            codes = sfc.index_boxes(b[:, 0], b[:, 1], off,
                                    b[:, 2], b[:, 3], off, lenient=True)
            perm = np.lexsort((codes, bins))
            bins_s = bins[perm]
            ubins, seg_starts = np.unique(bins_s, return_index=True)
            self._xz3 = (ubins, np.append(seg_starts, len(bins_s)),
                         codes[perm], rows[perm].astype(np.int64))
        return self._xz3

    # -- candidates --------------------------------------------------------

    def candidates_xz2(self, boxes, *, max_rows: int | None = None,
                       max_ranges: int | None = None) -> np.ndarray | None:
        """Candidate rows whose extent may intersect any query box."""
        codes_sorted, perm = self._build_xz2()
        ranges = xz2sfc().ranges(
            [(b[0], b[1], b[2], b[3]) for b in boxes],
            max_ranges=max_ranges)
        if len(ranges) == 0:
            return np.empty(0, dtype=np.int64)
        los = np.searchsorted(codes_sorted, ranges[:, 0], side="left")
        his = np.searchsorted(codes_sorted, ranges[:, 1], side="right")
        # escape rows count against the cap: they join every candidate
        # set unconditionally
        if max_rows is not None and \
                int(np.sum(his - los)) + len(self._escape) > max_rows:
            return None
        pos = multi_arange(los, his)
        cand = perm[pos] if len(pos) else np.empty(0, dtype=np.int64)
        return np.unique(np.concatenate([cand, self._escape]))

    def candidates_xz3(self, boxes, intervals_ms, *,
                       max_rows: int | None = None,
                       max_ranges: int | None = None) -> np.ndarray | None:
        """Per-time-bin fan-out like the z3 index: interior bins use
        whole-period windows, edge bins their partial offsets."""
        built = self._build_xz3()
        if built is None:
            return None
        ubins, seg_offsets, codes_sorted, perm = built
        sfc = xz3sfc(period=self.period)

        def range_fn(key):
            lo_off, hi_off = key
            return sfc.ranges(
                [(bx[0], bx[1], float(lo_off),
                  bx[2], bx[3], float(hi_off)) for bx in boxes],
                max_ranges=max_ranges)

        pos = binned_candidate_positions(
            ubins, seg_offsets, codes_sorted, intervals_ms, self.period,
            range_fn, max_rows,
            base_total=len(self._escape))  # escapes count against cap
        if pos is None:
            return None
        cand = perm[pos] if len(pos) else np.empty(0, dtype=np.int64)
        return np.unique(np.concatenate([cand, self._escape]))
