"""Sorted-attribute secondary index: the AttributeIndex analog.

The reference's attribute index stores rows keyed by
[attribute value][secondary date/z][feature id] and answers attribute
predicates with key-range scans, then joins the matching ids back to the
record table (/root/reference/geomesa-accumulo/geomesa-accumulo-datastore/
src/main/scala/org/locationtech/geomesa/accumulo/index/AttributeIndex.scala:386-395,
AttributeIndexKeySpace value-to-bytes encoding).

Columnar analog: one sorted permutation per indexed attribute. Typed
bounds from ``extract_attribute_bounds`` binary-search into the sorted
key array, yielding contiguous slices of the permutation — row indices
into the main columns. That gather IS the positional join the
reference's BatchMultiScanner performs across tables; here both "tables"
are columns of the same batch so the join is an index operation.

Dictionary-encoded strings never materialize: bounds are translated to
code-space thresholds against the sorted vocab (the ArrowFilterOptimizer
trick, /root/reference/geomesa-arrow/geomesa-arrow-gt/src/main/scala/org/
locationtech/geomesa/arrow/filter/ArrowFilterOptimizer.scala:36), so a
string range scan is an integer binary search.
"""

from __future__ import annotations

import numpy as np

from ..features.batch import (BoolColumn, Column, DateColumn, NumericColumn,
                              StringColumn)
from ..filters.helper import Bound, FilterValues, to_millis

__all__ = ["AttributeKeyIndex"]


class AttributeKeyIndex:
    """Sorted permutation over one column; bounds -> candidate rows."""

    def __init__(self, col: Column):
        if isinstance(col, NumericColumn):
            keys = col.values
            self._kind = "num"
        elif isinstance(col, DateColumn):
            keys = col.millis
            self._kind = "date"
        elif isinstance(col, StringColumn):
            # codes index a sorted vocab, so code order == lexicographic
            keys = col.codes
            self._kind = "str"
            self._vocab = col.vocab.astype(str)
        elif isinstance(col, BoolColumn):
            keys = col.values.astype(np.int8)
            self._kind = "bool"
        else:
            raise TypeError(f"cannot index {type(col).__name__}")
        rows = np.flatnonzero(col.valid)  # nulls are not indexed
        order = np.argsort(keys[rows], kind="stable")
        self.sorted_keys = keys[rows][order]
        self.sorted_rows = rows[order]

    @property
    def n(self) -> int:
        return len(self.sorted_rows)

    # -- bound translation --------------------------------------------------

    def _pos(self, bound: Bound, *, lower: bool) -> int:
        """Permutation position for one side of a Bounds interval."""
        if not bound.is_bounded:
            return 0 if lower else self.n
        v = bound.value
        if self._kind == "str":
            # code-space threshold t: lower keeps codes >= t, upper keeps
            # codes < t; inclusivity is absorbed by the vocab search side
            s = str(v)
            if lower:
                side = "left" if bound.inclusive else "right"
            else:
                side = "right" if bound.inclusive else "left"
            t = int(np.searchsorted(self._vocab, s, side=side))
            return int(np.searchsorted(self.sorted_keys, t, side="left"))
        if self._kind == "date":
            v = to_millis(v)
        elif self._kind == "bool":
            v = int(bool(v))
        if lower:
            side = "left" if bound.inclusive else "right"
        else:
            side = "right" if bound.inclusive else "left"
        return int(np.searchsorted(self.sorted_keys, v, side=side))

    # -- query --------------------------------------------------------------

    def candidates(self, bounds: FilterValues,
                   max_rows: int | None = None) -> np.ndarray | None:
        """Sorted row indices whose value falls in any of the bounds.

        Returns None when the bounds cannot be answered by range scans
        (empty/unbounded extraction), or when the candidate set exceeds
        ``max_rows`` — wide bounds cost more to gather + re-evaluate than
        a dense column scan, the same crossover the z index applies via
        SCAN_BLOCK_THRESHOLD (index/zkeys.py).
        """
        if bounds.disjoint:
            return np.empty(0, dtype=np.int64)
        if bounds.is_empty or not any(b.is_bounded for b in bounds):
            return None
        slices = []
        total = 0
        for b in bounds:
            lo = self._pos(b.lower, lower=True)
            hi = self._pos(b.upper, lower=False)
            if hi > lo:
                total += hi - lo
                if max_rows is not None and total > max_rows:
                    return None
                slices.append(self.sorted_rows[lo:hi])
        if not slices:
            return np.empty(0, dtype=np.int64)
        rows = np.concatenate(slices)
        # OR'd bounds are union-merged upstream but may still touch after
        # code-space rounding; unique sorts + dedupes in one pass
        return np.unique(rows)
