"""Sorted-attribute secondary index: the AttributeIndex analog.

The reference's attribute index stores rows keyed by
[attribute value][secondary date/z][feature id] and answers attribute
predicates with key-range scans, then joins the matching ids back to the
record table (/root/reference/geomesa-accumulo/geomesa-accumulo-datastore/
src/main/scala/org/locationtech/geomesa/accumulo/index/AttributeIndex.scala:386-395,
AttributeIndexKeySpace value-to-bytes encoding). The [secondary date]
tier is reproduced here: when the schema has a default date, keys sort
by (value, millis), and equality scans narrow their slice with the
filter's date bounds before the positional join — the reference's
secondary key-space range tightening
(geomesa-index-api/.../index/AttributeIndex.scala:40,124-158).

Columnar analog: one sorted permutation per indexed attribute. Typed
bounds from ``extract_attribute_bounds`` binary-search into the sorted
key array, yielding contiguous slices of the permutation — row indices
into the main columns. That gather IS the positional join the
reference's BatchMultiScanner performs across tables; here both "tables"
are columns of the same batch so the join is an index operation.

Dictionary-encoded strings never materialize: bounds are translated to
code-space thresholds against the sorted vocab (the ArrowFilterOptimizer
trick, /root/reference/geomesa-arrow/geomesa-arrow-gt/src/main/scala/org/
locationtech/geomesa/arrow/filter/ArrowFilterOptimizer.scala:36), so a
string range scan is an integer binary search.
"""

from __future__ import annotations

import numpy as np

from ..features.batch import (BoolColumn, Column, DateColumn, NumericColumn,
                              StringColumn)
from ..filters.helper import Bound, FilterValues, to_millis

__all__ = ["AttributeKeyIndex"]


class AttributeKeyIndex:
    """Sorted permutation over one column — keyed (value, date) when a
    secondary date column is supplied; bounds -> candidate rows."""

    def __init__(self, col: Column, date_millis: np.ndarray | None = None):
        if isinstance(col, NumericColumn):
            keys = col.values
            self._kind = "num"
        elif isinstance(col, DateColumn):
            keys = col.millis
            self._kind = "date"
        elif isinstance(col, StringColumn):
            # codes index a sorted vocab, so code order == lexicographic
            keys = col.codes
            self._kind = "str"
            self._vocab = col.vocab.astype(str)
        elif isinstance(col, BoolColumn):
            keys = col.values.astype(np.int8)
            self._kind = "bool"
        else:
            raise TypeError(f"cannot index {type(col).__name__}")
        rows = np.flatnonzero(col.valid)  # nulls are not indexed
        if date_millis is not None:
            dm = np.asarray(date_millis, np.int64)[rows]
            order = np.lexsort((dm, keys[rows]))
            self.sorted_millis = dm[order]
        else:
            order = np.argsort(keys[rows], kind="stable")
            self.sorted_millis = None
        self.sorted_keys = keys[rows][order]
        self.sorted_rows = rows[order]

    @property
    def n(self) -> int:
        return len(self.sorted_rows)

    # -- bound translation --------------------------------------------------

    def _pos(self, bound: Bound, *, lower: bool) -> int:
        """Permutation position for one side of a Bounds interval."""
        if not bound.is_bounded:
            return 0 if lower else self.n
        v = bound.value
        if self._kind == "str":
            # code-space threshold t: lower keeps codes >= t, upper keeps
            # codes < t; inclusivity is absorbed by the vocab search side
            s = str(v)
            if lower:
                side = "left" if bound.inclusive else "right"
            else:
                side = "right" if bound.inclusive else "left"
            t = int(np.searchsorted(self._vocab, s, side=side))
            return int(np.searchsorted(self.sorted_keys, t, side="left"))
        if self._kind == "date":
            v = to_millis(v)
        elif self._kind == "bool":
            v = int(bool(v))
        if lower:
            side = "left" if bound.inclusive else "right"
        else:
            side = "right" if bound.inclusive else "left"
        return int(np.searchsorted(self.sorted_keys, v, side=side))

    # -- query --------------------------------------------------------------

    @staticmethod
    def _is_point_bound(b) -> bool:
        """An equality bound [v, v]: its slice holds ONE value, so the
        (value, date) composite order is date-sorted within it and the
        secondary tier can range-scan the date."""
        return (b.lower.is_bounded and b.upper.is_bounded
                and b.lower.inclusive and b.upper.inclusive
                and b.lower.value == b.upper.value)

    def candidates(self, bounds: FilterValues,
                   max_rows: int | None = None,
                   intervals_ms=None) -> np.ndarray | None:
        """Sorted row indices whose value falls in any of the bounds,
        with equality slices narrowed to ``intervals_ms`` (inclusive
        [lo, hi] epoch-millis pairs) via the secondary date key.

        Returns None when the bounds cannot be answered by range scans
        (empty/unbounded extraction), or when the candidate set exceeds
        ``max_rows`` — wide bounds cost more to gather + re-evaluate than
        a dense column scan, the same crossover the z index applies via
        SCAN_BLOCK_THRESHOLD (index/zkeys.py).
        """
        if bounds.disjoint:
            return np.empty(0, dtype=np.int64)
        if bounds.is_empty or not any(b.is_bounded for b in bounds):
            return None
        slices = []
        total = 0
        for b in bounds:
            lo = self._pos(b.lower, lower=True)
            hi = self._pos(b.upper, lower=False)
            if hi <= lo:
                continue
            if (intervals_ms and self.sorted_millis is not None
                    and self._is_point_bound(b)):
                seg = self.sorted_millis[lo:hi]
                for iv_lo, iv_hi in intervals_ms:
                    s = lo + int(np.searchsorted(seg, iv_lo, side="left"))
                    e = lo + int(np.searchsorted(seg, iv_hi, side="right"))
                    if e > s:
                        total += e - s
                        if max_rows is not None and total > max_rows:
                            return None
                        slices.append(self.sorted_rows[s:e])
                continue
            total += hi - lo
            if max_rows is not None and total > max_rows:
                return None
            slices.append(self.sorted_rows[lo:hi])
        if not slices:
            return np.empty(0, dtype=np.int64)
        rows = np.concatenate(slices)
        # OR'd bounds are union-merged upstream but may still touch after
        # code-space rounding (and date intervals may overlap); unique
        # sorts + dedupes in one pass
        return np.unique(rows)
