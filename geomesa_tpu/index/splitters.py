"""Table pre-split strategies (index/conf/Splitters.scala:16-45).

The reference computes initial tablet/region split keys so new tables
start distributed; here the same split keys seed the sharded store's
partition boundaries (a new type's rows hash/range across shards from
the first write instead of after a re-balance).
"""

from __future__ import annotations

__all__ = ["DigitSplitter", "HexSplitter", "AlphaNumericSplitter",
           "NoSplitter", "splitter_for"]


class DigitSplitter:
    """Numeric split points: options fmt (printf), min, max
    (Splitters.scala:16-27)."""

    def get_splits(self, options: dict | None = None) -> list[bytes]:
        options = options or {}
        fmt = options.get("fmt", "%01d")
        lo = int(options.get("min", 0))
        hi = int(options.get("max", 0))
        return [(fmt % i).encode() for i in range(lo, hi + 1)]


class HexSplitter:
    """Hex character split points; 0 omitted to avoid an empty initial
    shard (Splitters.scala:29-33)."""

    _splits = [c.encode() for c in "123456789abcdefABCDEF"]

    def get_splits(self, options: dict | None = None) -> list[bytes]:
        return list(self._splits)


class AlphaNumericSplitter:
    """[1-9a-zA-Z] single-character split points
    (Splitters.scala:35-39)."""

    _splits = [c.encode() for c in
               "123456789abcdefghijklmnopqrstuvwxyz"
               "ABCDEFGHIJKLMNOPQRSTUVWXYZ"]

    def get_splits(self, options: dict | None = None) -> list[bytes]:
        return list(self._splits)


class NoSplitter:
    def get_splits(self, options: dict | None = None) -> list[bytes]:
        return []


_REGISTRY = {
    "digit": DigitSplitter,
    "hex": HexSplitter,
    "alphanumeric": AlphaNumericSplitter,
    "none": NoSplitter,
}


def splitter_for(name: str):
    """Splitter by short name (the SFT user-data `table.splitter.class`
    analog)."""
    try:
        return _REGISTRY[name.lower()]()
    except KeyError:
        raise ValueError(f"unknown splitter '{name}'; "
                         f"one of {sorted(_REGISTRY)}") from None
