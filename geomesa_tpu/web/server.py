"""REST endpoints over a datastore (geomesa-web analog).

Mirrors the reference's servlet surface (geomesa-web-core
SpringScalatraBootstrap.scala:69, DataEndpoint, GeoMesaStatsEndpoint
web/stats/GeoMesaStatsEndpoint.scala:30, QueryAuditEndpoint) on the
stdlib http server — no framework dependency:

    GET  /rest/version
    GET  /rest/schemas                      -> ["type", ...]
    POST /rest/schemas/{type}   body=spec   -> create schema
    GET  /rest/schemas/{type}               -> {"name":..., "spec":...}
    DELETE /rest/schemas/{type}
    GET  /rest/query/{type}?cql=&maxFeatures=&sortBy=&sortOrder=
         &sampling=&sampleBy=&index=&auths=&format=json|geojson|arrow
         (the trailing params are the ViewParams-style hint mappings)
    GET  /rest/knn/{type}?x=&y=&k=          -> {"ids": [...], "distances": [...]}
    GET  /rest/stats/{type}?stat=MinMax(attr)&cql=
    GET  /rest/density/{type}?bbox=x0,y0,x1,y1&width=&height=&cql=
    GET  /rest/bin/{type}?cql=&track=&label=&sort=   -> BIN bytes
    GET  /rest/metrics                      -> metrics registry snapshot
    GET  /rest/runtime                      -> compile/device/transfer
                                               telemetry snapshot
    GET  /rest/slo                          -> SLO burn-rate/alert state
    GET  /rest/qos                          -> per-tenant QoS state
                                               (tenants plane)
    GET  /rest/profile                      -> collapsed-stack profile
                                               (?format=json for stats)
    GET  /rest/cache                        -> materialized-cache status
    POST /rest/cache/invalidate?type=       (bearer-gated)
    GET  /rest/sql?q=SELECT...  (or POST /rest/sql, body = statement)
    GET  /rest/audit?type=&since=
    GET  /rest/wal                          -> journal/WAL stats
    POST /rest/wal/checkpoint               (bearer-gated)
    POST /rest/wal/truncate?below=LSN       (bearer-gated)
    GET  /rest/replication                  -> router/shipper status
    POST /rest/replication/promote          (bearer-gated failover)
    GET  /rest/health                       -> liveness (always 200)
    GET  /rest/ready                        -> readiness (503 if the
         store is unreachable or the server is shedding load)

Queries run the normal planner/scan path; arrow responses stream IPC
bytes (content-type application/vnd.apache.arrow.file).

Conditional requests: GET /rest/density, /rest/stats and /rest/bin
carry an ETag derived from (canonical plan key, pushdown version) when
the store exposes an exact version (``pushdown_version``); a matching
If-None-Match gets 304 with no body. Stores without a single exact
version (the replication router, the cluster coordinator) send no ETag
— a 304 there could lie when differently-lagged members answer.

Fault surface (resilience layer):

- `geomesa.web.max.inflight` (unset = unlimited) caps concurrent
  requests; excess requests are SHED with 503 + Retry-After before any
  handler runs, so a retried shed is duplicate-safe even for writes.
- Status codes distinguish retryability for clients: parse/plan errors
  (ValueError, CQL/filter parse) are 400 (don't retry), unknown types
  404, unexpected handler faults 500 (retryable on idempotent calls).
- A client that disconnects mid-response (BrokenPipeError) is counted
  (`resilience.web.client_disconnects`), not traceback-dumped.
"""

from __future__ import annotations

import json
import random
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse

import numpy as np

from .. import __version__ as _version
from ..index.api import Query, QueryHints
from ..metrics import metrics
from ..utils.properties import SystemProperty
from ..wal.log import DurabilityError

__all__ = ["GeoMesaWebServer"]

# opt-in shared bearer token for the mutating endpoints (POST
# /rest/write, POST /rest/delete, DELETE /rest/schemas). Unset -> those
# endpoints stay open (embedded/test deployments); set -> requests
# without `Authorization: Bearer <token>` get 403.
WEB_AUTH_TOKEN = SystemProperty("geomesa.web.auth.token", None)

# the endpoints the shared token gates: (method, first path segment) —
# POST /rest/wal/* are the WAL admin mutations (checkpoint/truncate);
# GET /rest/wal stays open (read-only stats)
_GATED = {("POST", "write"), ("POST", "delete"), ("DELETE", "schemas"),
          ("POST", "wal"), ("POST", "replication"), ("POST", "integrity"),
          ("POST", "cluster"), ("POST", "cache"), ("POST", "cq"),
          ("POST", "reshard"), ("POST", "views"), ("POST", "reindex"),
          ("POST", "evolve")}

# load-shedding gate: max concurrent in-flight requests (unset ->
# unlimited). Requests over the cap get 503 + Retry-After BEFORE any
# handler state changes, so clients may retry them safely.
WEB_MAX_INFLIGHT = SystemProperty("geomesa.web.max.inflight", None)
# label web.request series with the caller's principal digest (the
# first step toward per-tenant QoS accounting). Default off: it
# multiplies series cardinality by the tenant count — the registry's
# geomesa.metrics.max.series guard bounds the blast radius when on
WEB_METRICS_PRINCIPAL = SystemProperty("geomesa.metrics.principal",
                                       "false")
# the Retry-After hint (seconds) a shed response carries
WEB_RETRY_AFTER = SystemProperty("geomesa.web.retry.after.s", "1")

# route POST /rest/write through the process ingest pipeline: writes
# from concurrent clients coalesce into group commits (one WAL append /
# fsync decision per fused group), and admission control applies — a
# full in-flight-rows bucket or a deep read-batcher backlog answers
# 429 + Retry-After BEFORE the batch is staged, so a retry is
# duplicate-safe (ingest/pipeline.py)
WEB_INGEST_PIPELINE = SystemProperty("geomesa.ingest.web.pipeline", "true")


class GeoMesaWebServer:
    """Bind a datastore to an HTTP port. ``start()`` serves on a daemon
    thread (tests/notebooks); ``serve_forever()`` blocks (CLI).

    Concurrent ``/rest/query`` requests ride ThreadingHTTPServer's
    thread-per-request model into a QueryBatcher: requests for the same
    schema arriving within the linger window share ONE fused device
    scan (scan/batcher.py)."""

    def __init__(self, store, host: str = "127.0.0.1", port: int = 0,
                 audit=None, auth_token: str | None = None,
                 batcher=None, max_inflight: int | None = None,
                 cq=None, views=None):
        from ..scan.registry import shared_batcher
        self.store = store
        # continuous-query publisher behind /rest/cq: pass one in, or
        # the first POST /rest/cq/register creates it lazily (needs a
        # store with a mutation bus)
        self.cq = cq
        self._owns_cq = False
        # materialized-view registry behind /rest/views: pass one in
        # (a store may only have ONE registry hooking its write path),
        # or the first /rest/views request creates it lazily
        self.views = views
        self._owns_views = False
        self.audit = audit if audit is not None \
            else getattr(store, "audit", None)
        self.auth_token = (auth_token if auth_token is not None
                           else WEB_AUTH_TOKEN.get())
        if batcher is None and hasattr(store, "query_batched"):
            # process-wide registry, not a private instance: embedded
            # callers using shared_batcher(store) coalesce into the
            # SAME fused dispatches as web requests and share one
            # warmed plan cache (scan/registry.py)
            batcher = shared_batcher(store)
        self.batcher = batcher
        self.max_inflight = (max_inflight if max_inflight is not None
                             else WEB_MAX_INFLIGHT.as_int())
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        # lazy group-commit write plane (first POST /rest/write when
        # geomesa.ingest.web.pipeline is on)
        self._ingest_pipeline = None
        self._ingest_lock = threading.Lock()
        self._started_at = time.monotonic()
        # background hot-tile refresher: opt-in via the interval knob,
        # and only for stores that actually own a result cache (the
        # router/coordinator tiers delegate caching to their members)
        self.refresher = None
        from ..cache import CACHE_REFRESH_INTERVAL_S, CacheRefresher
        interval = CACHE_REFRESH_INTERVAL_S.as_float() or 0.0
        if interval > 0 and getattr(store, "result_cache", None) is not None:
            self.refresher = CacheRefresher(
                store, interval_s=interval).start()
        handler = _make_handler(self)
        self._httpd = _Httpd((host, port), handler)
        self._thread: threading.Thread | None = None
        # the serving tier owns the health plane's sampler: refcounted,
        # so N servers in one process share ONE profiler thread
        # (geomesa.prof.hz=0 parks it). Released in stop().
        from ..obs.prof import profiler
        profiler.start()
        self._owns_prof = True

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "GeoMesaWebServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def serve_forever(self):
        self._httpd.serve_forever()

    def stop(self):
        if self.refresher is not None:
            self.refresher.stop()
        if self._ingest_pipeline is not None:
            self._ingest_pipeline.close()
        if self._owns_cq and self.cq is not None:
            self.cq.close()
        if self._owns_views and self.views is not None:
            self.views.close()
        if self._owns_prof:
            self._owns_prof = False
            from ..obs.prof import profiler
            profiler.stop()
        self._httpd.shutdown()
        self._httpd.server_close()

    # -- request handling (called from the handler) -----------------------

    def handle(self, method: str, path: str, params: dict, body: bytes,
               headers=None):
        """Route -> (status, content_type, payload[, extra headers])."""
        parts = [unquote(p) for p in path.strip("/").split("/") if p]
        if not parts or parts[0] != "rest":
            return 404, "application/json", _j({"error": "not found"})
        parts = parts[1:]
        # health surface bypasses auth AND the shed gate: probes must
        # see an overloaded-but-alive server, not a 503 liveness fail
        if method == "GET" and parts == ["health"]:
            return 200, "application/json", _j(
                {"status": "ok", "version": _version,
                 "uptime_s": round(time.monotonic() - self._started_at, 3),
                 "resilience": self._resilience_detail(),
                 "batcher": self._batcher_detail(),
                 "durability": self._durability_detail(),
                 "ingest": self._ingest_detail(),
                 "qos": self._qos_detail()})
        if method == "GET" and parts == ["ready"]:
            return self._ready()
        route = parts[0] if parts else ""
        if not self._acquire_slot():
            metrics.counter("resilience.web.sheds")
            # a shed IS an availability event on the route's SLO: the
            # caller got a 503, whatever the reason
            from ..obs.slo import slo_engine
            slo_engine.record(route, ok=False, latency_s=0.0)
            return (503, "application/json",
                    _j({"error": "overloaded: in-flight request cap "
                                 "reached", "retryable": True}),
                    {"Retry-After": self._retry_after()})
        # per-tenant shed gate (QoS on only): a tenant over ITS
        # in-flight cap gets 503 while every other tenant proceeds
        from ..tenants import tenant_registry, tenant_scope
        tenant = self._tenant(headers)
        if tenant is not None \
                and not tenant_registry.try_acquire_inflight(tenant):
            self._release_slot()
            from ..obs.slo import slo_engine
            slo_engine.record(route, ok=False, latency_s=0.0,
                              tenant=tenant)
            return (503, "application/json",
                    _j({"error": "overloaded: tenant in-flight cap "
                                 "reached", "retryable": True,
                        "tenant": tenant}),
                    {"Retry-After": self._retry_after()})
        slot_owned = True
        try:
            from ..audit import principal_scope
            from ..obs import TRACE_HEADER, tracer
            from ..obs.slo import slo_engine
            hdr = headers.get(TRACE_HEADER) if headers is not None \
                else None
            name = f"{method} /rest/{parts[0] if parts else ''}"
            # the web span is the local trace root; an incoming
            # X-GeoMesa-Trace header continues the caller's trace
            # (RemoteDataStore client leg, upstream coordinator)
            labels = {"route": route, "method": method}
            if str(WEB_METRICS_PRINCIPAL.get()).lower() in \
                    ("true", "1", "yes"):
                labels["principal"] = self._principal(headers) or "anon"
            t_req = time.perf_counter()
            with tracer.span("web", name, root=True, remote=hdr) as wsp, \
                    metrics.time("web.request", labels=labels):
                if tenant is not None:
                    wsp.set_attr(tenant=tenant)
                with principal_scope(self._principal(headers)), \
                        tenant_scope(tenant):
                    out = self._handle_routed(method, parts, params,
                                              body, headers)
                wsp.set_attr(status=int(out[0]))
                slo_engine.record(route, ok=int(out[0]) < 500,
                                  latency_s=time.perf_counter() - t_req,
                                  tenant=tenant)
                if len(out) >= 3 and not isinstance(
                        out[2], (bytes, bytearray, str)):
                    # streaming payload: the generator outlives this
                    # frame, so the in-flight slot travels with it and
                    # releases when the stream finishes (or dies).
                    # (The web span closes at handoff — streamed
                    # byte time is not in the trace.)
                    wsp.annotate("streaming")
                    out = (*out[:2], self._slot_guard(out[2], tenant),
                           *out[3:])
                    slot_owned = False
                return out
        finally:
            if slot_owned:
                self._release_slot()
                if tenant is not None:
                    tenant_registry.release_inflight(tenant)

    @staticmethod
    def _principal(headers) -> str | None:
        """Audit principal from the Authorization header: a stable
        token digest, never the bearer token itself."""
        got = (headers or {}).get("Authorization", "") or ""
        if got.startswith("Bearer ") and got[7:]:
            import hashlib
            return "bearer:" + hashlib.sha1(
                got[7:].encode()).hexdigest()[:8]
        return None

    @staticmethod
    def _tenant(headers) -> str | None:
        """QoS tenant from the Authorization header via the
        ``geomesa.web.auth.tokens`` map; None when QoS is disabled (the
        bit-identical off path)."""
        from ..tenants import qos_enabled, tenant_registry
        if not qos_enabled():
            return None
        got = (headers or {}).get("Authorization", "") or ""
        token = got[7:] if got.startswith("Bearer ") else None
        return tenant_registry.resolve_token(token or None)

    @staticmethod
    def _retry_after() -> str:
        """The advertised Retry-After with bounded full jitter:
        U(0.5x, 1.5x) around ``geomesa.web.retry.after.s``, so a herd
        of shed clients doesn't retry in one synchronized wave."""
        try:
            base = float(WEB_RETRY_AFTER.get() or 1.0)
        except (TypeError, ValueError):
            base = 1.0
        base = max(base, 1e-3)
        return f"{random.uniform(0.5 * base, 1.5 * base):.4f}"

    def _handle_routed(self, method, parts, params, body, headers):
        if parts and (method, parts[0]) in _GATED \
                and not self._authorized(headers):
            return 403, "application/json", _j({"error": "forbidden"})
        try:
            return self._route(method, parts, params, body, headers)
        except KeyError as e:
            return 404, "application/json", _j({"error": str(e)})
        except DurabilityError as e:
            # the WAL poisoned itself (failed fsync/write): the
            # store is read-only degraded. 503 tells clients the
            # SERVER can't take writes — reads still work — and
            # retrying here is pointless until an operator recycles
            # the process
            metrics.counter("integrity.web.write_rejects")
            return (503, "application/json",
                    _j({"error": repr(e), "retryable": False,
                        "degraded": "read-only"}))
        except ValueError as e:
            # parse/plan errors (CQL/filter parse is a ValueError
            # subclass): the request is malformed, do NOT retry
            return 400, "application/json", _j({"error": repr(e)})
        except Exception as e:
            # unexpected server fault: 500 so clients know the
            # request (not the server's health) might still be fine
            metrics.counter("resilience.web.errors")
            return 500, "application/json", _j({"error": repr(e)})

    def _slot_guard(self, gen, tenant=None):
        """Hold the shed slot (and the tenant's in-flight slot) for a
        streaming response's lifetime."""
        try:
            yield from gen
        finally:
            self._release_slot()
            if tenant is not None:
                from ..tenants import tenant_registry
                tenant_registry.release_inflight(tenant)

    def _ready(self):
        """Readiness: the store answers and we're under the shed cap.
        Load balancers drain on 503 here while /rest/health stays 200."""
        with self._inflight_lock:
            inflight = self._inflight
        shedding = (self.max_inflight is not None
                    and inflight >= self.max_inflight)
        store_ok = True
        try:
            self.store.get_type_names()
        except Exception:
            store_ok = False
        ready = store_ok and not shedding
        body = _j({"ready": ready, "store_ok": store_ok,
                   "inflight": inflight,
                   "max_inflight": self.max_inflight})
        if ready:
            return 200, "application/json", body
        return (503, "application/json", body,
                {"Retry-After": self._retry_after()})

    def _durability_detail(self) -> dict | None:
        """Durability health: None for non-durable stores, otherwise
        whether the WAL has poisoned itself (read-only degraded mode)
        and why — the operator-facing face of fsyncgate semantics."""
        journal = getattr(self.store, "journal", None)
        if journal is None:
            return None
        out = {"poisoned": bool(journal.poisoned)}
        if journal.poisoned:
            out["mode"] = "read-only"
            cause = journal.wal.poison_cause
            if cause is not None:
                out["cause"] = repr(cause)
        return out

    def _ingest_detail(self) -> dict | None:
        """Ingest-plane health: in-flight staged rows against the
        admission bucket and whether new writes would currently shed.
        None until the first pipelined write creates the plane."""
        pipe = self._ingest_pipeline
        if pipe is None:
            return None
        gov = pipe.governor
        return {"inflight_rows": gov.inflight_rows,
                "max_inflight_rows": gov.max_inflight_rows,
                "group_cap_rows": pipe.effective_group_rows(),
                "shedding": gov.should_shed()}

    def _qos_detail(self) -> dict | None:
        """Tenant QoS health: per-tenant in-flight/budget state (the
        ``/rest/qos`` document). None while QoS is disabled."""
        from ..tenants import qos_enabled, tenant_registry
        if not qos_enabled():
            return None
        return tenant_registry.status()

    def _batcher_detail(self) -> dict | None:
        """Serving-tier batcher health: per-type pending-queue depth
        across the process-wide registry (every caller coalescing into
        this process, not just the web tier) plus this server's own
        batcher counters. None when the store can't batch."""
        if self.batcher is None:
            return None
        from ..scan.registry import batcher_registry
        return {"queue_depths": batcher_registry.queue_depths(),
                "stats": self.batcher.stats()}

    def _resilience_detail(self) -> dict:
        """Per-endpoint latency estimates for the health surface — the
        observability half of hedged requests: operators (and a future
        hedging client) read the p99-ish numbers the breaker boards
        publish as ``resilience.latency.p99.<key>`` gauges."""
        snap = metrics.snapshot()
        prefix = "resilience.latency.p99."
        latency = {k[len(prefix):]: round(v, 3)
                   for k, v in snap.get("gauges", {}).items()
                   if k.startswith(prefix) and v is not None}
        return {"latency_p99_ms": latency}

    def _acquire_slot(self) -> bool:
        with self._inflight_lock:
            if self.max_inflight is not None \
                    and self._inflight >= self.max_inflight:
                return False
            self._inflight += 1
            metrics.gauge("resilience.web.inflight", self._inflight)
            return True

    def _release_slot(self):
        with self._inflight_lock:
            self._inflight -= 1
            metrics.gauge("resilience.web.inflight", self._inflight)

    def _authorized(self, headers) -> bool:
        if not self.auth_token:
            return True  # gate not opted in: endpoints stay open
        got = (headers or {}).get("Authorization", "")
        return got == f"Bearer {self.auth_token}"

    def _route(self, method, parts, params, body, headers=None):
        if parts == ["version"]:
            return 200, "application/json", _j({"version": _version})
        if parts == ["schemas"]:
            return 200, "application/json", _j(self.store.get_type_names())
        if len(parts) == 2 and parts[0] == "schemas":
            name = parts[1]
            if method == "POST":
                self.store.create_schema(name, body.decode())
                return 201, "application/json", _j({"created": name})
            if method == "DELETE":
                self.store.remove_schema(name)
                return 200, "application/json", _j({"removed": name})
            sft = self.store.get_schema(name)
            return 200, "application/json", _j(
                {"name": name, "spec": sft.to_spec(),
                 "attributes": [{"name": a.name, "type": str(a.type)}
                                for a in sft.attributes]})
        if len(parts) == 2 and parts[0] == "query":
            return self._query(parts[1], params)
        if len(parts) == 2 and parts[0] == "count":
            hinted = {"sampling", "sampleBy", "index", "auths",
                      "maxFeatures", "properties"}
            if hinted & params.keys():
                # hinted/sampled/limited counts evaluate server-side
                # through the full Query surface — the client gets one
                # number either way, never O(n) rows over the wire
                n = self.store.query_count(self._parse_query(parts[1],
                                                             params))
            elif "cql" in params:
                n = self.store.query_count(params["cql"][0], parts[1])
            else:
                # total stored features — the SPI count() contract
                # (NOT visibility-filtered, matching local stores)
                n = self.store.count(parts[1])
            out = {"count": int(n)}
            if getattr(n, "complete", True) is False:
                out["complete"] = False
                out["missing_z_ranges"] = getattr(n, "missing_z_ranges", [])
                return (200, "application/json", _j(out),
                        _partial_headers(n))
            return 200, "application/json", _j(out)
        if len(parts) == 2 and parts[0] == "write" and method == "POST":
            # body = Arrow IPC stream; a reserved __vis__ column (when
            # present) carries per-row visibility labels — the same
            # convention the parquet tier persists
            sft = self.store.get_schema(parts[1])
            vis = None
            import pyarrow as pa
            import io as _io
            with pa.ipc.open_file(_io.BytesIO(body)) as rd:
                table = rd.read_all()
            if "__vis__" in table.schema.names:
                vis = np.asarray(table.column("__vis__").to_pylist(),
                                 dtype=object)
                table = table.drop_columns(["__vis__"])
            from ..features.batch import FeatureBatch
            batches = [FeatureBatch.from_arrow(sft, rb)
                       for rb in table.to_batches() if rb.num_rows]
            if batches:
                fused = FeatureBatch.concat_all(batches)
                if str(WEB_INGEST_PIPELINE.get()).lower() in (
                        "true", "1", "yes"):
                    refused = self._pipeline_write(parts[1], fused, vis)
                    if refused is not None:
                        return refused
                else:
                    self.store.write(parts[1], fused, visibilities=vis)
            n = sum(b.n for b in batches)
            out = {"written": n, "lsn": self._tail_lsn()}
            vec = getattr(self.store, "lsn_vector", None)
            if callable(vec):
                # cluster stores: the per-shard acked-LSN vector this
                # write is included in (read-your-writes token)
                out["lsn_vector"] = vec()
            return 200, "application/json", _j(out)
        if len(parts) == 2 and parts[0] == "delete" and method == "POST":
            ids = json.loads(body.decode())
            self.store.delete(parts[1], ids)
            out = {"deleted": len(ids), "lsn": self._tail_lsn()}
            vec = getattr(self.store, "lsn_vector", None)
            if callable(vec):
                out["lsn_vector"] = vec()
            return 200, "application/json", _j(out)
        if len(parts) == 2 and parts[0] == "estimate":
            # sketch-based cardinality estimate (never scans): the
            # remote leg of the cluster-merged SQL planner estimates
            from ..sql.planner import estimate_for_store
            est = estimate_for_store(
                self.store, parts[1], params.get("cql", ["INCLUDE"])[0])
            return 200, "application/json", _j(
                {"type": parts[1],
                 "estimate": None if est is None else int(est)})
        if len(parts) == 2 and parts[0] == "knn":
            return self._knn(parts[1], params)
        if len(parts) == 2 and parts[0] == "stats":
            return self._stats(parts[1], params, headers)
        if len(parts) == 2 and parts[0] == "density":
            return self._density(parts[1], params, headers)
        if len(parts) == 2 and parts[0] == "bin":
            return self._bin(parts[1], params, headers)
        if method == "GET" and parts == ["metrics"]:
            if params.get("format", [""])[0] == "prometheus":
                return (200, "text/plain; version=0.0.4",
                        metrics.prometheus_text())
            return 200, "application/json", _j(metrics.snapshot())
        if method == "GET" and parts == ["runtime"]:
            from ..obs.runtime import runtime
            return 200, "application/json", _j(runtime.snapshot())
        if method == "GET" and parts == ["slo"]:
            from ..obs.slo import slo_engine
            return 200, "application/json", _j(slo_engine.status())
        if method == "GET" and parts == ["qos"]:
            from ..tenants import tenant_registry
            return 200, "application/json", _j(tenant_registry.status())
        if method == "GET" and parts == ["profile"]:
            from ..obs.prof import profiler, watchdog
            if params.get("format", [""])[0] == "json":
                return 200, "application/json", _j(
                    {"profiler": profiler.stats(),
                     "watchdog": watchdog.stats()})
            # default: collapsed-stack text (flamegraph.pl/speedscope
            # input — "frame;frame;frame N" per line)
            return 200, "text/plain", profiler.collapsed()
        if method == "GET" and parts and parts[0] == "trace":
            from ..obs import tracer
            if len(parts) == 1:
                limit = int(params.get("limit", ["50"])[0])
                return 200, "application/json", _j(tracer.traces(limit))
            spans = tracer.get(parts[1])
            if spans is None:
                raise KeyError(f"unknown trace: {parts[1]}")
            return 200, "application/json", _j(
                {"trace_id": parts[1], "spans": spans})
        if parts and parts[0] == "cache":
            return self._cache(method, parts[1:], params)
        if parts and parts[0] == "cq":
            return self._cq(method, parts[1:], params, body)
        if parts and parts[0] == "views":
            return self._views(method, parts[1:], params, body, headers)
        if parts == ["sql", "join-partial"]:
            # one shard-group leg of a distributed broadcast join:
            # this server joins the shipped small side against its
            # local slice of the big side
            from ..sql.distributed import join_partial_leg
            spec = json.loads(body.decode()) if body else {}
            return 200, "application/json", _j(
                join_partial_leg(self.store, spec))
        if parts == ["sql"]:
            # POST body or ?q= : a SELECT with ST_* predicates/joins
            stmt = (body.decode() if method == "POST" and body
                    else params.get("q", [""])[0])
            if not stmt.strip():
                return 400, "application/json", _j(
                    {"error": "missing SQL statement"})
            if params.get("mode", [""])[0] == "partial":
                # one shard-group leg of a distributed aggregate:
                # mergeable partials computed next to the data
                from ..sql.distributed import partial_aggregate
                return 200, "application/json", _j(
                    partial_aggregate(self.store, stmt))
            from ..sql import SqlEngine
            res = SqlEngine(self.store).query(stmt)
            payload = {"columns": res.names,
                       "rows": [list(r) for r in res.rows()]}
            if res.plan is not None:
                payload["plan"] = res.plan
            if not res.complete:
                payload["complete"] = False
                payload["missing_groups"] = res.missing_groups
                payload["missing_z_ranges"] = res.missing_z_ranges
            return 200, "application/json", _j(payload)
        if parts and parts[0] == "wal":
            return self._wal(method, parts[1:], params)
        if parts and parts[0] == "integrity":
            return self._integrity(method, parts[1:])
        if parts and parts[0] == "replication":
            return self._replication(method, parts[1:])
        if parts and parts[0] == "cluster":
            return self._cluster(method, parts[1:], params)
        if parts == ["topology"] and method == "GET":
            topology = getattr(self.store, "topology", None)
            if not callable(topology):
                return 404, "application/json", _j(
                    {"error": "store has no cluster topology"})
            counts = params.get("counts", ["true"])[0] != "false"
            return 200, "application/json", _j(
                topology(include_counts=counts))
        if parts and parts[0] == "reshard":
            return self._reshard(method, parts[1:], params)
        if (len(parts) == 2 and parts[0] == "reindex"
                and method == "POST"):
            # the blocking reindex oracle on the wire: holds the store
            # op lock for the rebuild (use /rest/evolve for online)
            v = params.get("version", [None])[0]
            self.store.reindex(parts[1],
                               int(v) if v is not None else None)
            return 200, "application/json", _j(
                {"reindexed": parts[1],
                 "index_version":
                     self.store.get_schema(parts[1]).index_version})
        if parts and parts[0] == "evolve":
            return self._evolve(method, parts[1:], params, body)
        if parts == ["audit"]:
            # a server fronting a store without its own logger still
            # answers: surfaces without one record into the process
            # global ring (audit/hook.py)
            from ..audit import global_audit
            log = self.audit if self.audit is not None else global_audit()
            evs = log.query(
                params.get("type", [None])[0],
                int(params["since"][0]) if "since" in params else None)
            return 200, "application/json", _j(
                [json.loads(e.to_json()) for e in evs])
        return 404, "application/json", _j({"error": "not found"})

    def _ingest_pipe(self):
        if self._ingest_pipeline is None:
            with self._ingest_lock:
                if self._ingest_pipeline is None:
                    from ..ingest import IngestPipeline
                    self._ingest_pipeline = IngestPipeline(self.store)
        return self._ingest_pipeline

    def _pipeline_write(self, type_name: str, batch, vis):
        """Stage through the group-commit pipeline. Returns None once
        the write has committed, or a ready 429 response when admission
        control refuses — the bucket of in-flight rows is full, or the
        read batchers are backed up and ingest must yield."""
        pipe = self._ingest_pipe()
        if pipe.governor.should_shed():
            metrics.counter("ingest.web.sheds")
            return (429, "application/json",
                    _j({"error": "ingest shed: read queues saturated",
                        "retryable": True}),
                    {"Retry-After": self._retry_after()})
        ack = pipe.write(type_name, batch, visibilities=vis, block=False)
        if ack is None:
            metrics.counter("ingest.web.backpressure")
            return (429, "application/json",
                    _j({"error": "ingest backpressure: in-flight row "
                                 "bucket full", "retryable": True}),
                    {"Retry-After": self._retry_after()})
        # block this request thread until the fused group commits: the
        # response's lsn must cover this write (read-your-writes)
        ack.wait()
        return None

    def _tail_lsn(self) -> int | None:
        """The WAL position after a mutation (None for non-durable
        stores). Replication routers fronting this server via
        RemoteDataStore use it as the write's ACK watermark."""
        journal = getattr(self.store, "journal", None)
        return journal.wal.last_lsn if journal is not None else None

    def _replication(self, method, parts):
        """Replication admin. GET /rest/replication reports whichever
        role this store plays: a ``ReplicatedDataStore`` answers with
        router status, a primary with a ``WalShipper`` attached as
        ``store.shipper`` answers with shipper status. POST
        /rest/replication/promote (bearer-gated) forces failover."""
        if method == "GET" and not parts:
            status = getattr(self.store, "replication_status", None)
            if callable(status):
                return 200, "application/json", _j(status())
            shipper = getattr(self.store, "shipper", None)
            if shipper is not None:
                return 200, "application/json", _j(shipper.status())
            return 404, "application/json", _j(
                {"error": "store has no replication role"})
        if method == "POST" and parts == ["promote"]:
            promote = getattr(self.store, "promote", None)
            if not callable(promote):
                return 404, "application/json", _j(
                    {"error": "store cannot promote (not a replication "
                              "router)"})
            return 200, "application/json", _j(promote())
        return 404, "application/json", _j({"error": "not found"})

    def _cluster(self, method, parts, params):
        """Cluster admin. GET /rest/cluster reports shard-group
        topology, owned z-ranges, the acked LSN vector and per-leg
        breaker/latency state; POST /rest/cluster/promote?group=NAME
        (bearer-gated) forces intra-group failover."""
        if method == "GET" and not parts:
            status = getattr(self.store, "cluster_status", None)
            if callable(status):
                return 200, "application/json", _j(status())
            return 404, "application/json", _j(
                {"error": "store has no cluster role"})
        if method == "POST" and parts == ["promote"]:
            promote = getattr(self.store, "promote_group", None)
            if not callable(promote):
                return 404, "application/json", _j(
                    {"error": "store cannot promote (not a cluster "
                              "coordinator)"})
            group = params.get("group", [None])[0]
            return 200, "application/json", _j(promote(group))
        return 404, "application/json", _j({"error": "not found"})

    def _reshard(self, method, parts, params):
        """Elastic-topology admin. GET /rest/reshard reports resharder
        state (in-flight migration, epoch history, cooldown); POST
        /rest/reshard/split|migrate|resume|abort (bearer-gated) drive
        the verbs and POST /rest/reshard/auto ticks — or with
        ?state=on|off starts/stops — the autoscaler loop. Typed
        reshard refusals (kill switch, cooldown, in-flight limit) map
        to 409: the request was well-formed but the topology cannot
        change right now."""
        if not hasattr(self.store, "resharder"):
            return 404, "application/json", _j(
                {"error": "store has no elastic topology"})
        from ..cluster.reshard import ReshardError
        resharder = self.store.resharder
        if method == "GET" and not parts:
            return 200, "application/json", _j(resharder.status())
        if method != "POST" or len(parts) != 1:
            return 404, "application/json", _j({"error": "not found"})
        verb = parts[0]
        try:
            if verb == "split":
                src = (params.get("src", [None])[0]
                       or params.get("group", [None])[0])
                if src is None:
                    return 400, "application/json", _j(
                        {"error": "split requires ?src=<group>"})
                at = params.get("at", [None])[0]
                entry = resharder.split(
                    src, dst=params.get("dst", [None])[0],
                    at=int(at) if at is not None else None,
                    reason="rest")
                return 200, "application/json", _j(entry)
            if verb == "migrate":
                need = ("prefix_lo", "prefix_hi", "src", "dst")
                missing = [k for k in need if params.get(k, [None])[0]
                           is None]
                if missing:
                    return 400, "application/json", _j(
                        {"error": "migrate requires "
                                  + ", ".join(f"?{k}=" for k in need)})
                entry = resharder.migrate(
                    int(params["prefix_lo"][0]),
                    int(params["prefix_hi"][0]),
                    params["src"][0], params["dst"][0], reason="rest")
                return 200, "application/json", _j(entry)
            if verb == "resume":
                return 200, "application/json", _j(resharder.resume())
            if verb == "abort":
                return 200, "application/json", _j(resharder.abort())
            if verb == "auto":
                scaler = self.store.autoscaler
                state = params.get("state", [None])[0]
                if state == "on":
                    scaler.start()
                elif state == "off":
                    scaler.stop()
                elif state is not None:
                    return 400, "application/json", _j(
                        {"error": "state must be on|off"})
                else:
                    return 200, "application/json", _j(
                        scaler.run_once())
                return 200, "application/json", _j(scaler.status())
        except ReshardError as e:
            return (409, "application/json",
                    _j({"error": str(e), "retryable": False}))
        return 404, "application/json", _j({"error": "not found"})

    def _evolve(self, method, parts, params, body):
        """Schema-evolution admin. GET /rest/evolve reports evolver
        state (active evolution phase/cursor, history); POST
        /rest/evolve/reindex?type=&version=, /rest/evolve/update?type=
        (change list as JSON body or ?changes=), /rest/evolve/resume
        and /rest/evolve/abort (bearer-gated) drive the verbs. Typed
        refusals (kill switch, verb in flight, bad change spec,
        mid-flip fence) map to 409: well-formed request, but the
        schema cannot change right now."""
        if not hasattr(self.store, "evolver"):
            return 404, "application/json", _j(
                {"error": "store has no schema-evolution plane"})
        from ..evolve import SchemaEvolutionError
        evolver = self.store.evolver
        if method == "GET" and not parts:
            return 200, "application/json", _j(evolver.status())
        if method != "POST" or len(parts) != 1:
            return 404, "application/json", _j({"error": "not found"})
        verb = parts[0]
        try:
            if verb == "reindex":
                tn = params.get("type", [None])[0]
                if tn is None:
                    return 400, "application/json", _j(
                        {"error": "reindex requires ?type=<name>"})
                v = params.get("version", [None])[0]
                entry = evolver.reindex(
                    tn, int(v) if v is not None else None)
                return 200, "application/json", _j(entry)
            if verb == "update":
                args = {k: v[0] for k, v in params.items()}
                if body:
                    try:
                        parsed = json.loads(body)
                        if not isinstance(parsed, dict):
                            raise ValueError("body must be a JSON "
                                             "object")
                        args.update(parsed)
                    except ValueError as e:
                        return 400, "application/json", _j(
                            {"error": f"bad JSON body: {e}"})
                tn = args.get("type")
                if not tn:
                    return 400, "application/json", _j(
                        {"error": "update requires a type"})
                changes = args.get("changes")
                if isinstance(changes, str):
                    try:
                        changes = json.loads(changes)
                    except ValueError as e:
                        return 400, "application/json", _j(
                            {"error": f"bad changes JSON: {e}"})
                entry = evolver.update_schema(tn, changes)
                return 200, "application/json", _j(entry)
            if verb == "resume":
                return 200, "application/json", _j(evolver.resume())
            if verb == "abort":
                return 200, "application/json", _j(evolver.abort())
        except SchemaEvolutionError as e:
            return (409, "application/json",
                    _j({"error": str(e), "retryable": False}))
        return 404, "application/json", _j({"error": "not found"})

    def _wal(self, method, parts, params):
        """Durability admin: GET /rest/wal (stats, open), POST
        /rest/wal/checkpoint and /rest/wal/truncate?below= (mutating,
        bearer-gated via _GATED)."""
        journal = getattr(self.store, "journal", None)
        if journal is None:
            return 404, "application/json", _j(
                {"error": "store is not durable (no WAL journal)"})
        if method == "GET" and not parts:
            return 200, "application/json", _j(journal.stats())
        if method == "POST" and parts == ["checkpoint"]:
            info = self.store.checkpoint()
            return 200, "application/json", _j(info)
        if method == "POST" and parts == ["truncate"]:
            if "below" in params:
                lsn = int(params["below"][0])
            else:
                from ..wal.snapshot import latest_checkpoint_lsn
                lsn = latest_checkpoint_lsn(journal.root)
            if lsn <= 0:
                return 400, "application/json", _j(
                    {"error": "no checkpoint and no ?below= LSN"})
            dropped = journal.wal.truncate_below(lsn)
            return 200, "application/json", _j(
                {"below": lsn, "segments_dropped": dropped})
        return 404, "application/json", _j({"error": "not found"})

    def _integrity(self, method, parts):
        """Storage integrity surface: GET /rest/integrity (read-only
        verification sweep of WAL CRCs + checkpoint digests, open) and
        POST /rest/integrity/scrub (one scrub pass WITH quarantine per
        the knob — mutating, bearer-gated via _GATED)."""
        journal = getattr(self.store, "journal", None)
        if journal is None:
            return 404, "application/json", _j(
                {"error": "store is not durable (no WAL journal)"})
        if method == "GET" and not parts:
            from ..integrity.scrub import integrity_report
            rep = integrity_report(journal.root)
            rep["poisoned"] = bool(journal.poisoned)
            return 200, "application/json", _j(rep)
        if method == "POST" and parts == ["scrub"]:
            scrubber = getattr(self.store, "scrubber", None)
            if scrubber is None:
                from ..integrity.scrub import Scrubber
                scrubber = Scrubber(journal=journal)
            return 200, "application/json", _j(scrubber.run_once())
        return 404, "application/json", _j({"error": "not found"})

    def _parse_query(self, name, params) -> Query:
        """URL params -> Query; shared by /rest/query and the hinted
        /rest/count path so both evaluate identical semantics."""
        q = Query(name, params.get("cql", ["INCLUDE"])[0])
        if "maxFeatures" in params:
            q.max_features = int(params["maxFeatures"][0])
        if "sortBy" in params:
            q.sort_by = params["sortBy"][0]
            q.sort_desc = (params.get("sortOrder", ["asc"])[0]
                           .lower() == "desc")
        # ViewParams analog (index/geotools ViewParams:28): URL params
        # map onto per-query hints
        if "properties" in params:
            q.properties = [p for p in params["properties"][0].split(",")
                            if p]
        if "sampling" in params:
            q.hints[QueryHints.SAMPLING] = float(params["sampling"][0])
        if "sampleBy" in params:
            q.hints[QueryHints.SAMPLE_BY] = params["sampleBy"][0]
        if "index" in params:
            q.hints[QueryHints.QUERY_INDEX] = params["index"][0]
        if "auths" in params:
            q.auths = [a for a in params["auths"][0].split(",") if a]
        return q

    def _query(self, name, params):
        fmt = params.get("format", ["json"])[0]
        q = self._parse_query(name, params)
        if fmt in ("arrow-stream", "bin"):
            return self._query_stream(name, q, params, fmt)
        if fmt == "arrow":
            from ..arrow.io import write_ipc
            res = self._run_query(q)
            sft = self.store.get_schema(name)
            batch = res.batch
            if batch is None:
                from ..features.batch import FeatureBatch
                batch = FeatureBatch.from_dict(
                    sft, np.empty(0, dtype=object),
                    {a.name: ((np.empty(0), np.empty(0))
                              if a.type.name == "Point" else [])
                     for a in sft.attributes})
            # projected results carry a projected schema
            return (200, "application/vnd.apache.arrow.file",
                    write_ipc(batch.sft, batch),
                    _partial_headers(res))
        res = self._run_query(q)
        sft = self.store.get_schema(name)
        if fmt == "geojson":
            from ..geometry.geojson import to_geojson
            feats = []
            if res.batch is not None:
                gf = sft.geom_field
                for f in res.features():
                    g = f.get(gf)
                    feats.append({
                        "type": "Feature", "id": f["id"],
                        "geometry": to_geojson(g) if g is not None else None,
                        "properties": {k: v for k, v in f.items()
                                       if k not in ("id", gf)}})
            return (200, "application/geo+json", _j(
                {"type": "FeatureCollection", "features": feats}),
                _partial_headers(res))
        rows = list(res.features()) if res.batch is not None else []
        out = {"count": len(rows), "features": rows}
        if getattr(res, "complete", True) is False:
            out["complete"] = False
            out["missing_z_ranges"] = getattr(res, "missing_z_ranges", [])
        return (200, "application/json", _j(out), _partial_headers(res))

    def _query_stream(self, name, q: Query, params, fmt: str):
        """format=arrow-stream|bin: chunked-transfer streaming. The
        scan still runs the fused vectorized path eagerly (plan/CQL
        errors map to 400 before any bytes leave), then the result
        *encodes* incrementally — the first batch is on the wire while
        the rest is still being encoded, and neither side ever holds
        the full serialized payload."""
        from ..arrow.delta import (ARROW_STREAM_MIME, empty_batch,
                                   stream_bin, stream_ipc)
        res = self._run_query(q)
        sft = self.store.get_schema(name)
        batch = res.batch if res.batch is not None else empty_batch(sft)
        hdrs = _partial_headers(res)
        rows = (int(params["batchRows"][0]) if "batchRows" in params
                else None)
        if fmt == "bin":
            track = params.get("track", [None])[0]
            label = params.get("label", [None])[0]
            return (200, "application/octet-stream",
                    stream_bin(sft, batch, track=track, label=label,
                               batch_rows=rows),
                    hdrs)
        # projected results carry a projected schema (batch.sft)
        return (200, ARROW_STREAM_MIME,
                stream_ipc(batch.sft, batch, batch_rows=rows), hdrs)

    def _run_query(self, q: Query):
        """Queries coalesce through the batcher (one fused scan per
        linger window per schema); stores without batching run direct."""
        if self.batcher is not None:
            return self.batcher.query(q)
        return self.store.query(q)

    def _knn(self, name, params):
        """GET /rest/knn/{type}?x=&y=&k= — k nearest features to the
        query point. Concurrent requests on the same (type, k) coalesce
        through the batcher into ONE fused multi-query top-k dispatch
        (scan/batcher.QueryBatcher.knn), the same admission queue bbox
        queries ride."""
        try:
            x = float(params["x"][0])
            y = float(params["y"][0])
        except (KeyError, ValueError):
            return 400, "application/json", _j(
                {"error": "knn requires numeric x and y params"})
        k = int(params.get("k", ["10"])[0])
        if self.batcher is not None:
            ids, dists = self.batcher.knn(name, x, y, k)
        else:
            from ..analytics.processes import knn_process
            ids, dists = knn_process(self.store, name, x, y, k)
        return 200, "application/json", _j(
            {"ids": [str(i) for i in ids],
             "distances": np.asarray(dists, np.float64).tolist()})

    # -- conditional-request plumbing (ETag = plan key + LSN) --------------

    def _etag_for(self, type_name: str, plan_key: str) -> str | None:
        """ETag for a pushdown response: hash of (type, canonical plan
        key, pushdown version). Computed BEFORE the result — a version
        advancing mid-request makes the tag mismatch (full 200), never
        a stale 304. None when the store has no exact single version
        (router/cluster tiers)."""
        pv = getattr(self.store, "pushdown_version", None)
        if not callable(pv):
            return None
        try:
            v = pv(type_name)
        except Exception:
            return None
        import hashlib
        h = hashlib.sha1(
            f"{type_name}|{plan_key}|{v}".encode()).hexdigest()[:20]
        return f'"{h}"'

    @staticmethod
    def _not_modified(etag: str, headers) -> bool:
        if headers is None or etag is None:
            return False
        try:
            inm = headers.get("If-None-Match")
        except AttributeError:
            inm = None
        if not inm:
            return False
        if inm.strip() == "*":
            return True
        cands = [c.strip() for c in inm.split(",")]
        return etag in cands or f"W/{etag}" in cands

    def _stats(self, name, params, headers=None):
        from ..cache.keys import stats_key
        spec = params.get("stat", ["Count()"])[0]
        flt, key = stats_key(params.get("cql", [None])[0], spec)
        etag = self._etag_for(name, key)
        if etag is not None and self._not_modified(etag, headers):
            return 304, "application/json", b"", {"ETag": etag}
        stat = self.store.stats_query(name, spec, flt)
        extra = {"ETag": etag} if etag is not None else {}
        return 200, "application/json", _j(stat.to_json_object()), extra

    def _density(self, name, params, headers=None):
        from ..cache.keys import density_key
        bbox = tuple(float(v) for v in params["bbox"][0].split(","))
        width = int(params.get("width", ["256"])[0])
        height = int(params.get("height", ["256"])[0])
        cql = params.get("cql", ["INCLUDE"])[0]
        flt, key = density_key(cql, bbox, width, height)
        etag = self._etag_for(name, key)
        if etag is not None and self._not_modified(etag, headers):
            return 304, "application/json", b"", {"ETag": etag}
        grid = self.store.density(name, flt, bbox, width, height)
        hdrs = _partial_headers(grid)
        if etag is not None and getattr(grid, "complete", True) is not False:
            hdrs["ETag"] = etag
        return (200, "application/json", _j(
            {"bbox": bbox, "width": width, "height": height,
             "grid": np.asarray(grid).tolist()}), hdrs)

    def _bin(self, name, params, headers=None):
        """GET /rest/bin/{type}?cql=&track=&label=&sort= — the compact
        BIN record stream (bin_query), conditional like density."""
        from ..cache.keys import bin_key
        cql = params.get("cql", ["INCLUDE"])[0]
        track = params.get("track", [None])[0]
        label = params.get("label", [None])[0]
        sort = params.get("sort", ["false"])[0].lower() in ("1", "true",
                                                            "yes")
        flt, key = bin_key(cql, track, label, sort)
        etag = self._etag_for(name, key)
        if etag is not None and self._not_modified(etag, headers):
            return 304, "application/octet-stream", b"", {"ETag": etag}
        data = self.store.bin_query(name, flt, track=track, label=label,
                                    sort=sort)
        hdrs = _partial_headers(data)
        if etag is not None and getattr(data, "complete", True) is not False:
            hdrs["ETag"] = etag
        return 200, "application/octet-stream", bytes(data), hdrs

    def _cq_publisher(self):
        if self.cq is None:
            from ..store.continuous import ContinuousQueryPublisher
            try:
                self.cq = ContinuousQueryPublisher(self.store)
            except ValueError:
                return None
            self._owns_cq = True
        return self.cq

    def _cq(self, method, parts, params, body):
        """Continuous-query admin: GET /rest/cq (registered queries +
        per-type device filter-set stats, open); POST
        /rest/cq/register?name=&type=&ecql= and POST
        /rest/cq/unregister?name= (mutating, bearer-gated via _GATED).
        Register args also accepted as a JSON body — long ECQL reads
        better there than in a query string."""
        if method == "GET" and not parts:
            out = {"queries": [], "device": []}
            if self.cq is not None:
                out["queries"] = [
                    {"name": q.name, "type": q.type_name, "ecql": q.ecql,
                     "topic": q.topic, "matched": q.matched,
                     "published": q.published}
                    for q in self.cq.queries()]
                out["device"] = self.cq.device_stats()
            return 200, "application/json", _j(out)
        if method == "POST" and parts in (["register"], ["unregister"]):
            args = {k: v[0] for k, v in params.items()}
            if body:
                try:
                    parsed = json.loads(body)
                    if not isinstance(parsed, dict):
                        raise ValueError("body must be a JSON object")
                    args.update(parsed)
                except ValueError as e:
                    return 400, "application/json", _j(
                        {"error": f"bad JSON body: {e}"})
            pub = self._cq_publisher()
            if pub is None:
                return 404, "application/json", _j(
                    {"error": "store has no mutation bus for "
                              "continuous queries"})
            name = args.get("name")
            if not name:
                return 400, "application/json", _j(
                    {"error": "name required"})
            if parts == ["register"]:
                type_name = args.get("type")
                if not type_name:
                    return 400, "application/json", _j(
                        {"error": "type required"})
                ecql = args.get("ecql") or "INCLUDE"
                try:
                    cq = pub.register(name, type_name, ecql)
                except ValueError as e:
                    status = 409 if "exists" in str(e) else 400
                    return status, "application/json", _j(
                        {"error": str(e)})
                return 200, "application/json", _j(
                    {"registered": cq.name, "type": cq.type_name,
                     "topic": cq.topic})
            pub.unregister(name)
            return 200, "application/json", _j({"unregistered": name})
        return 404, "application/json", _j({"error": "not found"})

    def _views_registry(self):
        if self.views is None:
            from ..views import ViewRegistry
            self.views = ViewRegistry(self.store)
            self._owns_views = True
        return self.views

    def _views(self, method, parts, params, body, headers=None):
        """Materialized-view admin: GET /rest/views (status, open),
        GET /rest/views/{name} (rows at the view's LSN — conditional,
        ETag = exact pushdown version), POST
        /rest/views/register?name=&sql=, POST
        /rest/views/unregister?name= and POST /rest/views/refresh?name=
        (mutating, bearer-gated via _GATED). Register args also
        accepted as a JSON body — a standing SELECT reads better there
        than in a query string."""
        reg = self._views_registry()
        if method == "GET" and not parts:
            return 200, "application/json", _j({"views": reg.status()})
        if method == "GET" and len(parts) == 1:
            v = reg.get(parts[0])
            etag = self._etag_for(v.state.table, f"view:{v.name}")
            if etag is not None and self._not_modified(etag, headers):
                return 304, "application/json", b"", {"ETag": etag}
            res = reg.result(parts[0])
            extra = {"ETag": etag} if etag is not None else {}
            return (200, "application/json", _j(
                {"name": v.name, "lsn": v.lsn, "columns": res.names,
                 "rows": [list(r) for r in res.rows()]}), extra)
        if method == "POST" and parts in (["register"], ["unregister"],
                                          ["refresh"]):
            args = {k: v[0] for k, v in params.items()}
            if body:
                try:
                    parsed = json.loads(body)
                    if not isinstance(parsed, dict):
                        raise ValueError("body must be a JSON object")
                    args.update(parsed)
                except ValueError as e:
                    return 400, "application/json", _j(
                        {"error": f"bad JSON body: {e}"})
            name = args.get("name")
            if not name:
                return 400, "application/json", _j(
                    {"error": "name required"})
            if parts == ["register"]:
                sql = args.get("sql")
                if not sql:
                    return 400, "application/json", _j(
                        {"error": "sql required"})
                try:
                    view = reg.register(name, sql)
                except ValueError as e:
                    # unsupported/malformed statements refuse typed at
                    # compile time — surface the parser/planner message
                    # as a client error, never a 500
                    return 400, "application/json", _j(
                        {"error": str(e)})
                return 201, "application/json", _j(
                    {"registered": view.name,
                     "status": view.status(reg._lsn(view.state.table))})
            if parts == ["refresh"]:
                return 200, "application/json", _j(
                    {"refreshed": name, "status": reg.refresh(name)})
            reg.unregister(name)
            return 200, "application/json", _j({"unregistered": name})
        return 404, "application/json", _j({"error": "not found"})

    def _cache(self, method, parts, params):
        """Materialized-cache admin: GET /rest/cache (status, open),
        POST /rest/cache/invalidate?type= (mutating, bearer-gated via
        _GATED)."""
        if method == "GET" and not parts:
            cs = getattr(self.store, "cache_status", None)
            if not callable(cs):
                return 404, "application/json", _j(
                    {"error": "store has no result cache"})
            out = cs()
            if self.refresher is not None:
                out["refresher"] = self.refresher.status()
            return 200, "application/json", _j(out)
        if method == "POST" and parts == ["invalidate"]:
            inv = getattr(self.store, "invalidate_cache", None)
            if not callable(inv):
                return 404, "application/json", _j(
                    {"error": "store has no result cache"})
            tn = params.get("type", [None])[0]
            n = inv(tn)
            return 200, "application/json", _j(
                {"invalidated": int(n), "type": tn})
        return 404, "application/json", _j({"error": "not found"})


class _Httpd(ThreadingHTTPServer):
    def handle_error(self, request, client_address):
        # a client vanishing mid-exchange (reset, broken pipe — e.g.
        # the internal wfile.flush after our handler) is routine on a
        # real network; anything else keeps the stock traceback dump
        import sys
        exc = sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError)):
            metrics.counter("resilience.web.client_disconnects")
            return
        super().handle_error(request, client_address)


def _j(obj) -> bytes:
    return json.dumps(obj, default=_default).encode()


def _partial_headers(res) -> dict:
    """Response headers for the cluster partial-results contract: a
    result flagged ``complete=False`` (a shard group was down and
    ``geomesa.cluster.allow.partial`` let the query degrade) is marked
    so no transport strips the flag. Cluster results also carry the
    topology epoch they were planned against, so clients straddling an
    online reshard can detect the flip."""
    hdrs: dict = {}
    epoch = getattr(res, "topology_epoch", None)
    if epoch is not None:
        hdrs["X-GeoMesa-Topology-Epoch"] = str(int(epoch))
    if getattr(res, "complete", True) is not False:
        return hdrs
    hdrs["X-GeoMesa-Complete"] = "false"
    groups = getattr(res, "missing_groups", None)
    if groups:
        hdrs["X-GeoMesa-Missing-Groups"] = ",".join(groups)
    return hdrs


def _default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    from ..geometry import Geometry
    if isinstance(o, Geometry):
        from ..geometry.wkt import to_wkt
        return to_wkt(o)
    return str(o)


def _make_handler(server: GeoMesaWebServer):
    class Handler(BaseHTTPRequestHandler):
        # HTTP/1.1 so streaming responses may use chunked
        # transfer-encoding — the framing is what makes a mid-stream
        # server death *detectable* (no terminal chunk -> the client's
        # read raises instead of returning a silently-truncated body)
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):  # quiet
            pass

        def _respond(self):
            u = urlparse(self.path)
            params = parse_qs(u.query)
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else b""
            out = server.handle(
                self.command, u.path, params, body, headers=self.headers)
            status, ctype, payload = out[:3]
            extra = out[3] if len(out) > 3 else {}
            if not isinstance(payload, (bytes, bytearray, str)):
                return self._respond_chunked(status, ctype, payload, extra)
            if isinstance(payload, str):
                # text routes (prometheus exposition, collapsed-stack
                # profiles) hand back str; the socket needs bytes, and
                # Content-Length must count bytes, not characters
                payload = payload.encode("utf-8")
            try:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                for k, v in extra.items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(payload)
            except (BrokenPipeError, ConnectionResetError):
                # the client hung up mid-response: its problem, not a
                # server fault — count it, don't dump a traceback
                metrics.counter("resilience.web.client_disconnects")
                self.close_connection = True

        def _respond_chunked(self, status, ctype, payload, extra):
            """Stream an iterator payload with chunked framing. A
            producer fault mid-stream drops the connection WITHOUT the
            terminal 0-chunk, so the client raises (IncompleteRead /
            connection error) rather than seeing a short body."""
            gen = iter(payload)
            try:
                try:
                    first = next(gen)  # encode errors -> 500, pre-headers
                except StopIteration:
                    first = None
                except Exception as e:
                    metrics.counter("resilience.web.errors")
                    err = _j({"error": repr(e)})
                    self.send_response(500)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(err)))
                    self.end_headers()
                    self.wfile.write(err)
                    return
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Transfer-Encoding", "chunked")
                for k, v in extra.items():
                    self.send_header(k, str(v))
                self.end_headers()
                if first:
                    self._chunk(first)
                for chunk in gen:
                    if chunk:
                        self._chunk(chunk)
                self.wfile.write(b"0\r\n\r\n")
            except (BrokenPipeError, ConnectionResetError):
                metrics.counter("resilience.web.client_disconnects")
                self.close_connection = True
            except Exception:
                # producer died mid-stream: sever without the terminal
                # chunk — truncation must be loud on the client
                metrics.counter("resilience.web.stream_aborts")
                self.close_connection = True
                try:
                    self.wfile.flush()
                    self.connection.close()
                except OSError:
                    pass
            finally:
                close = getattr(gen, "close", None)
                if close is not None:
                    close()

        def _chunk(self, data: bytes):
            self.wfile.write(b"%x\r\n" % len(data))
            self.wfile.write(data)
            self.wfile.write(b"\r\n")

        do_GET = do_POST = do_DELETE = _respond

    return Handler
