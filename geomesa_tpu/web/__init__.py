"""REST surface (geomesa-web analog)."""

from .server import GeoMesaWebServer

__all__ = ["GeoMesaWebServer"]
