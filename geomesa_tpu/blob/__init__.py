"""Blobstore: binary payloads indexed by geo/time metadata
(geomesa-blobstore analog: blob/accumulo/AccumuloBlobStoreImpl.scala:24 —
a blob table + an SFT-indexed metadata table; FileHandler SPI extracts
geometry from the input).

Blobs live in a directory (or in-memory dict); metadata rows go through
the normal indexed store so spatial/temporal queries find blob ids.
"""

from __future__ import annotations

import os
import uuid
from typing import Any

import numpy as np

from ..features.batch import FeatureBatch
from ..features.sft import parse_spec
from ..index.api import Query
from ..store.memory import InMemoryDataStore

__all__ = ["BlobStore", "FileHandler", "WktFileHandler"]

_SPEC = ("filename:String,thumbnail:String,dtg:Date,"
         "*geom:Point:srid=4326;geomesa.index.dtg='dtg'")


class FileHandler:
    """SPI: can this handler extract (x, y, dtg) metadata for an input?
    (blob/core/handlers/FileHandler analog)."""

    def can_process(self, filename: str, params: dict) -> bool:
        raise NotImplementedError

    def extract(self, data: bytes, filename: str,
                params: dict) -> dict[str, Any]:
        raise NotImplementedError


class WktFileHandler(FileHandler):
    """Metadata passed explicitly via params (wkt/x/y/dtg) — the
    WKTFileHandler of the reference."""

    def can_process(self, filename: str, params: dict) -> bool:
        return "wkt" in params or ("x" in params and "y" in params)

    def extract(self, data, filename, params):
        if "wkt" in params:
            from ..geometry.wkt import parse_wkt
            g = parse_wkt(params["wkt"])
            c = g.centroid if hasattr(g, "centroid") else g
            x, y = float(c.x), float(c.y)
        else:
            x, y = float(params["x"]), float(params["y"])
        return {"x": x, "y": y, "dtg": int(params.get("dtg", 0)),
                "filename": filename}


class BlobStore:
    def __init__(self, directory: str | None = None,
                 handlers: list[FileHandler] | None = None):
        self.directory = directory
        if directory:
            os.makedirs(directory, exist_ok=True)
        self._blobs: dict[str, bytes] = {}
        self.handlers = handlers or [WktFileHandler()]
        self._store = InMemoryDataStore()
        self._store.create_schema(parse_spec("blobs", _SPEC))

    # -- io ---------------------------------------------------------------

    def put(self, data: bytes, filename: str = "",
            **params) -> str:
        """Store a blob; a FileHandler extracts geo metadata. Returns id."""
        for h in self.handlers:
            if h.can_process(filename, params):
                meta = h.extract(data, filename, params)
                break
        else:
            raise ValueError(f"no handler for {filename!r} with "
                             f"params {sorted(params)}")
        blob_id = uuid.uuid4().hex
        if self.directory:
            with open(os.path.join(self.directory, blob_id), "wb") as fh:
                fh.write(data)
        else:
            self._blobs[blob_id] = data
        self._store.write("blobs", FeatureBatch.from_dict(
            self._store.get_schema("blobs"), [blob_id],
            {"filename": [meta.get("filename") or filename],
             "thumbnail": [None],
             "dtg": np.array([meta.get("dtg", 0)], dtype=np.int64),
             "geom": (np.array([meta["x"]]), np.array([meta["y"]]))}))
        return blob_id

    def get(self, blob_id: str) -> tuple[bytes, str] | None:
        """(payload, filename) or None."""
        res = self._store.query(Query("blobs", f"IN ('{blob_id}')"))
        if res.batch is None or res.batch.n == 0:
            return None
        fname = res.batch.col("filename").value(0) or ""
        if self.directory:
            path = os.path.join(self.directory, blob_id)
            if not os.path.exists(path):
                return None
            with open(path, "rb") as fh:
                return fh.read(), fname
        data = self._blobs.get(blob_id)
        return None if data is None else (data, fname)

    def delete(self, blob_id: str):
        self._store.delete("blobs", [blob_id])
        if self.directory:
            path = os.path.join(self.directory, blob_id)
            if os.path.exists(path):
                os.remove(path)
        else:
            self._blobs.pop(blob_id, None)

    # -- queries -----------------------------------------------------------

    def query_ids(self, ecql: str) -> list[str]:
        """Blob ids whose metadata matches (BlobstoreServlet query)."""
        res = self._store.query(Query("blobs", ecql))
        return [] if res.batch is None else [str(i) for i in res.batch.ids]
