"""Schema-less GeoJSON store (geomesa-geojson analog:
geojson/GeoJsonGtIndex.scala:42 — arbitrary GeoJSON features indexed
without a declared schema, queried with dot-notation property paths).

Properties flatten to dot-notation keys; an SFT is inferred (and
widened) from observed values, so the store keeps the columnar device
execution path underneath. Queries accept either a mongo-ish property
dict ({"properties.name": "x", "geo.bbox": [..]}) or raw ECQL over the
flattened attribute names (dots become '$').
"""

from __future__ import annotations

import json
from typing import Any, Iterable

import numpy as np

from ..features.batch import FeatureBatch
from ..features.sft import AttributeSpec, AttributeType, SimpleFeatureType
from ..geometry.geojson import from_geojson, to_geojson
from ..index.api import Query
from ..store.memory import InMemoryDataStore

__all__ = ["GeoJsonIndex"]


def _flatten(obj: Any, prefix: str = "") -> dict[str, Any]:
    out: dict[str, Any] = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            key = f"{prefix}.{k}" if prefix else str(k)
            if isinstance(v, dict):
                out.update(_flatten(v, key))
            else:
                out[key] = v
    return out


def _attr_name(path: str) -> str:
    """Dot paths are legal SFT attribute names and ECQL identifiers, so
    they pass through unchanged (the 'dot notation' of GeoJsonQuery)."""
    return path


def _infer_type(values: list) -> str:
    kinds = {type(v) for v in values if v is not None}
    if kinds <= {bool}:
        return "Boolean"
    if kinds <= {int, bool}:
        return "Long"
    if kinds <= {int, float, bool}:
        return "Double"
    return "String"


class GeoJsonIndex:
    """Index GeoJSON features; ids auto-assigned unless present."""

    def __init__(self, name: str = "geojson"):
        self.name = name
        self._store = InMemoryDataStore()
        self._attrs: dict[str, str] = {}   # attr name -> type
        self._counter = 0
        self._rows: list[dict] = []        # raw rows (re-typed on schema growth)
        self._geoms: list = []
        self._ids: list[str] = []

    # -- writes ------------------------------------------------------------

    def put(self, features) -> list[str]:
        """Add GeoJSON: a Feature, FeatureCollection, or iterable."""
        feats = self._normalize(features)
        ids = []
        for f in feats:
            fid = str(f.get("id") or f"gj{self._counter}")
            self._counter += 1
            props = _flatten(f.get("properties") or {})
            row = {_attr_name(k): v for k, v in props.items()}
            geom = f.get("geometry")
            self._ids.append(fid)
            self._rows.append(row)
            self._geoms.append(from_geojson(geom) if geom else None)
            ids.append(fid)
        self._rebuild()
        return ids

    def _normalize(self, features) -> list[dict]:
        if isinstance(features, str):
            features = json.loads(features)
        if isinstance(features, dict):
            if features.get("type") == "FeatureCollection":
                return list(features.get("features") or [])
            return [features]
        return list(features)

    def _rebuild(self):
        # widen schema to cover all observed keys
        cols: dict[str, list] = {}
        for key in {k for r in self._rows for k in r}:
            cols[key] = [r.get(key) for r in self._rows]
        attrs = [AttributeSpec(k, AttributeType(_infer_type(v)))
                 for k, v in sorted(cols.items())]
        attrs.append(AttributeSpec("geom", AttributeType("Geometry"),
                                   default_geom=True))
        sft = SimpleFeatureType(self.name, attrs)
        store = InMemoryDataStore()
        store.create_schema(sft)
        data: dict[str, Any] = {k: v for k, v in cols.items()}
        data["geom"] = self._geoms
        if self._ids:
            store.write(self.name, FeatureBatch.from_dict(
                sft, np.asarray(self._ids, dtype=object), data))
        self._store = store
        self._sft = sft

    # -- queries -----------------------------------------------------------

    def query(self, q: "dict | str" = "INCLUDE") -> list[dict]:
        """Return GeoJSON features. Dict queries: {"properties.a.b": value}
        for equality, {"bbox": [x0,y0,x1,y1]} for spatial."""
        ecql = q if isinstance(q, str) else self._dict_to_ecql(q)
        res = self._store.query(Query(self.name, ecql))
        out = []
        if res.batch is not None:
            gcol = res.batch.columns["geom"]
            for i in range(res.batch.n):
                props: dict[str, Any] = {}
                for a in self._sft.attributes:
                    if a.name == "geom":
                        continue
                    v = res.batch.columns[a.name].value(i)
                    if v is not None:
                        _set_path(props, a.name.split("."), v)
                g = gcol.value(i)
                out.append({"type": "Feature",
                            "id": str(res.batch.ids[i]),
                            "geometry": to_geojson(g) if g is not None
                            else None,
                            "properties": props})
        return out

    def _dict_to_ecql(self, q: dict) -> str:
        clauses = []
        for k, v in q.items():
            if k == "bbox":
                clauses.append(f"BBOX(geom, {v[0]}, {v[1]}, {v[2]}, {v[3]})")
            else:
                attr = _attr_name(k)
                if attr not in {a.name for a in self._sft.attributes}:
                    return "EXCLUDE"
                if isinstance(v, str):
                    clauses.append(f"{attr} = '{v}'")
                else:
                    clauses.append(f"{attr} = {v}")
        return " AND ".join(clauses) if clauses else "INCLUDE"

    def get(self, fid: str) -> dict | None:
        hits = self.query(f"IN ('{fid}')")
        return hits[0] if hits else None

    def delete(self, fids: Iterable[str]):
        drop = set(fids)
        keep = [i for i, f in enumerate(self._ids) if f not in drop]
        self._ids = [self._ids[i] for i in keep]
        self._rows = [self._rows[i] for i in keep]
        self._geoms = [self._geoms[i] for i in keep]
        self._rebuild()

    @property
    def size(self) -> int:
        return len(self._ids)


def _set_path(d: dict, parts: list[str], value):
    for p in parts[:-1]:
        d = d.setdefault(p, {})
    d[parts[-1]] = value
