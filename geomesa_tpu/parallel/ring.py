"""Ring collectives: distributed join + KNN over the device mesh.

The reference scales joins by spatially partitioning both sides and
joining partition-aligned pairs on Spark executors
(GeoMesaSparkSQL.scala:228-289,312-360 zipPartitions sweepline); its
KNN is an iterative geohash-spiral (knn/KNNQuery.scala:27). On a TPU
mesh the same work becomes ring pipelines (the ring-attention shape):

- **Ring join**: left side stays sharded and resident; the right side's
  shard rotates around the ring via ``ppermute``. After ``n_devices``
  steps every (left-shard, right-shard) block pair has met exactly
  once, with compute and ICI transfer overlapped — no all-gather
  memory spike, communication cost = one right-shard per step over
  ICI (SURVEY.md §2.6 "TPU-native equivalent").
- **KNN**: shard-local top-k prune (f32), ``all_gather`` of the tiny
  per-shard candidate sets, exact f64 re-rank on host.

f32 distance arithmetic is conservative: pairs within ``band`` of the
radius are counted separately so callers can resolve them exactly on
host (same two-tier contract as analytics/join.dwithin_join).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.jaxcache import ensure_compile_cache

ensure_compile_cache()
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax.shard_map graduated from jax.experimental in newer releases;
# resolve whichever this jax ships
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

__all__ = ["ring_dwithin_counts", "distributed_knn", "shard_points",
           "shard_points_split"]


def shard_points(x: np.ndarray, y: np.ndarray, mesh: Mesh, fill=1e9):
    """Pad to equal shards and device_put sharded f32 coords.

    Returns (xj, yj, valid, n): pad rows get `fill` (far outside any
    realistic query) and valid=False."""
    n = len(x)
    k = mesh.devices.size
    n_padded = ((n + k - 1) // k) * k
    pad = n_padded - n

    def prep(a):
        a = np.asarray(a, np.float64).astype(np.float32)
        return np.concatenate([a, np.full(pad, fill, np.float32)]) if pad else a

    valid = np.ones(n_padded, dtype=bool)
    valid[n:] = False
    sharding = NamedSharding(mesh, P("data"))
    put = functools.partial(jax.device_put, device=sharding)
    return put(prep(x)), put(prep(y)), put(valid), n


@functools.lru_cache(maxsize=32)
def _ring_dwithin_fn(mesh: Mesh, r_in2: float, r_out2: float):
    k = mesh.shape["data"]
    perm = [(i, (i + 1) % k) for i in range(k)]

    def body(lx, ly, lvalid, rx, ry, rvalid):
        def block(rx, ry, rvalid, sure, band):
            d2 = ((lx[:, None] - rx[None, :]) ** 2
                  + (ly[:, None] - ry[None, :]) ** 2)
            ok = rvalid[None, :]
            sure = sure + jnp.sum((d2 <= r_in2) & ok, axis=1,
                                  dtype=jnp.int32)
            band = band + jnp.sum((d2 > r_in2) & (d2 <= r_out2) & ok,
                                  axis=1, dtype=jnp.int32)
            return sure, band

        def step(_, carry):
            rx, ry, rvalid, sure, band = carry
            sure, band = block(rx, ry, rvalid, sure, band)
            rx = lax.ppermute(rx, "data", perm)
            ry = lax.ppermute(ry, "data", perm)
            rvalid = lax.ppermute(rvalid, "data", perm)
            return rx, ry, rvalid, sure, band

        # the carry must be marked device-varying over the mesh axis to
        # match the loop outputs under shard_map
        zeros = jnp.zeros(lx.shape, jnp.int32)
        pcast = getattr(lax, "pcast", None)
        pvary = getattr(lax, "pvary", None)
        if pcast is not None:
            zeros = pcast(zeros, "data", to="varying")
        elif pvary is not None:
            zeros = pvary(zeros, ("data",))
        # else: jax predates varying-ness tracking; shard_map accepts
        # the replicated carry as-is
        # k-1 [compute, rotate] steps, then the final block without the
        # rotation (its permuted output would be discarded)
        rx, ry, rvalid, sure, band = lax.fori_loop(
            0, k - 1, step, (rx, ry, rvalid, zeros, zeros))
        sure, band = block(rx, ry, rvalid, sure, band)
        return jnp.where(lvalid, sure, 0), jnp.where(lvalid, band, 0)

    specs = (P("data"),) * 6
    return jax.jit(_shard_map(body, mesh=mesh, in_specs=specs,
                                 out_specs=(P("data"), P("data"))))


def ring_dwithin_counts(lx, ly, lvalid, rx, ry, rvalid, mesh: Mesh,
                        radius_deg: float, coord_span: float = 360.0):
    """Per-left-point neighbor counts within `radius_deg` (planar) of
    any right point, via the ring pipeline.

    Returns (sure, band_counts) host int32 arrays over the padded left
    length: `sure` pairs are definitely within radius in f64 terms;
    left rows with band_counts > 0 have pairs inside the f32 error band
    around the radius and need a host f64 recheck for exact totals.
    The band is derived from f32 eps and `coord_span` (the coordinate
    magnitude bound — 360 for degrees; pass the actual span for
    projected coordinates) via the same rule as
    analytics/join._f32_band, so the contract holds at any scale.
    """
    from ..utils.fp import f32_band
    r2_hi, r2_lo = f32_band(radius_deg, coord_span)
    fn = _ring_dwithin_fn(mesh, float(r2_lo), float(r2_hi))
    sure, bandc = fn(lx, ly, lvalid, rx, ry, rvalid)
    return np.asarray(sure), np.asarray(bandc)


def shard_points_split(x: np.ndarray, y: np.ndarray, mesh: Mesh,
                       fill=1e9):
    """Two-float sharded coords: ((xhi, xlo, yhi, ylo), valid, n).

    The (hi, lo) pairs reconstruct f64 to ~1e-12 deg on host, so exact
    re-ranks never need a full host coordinate copy — candidate coords
    travel back with the candidates themselves (tiny transfers), which
    is what keeps distributed KNN distributed at 50M+ rows."""
    from ..scan.zscan import split_two_float
    n = len(x)
    k = mesh.devices.size
    n_padded = ((n + k - 1) // k) * k
    pad = n_padded - n

    def padded(a):
        a = np.asarray(a, np.float64)
        return np.concatenate([a, np.full(pad, fill)]) if pad else a

    xhi, xlo = split_two_float(padded(x))
    yhi, ylo = split_two_float(padded(y))
    valid = np.ones(n_padded, dtype=bool)
    valid[n:] = False
    sharding = NamedSharding(mesh, P("data"))
    put = functools.partial(jax.device_put, device=sharding)
    return ((put(xhi), put(xlo), put(yhi), put(ylo)), put(valid), n)


@functools.lru_cache(maxsize=32)
def _knn_prune_split_fn(mesh: Mesh, k: int):
    """Shard-local prune that also ships each candidate's two-float
    coords back — the exact re-rank needs only these 4k floats per
    shard, not the whole table."""
    def body(xhi, xlo, yhi, ylo, pvalid, q):
        d2 = (xhi - q[0]) ** 2 + (yhi - q[1]) ** 2
        d2 = jnp.where(pvalid, d2, jnp.float32(np.inf))
        neg_top, idx = lax.top_k(-d2, k)
        shard = lax.axis_index("data")
        gids = shard.astype(jnp.int32) * xhi.shape[0] + idx.astype(jnp.int32)
        take = lambda a: jnp.take(a, idx)
        return (-neg_top, gids, take(xhi), take(xlo), take(yhi), take(ylo))

    return jax.jit(_shard_map(
        body, mesh=mesh,
        in_specs=(P("data"),) * 5 + (P(),),
        out_specs=(P("data"),) * 6))


@functools.lru_cache(maxsize=32)
def _knn_prune_fn(mesh: Mesh, k: int):
    def body(px, py, pvalid, q):
        d2 = (px - q[0]) ** 2 + (py - q[1]) ** 2
        d2 = jnp.where(pvalid, d2, jnp.float32(np.inf))
        neg_top, idx = lax.top_k(-d2, k)
        # global row ids: shard offset + local index
        shard = lax.axis_index("data")
        gids = shard.astype(jnp.int32) * px.shape[0] + idx.astype(jnp.int32)
        # each shard emits its k candidates; the (k * n_devices)-row
        # sharded outputs gather host-side (tiny transfer)
        return -neg_top, gids

    return jax.jit(_shard_map(
        body, mesh=mesh, in_specs=(P("data"), P("data"), P("data"), P()),
        out_specs=(P("data"), P("data"))))


def distributed_knn(px, py, pvalid, mesh: Mesh, n: int,
                    qx: float, qy: float, k: int,
                    host_x: np.ndarray | None = None,
                    host_y: np.ndarray | None = None,
                    split=None) -> np.ndarray:
    """k nearest rows to (qx, qy): device prune to k candidates per
    shard, gather the tiny candidate sets, exact re-rank on host.

    Each shard over-fetches (2k + 16 candidates, clamped to the shard
    length) so f32 ranking ties at the k-th boundary don't drop a true
    f64 top-k member; the result is exact unless more than 2k + 16
    points of one shard land inside the f32 error band of the k-th
    distance (vanishing for real data; the reference's geohash-spiral
    KNN is likewise approximate at its precision floor,
    knn/KNNQuery.scala:27).

    Exact re-rank sources, in preference order:
    - ``split`` (from shard_points_split, pass px=py=None): candidates
      return WITH their two-float coords, reconstructed host-side to
      ~1e-12 deg — no host coordinate copy at any scale;
    - ``host_x/host_y``: full f64 host arrays (small tables only);
    - neither: the f32 device distances rank as-is.
    Returns global row indices, nearest first.
    """
    kk = min(k, max(n, 1))
    size = mesh.devices.size
    shard_len = (split[0] if split is not None else px).shape[0] // size
    fetch = min(2 * kk + 16, max(shard_len, 1))
    q = jnp.asarray(np.array([qx, qy], np.float32))
    if split is not None:
        fn = _knn_prune_split_fn(mesh, fetch)
        dists, gids, cxh, cxl, cyh, cyl = fn(*split, pvalid, q)
        dists = np.asarray(dists)
        gids = np.asarray(gids)
        mask = (dists < np.inf) & (gids < n)
        keep = gids[mask]
        cx = (np.asarray(cxh, np.float64)
              + np.asarray(cxl, np.float64))[mask]
        cy = (np.asarray(cyh, np.float64)
              + np.asarray(cyl, np.float64))[mask]
        d2 = (cx - qx) ** 2 + (cy - qy) ** 2
        order = np.argsort(d2, kind="stable")
        return keep[order][:kk]
    fn = _knn_prune_fn(mesh, fetch)
    dists, gids = fn(px, py, pvalid, q)
    dists = np.asarray(dists)
    gids = np.asarray(gids)
    mask = (dists < np.inf) & (gids < n)
    keep = gids[mask]
    if host_x is not None and host_y is not None:
        d2 = ((host_x[keep] - qx) ** 2 + (host_y[keep] - qy) ** 2)
        order = np.argsort(d2, kind="stable")
    else:
        order = np.argsort(dists[mask], kind="stable")
    return keep[order][:kk]
