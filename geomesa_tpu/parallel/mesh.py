"""Mesh-sharded scans: the multi-chip execution path.

Data parallelism over a ``jax.sharding.Mesh`` axis ``"data"``: feature
columns shard evenly across devices (the analog of tablet splits,
SURVEY.md 2.5 #2-3); the scan kernel runs shard-locally under
``shard_map``; aggregations reduce over ICI with ``psum`` (the analog of
"server-side aggregate -> client reduce", SURVEY.md 2.5 #5).

Masks stay device-resident and sharded — downstream aggregation kernels
(density/stats/bin) consume them without gathering; only final small
results cross to the host.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.jaxcache import ensure_compile_cache

ensure_compile_cache()
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax.shard_map graduated from jax.experimental in newer releases;
# resolve whichever this jax ships
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

from ..scan import zscan

__all__ = ["data_mesh", "DistributedScanData", "shard_scan_data",
           "distributed_scan_mask", "distributed_count",
           "distributed_contains_counts",
           "distributed_density", "distributed_histogram",
           "distributed_minmax", "DistributedExtentData",
           "shard_extent_data", "distributed_tristate"]


def data_mesh(n_devices: int | None = None) -> Mesh:
    """A 1-D mesh over the data axis."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), axis_names=("data",))


@dataclasses.dataclass
class DistributedScanData:
    """Sharded device columns + padding info + host originals (kept for
    the exact f64 boundary patch, mirroring the single-chip store)."""
    xhi: jax.Array
    xlo: jax.Array
    yhi: jax.Array
    ylo: jax.Array
    tday: jax.Array
    tms: jax.Array
    n: int            # true (unpadded) row count
    n_padded: int
    mesh: Mesh
    host_x: np.ndarray
    host_y: np.ndarray
    host_millis: np.ndarray
    host_xhi: np.ndarray
    host_yhi: np.ndarray


def shard_scan_data(x: np.ndarray, y: np.ndarray, millis: np.ndarray,
                    mesh: Mesh) -> DistributedScanData:
    """Host columns -> evenly-sharded device columns (padded so every
    shard is equal; pad rows carry out-of-domain coords so no query
    matches them)."""
    n = len(x)
    k = mesh.devices.size
    n_padded = ((n + k - 1) // k) * k
    pad = n_padded - n

    def prep(arr, fill):
        arr = np.asarray(arr)
        if pad:
            arr = np.concatenate([arr, np.full(pad, fill, dtype=arr.dtype)])
        return arr

    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64)
    millis_h = np.asarray(millis, np.int64)
    xhi, xlo = zscan.split_two_float(prep(x, 1e9))
    yhi, ylo = zscan.split_two_float(prep(y, 1e9))
    millis_p = prep(millis_h, -1)
    tday = (millis_p // zscan.MILLIS_PER_DAY).astype(np.int32)
    tms = (millis_p - tday.astype(np.int64) * zscan.MILLIS_PER_DAY).astype(np.int32)

    sharding = NamedSharding(mesh, P("data"))
    put = functools.partial(jax.device_put, device=sharding)
    return DistributedScanData(
        put(xhi), put(xlo), put(yhi), put(ylo),
        put(tday), put(tms),
        n, n_padded, mesh, x, y, millis_h, xhi[:n], yhi[:n])


def _shard_mask_fn(time_any: bool):
    """Shard-local scan body; runs identically on every device."""
    def body(xhi, xlo, yhi, ylo, tday, tms, boxes, box_valid, times, tvalid):
        return zscan._scan_mask(xhi, xlo, yhi, ylo, tday, tms,
                                boxes, box_valid, times, tvalid, time_any)
    return body


_SPECS_IN = (P("data"), P("data"), P("data"), P("data"),
             P("data"), P("data"), P(), P(), P(), P())


@functools.lru_cache(maxsize=32)
def _mask_fn(mesh: Mesh, time_any: bool):
    return jax.jit(_shard_map(_shard_mask_fn(time_any), mesh=mesh,
                                 in_specs=_SPECS_IN, out_specs=P("data")))


@functools.lru_cache(maxsize=32)
def _count_fn(mesh: Mesh, time_any: bool):
    body = _shard_mask_fn(time_any)

    def counted(*args):
        mask = body(*args)
        return jax.lax.psum(jnp.sum(mask, dtype=jnp.int32), "data")

    return jax.jit(_shard_map(counted, mesh=mesh,
                                 in_specs=_SPECS_IN, out_specs=P()))


def _args(data: DistributedScanData, q: zscan.ScanQuery):
    return (data.xhi, data.xlo, data.yhi, data.ylo, data.tday, data.tms,
            q.boxes, q.box_valid, q.times, q.time_valid)


def distributed_scan_mask(data: DistributedScanData,
                          q: zscan.ScanQuery) -> jax.Array:
    """Run the scan on every shard; returns the sharded bool mask (raw
    device verdict; use ``exact_host_mask`` for the f64-patched result)."""
    return _mask_fn(data.mesh, q.time_any)(*_args(data, q))


def exact_host_mask(data: DistributedScanData, q: zscan.ScanQuery) -> np.ndarray:
    """Gathered host mask with the exact f64 boundary patch applied
    (drops padding rows)."""
    mask = np.asarray(distributed_scan_mask(data, q))[:data.n]
    cand = zscan.boundary_candidates(data.host_xhi, data.host_yhi, q)
    return zscan.exact_patch(mask, cand, data.host_x, data.host_y,
                             data.host_millis, q)


@functools.partial(jax.jit, static_argnames=("cap",))
def _mask_hit_rows(mask, cap):
    """Device-side compaction of a (possibly sharded) scan mask: only
    the hit row ids come back. fill = len(mask), filtered by the
    caller's n bound (padding rows are also >= n)."""
    return jnp.nonzero(mask, size=cap, fill_value=mask.shape[0])[0]


def exact_hit_rows(data: DistributedScanData,
                   q: zscan.ScanQuery) -> np.ndarray:
    """Sorted matching row ids with the exact f64 boundary patch —
    count-then-compact on device, so host work and transfers are
    O(hits + boundary candidates), never a full-length mask (the
    materializing analog of distributed_count's psum shape)."""
    mask = distributed_scan_mask(data, q)
    # int32 is the real contract: single-table row counts are capped
    # below 2^31 (ZKeyIndex._perm_dtype)
    total = int(jnp.sum(mask, dtype=jnp.int32))
    if total:
        cap = 1 << (total - 1).bit_length()
        rows = np.asarray(_mask_hit_rows(mask, cap)).astype(np.int64)
        rows = rows[rows < data.n]
    else:
        rows = np.empty(0, dtype=np.int64)
    # boundary patch in ROW-SET space: recompute the two-float verdict
    # on host for just the boundary candidates, compare with exact f64,
    # and add/remove the flipped rows
    cand = zscan.boundary_candidates(data.host_xhi, data.host_yhi, q)
    if len(cand):
        dev, exact = _boundary_verdicts(data, q, cand)
        add = cand[exact & ~dev]
        remove = cand[dev & ~exact]
        if len(remove):
            rows = np.setdiff1d(rows, remove, assume_unique=True)
        if len(add):
            rows = np.union1d(rows, add)
    # already sorted: nonzero indices ascend, setdiff1d preserves the
    # (sorted) input order, union1d sorts
    return rows


def _boundary_verdicts(data: DistributedScanData, q: zscan.ScanQuery,
                       cand: np.ndarray):
    """(two_float, exact_f64) bool verdicts for the candidate rows,
    with identical arithmetic to the device kernel for the former."""
    dev = np.zeros(len(cand), dtype=bool)
    xhi, xlo = zscan.split_two_float(data.host_x[cand])
    yhi, ylo = zscan.split_two_float(data.host_y[cand])
    boxes = q.boxes_np
    for i in range(q.n_boxes):
        b = boxes[i]
        dev |= (((xhi > b[0]) | ((xhi == b[0]) & (xlo >= b[1])))
                & ((xhi < b[2]) | ((xhi == b[2]) & (xlo <= b[3])))
                & ((yhi > b[4]) | ((yhi == b[4]) & (ylo >= b[5])))
                & ((yhi < b[6]) | ((yhi == b[6]) & (ylo <= b[7]))))
    exact = np.zeros(len(cand), dtype=bool)
    for i in range(q.n_boxes):
        xmin, ymin, xmax, ymax = q.host_boxes[i]
        cx, cy = data.host_x[cand], data.host_y[cand]
        exact |= (cx >= xmin) & (cx <= xmax) & (cy >= ymin) & (cy <= ymax)
    if not q.time_any:
        cm = data.host_millis[cand]
        t_ok = np.zeros(len(cand), dtype=bool)
        for lo, hi in q.host_intervals:
            t_ok |= (cm >= lo) & (cm <= hi)
        dev &= t_ok
        exact &= t_ok
    return dev, exact


def _shard_batch_mask_fn():
    """Shard-local BATCHED scan body: the scalar kernel vmapped over a
    stacked query batch, plus the per-query boundary-candidate mask
    (two-float hi-cell collisions) computed in the same launch. Pad
    rows carry out-of-domain coords (1e9) so neither output can flag
    them; per-query time_any is absorbed into catch-all intervals by
    zscan.stack_queries, so the temporal compare always runs."""
    def body(xhi, xlo, yhi, ylo, tday, tms, boxes, box_valid, times, tvalid):
        def one(bx, bv, tx, tv):
            return (zscan._mask_body(xhi, xlo, yhi, ylo, tday, tms,
                                     bx, bv, tx, tv, time_any=False,
                                     n_valid=None),
                    zscan._cand_body(xhi, yhi, bx, bv))
        return jax.vmap(one)(boxes, box_valid, times, tvalid)
    return body


@functools.lru_cache(maxsize=32)
def _batch_mask_fn(mesh: Mesh):
    return jax.jit(_shard_map(
        _shard_batch_mask_fn(), mesh=mesh, in_specs=_SPECS_IN,
        out_specs=(P(None, "data"), P(None, "data"))))


def batch_exact_hit_rows(data: DistributedScanData,
                         bq: zscan.BatchedScanQuery) -> list[np.ndarray]:
    """Micro-batched exact_hit_rows: ONE shard-mapped launch evaluates
    every query in the batch on every device, then per-query
    count-then-compact keeps host work and transfers O(hits +
    candidates) per query — the multi-query analog of exact_hit_rows."""
    mask, cand = _batch_mask_fn(data.mesh)(
        data.xhi, data.xlo, data.yhi, data.ylo, data.tday, data.tms,
        bq.boxes, bq.box_valid, bq.times, bq.time_valid)
    counts = np.asarray(zscan._batch_count(mask))
    ccounts = np.asarray(zscan._batch_count(cand))
    size = 1 << max(int(counts.max()) - 1, 0).bit_length()
    csize = 1 << max(int(ccounts.max()) - 1, 0).bit_length()
    idx = np.asarray(zscan._batch_nonzero(mask, size))
    cidx = np.asarray(zscan._batch_nonzero(cand, csize))
    out = []
    for i, sq in enumerate(bq.queries):
        rows = idx[i, :counts[i]].astype(np.int64)
        rows = rows[rows < data.n]
        crows = cidx[i, :ccounts[i]].astype(np.int64)
        crows = crows[crows < data.n]
        out.append(zscan.patch_hit_rows(rows, sq, data.host_x,
                                        data.host_y, data.host_millis,
                                        crows))
    return out


def _exact_count_adjustment(data: DistributedScanData,
                            q: zscan.ScanQuery) -> int:
    """Difference between exact-f64 and two-float verdicts over the
    boundary candidates (time is exact in both, so only spatial flips)."""
    cand = zscan.boundary_candidates(data.host_xhi, data.host_yhi, q)
    if len(cand) == 0:
        return 0
    dev, exact = _boundary_verdicts(data, q, cand)
    return int(exact.sum()) - int(dev.sum())


def distributed_count(data: DistributedScanData, q: zscan.ScanQuery) -> int:
    """Fused scan + global count: psum over the mesh (the 'server-side
    aggregate, client reduce' shape in one XLA program), corrected by the
    host boundary adjustment so the result is exact-f64."""
    device = int(_count_fn(data.mesh, q.time_any)(*_args(data, q)))
    return device + _exact_count_adjustment(data, q)


@functools.lru_cache(maxsize=32)
def _density_fn(mesh: Mesh, time_any: bool,
                bbox: tuple[float, float, float, float],
                width: int, height: int):
    body = _shard_mask_fn(time_any)
    xmin, ymin, xmax, ymax = bbox
    sx = width / (xmax - xmin) if xmax > xmin else 0.0
    sy = height / (ymax - ymin) if ymax > ymin else 0.0

    def density(xhi, xlo, yhi, ylo, tday, tms, boxes, bvalid, times, tvalid):
        mask = body(xhi, xlo, yhi, ylo, tday, tms, boxes, bvalid, times, tvalid)
        # GridSnap pixel binning; f32 coords are ample for pixel indices
        x = xhi.astype(jnp.float32) + xlo
        y = yhi.astype(jnp.float32) + ylo
        col = jnp.clip(((x - xmin) * sx).astype(jnp.int32), 0, width - 1)
        row = jnp.clip(((y - ymin) * sy).astype(jnp.int32), 0, height - 1)
        flat = row * width + col
        grid = jnp.zeros((height * width,), dtype=jnp.float32)
        grid = grid.at[flat].add(mask.astype(jnp.float32))
        return jax.lax.psum(grid, "data")

    return jax.jit(_shard_map(density, mesh=mesh,
                                 in_specs=_SPECS_IN, out_specs=P()))


@functools.lru_cache(maxsize=32)
def _hist_fn(mesh: Mesh, nbins: int, lo: float, hi: float):
    scale = nbins / (hi - lo)

    def body(values, mask):
        # np.histogram semantics: values outside [lo, hi] are dropped,
        # the last bin is closed at hi
        mask = mask & (values >= lo) & (values <= hi)
        b = jnp.clip(((values - lo) * scale).astype(jnp.int32), 0, nbins - 1)
        h = jnp.zeros((nbins,), jnp.int32)
        h = h.at[b].add(mask.astype(jnp.int32))
        return jax.lax.psum(h, "data")

    return jax.jit(_shard_map(body, mesh=mesh,
                                 in_specs=(P("data"), P("data")),
                                 out_specs=P()))


def distributed_histogram(values: jax.Array, mask: jax.Array, mesh: Mesh,
                          nbins: int, lo: float, hi: float) -> np.ndarray:
    """Shard-local scatter-add histogram merged over ICI with psum —
    the StatsCombiner server-side merge analog
    (accumulo/data/stats/StatsCombiner.scala; Histogram/BinnedArray,
    utils/stats/). `values`/`mask` are 'data'-sharded f32/bool arrays.
    np.histogram semantics: out-of-range values are dropped."""
    if nbins <= 0 or not hi > lo:
        raise ValueError(f"invalid histogram range: nbins={nbins}, "
                         f"lo={lo}, hi={hi}")
    fn = _hist_fn(mesh, int(nbins), float(lo), float(hi))
    return np.asarray(fn(values, mask))


@functools.lru_cache(maxsize=32)
def _minmax_fn(mesh: Mesh):
    def body(values, mask):
        vmin = jnp.min(jnp.where(mask, values, jnp.float32(np.inf)))
        vmax = jnp.max(jnp.where(mask, values, jnp.float32(-np.inf)))
        return (jax.lax.pmin(vmin, "data"), jax.lax.pmax(vmax, "data"))

    return jax.jit(_shard_map(body, mesh=mesh,
                                 in_specs=(P("data"), P("data")),
                                 out_specs=(P(), P())))


def distributed_minmax(values: jax.Array, mask: jax.Array,
                       mesh: Mesh) -> tuple[float, float]:
    """Global (min, max) of masked sharded values via pmin/pmax
    (MinMax sketch merge, utils/stats/MinMax.scala analog)."""
    vmin, vmax = _minmax_fn(mesh)(values, mask)
    return float(vmin), float(vmax)


@dataclasses.dataclass
class DistributedExtentData:
    """Mesh-sharded per-feature bboxes for the XZ-analog extent scan
    (outward-rounded f32, pad rows valid=False) + optional exact time
    columns — the distributed counterpart of gscan.ExtentScanData."""
    bxmin: jax.Array
    bymin: jax.Array
    bxmax: jax.Array
    bymax: jax.Array
    valid: jax.Array
    tday: jax.Array
    tms: jax.Array
    has_time: bool
    n: int
    n_padded: int
    mesh: Mesh


def shard_extent_data(bounds: np.ndarray, millis: np.ndarray | None,
                      mesh: Mesh) -> DistributedExtentData:
    """(n, 4) f64 bounds [xmin ymin xmax ymax] (NaN rows = null geoms)
    -> evenly-sharded outward-rounded f32 device columns."""
    from ..scan.gscan import _round_out
    bounds = np.asarray(bounds, np.float64)
    n = len(bounds)
    k = mesh.devices.size
    n_padded = ((n + k - 1) // k) * k
    pad = n_padded - n
    valid = ~np.isnan(bounds[:, 0])
    safe = np.where(valid[:, None], bounds, 0.0)
    xmin, xmax = _round_out(safe[:, 0], safe[:, 2])
    ymin, ymax = _round_out(safe[:, 1], safe[:, 3])

    def prep(a, fill, dtype):
        a = np.asarray(a, dtype)
        if pad:
            a = np.concatenate([a, np.full(pad, fill, dtype)])
        return a

    has_time = millis is not None
    if has_time:
        millis = np.asarray(millis, np.int64)
        tday = (millis // zscan.MILLIS_PER_DAY).astype(np.int32)
        tms = (millis - tday.astype(np.int64)
               * zscan.MILLIS_PER_DAY).astype(np.int32)
    else:
        tday = np.zeros(n, np.int32)
        tms = np.zeros(n, np.int32)

    sharding = NamedSharding(mesh, P("data"))
    put = functools.partial(jax.device_put, device=sharding)
    return DistributedExtentData(
        put(prep(xmin, 0, np.float32)), put(prep(ymin, 0, np.float32)),
        put(prep(xmax, 0, np.float32)), put(prep(ymax, 0, np.float32)),
        put(prep(valid, False, bool)),
        put(prep(tday, 0, np.int32)), put(prep(tms, 0, np.int32)),
        has_time, n, n_padded, mesh)


@functools.lru_cache(maxsize=32)
def _tristate_fn(mesh: Mesh, time_any: bool, has_time: bool):
    from ..scan import gscan

    def body(bxmin, bymin, bxmax, bymax, valid, tday, tms,
             outer, inner, bvalid, times, tvalid):
        return gscan._tristate_body(bxmin, bymin, bxmax, bymax, valid,
                                    tday, tms, outer, inner, bvalid,
                                    times, tvalid, time_any, has_time)

    return jax.jit(_shard_map(
        body, mesh=mesh, in_specs=(P("data"),) * 7 + (P(),) * 5,
        out_specs=P("data")))


def distributed_tristate(data: DistributedExtentData, q) -> np.ndarray:
    """Shard-local extent tristate classification over the mesh;
    returns host int8[n] (0=OUT, 1=MAYBE, 2=IN) with padding dropped.
    Same exactness contract as gscan.extent_tristate — the MAYBE band
    goes to the caller's exact host predicate."""
    fn = _tristate_fn(data.mesh, q.time_any, data.has_time)
    out = fn(data.bxmin, data.bymin, data.bxmax, data.bymax, data.valid,
             data.tday, data.tms,
             q.outer, q.inner, q.box_valid, q.times, q.time_valid)
    return np.asarray(out)[:data.n]


@functools.lru_cache(maxsize=32)
def _contains_fn(mesh: Mesh, band_cap: int):
    """Shard-local ST_Contains partial counts: every device runs the
    f32 crossing-number PIP over its own point shard for ALL polygons
    (lax.map — sequential per polygon, one launch), psums the definite
    counts over ICI, and compacts its band rows (global ids via
    axis_index) so the host patch stays O(band)."""
    from ..analytics.join import _pip_body
    from ..scan.gscan import EDGE_EPS

    def body(x, y, boxes, edges, evalid):
        eps = jnp.float32(EDGE_EPS)
        base = jax.lax.axis_index("data") * x.shape[0]

        def one(args):
            bx, e, ev = args
            inbox = ((x >= bx[0] - eps) & (x <= bx[2] + eps)
                     & (y >= bx[1] - eps) & (y <= bx[3] + eps))
            inside, band = _pip_body(x, y, e, ev)
            definite = inbox & inside & ~band
            banded = inbox & band
            bpos = jnp.flatnonzero(banded, size=band_cap, fill_value=-1)
            grows = jnp.where(bpos >= 0, base + bpos, -1)
            return (jnp.sum(definite, dtype=jnp.int32),
                    jnp.sum(banded, dtype=jnp.int32)[None],
                    grows.astype(jnp.int32))

        dc, bc, brows = jax.lax.map(one, (boxes, edges, evalid))
        return jax.lax.psum(dc, "data"), bc, brows

    return jax.jit(_shard_map(
        body, mesh=mesh,
        in_specs=(P("data"), P("data"), P(), P(), P()),
        out_specs=(P(), P(None, "data"), P(None, "data"))))


def distributed_contains_counts(data: DistributedScanData, polygons,
                                band_cap: int = 512) -> np.ndarray:
    """Mesh-sharded exact ST_Contains counts: points vs many polygons.

    The multi-chip promotion of analytics/join.contains_join's counts
    path — device-local partial verdicts merge over ICI (psum for the
    definite counts) and only per-shard band rows (points within
    gscan.EDGE_EPS of a boundary) come back for the exact host f64
    patch, so counts carry the same exact-by-construction contract.
    Shards whose band overflows ``band_cap`` fall back to an exact host
    recount of that polygon's bbox candidates (rare: the band is a
    ~1e-4 deg strip around the boundary)."""
    from ..analytics.join import _poly_pad, pack_polygon_batch
    from ..analytics.st_functions import contains_points
    k = len(polygons)
    counts = np.zeros(k, dtype=np.int64)
    if k == 0 or data.n == 0:
        return counts
    edges, evalid, boxes = pack_polygon_batch(
        polygons, pad_to=_poly_pad(k))
    dc, bc, brows = _contains_fn(data.mesh, int(band_cap))(
        data.xhi, data.yhi, jnp.asarray(boxes), jnp.asarray(edges),
        jnp.asarray(evalid))
    counts[:] = np.asarray(dc)[:k]
    bc = np.asarray(bc)[:k]          # (k, ndev) per-shard band counts
    brows = np.asarray(brows)[:k]    # (k, band_cap * ndev) global ids
    hx, hy = data.host_x, data.host_y
    for j in np.flatnonzero(bc.sum(axis=1)):
        poly = polygons[j]
        if (bc[j] > band_cap).any():
            # a shard compacted fewer band rows than it had: recount
            # this polygon exactly on host over its bbox candidates
            xmin, ymin, xmax, ymax = poly.envelope.as_tuple()
            m = ((hx >= xmin) & (hx <= xmax)
                 & (hy >= ymin) & (hy <= ymax))
            counts[j] = int(contains_points(poly, hx[m], hy[m]).sum())
            continue
        rows = brows[j]
        rows = rows[(rows >= 0) & (rows < data.n)]
        counts[j] += int(contains_points(poly, hx[rows],
                                         hy[rows]).sum())
    return counts


def distributed_density(data: DistributedScanData, q: zscan.ScanQuery,
                        bbox: tuple[float, float, float, float],
                        width: int, height: int) -> np.ndarray:
    """Density surface: shard-local scatter-add onto the pixel grid,
    psum over ICI (DensityScan analog, index/iterators/DensityScan.scala:30).
    Pixel-snap output; boundary-band f64 differences are below pixel
    resolution, so no host patch is applied."""
    fn = _density_fn(data.mesh, q.time_any,
                     tuple(float(v) for v in bbox), width, height)
    out = fn(*_args(data, q))
    return np.asarray(out).reshape(height, width)
