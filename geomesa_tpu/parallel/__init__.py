"""Distribution layer: device meshes, sharded scans, ICI collectives.

The reference distributes scans across database tablet/region servers
and reduces partial aggregates client-side (SURVEY.md 2.5/2.6); here the
"servers" are mesh devices holding column shards, the "iterator stack"
is a shard_map'd kernel, and the "client reduce" is a psum/all_gather
over ICI. Ring pipelines (ring.py) cover the join/KNN shapes the
reference runs on Spark executors.
"""

from .mesh import (DistributedExtentData, DistributedScanData, data_mesh,
                   distributed_count, distributed_density,
                   distributed_histogram, distributed_minmax,
                   distributed_scan_mask, distributed_tristate,
                   exact_hit_rows, exact_host_mask, shard_extent_data,
                   shard_scan_data)
from .ring import (distributed_knn, ring_dwithin_counts, shard_points,
                   shard_points_split)

__all__ = ["DistributedExtentData", "DistributedScanData", "data_mesh",
           "distributed_count", "distributed_density",
           "distributed_histogram", "distributed_minmax",
           "distributed_scan_mask", "distributed_tristate",
           "exact_hit_rows", "exact_host_mask", "shard_extent_data",
           "shard_scan_data",
           "distributed_knn", "ring_dwithin_counts", "shard_points",
           "shard_points_split"]
