"""Distribution layer: device meshes, sharded scans, ICI collectives.

The reference distributes scans across database tablet/region servers
and reduces partial aggregates client-side (SURVEY.md 2.5/2.6); here the
"servers" are mesh devices holding column shards, the "iterator stack"
is a shard_map'd kernel, and the "client reduce" is a psum/all_gather
over ICI.
"""

from .mesh import (DistributedScanData, data_mesh, distributed_count,
                   distributed_density, distributed_scan_mask,
                   exact_host_mask, shard_scan_data)

__all__ = ["DistributedScanData", "data_mesh", "distributed_count",
           "distributed_density", "distributed_scan_mask",
           "exact_host_mask", "shard_scan_data"]
