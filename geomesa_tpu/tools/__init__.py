"""L9 CLI (geomesa-tools analog, SURVEY.md 2.4)."""

from .cli import main

__all__ = ["main"]
