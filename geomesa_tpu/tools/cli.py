"""Command implementations for the geomesa-tpu CLI.

Subcommands (mirroring the reference's tools/ command set):

    create-schema   --path R --name T --spec S [--partition-scheme ...]
    describe-schema --path R --name T
    delete-schema   --path R --name T
    list-schemas    --path R
    ingest          --path R --name T --converter conf.json FILES...
    export          --path R --name T [--cql F]
                    [--format csv|tsv|geojson|gml|avro|arrow|arrow-stream|bin]
                    (arrow-stream/bin stream: constant memory, SIGPIPE-clean)
    count           --path R --name T [--cql F]
    explain         --path R --name T --cql F
    stats           --path R --name T --stat-spec 'MinMax(a)' [--cql F]
    density         --path R --name T --bbox x1,y1,x2,y2 --size WxH [--cql F]
    sql             --path R 'SELECT ... WHERE ST_...'
    serve           --path R [--host H] [--port P]
    wal inspect|replay|truncate --wal-dir D [--below-lsn N] [--token T]
    integrity verify|scrub --wal-dir D [--token T]
    replication status|promote --path remote://h:p [--token T]
    version / env
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np

__all__ = ["main"]


def _store(args):
    """Datastore from the --path argument (the per-backend runner
    dispatch of the reference's CLI): a plain directory opens the
    parquet fs store; ``fs-mesh://<dir>`` serves the same durable root
    through the device mesh; ``remote://host:port`` speaks to a
    GeoMesaWebServer over the network."""
    path = args.path
    if path.startswith("remote://"):
        from ..store import RemoteDataStore
        host, _, port = path[len("remote://"):].partition(":")
        # no explicit port -> the serve command's default
        return RemoteDataStore(host or "127.0.0.1",
                               int(port) if port else 8080)
    if path.startswith("cluster://"):
        from ..cluster import ClusterDataStore
        return ClusterDataStore.from_uri(path)
    if path.startswith("fs-mesh://"):
        from ..store import FsBackedDistributedDataStore
        return FsBackedDistributedDataStore(path[len("fs-mesh://"):])
    from ..store import FileSystemDataStore
    return FileSystemDataStore(path)


def cmd_create_schema(args) -> int:
    ds = _store(args)
    scheme = None
    if args.partition_scheme:
        from ..store.partitions import scheme_from_config
        scheme = scheme_from_config(json.loads(args.partition_scheme))
    ds.create_schema(args.name, args.spec, scheme=scheme)
    print(f"created schema {args.name!r}")
    return 0


def cmd_describe_schema(args) -> int:
    sft = _store(args).get_schema(args.name)
    print(f"{sft.type_name}:")
    for a in sft.attributes:
        flags = []
        if a.default_geom:
            flags.append("default-geom")
        if a.indexed:
            flags.append("indexed")
        print(f"  {a.name}: {a.type}" + (f" ({', '.join(flags)})" if flags else ""))
    if sft.user_data:
        print("  user-data:", json.dumps(sft.user_data))
    return 0


def cmd_delete_schema(args) -> int:
    ds = _store(args)
    ds.get_schema(args.name)  # validate (KeyError on absence)
    ds.remove_schema(args.name)
    print(f"deleted schema {args.name!r}")
    return 0


def cmd_list_schemas(args) -> int:
    for name in _store(args).get_type_names():
        print(name)
    return 0


def cmd_ingest(args) -> int:
    """Streaming ingest: converter batches of ``geomesa.ingest.batch.
    rows`` flow through the group-commit pipeline as they parse —
    constant memory over any file size, columnar conversion unless
    ``geomesa.ingest.vectorized=false``, coalesced journal/store writes
    (ingest/pipeline.py). ``--scalar`` forces the record-at-a-time
    oracle; ``--no-pipeline`` writes each chunk directly."""
    from ..convert import EvaluationContext, converter_for
    from ..convert.vectorized import INGEST_VECTORIZED
    ds = _store(args)
    sft = ds.get_schema(args.name)
    with open(args.converter) as fh:
        conf = json.load(fh)
    conv = converter_for(sft, conf)
    if getattr(args, "scalar", False):
        INGEST_VECTORIZED.thread_local_set("false")
    pipe = None
    if not getattr(args, "no_pipeline", False):
        from ..ingest import IngestPipeline
        pipe = IngestPipeline(ds)
    total = EvaluationContext()
    try:
        for path in args.files:
            # per-source context, merged at flush: per-file reporting
            # stays exact even when a future caller converts sources on
            # parallel workers
            ctx = EvaluationContext()
            with open(path) as fh:
                for batch, _ in conv.iter_batches(fh, ctx):
                    if not batch.n:
                        continue
                    if pipe is not None:
                        pipe.write(args.name, batch)  # blocking put
                    else:
                        ds.write(args.name, batch)
            if pipe is not None:
                pipe.flush()
            total.merge(ctx)
            c = ctx.counters()
            print(f"{path}: ingested {c['success']}, "
                  f"failed {c['failure']}")
    finally:
        if pipe is not None:
            pipe.observe_context(total)
            pipe.close()
        if getattr(args, "scalar", False):
            INGEST_VECTORIZED.thread_local_set(None)
    counts = total.counters()
    print(f"total: {counts['success']} ingested, "
          f"{counts['failure']} failed")
    return 0 if counts["failure"] == 0 else 1


def _query(args):
    from ..index.api import Query
    ds = _store(args)
    q = Query(args.name, args.cql or "INCLUDE")
    if getattr(args, "max_features", None):
        q.max_features = args.max_features
    return ds, ds.query(q)


def cmd_export(args) -> int:
    fmt = args.format
    if fmt in ("arrow-stream", "bin"):
        # streaming formats never materialize the result: batches flow
        # from query_stream straight to stdout in constant memory, so
        # `export | head -c ...` over a 100M-row type is safe
        from ..index.api import Query
        ds = _store(args)
        q = Query(args.name, args.cql or "INCLUDE")
        if getattr(args, "max_features", None):
            q.max_features = args.max_features
        raw = sys.stdout.buffer
        if fmt == "arrow-stream":
            from ..arrow.delta import DeltaWriter
            with DeltaWriter(raw, ds.get_schema(args.name)) as w:
                for piece in ds.query_stream(q):
                    w.write(piece)
                    w.flush()
        else:
            from ..scan.aggregations import encode_bin_batch
            sft = ds.get_schema(args.name)
            for piece in ds.query_stream(q):
                raw.write(encode_bin_batch(sft, piece.ids, piece))
        raw.flush()
        return 0
    ds, res = _query(args)
    out = sys.stdout
    if res.batch is None or res.n == 0:
        print("0 features", file=sys.stderr)
        return 0
    if fmt in ("csv", "tsv"):
        sep = "," if fmt == "csv" else "\t"
        names = [a.name for a in res.batch.sft.attributes]
        out.write("id" + sep + sep.join(names) + "\n")
        for f in res.features():
            out.write(sep.join([str(f["id"])] + [
                "" if f[n] is None else str(f[n]) for n in names]) + "\n")
    elif fmt == "geojson":
        from ..geometry.geojson import to_geojson
        feats = []
        geom_field = res.batch.sft.geom_field
        for f in res.features():
            g = f.get(geom_field)
            gj = to_geojson(g) if g is not None else None
            props = {k: v for k, v in f.items()
                     if k not in ("id", geom_field)}
            feats.append({"type": "Feature", "id": f["id"],
                          "geometry": gj, "properties": props})
        json.dump({"type": "FeatureCollection", "features": feats}, out,
                  default=str)
        out.write("\n")
    elif fmt == "arrow":
        from ..arrow.io import write_ipc
        sys.stdout.buffer.write(write_ipc(res.batch.sft, res.batch))
    elif fmt == "avro":
        from ..convert.avro_writer import write_avro_batch
        sys.stdout.buffer.write(write_avro_batch(res.batch.sft, res.batch))
    elif fmt == "gml":
        from xml.sax.saxutils import escape, quoteattr

        from ..geometry import to_wkt
        geom_field = res.batch.sft.geom_field
        out.write('<?xml version="1.0" encoding="UTF-8"?>\n'
                  '<wfs:FeatureCollection xmlns:wfs="http://www.opengis.net'
                  '/wfs" xmlns:gml="http://www.opengis.net/gml">\n')
        for f in res.features():
            out.write(f'  <gml:featureMember><feature fid='
                      f'{quoteattr(str(f["id"]))}>\n')
            for k, v in f.items():
                if k == "id" or v is None:
                    continue
                sv = to_wkt(v) if k == geom_field else str(v)
                out.write(f"    <{k}>{escape(sv)}</{k}>\n")
            out.write("  </feature></gml:featureMember>\n")
        out.write("</wfs:FeatureCollection>\n")
    else:
        print(f"unknown format {fmt!r}", file=sys.stderr)
        return 2
    return 0


def cmd_count(args) -> int:
    ds = _store(args)
    from ..index.api import Query
    print(ds.query_count(Query(args.name, args.cql or "INCLUDE")))
    return 0


def cmd_explain(args) -> int:
    from ..index.api import Query
    ds = _store(args)
    ds.query(Query(args.name, args.cql), explain_out=print)
    return 0


def cmd_stats(args) -> int:
    ds = _store(args)
    # load everything through the fs store's cache then run the sketch
    mem = ds._load(ds._state(args.name),
                   ds._files_for(ds._state(args.name), None))
    stat = mem.stats_query(args.name, args.stat_spec, args.cql)
    print(stat.to_json())
    return 0


def cmd_density(args) -> int:
    ds = _store(args)
    x1, y1, x2, y2 = (float(v) for v in args.bbox.split(","))
    w, h = (int(v) for v in args.size.split("x"))
    mem = ds._load(ds._state(args.name),
                   ds._files_for(ds._state(args.name), None))
    grid = mem.density(args.name, args.cql or "INCLUDE",
                       (x1, y1, x2, y2), w, h)
    json.dump({"width": w, "height": h, "bbox": [x1, y1, x2, y2],
               "grid": grid.tolist()}, sys.stdout)
    print()
    return 0


def cmd_estimate(args) -> int:
    """Sketch-based cardinality estimate (no scan) — the planner's
    view of how many rows a filter matches."""
    from ..sql.planner import estimate_for_store
    est = estimate_for_store(_store(args), args.name,
                             args.cql or "INCLUDE")
    print(json.dumps({"type": args.name, "cql": args.cql or "INCLUDE",
                      "estimate": est}))
    return 0


def cmd_sql(args) -> int:
    """Run a SQL SELECT against the store (spark-sql surface analog)."""
    from ..sql import SqlEngine
    res = SqlEngine(_store(args)).query(args.query)
    if getattr(args, "explain", False):
        # EXPLAIN surface: what pushed down, which legs ran, what
        # merged where (or why execution stayed local)
        print(json.dumps(res.plan or {"mode": "local"}, indent=2,
                         default=str))
        return 0
    print("\t".join(res.names))
    for row in res.rows():
        print("\t".join("" if v is None else str(v) for v in row))
    if not res.complete:
        print(f"# PARTIAL result - missing groups: "
              f"{','.join(res.missing_groups)}", file=sys.stderr)
    return 0


def cmd_serve(args) -> int:
    """REST endpoints over the store (geomesa-web analog)."""
    from ..web import GeoMesaWebServer
    srv = GeoMesaWebServer(_store(args), host=args.host, port=args.port)
    print(f"serving on http://{args.host}:{srv.port}/rest/", file=sys.stderr)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        srv.stop()
    return 0


def cmd_reindex(args) -> int:
    """Migrate a type's z-index layout version (the reference's
    reindex/WriteIndexJob: rebuild index tables at the current layout
    while the old ones keep serving)."""
    from ..features.sft import CURRENT_INDEX_VERSION
    ds = _store(args)
    before = ds.get_schema(args.name).index_version
    to = args.index_version or CURRENT_INDEX_VERSION
    ds.reindex(args.name, to)
    print(f"reindexed {args.name}: v{before} -> v{to}")
    return 0


def _wal_admin_ok(args) -> bool:
    """Mutating wal commands honor the same shared bearer token that
    gates the web tier's mutating endpoints: when
    ``geomesa.web.auth.token`` is set, --token must match."""
    from ..web.server import WEB_AUTH_TOKEN
    expected = WEB_AUTH_TOKEN.get()
    if not expected or getattr(args, "token", None) == expected:
        return True
    print("this command is gated: pass --token matching "
          "geomesa.web.auth.token", file=sys.stderr)
    return False


def cmd_wal(args) -> int:
    """WAL administration over a durable root (the directory passed as
    ``durable_dir=``, holding ``log/`` + ``snapshots/``)."""
    import os
    root = args.wal_dir
    logdir = os.path.join(root, "log")
    if args.wal_command == "inspect":
        # read-only: never truncates a torn tail, safe on a live log
        from ..wal.log import inspect_dir
        from ..wal.snapshot import latest_checkpoint_lsn
        out = inspect_dir(logdir)
        out["checkpoint_lsn"] = latest_checkpoint_lsn(root)
        json.dump(out, sys.stdout, indent=2)
        print()
        return 0
    if args.wal_command == "replay":
        # rebuild a store from checkpoint + log and report what replay
        # did (opening the log repairs a torn tail, like a store reopen)
        from ..store.memory import InMemoryDataStore
        from ..wal.log import WriteAheadLog
        from ..wal.recovery import recover
        store = InMemoryDataStore()
        wal = WriteAheadLog(logdir, fsync="never")
        try:
            report = recover(store, wal, root)
        finally:
            wal.close()
        out = report.to_json_object()
        out["types"] = {tn: store.count(tn)
                        for tn in store.get_type_names()}
        json.dump(out, sys.stdout, indent=2)
        print()
        return 0
    if args.wal_command == "truncate":
        if not _wal_admin_ok(args):
            return 3
        from ..wal.log import WriteAheadLog
        from ..wal.snapshot import latest_checkpoint_lsn
        lsn = (args.below_lsn if args.below_lsn is not None
               else latest_checkpoint_lsn(root))
        if lsn <= 0:
            print("nothing to truncate: no checkpoint and no "
                  "--below-lsn", file=sys.stderr)
            return 2
        wal = WriteAheadLog(logdir, fsync="never")
        try:
            dropped = wal.truncate_below(lsn)
        finally:
            wal.close()
        print(f"dropped {dropped} segment(s) below lsn {lsn}")
        return 0
    print(f"unknown wal command {args.wal_command!r}", file=sys.stderr)
    return 2


def cmd_integrity(args) -> int:
    """Storage integrity over a durable root: ``verify`` is a read-only
    sweep (WAL segment CRCs + checkpoint digests; rc 1 when anything is
    corrupt), ``scrub`` additionally quarantines corrupt checkpoints
    (``*.corrupt``) and is token-gated like the other mutating admin
    commands."""
    root = args.wal_dir
    if args.integrity_command == "verify":
        from ..integrity.scrub import integrity_report
        out = integrity_report(root)
        json.dump(out, sys.stdout, indent=2)
        print()
        return 0 if out["ok"] else 1
    if args.integrity_command == "scrub":
        if not _wal_admin_ok(args):
            return 3
        from ..integrity.scrub import Scrubber
        from ..wal.durable import Journal
        journal = Journal(root, fsync="never")
        try:
            out = Scrubber(journal=journal).run_once()
        finally:
            journal.close()
        json.dump(out, sys.stdout, indent=2)
        print()
        return 0 if out["ok"] else 1
    print(f"unknown integrity command {args.integrity_command!r}",
          file=sys.stderr)
    return 2


def cmd_replication(args) -> int:
    """Replication administration against a serving node: ``status``
    reads /rest/replication (router or shipper view), ``promote``
    forces failover (bearer-gated like the other mutating admin
    surfaces — --token rides as the Authorization header)."""
    path = args.path
    if not path.startswith("remote://"):
        # local roots have no replication role to interrogate — the
        # router/shipper live in a serving process, not on disk
        print("replication commands need --path remote://host:port",
              file=sys.stderr)
        return 2
    from ..store import RemoteDataStore
    host, _, port = path[len("remote://"):].partition(":")
    ds = RemoteDataStore(host or "127.0.0.1", int(port) if port else 8080,
                         auth_token=getattr(args, "token", None))
    if args.repl_command == "status":
        json.dump(ds.replication_status(), sys.stdout, indent=2)
        print()
        return 0
    if args.repl_command == "promote":
        from ..store.remote import RemoteError
        try:
            out = ds.promote()
        except KeyError as e:
            # server's 404: the node has no router role to promote
            print(f"promote refused: {e.args[0]}", file=sys.stderr)
            return 2
        except RemoteError as e:
            if e.status == 403:
                print("promote is gated: pass --token matching "
                      "geomesa.web.auth.token", file=sys.stderr)
                return 3
            raise
        json.dump(out, sys.stdout, indent=2)
        print()
        return 0
    print(f"unknown replication command {args.repl_command!r}",
          file=sys.stderr)
    return 2


def cmd_cluster(args) -> int:
    """Cluster administration: ``status`` reads the coordinator's
    topology (shard groups, owned z-ranges, LSN vector, breakers) —
    from a serving node's /rest/cluster or directly from a
    ``cluster://`` federation uri; ``promote`` forces intra-group
    failover (bearer-gated on remote nodes)."""
    path = args.path
    if path.startswith("cluster://"):
        from ..cluster import ClusterDataStore
        ds = ClusterDataStore.from_uri(path,
                                       auth_token=getattr(args, "token",
                                                          None))
    elif path.startswith("remote://"):
        from ..store import RemoteDataStore
        host, _, port = path[len("remote://"):].partition(":")
        ds = RemoteDataStore(host or "127.0.0.1",
                             int(port) if port else 8080,
                             auth_token=getattr(args, "token", None))
    else:
        print("cluster commands need --path remote://host:port or "
              "cluster://h1:p1,h2:p2", file=sys.stderr)
        return 2
    if args.cluster_command == "status":
        json.dump(ds.cluster_status(), sys.stdout, indent=2)
        print()
        return 0
    if args.cluster_command == "promote":
        from ..store.remote import RemoteError
        try:
            out = ds.promote_group(getattr(args, "group", None))
        except KeyError as e:
            print(f"promote refused: {e.args[0]}", file=sys.stderr)
            return 2
        except ValueError as e:
            print(f"promote refused: {e}", file=sys.stderr)
            return 2
        except RemoteError as e:
            if e.status == 403:
                print("promote is gated: pass --token matching "
                      "geomesa.web.auth.token", file=sys.stderr)
                return 3
            raise
        json.dump(out, sys.stdout, indent=2)
        print()
        return 0
    print(f"unknown cluster command {args.cluster_command!r}",
          file=sys.stderr)
    return 2


def cmd_reshard(args) -> int:
    """Elastic-topology administration: ``status`` dumps the
    epoch-stamped segment map plus resharder state (in-flight
    migration, history, cooldown); ``split``/``migrate`` move z-prefix
    ranges online; ``auto`` ticks (or --state starts/stops) the
    SLO-driven autoscaler. Mutating verbs are bearer-gated on remote
    nodes (403 -> exit 3); typed reshard refusals (kill switch,
    cooldown, broken migration) exit 2."""
    path = args.path
    remote = path.startswith("remote://")
    if remote:
        from ..store import RemoteDataStore
        host, _, port = path[len("remote://"):].partition(":")
        ds = RemoteDataStore(host or "127.0.0.1",
                             int(port) if port else 8080,
                             auth_token=getattr(args, "token", None))
    elif path.startswith("cluster://"):
        from ..cluster import ClusterDataStore
        ds = ClusterDataStore.from_uri(path,
                                       auth_token=getattr(args, "token",
                                                          None))
    else:
        print("reshard commands need --path remote://host:port or "
              "cluster://h1:p1,h2:p2", file=sys.stderr)
        return 2
    from ..cluster.reshard import ReshardError
    from ..store.remote import RemoteError
    cmd = args.reshard_command
    try:
        if cmd == "status":
            out = {"topology": ds.topology(),
                   "reshard": (ds.reshard_status() if remote
                               else ds.resharder.status())}
        elif cmd == "split":
            if remote:
                out = ds.reshard("split", src=args.src, dst=args.dst,
                                 at=args.at)
            else:
                out = ds.resharder.split(args.src, dst=args.dst,
                                         at=args.at, reason="cli")
        elif cmd == "migrate":
            if remote:
                out = ds.reshard("migrate", prefix_lo=args.prefix_lo,
                                 prefix_hi=args.prefix_hi,
                                 src=args.src, dst=args.dst)
            else:
                out = ds.resharder.migrate(args.prefix_lo,
                                           args.prefix_hi, args.src,
                                           args.dst, reason="cli")
        elif cmd == "auto":
            state = getattr(args, "state", None)
            if remote:
                out = ds.reshard("auto", state=state)
            elif state == "on":
                ds.autoscaler.start()
                out = ds.autoscaler.status()
            elif state == "off":
                ds.autoscaler.stop()
                out = ds.autoscaler.status()
            else:
                out = ds.autoscaler.run_once()
        else:
            print(f"unknown reshard command {cmd!r}", file=sys.stderr)
            return 2
    except ReshardError as e:
        print(f"reshard refused: {e}", file=sys.stderr)
        return 2
    except (KeyError, ValueError) as e:
        msg = e.args[0] if e.args else e
        print(f"reshard refused: {msg}", file=sys.stderr)
        return 2
    except RemoteError as e:
        if e.status == 403:
            print("reshard is gated: pass --token matching "
                  "geomesa.web.auth.token", file=sys.stderr)
            return 3
        if e.status == 409:
            print(f"reshard refused: {e}", file=sys.stderr)
            return 2
        raise
    json.dump(out, sys.stdout, indent=2)
    print()
    return 0


def cmd_evolve(args) -> int:
    """Online schema evolution: ``status`` dumps evolver state (active
    evolution phase/cursor, history); ``reindex`` migrates a type's
    z-index layout as a shadow build with WAL-tail catch-up and an
    atomic flip; ``update`` applies a change list (add/widen/drop);
    ``resume``/``abort`` recover an interrupted evolution. Mutating
    verbs are bearer-gated on remote nodes (403 -> exit 3); typed
    evolve refusals (kill switch, verb in flight, bad change spec)
    exit 2."""
    path = args.path
    remote = path.startswith("remote://")
    if remote:
        from ..store import RemoteDataStore
        host, _, port = path[len("remote://"):].partition(":")
        ds = RemoteDataStore(host or "127.0.0.1",
                             int(port) if port else 8080,
                             auth_token=getattr(args, "token", None))
    else:
        ds = _store(args)
        if not hasattr(ds, "evolver"):
            print("store has no schema-evolution plane",
                  file=sys.stderr)
            return 2
    from ..evolve import SchemaEvolutionError
    from ..store.remote import RemoteError
    cmd = args.evolve_command
    changes = None
    if cmd == "update":
        try:
            changes = json.loads(args.changes)
        except ValueError as e:
            print(f"bad --changes JSON: {e}", file=sys.stderr)
            return 2
    try:
        if cmd == "status":
            out = ds.evolve_status() if remote else ds.evolver.status()
        elif cmd == "reindex":
            if remote:
                out = ds.evolve("reindex", type=args.type,
                                version=args.index_version)
            else:
                out = ds.evolver.reindex(args.type, args.index_version)
        elif cmd == "update":
            if remote:
                out = ds.evolve("update", type=args.type,
                                changes=changes)
            else:
                out = ds.evolver.update_schema(args.type, changes)
        elif cmd == "resume":
            out = ds.evolve("resume") if remote else ds.evolver.resume()
        elif cmd == "abort":
            out = ds.evolve("abort") if remote else ds.evolver.abort()
        else:
            print(f"unknown evolve command {cmd!r}", file=sys.stderr)
            return 2
    except SchemaEvolutionError as e:
        print(f"evolve refused: {e}", file=sys.stderr)
        return 2
    except (KeyError, ValueError) as e:
        msg = e.args[0] if e.args else e
        print(f"evolve refused: {msg}", file=sys.stderr)
        return 2
    except RemoteError as e:
        if e.status == 403:
            print("evolve is gated: pass --token matching "
                  "geomesa.web.auth.token", file=sys.stderr)
            return 3
        if e.status in (400, 409):
            print(f"evolve refused: {e}", file=sys.stderr)
            return 2
        raise
    json.dump(out, sys.stdout, indent=2)
    print()
    return 0


def cmd_cache(args) -> int:
    """Materialized-cache administration against a serving node:
    ``status`` dumps the store's cache/version state (entries, bytes,
    hit/miss counters, refresher); ``invalidate`` drops entries (one
    --type or all; bearer-gated on remote nodes)."""
    path = args.path
    if not path.startswith("remote://"):
        print("cache commands need --path remote://host:port",
              file=sys.stderr)
        return 2
    from ..store import RemoteDataStore
    host, _, port = path[len("remote://"):].partition(":")
    ds = RemoteDataStore(host or "127.0.0.1", int(port) if port else 8080,
                         auth_token=getattr(args, "token", None))
    if args.cache_command == "status":
        json.dump(ds.cache_status(), sys.stdout, indent=2)
        print()
        return 0
    if args.cache_command == "invalidate":
        from ..store.remote import RemoteError
        tn = getattr(args, "type", None)
        try:
            n = ds.invalidate_cache(tn)
        except KeyError as e:
            print(f"invalidate refused: {e.args[0]}", file=sys.stderr)
            return 2
        except RemoteError as e:
            if e.status == 403:
                print("invalidate is gated: pass --token matching "
                      "geomesa.web.auth.token", file=sys.stderr)
                return 3
            raise
        json.dump({"invalidated": n, "type": tn}, sys.stdout, indent=2)
        print()
        return 0
    print(f"unknown cache command {args.cache_command!r}",
          file=sys.stderr)
    return 2


def cmd_cq(args) -> int:
    """Continuous-query administration against a serving node:
    ``list`` dumps registered standing queries + device filter-set
    stats; ``register``/``unregister`` mutate the standing population
    (bearer-gated on remote nodes)."""
    path = args.path
    if not path.startswith("remote://"):
        print("cq commands need --path remote://host:port",
              file=sys.stderr)
        return 2
    from ..store import RemoteDataStore
    from ..store.remote import RemoteError
    host, _, port = path[len("remote://"):].partition(":")
    ds = RemoteDataStore(host or "127.0.0.1", int(port) if port else 8080,
                         auth_token=getattr(args, "token", None))
    try:
        if args.cq_command == "list":
            json.dump(ds.cq_status(), sys.stdout, indent=2)
        elif args.cq_command == "register":
            json.dump(ds.cq_register(args.name, getattr(args, "type"),
                                     args.cql or "INCLUDE"),
                      sys.stdout, indent=2)
        elif args.cq_command == "unregister":
            json.dump(ds.cq_unregister(args.name), sys.stdout, indent=2)
        else:
            print(f"unknown cq command {args.cq_command!r}",
                  file=sys.stderr)
            return 2
    except RemoteError as e:
        if e.status == 403:
            print("cq mutation is gated: pass --token matching "
                  "geomesa.web.auth.token", file=sys.stderr)
            return 3
        raise
    print()
    return 0


def cmd_views(args) -> int:
    """Materialized-view administration against a serving node:
    ``list`` dumps registered views + fold counters; ``get`` dumps one
    view's rows at its fold LSN; ``register``/``unregister`` mutate
    the standing population (bearer-gated on remote nodes)."""
    path = args.path
    if not path.startswith("remote://"):
        print("views commands need --path remote://host:port",
              file=sys.stderr)
        return 2
    from ..store import RemoteDataStore
    from ..store.remote import RemoteError
    host, _, port = path[len("remote://"):].partition(":")
    ds = RemoteDataStore(host or "127.0.0.1", int(port) if port else 8080,
                         auth_token=getattr(args, "token", None))
    try:
        if args.views_command == "list":
            json.dump(ds.views_status(), sys.stdout, indent=2)
        elif args.views_command == "get":
            json.dump(ds.views_get(args.name), sys.stdout, indent=2)
        elif args.views_command == "register":
            json.dump(ds.views_register(args.name, args.sql),
                      sys.stdout, indent=2)
        elif args.views_command == "unregister":
            json.dump(ds.views_unregister(args.name), sys.stdout,
                      indent=2)
        else:
            print(f"unknown views command {args.views_command!r}",
                  file=sys.stderr)
            return 2
    except RemoteError as e:
        if e.status == 403:
            print("views mutation is gated: pass --token matching "
                  "geomesa.web.auth.token", file=sys.stderr)
            return 3
        if e.status == 400:
            print(f"statement refused: {e}", file=sys.stderr)
            return 2
        raise
    print()
    return 0


def cmd_trace(args) -> int:
    """Distributed-trace inspection against a serving node: ``list``
    dumps recent trace summaries (id, root, duration, span kinds);
    ``get`` dumps one trace's full span tree by id."""
    path = args.path
    if not path.startswith("remote://"):
        print("trace commands need --path remote://host:port",
              file=sys.stderr)
        return 2
    from ..store import RemoteDataStore
    host, _, port = path[len("remote://"):].partition(":")
    ds = RemoteDataStore(host or "127.0.0.1", int(port) if port else 8080,
                         auth_token=getattr(args, "token", None))
    if args.trace_command == "list":
        json.dump(ds.traces(limit=args.limit), sys.stdout, indent=2)
        print()
        return 0
    if args.trace_command == "get":
        try:
            out = ds.trace(args.id)
        except KeyError:
            # the wire client maps the server's 404 to KeyError
            print(f"no such trace {args.id!r} (evicted or never "
                  "sampled — raise geomesa.trace.sample or "
                  "geomesa.trace.max.spans)", file=sys.stderr)
            return 2
        json.dump(out, sys.stdout, indent=2)
        print()
        return 0
    print(f"unknown trace command {args.trace_command!r}",
          file=sys.stderr)
    return 2


def cmd_health(args) -> int:
    """Runtime-health surfaces of a serving node: ``slo`` dumps the
    burn-rate/alert state, ``runtime`` the compile/device/transfer
    telemetry, ``profile`` the collapsed-stack profile text."""
    path = args.path
    if not path.startswith("remote://"):
        print("health commands need --path remote://host:port",
              file=sys.stderr)
        return 2
    from ..store import RemoteDataStore
    host, _, port = path[len("remote://"):].partition(":")
    ds = RemoteDataStore(host or "127.0.0.1", int(port) if port else 8080,
                         auth_token=getattr(args, "token", None))
    if args.health_command == "slo":
        json.dump(ds.slo_status(), sys.stdout, indent=2)
        print()
        return 0
    if args.health_command == "runtime":
        json.dump(ds.runtime_snapshot(), sys.stdout, indent=2)
        print()
        return 0
    if args.health_command == "profile":
        sys.stdout.write(ds.profile_collapsed())
        return 0
    print(f"unknown health command {args.health_command!r}",
          file=sys.stderr)
    return 2


def cmd_qos(args) -> int:
    """Multi-tenant QoS surface of a serving node: ``status`` dumps
    the per-tenant admission/budget state (GET /rest/qos)."""
    path = args.path
    if not path.startswith("remote://"):
        print("qos commands need --path remote://host:port",
              file=sys.stderr)
        return 2
    from ..store import RemoteDataStore
    host, _, port = path[len("remote://"):].partition(":")
    ds = RemoteDataStore(host or "127.0.0.1", int(port) if port else 8080,
                         auth_token=getattr(args, "token", None))
    if args.qos_command == "status":
        json.dump(ds.qos_status(), sys.stdout, indent=2)
        print()
        return 0
    print(f"unknown qos command {args.qos_command!r}", file=sys.stderr)
    return 2


def cmd_version(args) -> int:
    from .. import __version__
    print(f"geomesa-tpu {__version__}")
    return 0


def cmd_env(args) -> int:
    import jax
    print(f"devices: {jax.devices()}")
    print(f"backend: {jax.default_backend()}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="geomesa-tpu",
                                description="TPU-native spatio-temporal "
                                            "analytics CLI")
    sub = p.add_subparsers(dest="command", required=True)

    def add(name, fn, *specs, needs_store=True):
        sp = sub.add_parser(name)
        if needs_store:
            sp.add_argument("--path", required=True,
                            help="datastore root directory")
        for spec in specs:
            sp.add_argument(*spec[0], **spec[1])
        sp.set_defaults(fn=fn)
        return sp

    name_arg = (["--name"], {"required": True})
    cql_arg = (["--cql"], {"default": None})

    add("create-schema", cmd_create_schema, name_arg,
        (["--spec"], {"required": True}),
        (["--partition-scheme"], {"default": None,
                                  "help": "scheme config JSON"}))
    add("describe-schema", cmd_describe_schema, name_arg)
    add("delete-schema", cmd_delete_schema, name_arg)
    add("list-schemas", cmd_list_schemas)
    add("ingest", cmd_ingest, name_arg,
        (["--converter"], {"required": True}),
        (["--scalar"], {"action": "store_true",
                        "help": "force the record-at-a-time converter "
                                "oracle (kill switch for the columnar "
                                "path)"}),
        (["--no-pipeline"], {"action": "store_true",
                             "help": "write each chunk directly instead "
                                     "of through the group-commit "
                                     "pipeline"}),
        (["files"], {"nargs": "+"}))
    add("export", cmd_export, name_arg, cql_arg,
        (["--format"], {"default": "csv",
                        "help": "csv|tsv|geojson|gml|avro|arrow "
                                "(materialized) or arrow-stream|bin "
                                "(streamed: constant memory, "
                                "SIGPIPE-clean)"}),
        (["--max-features"], {"type": int, "default": None,
                              "dest": "max_features"}))
    add("count", cmd_count, name_arg, cql_arg)
    add("estimate", cmd_estimate, name_arg, cql_arg)
    add("reindex", cmd_reindex, name_arg,
        (["--index-version"], {"type": int, "default": None,
                               "help": "target layout version "
                                       "(default: current)"}))
    add("explain", cmd_explain, name_arg,
        (["--cql"], {"required": True}))
    add("stats", cmd_stats, name_arg, cql_arg,
        (["--stat-spec"], {"required": True}))
    add("density", cmd_density, name_arg, cql_arg,
        (["--bbox"], {"required": True}),
        (["--size"], {"required": True}))
    add("sql", cmd_sql, (["query"], {"help": "SELECT statement"}),
        (["--explain"], {"action": "store_true",
                         "help": "print the distributed plan instead "
                                 "of rows"}))
    add("serve", cmd_serve,
        (["--host"], {"default": "127.0.0.1"}),
        (["--port"], {"type": int, "default": 8080}))
    walp = sub.add_parser("wal", help="write-ahead log administration")
    walsub = walp.add_subparsers(dest="wal_command", required=True)
    for wname, whelp in (("inspect", "summarize segments/records"),
                         ("replay", "rebuild a store from the log and "
                                    "report recovery"),
                         ("truncate", "drop segments below a checkpoint "
                                      "LSN (token-gated)")):
        wp = walsub.add_parser(wname, help=whelp)
        wp.add_argument("--wal-dir", required=True, dest="wal_dir",
                        help="durable root (the durable_dir= directory)")
        if wname == "truncate":
            wp.add_argument("--below-lsn", type=int, default=None,
                            dest="below_lsn",
                            help="retention LSN (default: last "
                                 "checkpoint)")
            wp.add_argument("--token", default=None,
                            help="admin bearer token "
                                 "(geomesa.web.auth.token)")
        wp.set_defaults(fn=cmd_wal)

    intp = sub.add_parser("integrity",
                          help="storage integrity verification / scrub")
    intsub = intp.add_subparsers(dest="integrity_command", required=True)
    for iname, ihelp in (("verify", "read-only sweep: WAL CRCs + "
                                    "checkpoint digests (rc 1 on "
                                    "corruption)"),
                         ("scrub", "verify AND quarantine corrupt "
                                   "checkpoints (token-gated)")):
        ip = intsub.add_parser(iname, help=ihelp)
        ip.add_argument("--wal-dir", required=True, dest="wal_dir",
                        help="durable root (the durable_dir= directory)")
        if iname == "scrub":
            ip.add_argument("--token", default=None,
                            help="admin bearer token "
                                 "(geomesa.web.auth.token)")
        ip.set_defaults(fn=cmd_integrity)

    replp = sub.add_parser("replication",
                           help="replication administration")
    replsub = replp.add_subparsers(dest="repl_command", required=True)
    for rname, rhelp in (("status", "router/shipper replication state"),
                         ("promote", "force failover to the most "
                                     "caught-up replica (token-gated)")):
        rp = replsub.add_parser(rname, help=rhelp)
        rp.add_argument("--path", required=True,
                        help="serving node, remote://host:port")
        rp.add_argument("--token", default=None,
                        help="admin bearer token "
                             "(geomesa.web.auth.token)")
        rp.set_defaults(fn=cmd_replication)

    clp = sub.add_parser("cluster",
                         help="sharded cluster administration")
    clsub = clp.add_subparsers(dest="cluster_command", required=True)
    for cname, chelp in (("status", "shard topology, owned z-ranges, "
                                    "LSN vector, leg breakers"),
                         ("promote", "force intra-group failover "
                                     "(token-gated)")):
        cp = clsub.add_parser(cname, help=chelp)
        cp.add_argument("--path", required=True,
                        help="coordinator node remote://host:port, or "
                             "federation cluster://h1:p1,h2:p2")
        cp.add_argument("--token", default=None,
                        help="admin bearer token "
                             "(geomesa.web.auth.token)")
        if cname == "promote":
            cp.add_argument("--group", default=None,
                            help="shard group name to promote inside")
        cp.set_defaults(fn=cmd_cluster)

    rsp = sub.add_parser("reshard",
                         help="elastic topology: online z-shard "
                              "split/migration + autoscaler")
    rssub = rsp.add_subparsers(dest="reshard_command", required=True)
    for rname, rhelp in (("status", "epoch-stamped segment map + "
                                    "resharder/migration state"),
                         ("split", "split a hot group's widest range "
                                   "at its key-density median "
                                   "(token-gated)"),
                         ("migrate", "move one z-prefix range between "
                                     "groups online (token-gated)"),
                         ("auto", "tick or start/stop the SLO-driven "
                                  "autoscaler (token-gated)")):
        rp_ = rssub.add_parser(rname, help=rhelp)
        rp_.add_argument("--path", required=True,
                         help="coordinator node remote://host:port, or "
                              "federation cluster://h1:p1,h2:p2")
        rp_.add_argument("--token", default=None,
                         help="admin bearer token "
                              "(geomesa.web.auth.token)")
        if rname == "split":
            rp_.add_argument("--src", required=True,
                             help="hot shard group to split")
            rp_.add_argument("--dst", default=None,
                             help="receiving group (default: lowest "
                                  "p99)")
            rp_.add_argument("--at", type=int, default=None,
                             help="split prefix (default: weighted "
                                  "median of the key density)")
        if rname == "migrate":
            rp_.add_argument("--prefix-lo", type=int, required=True,
                             dest="prefix_lo",
                             help="first z prefix to move (inclusive)")
            rp_.add_argument("--prefix-hi", type=int, required=True,
                             dest="prefix_hi",
                             help="last z prefix to move (exclusive)")
            rp_.add_argument("--src", required=True,
                             help="group that owns the range now")
            rp_.add_argument("--dst", required=True,
                             help="group that should own it")
        if rname == "auto":
            rp_.add_argument("--state", choices=("on", "off"),
                             default=None,
                             help="start/stop the background loop "
                                  "(default: run one tick)")
        rp_.set_defaults(fn=cmd_reshard)

    evp = sub.add_parser("evolve",
                         help="online schema evolution: shadow-build "
                              "reindex/update with atomic flip")
    evsub = evp.add_subparsers(dest="evolve_command", required=True)
    for ename, ehelp in (("status", "active evolution phase/cursor + "
                                    "history"),
                         ("reindex", "migrate a type's z-index layout "
                                     "online (token-gated)"),
                         ("update", "add/widen/drop attributes online "
                                    "(token-gated)"),
                         ("resume", "re-drive an interrupted "
                                    "evolution (token-gated)"),
                         ("abort", "cancel and restore the pre-evolve "
                                   "state (token-gated)")):
        ep_ = evsub.add_parser(ename, help=ehelp)
        ep_.add_argument("--path", required=True,
                         help="serving node remote://host:port, or a "
                              "durable store directory")
        ep_.add_argument("--token", default=None,
                         help="admin bearer token "
                              "(geomesa.web.auth.token)")
        if ename in ("reindex", "update"):
            ep_.add_argument("--type", required=True,
                             help="schema to evolve")
        if ename == "reindex":
            ep_.add_argument("--index-version", type=int, default=None,
                             dest="index_version",
                             help="target z-index layout version "
                                  "(default: current)")
        if ename == "update":
            ep_.add_argument("--changes", required=True,
                             help="JSON change list, e.g. "
                                  '\'[{"op": "add", "name": "score", '
                                  '"type": "Double", "default": 0}]\'')
        ep_.set_defaults(fn=cmd_evolve)

    cap = sub.add_parser("cache",
                         help="materialized pushdown-cache "
                              "administration")
    casub = cap.add_subparsers(dest="cache_command", required=True)
    for aname, ahelp in (("status", "cache entries/bytes/counters and "
                                    "pushdown versions"),
                         ("invalidate", "drop cached entries "
                                        "(token-gated)")):
        ap = casub.add_parser(aname, help=ahelp)
        ap.add_argument("--path", required=True,
                        help="serving node, remote://host:port")
        ap.add_argument("--token", default=None,
                        help="admin bearer token "
                             "(geomesa.web.auth.token)")
        if aname == "invalidate":
            ap.add_argument("--type", default=None,
                            help="schema to invalidate (default: all)")
        ap.set_defaults(fn=cmd_cache)

    cqp = sub.add_parser("cq",
                         help="continuous-query (standing geofence) "
                              "administration")
    cqsub = cqp.add_subparsers(dest="cq_command", required=True)
    for qname, qhelp in (("list", "registered queries + device "
                                  "filter-set stats"),
                         ("register", "add a standing query "
                                      "(token-gated)"),
                         ("unregister", "drop a standing query "
                                        "(token-gated)")):
        qp = cqsub.add_parser(qname, help=qhelp)
        qp.add_argument("--path", required=True,
                        help="serving node, remote://host:port")
        qp.add_argument("--token", default=None,
                        help="admin bearer token "
                             "(geomesa.web.auth.token)")
        if qname in ("register", "unregister"):
            qp.add_argument("--name", required=True,
                            help="continuous query name")
        if qname == "register":
            qp.add_argument("--type", required=True,
                            help="schema the query watches")
            qp.add_argument("--cql", default=None,
                            help="ECQL filter (default INCLUDE)")
        qp.set_defaults(fn=cmd_cq)

    vwp = sub.add_parser("views",
                         help="materialized-view (standing aggregate) "
                              "administration")
    vwsub = vwp.add_subparsers(dest="views_command", required=True)
    for vname, vhelp in (("list", "registered views + fold counters"),
                         ("get", "one view's rows at its fold LSN"),
                         ("register", "add a standing aggregate view "
                                      "(token-gated)"),
                         ("unregister", "drop a view (token-gated)")):
        vp = vwsub.add_parser(vname, help=vhelp)
        vp.add_argument("--path", required=True,
                        help="serving node, remote://host:port")
        vp.add_argument("--token", default=None,
                        help="admin bearer token "
                             "(geomesa.web.auth.token)")
        if vname in ("get", "register", "unregister"):
            vp.add_argument("--name", required=True,
                            help="materialized view name")
        if vname == "register":
            vp.add_argument("--sql", required=True,
                            help="single-table GROUP BY aggregate "
                                 "SELECT the view maintains")
        vp.set_defaults(fn=cmd_views)

    trp = sub.add_parser("trace",
                         help="distributed request-trace inspection")
    trsub = trp.add_subparsers(dest="trace_command", required=True)
    for tname, thelp in (("list", "recent trace summaries"),
                         ("get", "one trace's full span tree")):
        tp = trsub.add_parser(tname, help=thelp)
        tp.add_argument("--path", required=True,
                        help="serving node, remote://host:port")
        tp.add_argument("--token", default=None,
                        help="admin bearer token "
                             "(geomesa.web.auth.token)")
        if tname == "list":
            tp.add_argument("--limit", type=int, default=50,
                            help="max summaries (newest first)")
        if tname == "get":
            tp.add_argument("--id", required=True, help="trace id")
        tp.set_defaults(fn=cmd_trace)

    hp = sub.add_parser("health",
                        help="runtime health plane: SLO burn rates, "
                             "runtime telemetry, profiler")
    hsub = hp.add_subparsers(dest="health_command", required=True)
    for hname, hhelp in (("slo", "burn-rate/alert state per route"),
                         ("runtime", "compile churn, device memory, "
                                     "transfer bytes"),
                         ("profile", "collapsed-stack profile text")):
        hcp = hsub.add_parser(hname, help=hhelp)
        hcp.add_argument("--path", required=True,
                         help="serving node, remote://host:port")
        hcp.add_argument("--token", default=None,
                         help="admin bearer token "
                              "(geomesa.web.auth.token)")
        hcp.set_defaults(fn=cmd_health)

    qp = sub.add_parser("qos",
                        help="multi-tenant QoS: per-tenant admission "
                             "and budget state")
    qsub = qp.add_subparsers(dest="qos_command", required=True)
    qcp = qsub.add_parser("status",
                          help="per-tenant in-flight caps, row "
                               "buckets, retry budgets")
    qcp.add_argument("--path", required=True,
                     help="serving node, remote://host:port")
    qcp.add_argument("--token", default=None,
                     help="bearer token (resolves the tenant via "
                          "geomesa.web.auth.tokens)")
    qcp.set_defaults(fn=cmd_qos)

    add("version", cmd_version, needs_store=False)
    add("env", cmd_env, needs_store=False)

    args = p.parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:
        # downstream closed early (e.g. `... | head`): exit quietly,
        # the unix convention for pipeline producers
        import os
        import sys
        try:
            sys.stdout.close()
        except Exception:
            pass
        os.dup2(os.open(os.devnull, os.O_WRONLY), 1)
        return 0
