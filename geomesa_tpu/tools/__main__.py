"""CLI entry: ``python -m geomesa_tpu.tools <command> ...``

The geomesa-tools analog (tools/Runner.scala:21): schema management,
ingest via converters, query/export, stats, explain — against a
filesystem datastore rooted at ``--path``.
"""

import sys

from .cli import main

sys.exit(main())
