"""SQL parser: the geomesa-spark-sql surface as a hand-rolled grammar.

The reference extends Spark SQL with spatial UDFs and catalyst rules
(/root/reference/geomesa-spark/geomesa-spark-sql/src/main/scala/org/
apache/spark/sql/SQLTypes.scala:22, SQLSpatialFunctions.scala:31-41);
here the surface is a self-contained SELECT subset:

    select   := SELECT items FROM table [alias]
                [JOIN table [alias] ON st_pred] [WHERE expr]
                [ORDER BY col [ASC|DESC]] [LIMIT n]
    items    := '*' | item (',' item)*
    item     := agg '(' (col|'*') ')' | col | ST_fn(args)
    expr     := SQL boolean algebra over comparisons, BETWEEN/IN/LIKE/
                IS NULL, and ST_ predicates with geometry constructors
                (ST_GeomFromText / ST_Point / ST_MakeBBOX)

Expressions parse into the SAME Filter AST the ECQL path uses
(filters/ast.py) — the rewrite of `ST_Contains(g, col)` into a
column-anchored predicate IS the reference's STContainsRule pushdown
(SQLRules.scala:99-246): by the time the engine sees the query, every
spatial constraint is planner-consumable.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any

import numpy as np

from ..filters import ast
from ..geometry import Geometry, Point, parse_wkt
from ..geometry.base import Envelope

__all__ = ["parse_sql", "SqlSelect", "SqlJoin", "SelectItem", "SqlError"]


class SqlError(ValueError):
    pass


@dataclasses.dataclass
class SelectItem:
    """One projected output: a column, *, an aggregate over one, or a
    scalar ST_* call over one (fn + literal args)."""
    expr: str                 # column name ('a.geom' qualified ok) or '*'
    agg: str | None = None    # count/min/max/sum/avg | 'st' (scalar)
    alias: str | None = None
    fn: str | None = None     # uppercased ST_* name when agg == 'st'
    args: tuple = ()          # literal args after the column

    @property
    def name(self) -> str:
        if self.alias:
            return self.alias
        if self.agg == "st":
            return f"{(self.fn or 'st').lower()}({self.expr})"
        if self.agg:
            return f"{self.agg}({self.expr})"
        return self.expr


@dataclasses.dataclass
class SqlJoin:
    table: str
    alias: str
    kind: str                 # 'dwithin' | 'contains' | 'intersects' | 'eq'
    distance: float | None    # for dwithin (degrees)
    left_prop: str            # qualified 'alias.col' (first ON arg)
    right_prop: str           # qualified 'alias.col' (second ON arg)
    outer: bool = False       # LEFT [OUTER] JOIN


@dataclasses.dataclass
class HavingCond:
    """One HAVING conjunct: an aggregate (or group key) compared to a
    literal. Conjuncts AND together."""
    item: SelectItem          # the aggregated (or plain) expression
    op: str                   # =, <>, <, >, <=, >=
    value: Any


@dataclasses.dataclass
class SqlSelect:
    items: list[SelectItem]
    table: str
    alias: str
    joins: list[SqlJoin]
    where: ast.Filter | None  # props qualified when a join is present
    order_by: str | None
    order_desc: bool
    limit: int | None
    group_by: list[str] | None = None
    having: list[HavingCond] | None = None


_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<lparen>\()
    | (?P<rparen>\))
    | (?P<comma>,)
    | (?P<star>\*)
    | (?P<op><=|>=|<>|!=|=|<|>)
    | (?P<string>'(?:[^']|'')*')
    | (?P<number>[-+]?\d*\.?\d+(?:[eE][-+]?\d+)?)
    | (?P<word>[A-Za-z_][A-Za-z0-9_.]*)
    )""", re.VERBOSE)

_AGGS = {"COUNT", "MIN", "MAX", "SUM", "AVG"}

from ..analytics.st_functions import SQL_SCALARS as _SQL_SCALARS  # noqa: E402

# ST predicate -> (column-first AST node, literal-first AST node): the
# literal-first rewrite is STContainsRule's argument flip
_ST_PREDS = {
    "ST_CONTAINS": (ast.Contains, ast.Within),
    "ST_WITHIN": (ast.Within, ast.Contains),
    "ST_COVERS": (ast.Contains, ast.Within),
    "ST_INTERSECTS": (ast.Intersects, ast.Intersects),
    "ST_DISJOINT": (ast.Disjoint, ast.Disjoint),
    "ST_CROSSES": (ast.Crosses, ast.Crosses),
    "ST_OVERLAPS": (ast.Overlaps, ast.Overlaps),
    "ST_TOUCHES": (ast.Touches, ast.Touches),
    "ST_EQUALS": (ast.GeomEquals, ast.GeomEquals),  # symmetric
}


class _Tokens:
    def __init__(self, text: str):
        self.toks: list[tuple[str, str]] = []
        pos = 0
        while pos < len(text):
            m = _TOKEN_RE.match(text, pos)
            if m is None:
                if text[pos:].strip() == "":
                    break
                raise SqlError(f"cannot tokenize at: {text[pos:pos+25]!r}")
            pos = m.end()
            kind = m.lastgroup
            self.toks.append((kind, m.group(kind)))
        self.i = 0

    def peek(self, ahead: int = 0):
        j = self.i + ahead
        return self.toks[j] if j < len(self.toks) else (None, None)

    def next(self):
        t = self.peek()
        self.i += 1
        return t

    def expect(self, kind: str, value: str | None = None) -> str:
        k, v = self.next()
        if k != kind or (value is not None
                         and (v or "").upper() != value.upper()):
            raise SqlError(f"expected {value or kind}, got {v!r}")
        return v

    def at_word(self, *words: str) -> bool:
        k, v = self.peek()
        return k == "word" and v.upper() in words

    def take_word(self, *words: str) -> bool:
        if self.at_word(*words):
            self.next()
            return True
        return False


def _unquote(s: str) -> str:
    return s[1:-1].replace("''", "'")


def _num(v: str) -> float:
    f = float(v)
    return int(f) if f.is_integer() and "." not in v and "e" not in v.lower() \
        else f


_RESERVED = {"FROM", "JOIN", "ON", "WHERE", "ORDER", "GROUP", "LIMIT",
             "AND", "OR", "NOT", "AS", "BY", "ASC", "DESC", "BETWEEN",
             "IN", "LIKE", "ILIKE", "IS", "NULL", "TRUE", "FALSE",
             "INNER", "LEFT", "OUTER", "HAVING"}

# geometry aggregates (the reference's ConvexHull UDAF,
# geomesa-spark-sql/.../udaf/ConvexHull.scala, plus the ST_Extent-style
# envelope fold)
_GEOM_AGGS = {"ST_CONVEXHULL": "convex_hull", "CONVEXHULL": "convex_hull",
              "CONVEX_HULL": "convex_hull",
              "ST_EXTENT": "extent", "EXTENT": "extent"}


class _Parser:
    def __init__(self, text: str):
        self.t = _Tokens(text)

    # -- top level ---------------------------------------------------------

    def select(self) -> SqlSelect:
        self.t.expect("word", "SELECT")
        items = self._items()
        self.t.expect("word", "FROM")
        table, alias = self._table_ref()
        joins = []
        while True:
            if self.t.take_word("LEFT"):
                self.t.take_word("OUTER")
                self.t.expect("word", "JOIN")
                joins.append(self._join(outer=True))
            elif self.t.take_word("INNER"):
                self.t.expect("word", "JOIN")
                joins.append(self._join())
            elif self.t.take_word("JOIN"):
                joins.append(self._join())
            else:
                break
        where = None
        if self.t.take_word("WHERE"):
            where = self._expr()
        group_by = None
        if self.t.take_word("GROUP"):
            self.t.expect("word", "BY")
            group_by = [self._name()]
            while self.t.peek()[0] == "comma":
                self.t.next()
                group_by.append(self._name())
        having = None
        if self.t.take_word("HAVING"):
            having = [self._having_cond()]
            while self.t.take_word("AND"):
                having.append(self._having_cond())
        order_by, desc = None, False
        if self.t.take_word("ORDER"):
            self.t.expect("word", "BY")
            order_by = self._name()
            if self.t.take_word("DESC"):
                desc = True
            else:
                self.t.take_word("ASC")
        limit = None
        if self.t.take_word("LIMIT"):
            limit = int(_num(self.t.expect("number")))
        k, v = self.t.peek()
        if k is not None:
            raise SqlError(f"unexpected trailing input: {v!r}")
        return SqlSelect(items, table, alias, joins, where,
                         order_by, desc, limit, group_by, having)

    def _having_cond(self) -> HavingCond:
        """agg(col|*) op literal, or group-key op literal."""
        k, v = self.t.peek()
        if k == "word" and (v.upper() in _AGGS
                            or v.upper() in _GEOM_AGGS) \
                and self.t.peek(1)[0] == "lparen":
            item = self._item()
        else:
            item = SelectItem(self._name())
        k, op = self.t.next()
        if k != "op":
            raise SqlError(f"expected operator in HAVING, got {op!r}")
        if op == "!=":
            op = "<>"
        return HavingCond(item, op, self._literal())

    def _table_ref(self) -> tuple[str, str]:
        name = self._name()
        alias = name
        if self.t.take_word("AS"):
            alias = self._name()
        elif (self.t.peek()[0] == "word"
              and self.t.peek()[1].upper() not in _RESERVED):
            alias = self._name()
        return name, alias

    def _join(self, outer: bool = False) -> SqlJoin:
        table, alias = self._table_ref()
        self.t.expect("word", "ON")
        # equi-join: ON a.col = b.col (no function-call parenthesis)
        if self.t.peek(1)[0] != "lparen":
            a = self._name()
            k, op = self.t.next()
            if k != "op" or op != "=":
                raise SqlError(f"expected '=' in equi-join ON, got {op!r}")
            b = self._name()
            if "." not in a or "." not in b:
                raise SqlError("join ON columns must be alias-qualified "
                               f"(got {a!r}, {b!r})")
            return SqlJoin(table, alias, "eq", None, a, b, outer)
        fn = self._name().upper()
        self.t.expect("lparen")
        a = self._name()
        self.t.expect("comma")
        b = self._name()
        if "." not in a or "." not in b:
            raise SqlError("join ON columns must be alias-qualified "
                           f"(got {a!r}, {b!r})")
        distance = None
        if fn == "ST_DWITHIN":
            self.t.expect("comma")
            distance = float(_num(self.t.expect("number")))
            kind = "dwithin"
        elif fn in ("ST_CONTAINS", "ST_COVERS"):
            kind = "contains"
        elif fn == "ST_INTERSECTS":
            kind = "intersects"
        else:
            raise SqlError(f"unsupported join predicate {fn}")
        self.t.expect("rparen")
        return SqlJoin(table, alias, kind, distance, a, b, outer)

    def _items(self) -> list[SelectItem]:
        items = [self._item()]
        while self.t.peek()[0] == "comma":
            self.t.next()
            items.append(self._item())
        return items

    def _item(self) -> SelectItem:
        k, v = self.t.peek()
        if k == "star":
            self.t.next()
            return SelectItem("*")
        if k == "word" and v.upper() in _GEOM_AGGS \
                and self.t.peek(1)[0] == "lparen":
            self.t.next()
            self.t.expect("lparen")
            col = self._name()
            self.t.expect("rparen")
            return SelectItem(col, _GEOM_AGGS[v.upper()], self._opt_alias())
        if k == "word" and v.upper() in _SQL_SCALARS \
                and self.t.peek(1)[0] == "lparen":
            fn = self.t.next()[1].upper()
            self.t.expect("lparen")
            if self.t.peek()[0] in ("number", "string"):
                # all-literal constructor (ST_MakeBBOX(0,0,1,1)): no
                # source column — the engine broadcasts the value
                kk, vv = self.t.next()
                first = _num(vv) if kk == "number" else _unquote(vv)
                col = "__const__"
                args = [first]
            else:
                col = self._name()
                args = []
            while self.t.peek()[0] == "comma":
                self.t.next()
                kk, vv = self.t.peek()
                if kk == "number":
                    args.append(_num(self.t.next()[1]))
                elif kk == "string":
                    args.append(_unquote(self.t.next()[1]))
                else:
                    g = self._geom_or_col()
                    if not isinstance(g, Geometry):
                        raise SqlError(
                            f"{fn}: literal argument expected, got "
                            f"column {g!r}")
                    args.append(g)
            self.t.expect("rparen")
            return SelectItem(col, "st", self._opt_alias(), fn=fn,
                              args=tuple(args))
        if k == "word" and v.upper() in _AGGS \
                and self.t.peek(1)[0] == "lparen":
            agg = self.t.next()[1].lower()
            self.t.expect("lparen")
            if self.t.peek()[0] == "star":
                self.t.next()
                col = "*"
            else:
                col = self._name()
            self.t.expect("rparen")
            alias = self._opt_alias()
            return SelectItem(col, agg, alias)
        col = self._name()
        return SelectItem(col, None, self._opt_alias())

    def _opt_alias(self) -> str | None:
        if self.t.take_word("AS"):
            return self._name()
        return None

    def _name(self) -> str:
        k, v = self.t.next()
        if k != "word":
            raise SqlError(f"expected identifier, got {v!r}")
        return v

    # -- boolean expressions (same shape as the ECQL parser) ---------------

    def _expr(self) -> ast.Filter:
        left = self._and()
        while self.t.take_word("OR"):
            left = ast.Or([left, self._and()])
        return left

    def _and(self) -> ast.Filter:
        left = self._not()
        while self.t.take_word("AND"):
            left = ast.And([left, self._not()])
        return left

    def _not(self) -> ast.Filter:
        if self.t.take_word("NOT"):
            return ast.Not(self._not())
        return self._primary()

    def _primary(self) -> ast.Filter:
        k, v = self.t.peek()
        if k == "lparen":
            self.t.next()
            e = self._expr()
            self.t.expect("rparen")
            return e
        if k == "word" and v.upper() in _ST_PREDS:
            return self._st_pred()
        if k == "word" and v.upper() == "ST_DWITHIN":
            return self._st_dwithin()
        return self._comparison()

    def _st_pred(self) -> ast.Filter:
        fn = self._name().upper()
        col_node, lit_node = _ST_PREDS[fn]
        self.t.expect("lparen")
        a = self._geom_or_col()
        self.t.expect("comma")
        b = self._geom_or_col()
        self.t.expect("rparen")
        if isinstance(a, str) and isinstance(b, Geometry):
            return col_node(a, b)
        if isinstance(a, Geometry) and isinstance(b, str):
            return lit_node(b, a)   # STContainsRule argument flip
        raise SqlError(f"{fn} needs one geometry column and one literal "
                       f"(joins use JOIN ... ON)")

    def _st_dwithin(self) -> ast.Filter:
        self.t.expect("word", "ST_DWITHIN")
        self.t.expect("lparen")
        a = self._geom_or_col()
        self.t.expect("comma")
        b = self._geom_or_col()
        self.t.expect("comma")
        d = float(_num(self.t.expect("number")))
        self.t.expect("rparen")
        if isinstance(a, str) and isinstance(b, Geometry):
            return ast.DWithin(a, b, d, "degrees")
        if isinstance(a, Geometry) and isinstance(b, str):
            return ast.DWithin(b, a, d, "degrees")
        raise SqlError("ST_DWithin needs one geometry column and one "
                       "literal (joins use JOIN ... ON)")

    def _geom_or_col(self):
        k, v = self.t.peek()
        if k == "word" and v.upper() in ("ST_GEOMFROMTEXT", "ST_GEOMFROMWKT",
                                         "ST_POINT", "ST_MAKEPOINT",
                                         "ST_MAKEBBOX", "ST_MAKEBOX2D"):
            fn = self._name().upper()
            self.t.expect("lparen")
            if fn in ("ST_GEOMFROMTEXT", "ST_GEOMFROMWKT"):
                g = parse_wkt(_unquote(self.t.expect("string")))
            elif fn in ("ST_POINT", "ST_MAKEPOINT"):
                x = _num(self.t.expect("number"))
                self.t.expect("comma")
                y = _num(self.t.expect("number"))
                g = Point(float(x), float(y))
            else:
                vals = [_num(self.t.expect("number"))]
                for _ in range(3):
                    self.t.expect("comma")
                    vals.append(_num(self.t.expect("number")))
                g = Envelope(*[float(x) for x in vals]).to_polygon()
            self.t.expect("rparen")
            return g
        return self._name()

    def _comparison(self) -> ast.Filter:
        prop = self._name()
        if self.t.take_word("IS"):
            neg = self.t.take_word("NOT")
            self.t.expect("word", "NULL")
            f: ast.Filter = ast.IsNull(prop)
            return ast.Not(f) if neg else f
        neg = self.t.take_word("NOT")
        if self.t.take_word("BETWEEN"):
            lo = self._literal()
            self.t.expect("word", "AND")
            hi = self._literal()
            f = ast.Between(prop, lo, hi)
            return ast.Not(f) if neg else f
        if self.t.take_word("IN"):
            self.t.expect("lparen")
            vals = [self._literal()]
            while self.t.peek()[0] == "comma":
                self.t.next()
                vals.append(self._literal())
            self.t.expect("rparen")
            f = ast.InList(prop, vals)
            return ast.Not(f) if neg else f
        if self.t.take_word("LIKE"):
            f = ast.Like(prop, str(self._literal()), True)
            return ast.Not(f) if neg else f
        if self.t.take_word("ILIKE"):
            f = ast.Like(prop, str(self._literal()), False)
            return ast.Not(f) if neg else f
        if neg:
            raise SqlError(f"unexpected NOT after {prop}")
        k, op = self.t.next()
        if k != "op":
            raise SqlError(f"expected operator after {prop}, got {op!r}")
        if op == "!=":
            op = "<>"
        return ast.Compare(op, prop, self._literal())

    def _literal(self) -> Any:
        k, v = self.t.next()
        if k == "string":
            return _unquote(v)
        if k == "number":
            return _num(v)
        if k == "word" and v.upper() == "TRUE":
            return True
        if k == "word" and v.upper() == "FALSE":
            return False
        raise SqlError(f"expected literal, got {v!r}")


def parse_sql(text: str) -> SqlSelect:
    return _Parser(text).select()
