"""Cost-based planning for cluster reads and distributed SQL.

The reference picks scan strategies from maintained stats — the
StatsBasedEstimator feeds CostBasedStrategyDecider's cost phase
(index/planner.py mirrors it per-store). This module lifts the same
idea to the cluster/SQL tier:

- **Cardinality estimates, cluster-merged**: ``estimate_for_store``
  answers "how many rows match this filter" without scanning, from
  whatever surface the store offers — a local ``DataStoreStats``
  sketch registry, a replicated group's primary, a remote group's
  ``/rest/estimate`` endpoint, or a ``ClusterDataStore``'s per-shard
  sum (each shard estimates its own slice; the coordinator adds).
- **Cost model**: ``CostModel`` turns estimated cardinalities into
  wall-clock cost terms for the distributed join strategies (broadcast
  vs cluster-materialize), with the per-leg overhead coefficient
  recalibrated online from the breaker board's observed leg-latency
  EWMAs (``geomesa.sql.planner.recalibrate``).
- **Join ordering**: ``reorder_joins`` greedily orders inner
  multi-join trees smallest-estimated-side-first, respecting each
  ON clause's anchor dependency.

``geomesa.sql.planner=false`` kills all of it: strategy choice falls
back to the exact-count static-threshold path, join order stays as
written, and plans carry no cost terms — bit-identical to the
pre-planner behavior. Cold types with no stats fall back the same
way, flagged ``plan["cost"]["fallback"] = "no-stats"`` — never an
error.
"""

from __future__ import annotations

from ..filters import ast, parse_ecql
from ..utils.properties import SystemProperty

__all__ = ["SQL_PLANNER", "PLANNER_RECALIBRATE", "CostModel",
           "estimate_for_store", "reorder_joins"]

# kill switch for cardinality-driven strategy selection and join
# ordering: "false" restores the exact-count static-threshold planner
SQL_PLANNER = SystemProperty("geomesa.sql.planner", "true")
# online recalibration of the per-leg cost coefficient from the
# breaker board's observed leg-latency EWMAs; "false" pins the static
# default (deterministic plans for tests/replay)
PLANNER_RECALIBRATE = SystemProperty("geomesa.sql.planner.recalibrate",
                                     "true")

# static cost coefficients (seconds). LEG_OVERHEAD_S is the scatter
# fixed cost per contacted leg (thread + breaker + merge bookkeeping)
# and is the recalibrated term; the per-row terms are transport and
# leg-local scan work. Only *ratios* matter for strategy choice, so
# rough magnitudes are fine — the reported cost terms make the chosen
# boundary auditable.
_LEG_OVERHEAD_S = 2e-3
_SHIP_S_PER_ROW = 2e-6
_SCAN_S_PER_ROW = 2e-7


def _as_filter(f) -> ast.Filter:
    if f is None:
        return ast.Include()
    if isinstance(f, str):
        return parse_ecql(f)
    return f


def estimate_for_store(store, type_name: str, f) -> int | None:
    """Best-effort cardinality estimate of ``f`` over ``store``'s
    ``type_name`` rows, or None when not estimable (cold type, cleared
    stats, unsupported filter shape, unreachable remote). Never
    raises — a planner that errors is worse than one that scans."""
    try:
        f = _as_filter(f)
        # replicated group: stats live on the primary
        primary = getattr(store, "primary", None)
        if primary is not None:
            return estimate_for_store(primary, type_name, f)
        # local store with a sketch registry
        stats = getattr(store, "stats", None)
        if stats is not None and hasattr(stats, "get"):
            est = stats.get(type_name)
            if est is None:
                return None
            return est.estimate_count(f)
        # remote / cluster stores answer through their own surface
        fn = getattr(store, "estimate_count", None)
        if callable(fn):
            return fn(type_name, f)
    except Exception:  # noqa: BLE001 — estimates are advisory
        return None
    return None


class CostModel:
    """Cost terms for the distributed join strategies, in estimated
    wall-clock seconds.

    - broadcast: ship the small side to every leg, each leg joins it
      against its local slice of the big side (scan work parallel
      across legs).
    - materialize: pull both sides to the coordinator and join there
      (all scan work serial at the coordinator).

    ``leg_s`` — the fixed per-leg overhead — recalibrates from the
    cluster breaker board's observed per-leg latency EWMAs when
    ``geomesa.sql.planner.recalibrate`` is on, so a cluster whose legs
    are genuinely slow (remote groups, cold caches) weighs fan-out
    width more heavily than an in-process one.
    """

    def __init__(self, n_legs: int, breakers=None, leg_names=None):
        self.n_legs = max(int(n_legs), 1)
        self.ship_s = _SHIP_S_PER_ROW
        self.scan_s = _SCAN_S_PER_ROW
        self.leg_s = _LEG_OVERHEAD_S
        self.recalibrated = False
        if breakers is not None and PLANNER_RECALIBRATE.as_bool():
            obs = []
            for name in (leg_names or []):
                try:
                    p99 = breakers.latency_p99_s(name)
                except Exception:  # noqa: BLE001 — advisory
                    p99 = None
                if p99:
                    obs.append(float(p99))
            if obs:
                self.leg_s = sum(obs) / len(obs)
                self.recalibrated = True

    def broadcast_cost(self, small_rows: int, big_rows: int) -> float:
        ship = self.n_legs * small_rows * self.ship_s
        scan = big_rows * self.scan_s / self.n_legs
        return self.n_legs * self.leg_s + ship + scan

    def materialize_cost(self, rows_a: int, rows_b: int) -> float:
        pulled = rows_a + rows_b
        return (self.n_legs * self.leg_s + pulled * self.ship_s
                + pulled * self.scan_s)

    def describe(self) -> dict:
        return {"leg_s": self.leg_s, "ship_s_per_row": self.ship_s,
                "scan_s_per_row": self.scan_s, "n_legs": self.n_legs,
                "recalibrated": self.recalibrated}


def _join_anchor(j) -> str | None:
    """The preceding alias a join's ON clause anchors to, or None for
    an irregular ON shape (reorder then bails to statement order)."""
    quals = {j.left_prop.split(".", 1)[0], j.right_prop.split(".", 1)[0]}
    if j.alias not in quals:
        return None
    other = quals - {j.alias}
    if len(other) != 1:
        return None
    return next(iter(other))


def reorder_joins(store, anchor_alias: str, joins, tables: dict,
                  side_f: dict):
    """Greedy smallest-first ordering of an inner multi-join tree:
    each step runs, among the joins whose anchor alias is already
    joined, the one with the smallest estimated (filtered) side —
    shrinking intermediate row sets early, exactly like the
    reference's relation-size join ordering. Returns ``(joins, note)``
    where note is None when the order is unchanged (or the planner is
    off / estimates are unavailable / the tree shape is irregular —
    inner joins only; callers must not pass outer joins)."""
    joins = list(joins)
    if len(joins) < 2 or not SQL_PLANNER.as_bool():
        return joins, None
    est: dict[str, int] = {}
    for j in joins:
        fs = side_f.get(j.alias) or []
        f = ast.And(fs) if len(fs) > 1 else (fs[0] if fs else ast.Include())
        e = estimate_for_store(store, tables[j.alias], f)
        if e is None:
            return joins, None
        est[j.alias] = int(e)
    avail = {anchor_alias}
    remaining = list(joins)
    ordered = []
    while remaining:
        runnable = [j for j in remaining
                    if (_join_anchor(j) or object()) in avail]
        if not runnable:
            return joins, None      # irregular shape: statement order
        pick = min(runnable, key=lambda j: est[j.alias])
        ordered.append(pick)
        avail.add(pick.alias)
        remaining.remove(pick)
    if [j.alias for j in ordered] == [j.alias for j in joins]:
        return joins, None
    note = {"order": [j.alias for j in ordered],
            "estimated_rows": est}
    return ordered, note
