"""SQL surface: SELECT with ST_* predicates over datastores.

The geomesa-spark-sql analog (see parser.py / engine.py for the
STContainsRule / SpatialJoinStrategy mapping)."""

from .engine import SqlEngine, SqlResult
from .parser import SqlError, parse_sql

__all__ = ["SqlEngine", "SqlResult", "parse_sql", "SqlError"]
