"""Distributed SQL over the cluster plane: partial-aggregate pushdown
and broadcast spatial joins.

The reference serves SQL through Spark executors running next to the
data (PAPER.md L7: geomesa-spark-sql partitions relations over the
store's splits); this module is that shape over ``ClusterDataStore``:

- **Partial aggregates**: COUNT/SUM/MIN/MAX/AVG (avg decomposed into
  sum+count), convex-hull unions and ST_Extent envelope folds are all
  associative, so each shard group reduces its own rows to a tiny
  per-group partial (`partial_aggregate`) and the coordinator merges
  partials by group key (`merge_partial_legs`). The coordinator's peak
  materialization is bounded by the number of groups x distinct keys —
  never by matching rows.
- **Broadcast joins**: when one join side fits under
  ``geomesa.sql.broadcast.rows``, the coordinator fetches it once,
  ships it to every shard group, and each leg runs the existing fused
  device join kernels against its local slice of the big side
  (`join_partial_leg`); count results psum-merge, aggregate results
  merge by key, row results concatenate. Exact because the z-prefix
  partition of the big side is disjoint and covering.
- **Streamed ORDER BY ... LIMIT**: plain projections with a LIMIT ride
  the k-way sort-merge stream (PR 11) instead of a full materialize.

Everything else falls back to the single-node engine with the reason
recorded on ``SqlResult.plan`` — the EXPLAIN surface.

Legs ride the cluster's per-leg deadlines, hedging, breakers and the
typed/flagged partial-results contract: a lost leg raises
``ShardUnavailableError`` unless ``geomesa.cluster.allow.partial``
flags the merged result ``complete=False`` instead.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..features.batch import (BoolColumn, DateColumn, FeatureBatch,
                              GeometryColumn, NumericColumn, PointColumn,
                              StringColumn)
from ..features.sft import parse_spec
from ..filters import ast
from ..geometry import Geometry, parse_wkt, to_wkt
from ..index.api import Query
from ..utils.properties import SystemProperty
from .parser import SelectItem, SqlSelect, parse_sql
from .planner import SQL_PLANNER, CostModel, estimate_for_store

__all__ = ["SQL_DISTRIBUTED", "SQL_BROADCAST_ROWS", "try_distributed",
           "partial_aggregate", "merge_partial_legs", "join_partial_leg"]

# kill switch: "false" forces every SQL statement down the single-node
# path (the coordinator still answers, it just materializes)
SQL_DISTRIBUTED = SystemProperty("geomesa.sql.distributed", "true")
# largest row count a join side may have and still be broadcast to
# every shard group (the reference's spark.sql.autoBroadcastJoinThreshold
# analog, in rows rather than bytes)
SQL_BROADCAST_ROWS = SystemProperty("geomesa.sql.broadcast.rows", "100000")

_MERGEABLE = ("count", "sum", "min", "max", "avg", "convex_hull", "extent")


class _Unsupported(ValueError):
    """Statement shape the distributed planner does not cover — the
    caller records the reason and falls back to the single-node path
    (which either answers or raises the proper user-facing error).
    ``cost`` (optional) carries the cost model's terms so the fallback
    plan can still explain the cardinality-driven decision."""

    cost: dict | None = None


class _FallbackReason(str):
    """The fallback reason string, plus the planner's cost terms when
    the decision was cost-based — the engine copies them onto the
    cluster-materialize plan (``plan["cost"]``)."""

    cost: dict | None = None


# -- partial planning ------------------------------------------------------

def _plan_partials(sel: SqlSelect, qualified: bool):
    """Decompose the select list (plus hidden HAVING aggregates) into
    mergeable components. Returns ``(key_items, leg_items, comps,
    keys)`` or None when some item is not mergeable.

    - ``key_items``: one aliased item per GROUP BY key (``__k{j}``)
    - ``leg_items``: aliased aggregate items each leg evaluates with
      the ordinary engine reduces (``__p{i}``; avg contributes a
      ``__p{i}s``/``__p{i}c`` sum+count pair)
    - ``comps``: output schema — how each final column is rebuilt from
      the merged accumulators
    """
    if sel.group_by is None:
        keys: list[str] = []
    elif qualified:
        keys = list(sel.group_by)
    else:
        keys = [k.split(".", 1)[1] if "." in k else k for k in sel.group_by]
    ext: list[SelectItem] = list(sel.items)
    sel_names = {it.name for it in sel.items}
    for cond in (sel.having or []):
        if cond.item.agg and cond.item.name not in sel_names:
            ext.append(cond.item)   # hidden: merged, filtered on, dropped
    key_items = [SelectItem(k, None, f"__k{j}") for j, k in enumerate(keys)]
    leg_items: list[SelectItem] = []
    comps: list[dict] = []
    for i, it in enumerate(ext):
        if not it.agg:
            e = it.expr if qualified else it.expr.split(".")[-1]
            if e not in keys:
                return None         # not a group key: engine will raise
            comps.append({"kind": "key", "name": it.name,
                          "key": keys.index(e)})
            continue
        if it.agg not in _MERGEABLE:
            return None             # scalar ST_* / unknown aggregate
        if it.agg == "avg":
            leg_items.append(SelectItem(it.expr, "sum", f"__p{i}s"))
            leg_items.append(SelectItem(it.expr, "count", f"__p{i}c"))
            comps.append({"kind": "avg", "name": it.name,
                          "sum": f"__p{i}s", "cnt": f"__p{i}c"})
            continue
        kind = {"convex_hull": "hull"}.get(it.agg, it.agg)
        leg_items.append(SelectItem(it.expr, it.agg, f"__p{i}"))
        comps.append({"kind": kind, "name": it.name, "src": f"__p{i}"})
    return key_items, leg_items, comps, keys


def _check_columns(cluster, table: str, exprs) -> None:
    """Reject unknown column references BEFORE scattering: a statement
    error must surface as the single-node path's user error, never as
    a ShardUnavailableError from every leg failing identically."""
    try:
        sft = cluster.get_schema(table)
    except Exception as e:
        raise _Unsupported(f"no schema for {table!r}: {e}") from e
    valid = {a.name for a in sft.attributes} | {"__fid__", "*"}
    for expr in exprs:
        if expr not in valid:
            raise _Unsupported(f"unknown column {expr!r} in {table!r}")


def _agg_aliases(comps) -> dict[str, str]:
    """leg column alias -> merge kind, for every non-key component."""
    out: dict[str, str] = {}
    for c in comps:
        if c["kind"] == "key":
            continue
        if c["kind"] == "avg":
            out[c["sum"]] = "sum"
            out[c["cnt"]] = "count"
        else:
            out[c["src"]] = c["kind"]
    return out


def _enc(v):
    if isinstance(v, np.generic):
        return v.item()
    return v


def _enc_val(kind, v):
    if v is None:
        return None
    if kind in ("hull", "extent"):
        return to_wkt(v)
    return _enc(v)


# -- partial leg (single table) --------------------------------------------

def partial_aggregate(store, stmt, query_kwargs=None) -> dict:
    """One shard group's leg of a distributed single-table aggregate:
    run the ordinary engine reduces over the local rows with the
    decomposed (avg -> sum+count) item list, and return the per-key
    partials in the JSON-able wire form the coordinator merges —
    identical for in-process and REST legs, so transport is invisible
    to correctness (WKT floats round-trip via repr, losslessly)."""
    from .engine import SqlEngine, _strip_qualifier
    sel = parse_sql(stmt) if isinstance(stmt, str) else stmt
    if sel.joins:
        raise ValueError("sql partial legs are single-table aggregates")
    plan = _plan_partials(sel, qualified=False)
    if plan is None:
        raise ValueError("statement has no mergeable aggregate form")
    key_items, leg_items, comps, keys = plan
    where = (_strip_qualifier(sel.where, sel.alias)
             if sel.where is not None else ast.Include())
    eng = SqlEngine(store)
    res = store.query(Query(sel.table, where), **(query_kwargs or {}))
    if sel.group_by is not None:
        out = eng._grouped(key_items + leg_items, keys, res.batch)
        key_rows = [[_enc(out.columns[k.name][r]) for k in key_items]
                    for r in range(out.n)]
    else:
        out = eng._aggregate(leg_items, res.batch, res.n)
        key_rows = [[]]
    cols = {alias: [_enc_val(kind, out.columns[alias][r])
                    for r in range(out.n)]
            for alias, kind in _agg_aliases(comps).items()}
    return {"keys": key_rows, "cols": cols, "n": out.n}


# -- coordinator merge -----------------------------------------------------

def _combine(acc: dict, alias: str, kind: str, v):
    if kind == "count":
        acc[alias] = acc.get(alias, 0) + int(v or 0)
        return
    if v is None:
        return
    if kind == "sum":
        cur = acc.get(alias)
        acc[alias] = v if cur is None else cur + v
    elif kind == "min":
        cur = acc.get(alias)
        acc[alias] = v if cur is None else min(cur, v)
    elif kind == "max":
        cur = acc.get(alias)
        acc[alias] = v if cur is None else max(cur, v)
    elif kind == "hull":
        g = parse_wkt(v) if isinstance(v, str) else v
        acc.setdefault(alias, []).append(np.vstack(g.coords_list()))
    elif kind == "extent":
        g = parse_wkt(v) if isinstance(v, str) else v
        cur = acc.get(alias)
        env = g.envelope
        acc[alias] = env if cur is None else cur.expand(env)


def merge_partial_legs(sel: SqlSelect, legs: list[dict],
                       qualified: bool):
    """Merge per-leg partials by group key and finalize: avg =
    sum/count, hulls re-hull the pooled leg hull vertices (exact —
    hull of hulls), extents fold envelopes; then HAVING, hidden-column
    drop and post-merge ORDER BY / LIMIT, mirroring the single-node
    output shapes exactly."""
    from .engine import SqlEngine, SqlResult, _order_limit
    from ..analytics.st_functions import convex_hull_points
    plan = _plan_partials(sel, qualified=qualified)
    if plan is None:
        raise ValueError("statement has no mergeable aggregate form")
    _, _, comps, keys = plan
    aliases = _agg_aliases(comps)
    acc: dict[tuple, dict] = {}
    for leg in legs:
        for r in range(leg["n"]):
            kt = tuple(leg["keys"][r]) if keys else ()
            a = acc.setdefault(kt, {})
            for alias, kind in aliases.items():
                _combine(a, alias, kind, leg["cols"][alias][r])
    if not keys and not acc:
        acc[()] = {}        # zero rows everywhere still yields one row
    groups = sorted(acc, key=lambda kt: tuple((x is None, x) for x in kt)) \
        if keys else list(acc)
    empty = keys and not acc

    def finalize(c, a):
        kind = c["kind"]
        if kind == "count":
            return a.get(c["src"], 0)
        if kind == "avg":
            cnt = a.get(c["cnt"], 0)
            s = a.get(c["sum"])
            return None if not cnt or s is None else s / cnt
        if kind == "hull":
            pts = a.get(c["src"])
            return None if pts is None else convex_hull_points(
                np.vstack(pts))
        if kind == "extent":
            env = a.get(c["src"])
            return None if env is None else env.to_polygon()
        return a.get(c["src"])

    names_all, cols_all = [], {}
    for c in comps:
        names_all.append(c["name"])
        if c["kind"] == "key":
            cols_all[c["name"]] = np.array(
                [kt[c["key"]] for kt in groups], dtype=object)
        else:
            cols_all[c["name"]] = np.array(
                [finalize(c, acc[kt]) for kt in groups], dtype=object)
    if empty:
        cols_all = {n: np.empty(0, object) for n in names_all}
    out_all = SqlResult(names_all, cols_all)

    def compute(it):
        e = it.expr if qualified else it.expr.split(".")[-1]
        if not it.agg and e in keys:
            return np.array([kt[keys.index(e)] for kt in groups],
                            dtype=object)
        raise ValueError(f"not an aggregate: {it.name} (HAVING terms "
                         f"must aggregate or be group keys)")

    out_all = SqlEngine._apply_having(out_all, sel.having, compute)
    sel_names = [it.name for it in sel.items]
    out = SqlResult(sel_names,
                    {n: out_all.columns[n] for n in sel_names})
    if sel.group_by is None:
        return out   # single-node ungrouped aggregates ignore ORDER/LIMIT
    order = sel.order_by
    if order is not None and order not in out.columns:
        alt = order.split(".")[-1] if qualified else None
        if qualified and alt in out.columns:
            order = alt
        elif not qualified:
            stripped = order.split(".", 1)[1] if "." in order else order
            if stripped in out.columns:
                order = stripped
    return _order_limit(out, order, sel.order_desc, sel.limit)


# -- broadcast batch codec -------------------------------------------------

def _encode_batch(type_name: str, sft, res) -> dict:
    """JSON-able wire form of a (small) query result: ids plus one
    typed encoding per column. Exact round trip — string dictionaries,
    epoch millis, nan-tagged point slots and repr-format WKT all
    reconstruct the identical columns on the far side."""
    payload = {"type": type_name, "spec": sft.to_spec(),
               "n": int(res.n), "ids": [str(i) for i in res.ids],
               "cols": {}}
    batch = res.batch
    if batch is None or res.n == 0:
        payload["n"] = 0
        payload["ids"] = []
        return payload
    for a in sft.attributes:
        c = batch.col(a.name)
        if isinstance(c, PointColumn):
            enc = {"k": "pt", "x": c.x.tolist(), "y": c.y.tolist(),
                   "v": np.asarray(c.valid, bool).tolist()}
        elif isinstance(c, GeometryColumn):
            enc = {"k": "geom",
                   "w": [None if g is None else to_wkt(g)
                         for g in c.geoms]}
        elif isinstance(c, DateColumn):
            enc = {"k": "date", "ms": c.millis.tolist(),
                   "v": np.asarray(c.valid, bool).tolist()}
        elif isinstance(c, StringColumn):
            enc = {"k": "str", "c": c.codes.tolist(),
                   "vocab": [str(s) for s in c.vocab]}
        elif isinstance(c, BoolColumn):
            enc = {"k": "bool", "b": c.values.tolist(),
                   "v": np.asarray(c.valid, bool).tolist()}
        else:
            enc = {"k": "num", "f": c.values.tolist(),
                   "dt": str(c.values.dtype),
                   "v": np.asarray(c.valid, bool).tolist()}
        payload["cols"][a.name] = enc
    return payload


def _decode_batch(payload: dict):
    """(sft, ids, batch|None) from `_encode_batch` output."""
    sft = parse_spec(payload["type"], payload["spec"])
    ids = np.asarray(payload["ids"], dtype=object)
    if payload["n"] == 0:
        empty = FeatureBatch.from_dict(
            sft, [], {a.name: np.empty(0, object)
                      for a in sft.attributes})
        return sft, ids, empty
    cols = {}
    for a in sft.attributes:
        e = payload["cols"][a.name]
        if e["k"] == "pt":
            cols[a.name] = PointColumn(
                a.name, np.asarray(e["x"], np.float64),
                np.asarray(e["y"], np.float64),
                np.asarray(e["v"], bool))
        elif e["k"] == "geom":
            cols[a.name] = GeometryColumn.from_geoms(a.name, e["w"])
        elif e["k"] == "date":
            cols[a.name] = DateColumn(
                a.name, np.asarray(e["ms"], np.int64),
                np.asarray(e["v"], bool))
        elif e["k"] == "str":
            cols[a.name] = StringColumn(
                a.name, np.asarray(e["c"], np.int32),
                np.asarray(e["vocab"], dtype=object))
        elif e["k"] == "bool":
            cols[a.name] = BoolColumn(
                a.name, np.asarray(e["b"], bool),
                np.asarray(e["v"], bool))
        else:
            cols[a.name] = NumericColumn(
                a.name, np.asarray(e["f"], np.dtype(e["dt"])),
                np.asarray(e["v"], bool))
    return sft, ids, FeatureBatch(sft, ids, cols)


class _BroadcastSide:
    """QueryResult stand-in for the shipped small side of a join —
    just enough surface (ids / batch / n) for the engine's join
    machinery."""

    def __init__(self, ids, batch):
        self.ids = ids
        self.batch = batch
        self.n = len(ids)


def _enc_cell(v):
    if isinstance(v, Geometry):
        return {"__wkt__": to_wkt(v)}
    return _enc(v)


def _dec_cell(v):
    if isinstance(v, dict) and "__wkt__" in v:
        return parse_wkt(v["__wkt__"])
    return v


# -- join leg --------------------------------------------------------------

def _split_where(sel: SqlSelect, aliases, outer_aliases):
    """Mirror of the engine's join WHERE split: each conjunct pushes
    below the join on its own side, except conjuncts on a LEFT join's
    right side, which defer to post-NULL-extension evaluation."""
    from .engine import _qualifier_of, _strip_qualifier
    side_f = {a: [] for a in aliases}
    deferred = []
    if sel.where is not None:
        conjuncts = (list(sel.where.children)
                     if isinstance(sel.where, ast.And) else [sel.where])
        for c in conjuncts:
            quals = _qualifier_of(c)
            if len(quals) != 1 or "" in quals:
                raise _Unsupported("WHERE conjuncts must reference "
                                   "exactly one aliased table")
            a = next(iter(quals))
            if a not in side_f:
                raise _Unsupported(f"unknown table qualifier {a!r}")
            if a in outer_aliases:
                deferred.append((a, _strip_qualifier(c, a)))
            else:
                side_f[a].append(_strip_qualifier(c, a))
    return side_f, deferred


def _and(fs) -> ast.Filter:
    if not fs:
        return ast.Include()
    return ast.And(fs) if len(fs) > 1 else fs[0]


def _count_mode_ok(sel: SqlSelect, j, deferred) -> bool:
    """Conditions under which a leg can use the engine's device
    count-reduce (no pair materialization) — same gate as the
    single-node COUNT(*) fast path."""
    return (not j.outer and not deferred and sel.group_by is None
            and not sel.having and j.kind != "eq"
            and len(sel.items) == 1 and sel.items[0].agg == "count"
            and sel.items[0].expr == "*")


def join_partial_leg(store, spec: dict, query_kwargs=None) -> dict:
    """One shard group's leg of a broadcast join: rebuild the shipped
    small side, query the local slice of the big side with its pushed
    WHERE conjuncts, run the engine's fused join kernels, and return
    the mode-appropriate partial (count / keyed aggregate partials /
    projected rows)."""
    from .engine import SqlEngine
    sel = parse_sql(spec["sql"])
    # ORDER/LIMIT are coordinator-side (post-merge): a leg must never
    # truncate its slice of the answer
    sel = dataclasses.replace(sel, order_by=None, limit=None)
    j = sel.joins[0]
    aliases = [sel.alias, j.alias]
    tables = {sel.alias: sel.table, j.alias: j.table}
    b_alias = spec["broadcast"]
    side_f, deferred = _split_where(
        sel, aliases, {j.alias} if j.outer else set())
    _, ids, batch = _decode_batch(spec["payload"])
    eng = SqlEngine(store)
    results = {}
    for a in aliases:
        if a == b_alias:       # already filtered at the coordinator
            results[a] = _BroadcastSide(ids, batch)
        else:
            results[a] = store.query(Query(tables[a], _and(side_f[a])),
                                     **(query_kwargs or {}))
    mode = spec["mode"]
    if any(results[a].n == 0 for a in aliases if a != b_alias):
        # empty local slice: inner joins pair nothing, and for LEFT
        # joins the local side is always the outer anchor — either way
        # this leg contributes an empty partial
        if mode == "count":
            return {"count": 0}
        if mode == "agg":
            plan = _plan_partials(sel, qualified=True)
            key_items, leg_items, comps, keys = plan
            return {"keys": [], "cols": {a: [] for a in _agg_aliases(comps)},
                    "n": 0}
        return {"names": [it.name for it in sel.items],
                "cols": {it.name: [] for it in sel.items}, "n": 0}
    if mode == "count":
        a_alias, a_col = j.left_prop.split(".", 1)
        b2, b_col = j.right_prop.split(".", 1)
        total = eng._join_count(
            j, results[a_alias], a_col, results[b2], b_col,
            a_table=tables[a_alias] if a_alias != b_alias else None)
        return {"count": int(total)}
    rows = {sel.alias: np.arange(results[sel.alias].n, dtype=np.int64)}
    # exclude the broadcast alias from the device-resident shortcut:
    # its rows are the cluster-wide small side, not this store's table
    leg_tables = {a: tables[a] for a in aliases if a != b_alias}
    rows = eng._apply_join(j, results, rows, leg_tables)
    for a, f in deferred:
        keep = eng._post_join_mask(f, results[a], rows[a])
        rows = {k: v[keep] for k, v in rows.items()}
    if mode == "agg":
        plan = _plan_partials(sel, qualified=True)
        if plan is None:
            raise ValueError("statement has no mergeable aggregate form")
        key_items, leg_items, comps, keys = plan
        if sel.group_by is not None:
            psel = dataclasses.replace(sel, items=key_items + leg_items,
                                       having=None)
            out = eng._grouped_join(psel, results, rows)
            key_rows = [[_enc(out.columns[k.name][r]) for k in key_items]
                        for r in range(out.n)]
        else:
            psel = dataclasses.replace(sel, items=leg_items, having=None)
            out = eng._project_join(psel, results, rows)
            key_rows = [[]]
        cols = {alias: [_enc_val(kind, out.columns[alias][r])
                        for r in range(out.n)]
                for alias, kind in _agg_aliases(comps).items()}
        return {"keys": key_rows, "cols": cols, "n": out.n}
    out = eng._project_join(sel, results, rows)
    return {"names": out.names,
            "cols": {nm: [_enc_cell(v) for v in out.columns[nm]]
                     for nm in out.names},
            "n": out.n}


# -- the distributed planner ----------------------------------------------

def try_distributed(engine, cluster, sel: SqlSelect, text: str):
    """Attempt distributed execution. Returns ``(SqlResult, None)`` on
    success or ``(None, reason)`` to fall back to the single-node path
    (which raises the proper error for genuinely invalid statements)."""
    if not SQL_DISTRIBUTED.as_bool():
        return None, "disabled (geomesa.sql.distributed=false)"
    try:
        if sel.joins:
            return _broadcast_join(engine, cluster, sel, text), None
        return _single_table_distributed(engine, cluster, sel, text), None
    except _Unsupported as e:
        reason = _FallbackReason(str(e))
        reason.cost = getattr(e, "cost", None)
        return None, reason


def _flag(out, missing, *extra_partials):
    """Attach the partial-results contract to a merged SqlResult:
    union of leg-scatter missing info and any flagged sub-results the
    plan consumed (e.g. the broadcast-side fetch)."""
    groups: list = []
    z_ranges: list = []
    if missing:
        groups += missing["groups"]
        z_ranges += missing["z_ranges"]
    for p in extra_partials:
        if p is not None and not getattr(p, "complete", True):
            for g in getattr(p, "missing_groups", []):
                if g not in groups:
                    groups.append(g)
            z_ranges += [z for z in getattr(p, "missing_z_ranges", [])
                         if z not in z_ranges]
    if groups:
        out.complete = False
        out.missing_groups = sorted(set(groups))
        out.missing_z_ranges = z_ranges
    return out


def _describe_partials(comps) -> list[str]:
    out = []
    for c in comps:
        if c["kind"] == "key":
            continue
        desc = {"avg": "sum+count partials, divide at merge",
                "hull": "per-leg hull, re-hull pooled vertices",
                "extent": "per-leg envelope, fold at merge",
                "count": "per-leg count, add at merge",
                "sum": "per-leg sum, add at merge",
                "min": "per-leg min, min at merge",
                "max": "per-leg max, max at merge"}[c["kind"]]
        out.append(f"{c['name']}: {desc}")
    return out


def _single_table_distributed(engine, cluster, sel: SqlSelect, text: str):
    if sel.having and sel.group_by is None:
        raise _Unsupported("HAVING without GROUP BY")
    aggs = [i for i in sel.items if i.agg and i.agg != "st"]
    plain = [i for i in sel.items if not i.agg or i.agg == "st"]
    if sel.group_by is None and not aggs:
        return _streamed_select(engine, cluster, sel)
    if sel.group_by is None and plain:
        raise _Unsupported("mixed aggregates and plain columns")
    plan = _plan_partials(sel, qualified=False)
    if plan is None:
        raise _Unsupported("select list is not a mergeable aggregate "
                           "(scalar ST_* or non-key plain column)")
    _, _, comps, keys = plan
    _check_columns(cluster, sel.table,
                   [i.expr for i in sel.items] + list(sel.group_by or []))
    from .engine import _strip_qualifier
    where = (_strip_qualifier(sel.where, sel.alias)
             if sel.where is not None else None)
    legs_sel, prune_info = cluster.prune_for(sel.table, where)
    results, missing = cluster.sql_partial(text, type_name=sel.table,
                                           legs=legs_sel)
    legs = sorted(results)
    out = merge_partial_legs(sel, [results[n] for n in legs],
                             qualified=False)
    out.plan = {
        "mode": "distributed-aggregate", "distributed": True,
        "table": sel.table,
        "pushdown": str(sel.where) if sel.where is not None else "INCLUDE",
        "legs": legs,
        "group_by": keys or None,
        "partials": _describe_partials(comps),
        "merge": "by-key" if keys else "fold",
        "order_limit": ("post-merge" if sel.group_by is not None
                        and (sel.order_by or sel.limit is not None)
                        else None),
    }
    if prune_info is not None:
        out.plan["prune"] = dict(
            prune_info, contacted=legs,
            pruned=(sorted(set(cluster._names) - set(legs_sel))
                    if legs_sel is not None else []))
    if SQL_PLANNER.as_bool():
        est = estimate_for_store(cluster, sel.table, where)
        out.plan["cost"] = (
            {"estimator": "stats", "estimated_rows": int(est)}
            if est is not None else {"fallback": "no-stats"})
    if missing:
        out.plan["missing_groups"] = missing["groups"]
    return _flag(out, missing)


def _streamed_select(engine, cluster, sel: SqlSelect):
    """Plain projection: with a LIMIT, ride the k-way merge stream so
    the coordinator holds at most LIMIT rows; without one, a scatter
    materializes the world and the single-node path is no worse."""
    from .engine import SqlResult, _strip_qualifier
    if sel.limit is None:
        raise _Unsupported("plain projection without LIMIT "
                           "(full materialization either way)")
    where = (_strip_qualifier(sel.where, sel.alias)
             if sel.where is not None else ast.Include())
    order = sel.order_by
    if order and "." in order:
        order = order.split(".", 1)[1]
    q = Query(sel.table, where, sort_by=order, sort_desc=sel.order_desc,
              max_features=sel.limit)
    stream = cluster.query_stream(q)
    batches = list(stream)
    batch = FeatureBatch.concat_all(batches) if batches else None
    ids = batch.ids if batch is not None else np.empty(0, object)
    out = engine._project(sel.items, batch, ids, sel.alias)
    res = SqlResult(out.names, out.columns)
    res.plan = {
        "mode": "distributed-stream", "distributed": True,
        "table": sel.table,
        "pushdown": str(sel.where) if sel.where is not None else "INCLUDE",
        "legs": [n for n in cluster._names
                 if n not in getattr(stream, "missing_groups", [])],
        "merge": "k-way-stream",
        "order_limit": f"streamed (limit={sel.limit})",
    }
    return _flag(res, None, stream)


def _broadcast_join(engine, cluster, sel: SqlSelect, text: str):
    if len(sel.joins) != 1:
        raise _Unsupported("chained joins")
    if sel.having and sel.group_by is None:
        raise _Unsupported("HAVING without GROUP BY")
    j = sel.joins[0]
    aliases = [sel.alias, j.alias]
    if len(set(aliases)) != 2:
        raise _Unsupported("duplicate table aliases")
    tables = {sel.alias: sel.table, j.alias: j.table}
    a_alias = j.left_prop.split(".", 1)[0]
    b2 = j.right_prop.split(".", 1)[0]
    if {a_alias, b2} != set(aliases):
        raise _Unsupported("ON must reference both joined tables")
    for k in (sel.group_by or []):
        if "." not in k:
            raise _Unsupported(f"unqualified GROUP BY key {k!r}")
    side_f, deferred = _split_where(
        sel, aliases, {j.alias} if j.outer else set())
    refs = {a: [] for a in aliases}
    for q in ([i.expr for i in sel.items] + list(sel.group_by or [])
              + [j.left_prop, j.right_prop]):
        if "." in q:
            al, col = q.split(".", 1)
            if al in refs:
                refs[al].append(col)
    for a in aliases:
        _check_columns(cluster, tables[a], refs[a])

    threshold = SQL_BROADCAST_ROWS.as_int() or 0
    # cardinality-driven side choice: estimated (filtered) rows from
    # the per-shard stats sketches replace the exact query_count
    # scatters — the planning cost drops from two cluster scans to
    # O(cells) sketch math. Estimates only pick the side; the shipped
    # batch's true size is re-checked against the threshold below.
    cost: dict = {}
    counts = None
    if SQL_PLANNER.as_bool():
        est = {a: estimate_for_store(cluster, tables[a], _and(side_f[a]))
               for a in aliases}
        if all(e is not None for e in est.values()):
            counts = {a: int(est[a]) for a in aliases}
            model = CostModel(len(cluster._groups),
                              breakers=getattr(cluster, "_breakers", None),
                              leg_names=list(cluster._names))
            lo, hi = sorted(counts.values())
            cost = {"estimator": "stats", "estimated_rows": dict(counts),
                    "threshold": threshold,
                    "broadcast_cost_s": model.broadcast_cost(lo, hi),
                    "materialize_cost_s": model.materialize_cost(lo, hi),
                    "coefficients": model.describe()}
        else:
            cost = {"fallback": "no-stats", "estimated_rows": est,
                    "threshold": threshold}
    if counts is None:        # planner off or cold stats: exact counts
        counts = {a: int(cluster.query_count(Query(tables[a],
                                                   _and(side_f[a]))))
                  for a in aliases}
    eligible = [a for a in aliases if counts[a] <= threshold]
    if j.outer:
        # broadcasting the anchor of a LEFT join would NULL-extend its
        # unmatched rows once per leg; only the right side distributes
        eligible = [a for a in eligible if a == j.alias]
    if not eligible:
        outer_note = ", LEFT join anchors cannot broadcast" \
            if j.outer else ""
        word = "estimated rows" if cost.get("estimator") else "rows"
        err = _Unsupported(
            f"no broadcastable side ({word}: "
            f"{ {a: counts[a] for a in aliases} }, threshold: "
            f"{threshold}{outer_note})")
        if cost:
            err.cost = dict(cost, strategy="cluster-materialize")
        raise err
    small = min(eligible, key=lambda a: counts[a])

    if _count_mode_ok(sel, j, deferred):
        mode = "count"
    elif all(i.agg and i.agg != "st" for i in sel.items) \
            or sel.group_by is not None:
        mode = "agg"
        if any(i.agg == "st" or (not i.agg and "." not in i.expr
                                 and i.expr != "*")
               for i in sel.items):
            raise _Unsupported("select list is not a mergeable "
                               "qualified aggregate")
        if _plan_partials(sel, qualified=True) is None:
            raise _Unsupported("select list is not a mergeable aggregate")
    else:
        if any(i.agg and i.agg != "st" for i in sel.items):
            raise _Unsupported("mixed aggregates and plain columns")
        mode = "rows"
        for it in sel.items:
            if "." not in it.expr and it.expr != "*":
                raise _Unsupported(f"unqualified join column {it.expr!r}")

    sres = cluster.query(Query(tables[small], _and(side_f[small])))
    if cost.get("estimator") == "stats" and sres.n > threshold:
        # the estimate undershot: the fetched side is too big to ship.
        # Fall back to cluster-materialize rather than broadcast a
        # side the operator's threshold forbids.
        err = _Unsupported(
            f"estimated broadcast side {small!r} has {sres.n} rows "
            f"(> threshold {threshold})")
        err.cost = dict(cost, strategy="cluster-materialize",
                        actual_rows=int(sres.n))
        raise err
    sft = cluster.get_schema(tables[small])
    spec = {"sql": text, "broadcast": small, "mode": mode,
            "payload": _encode_batch(tables[small], sft, sres)}
    # Z-prune the scatter by the LOCAL side's pushed filter: a leg
    # whose owned z range cannot hold local-side matches would join
    # the shipped batch against an empty slice — an empty partial
    other = next(a for a in aliases if a != small)
    legs_sel, prune_info = cluster.prune_for(tables[other],
                                             _and(side_f[other]))
    results, missing = cluster.sql_join_partial(
        spec, type_name=f"{tables[sel.alias]}*{tables[j.alias]}",
        legs=legs_sel)
    legs = sorted(results)

    from .engine import SqlResult, _order_limit
    if mode == "count":
        from ..analytics.join import psum_counts
        total = psum_counts(results[n]["count"] for n in legs)
        name = sel.items[0].name
        out = SqlResult([name], {name: np.array([total])})
    elif mode == "agg":
        out = merge_partial_legs(sel, [results[n] for n in legs],
                                 qualified=True)
    else:
        first = (results[legs[0]] if legs
                 else {"names": [it.name for it in sel.items],
                       "cols": {it.name: [] for it in sel.items}})
        names = first["names"]
        cols = {nm: np.array(
            [_dec_cell(v) for n in legs for v in results[n]["cols"][nm]],
            dtype=object) for nm in names}
        out = _order_limit(SqlResult(names, cols), sel.order_by,
                           sel.order_desc, sel.limit)
    out.plan = {
        "mode": "broadcast-join", "distributed": True,
        "join": {"kind": j.kind, "on": [j.left_prop, j.right_prop],
                 "outer": j.outer},
        "broadcast": {"side": small, "table": tables[small],
                      "rows": (int(sres.n)
                               if cost.get("estimator") == "stats"
                               else counts[small]),
                      "threshold": threshold},
        "pushdown": {a: str(_and(side_f[a])) for a in aliases},
        "deferred": [str(f) for _, f in deferred] or None,
        "legs": legs,
        "merge": {"count": "psum", "agg": "by-key" if sel.group_by
                  else "fold", "rows": "concat"}[mode],
    }
    if prune_info is not None:
        out.plan["prune"] = dict(
            prune_info, side=other, contacted=legs,
            pruned=(sorted(set(cluster._names) - set(legs_sel))
                    if legs_sel is not None else []))
    if cost:
        out.plan["cost"] = dict(cost, strategy="broadcast")
    if missing:
        out.plan["missing_groups"] = missing["groups"]
    return _flag(out, missing, sres)
