"""SQL execution: pushdown into the planner + device spatial joins.

Mirrors the reference's two catalyst rules
(/root/reference/geomesa-spark/geomesa-spark-sql/src/main/scala/org/
apache/spark/sql/SQLRules.scala):

- STContainsRule (:99): spatial/attribute predicates in WHERE are
  rewritten to Filter AST at parse time and handed to the store's
  planner as a Query — the same cost-based index selection the ECQL
  path gets, so `SELECT ... WHERE ST_Contains(...)` and the equivalent
  ECQL text produce identical plans and identical feature IDs.
- SpatialJoinStrategy (:270): `JOIN b ON ST_DWithin/ST_Contains/
  ST_Intersects` routes to the tiled device join kernels
  (analytics/join.py) instead of a nested-loop evaluation, with
  single-side WHERE conjuncts pushed below the join
  (GeoMesaJoinRelation.buildScan:312-360).

Aggregates (COUNT/MIN/MAX/SUM/AVG) reduce over the query result
columns; ORDER BY / LIMIT push into Query.sort_by / max_features.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterator

import numpy as np

from ..features.batch import (FeatureBatch, GeometryColumn, PointColumn)
from ..filters import ast
from ..index.api import Query
from .parser import SelectItem, SqlJoin, SqlSelect, parse_sql

# |a| x |b| above which a spatial join with two large sides routes
# through grid partitioning instead of the direct tiled kernel, and
# the minimum small-side size for the route (module globals so tests
# can exercise the branch at test scale)
_PARTITION_PAIR_BUDGET = 2e11
_PARTITION_MIN_SIDE = 50_000

__all__ = ["SqlEngine", "SqlResult"]


@dataclasses.dataclass
class SqlResult:
    """Columnar result table.

    ``plan`` is the EXPLAIN surface: what was pushed down, which legs
    ran, what merged where (or why execution stayed local). ``complete``
    / ``missing_groups`` / ``missing_z_ranges`` carry the cluster
    partial-results contract when the store allows partial answers."""
    names: list[str]
    columns: dict[str, np.ndarray]
    plan: dict | None = None
    complete: bool = True
    missing_groups: list = dataclasses.field(default_factory=list)
    missing_z_ranges: list = dataclasses.field(default_factory=list)

    @property
    def n(self) -> int:
        return 0 if not self.names else len(self.columns[self.names[0]])

    def rows(self) -> Iterator[tuple]:
        cols = [self.columns[n] for n in self.names]
        for i in range(self.n):
            yield tuple(c[i] for c in cols)

    def column(self, name: str) -> np.ndarray:
        return self.columns[name]


def _strip_qualifier(f: ast.Filter, alias: str) -> ast.Filter:
    """Rewrite 'alias.col' props to 'col' for single-table execution."""
    def fix(name: str) -> str:
        if "." in name:
            q, col = name.split(".", 1)
            if q != alias:
                raise ValueError(f"unknown table qualifier {q!r}")
            return col
        return name
    return _map_props(f, fix)


def _map_props(f: ast.Filter, fix) -> ast.Filter:
    if isinstance(f, (ast.And, ast.Or)):
        return type(f)([_map_props(c, fix) for c in f.children])
    if isinstance(f, ast.Not):
        return ast.Not(_map_props(f.child, fix))
    if hasattr(f, "prop"):
        # every Filter node (incl. SpatialPredicate subclasses, which
        # inherit the parent's dataclass fields) is a dataclass
        return dataclasses.replace(f, prop=fix(f.prop))
    return f


def _qualifier_of(f: ast.Filter) -> set[str]:
    """Table qualifiers referenced by the filter (empty = unqualified)."""
    out: set[str] = set()
    for node in ast.walk(f):
        prop = getattr(node, "prop", None)
        if prop and "." in prop:
            out.add(prop.split(".", 1)[0])
        elif prop:
            out.add("")
    return out


def _null_truth(f: ast.Filter):
    """Three-valued truth of a filter over an all-NULL row (SQL
    semantics for a LEFT join's NULL-extended side): True / False /
    None (UNKNOWN — excluded by WHERE)."""
    if isinstance(f, ast.IsNull):
        return True
    if isinstance(f, ast.Include):
        return True
    if isinstance(f, ast.Exclude):
        return False
    if isinstance(f, ast.Not):
        v = _null_truth(f.child)
        return None if v is None else not v
    if isinstance(f, ast.And):
        vals = [_null_truth(c) for c in f.children]
        if any(v is False for v in vals):
            return False
        return None if any(v is None for v in vals) else True
    if isinstance(f, ast.Or):
        vals = [_null_truth(c) for c in f.children]
        if any(v is True for v in vals):
            return True
        return None if any(v is None for v in vals) else False
    return None  # comparisons / LIKE / IN / spatial on NULL: UNKNOWN


def _factorize(col) -> tuple[np.ndarray, None]:
    """Column -> non-negative int64 dictionary codes; nulls form their
    own group (SQL GROUP BY collates NULLs together). Code 0 is the
    null group."""
    from ..features.batch import (BoolColumn, DateColumn, NumericColumn,
                                  StringColumn)
    valid = np.asarray(col.valid)
    n = len(valid)
    if isinstance(col, StringColumn):
        return col.codes.astype(np.int64) + 1, None  # -1 nulls -> 0
    if isinstance(col, BoolColumn):
        codes = np.where(valid, col.values.astype(np.int64) + 1, 0)
        return codes, None
    vals = getattr(col, "values", None)
    if vals is None:
        vals = getattr(col, "millis", None)
    if vals is None:
        raise ValueError(f"cannot GROUP BY column {col.name!r}")
    vals = np.asarray(vals)
    codes = np.zeros(n, dtype=np.int64)
    if valid.any():
        _, inv = np.unique(vals[valid], return_inverse=True)
        codes[valid] = inv.astype(np.int64) + 1
    return codes, None


def _order_limit(out: SqlResult, order: str | None, desc: bool,
                 limit: int | None) -> SqlResult:
    """Post-aggregation ORDER BY / LIMIT over result columns (grouped
    queries sort their OUTPUT, not the source rows)."""
    if order is not None:
        if order not in out.columns:
            raise ValueError(f"ORDER BY column {order!r} is not in the "
                             f"select list")
        vals = out.columns[order]
        idx = sorted(range(out.n),
                     key=lambda i: (vals[i] is None, vals[i]),
                     reverse=desc)
        out = SqlResult(out.names, {k: v[idx]
                                    for k, v in out.columns.items()})
    if limit is not None and out.n > limit:
        out = SqlResult(out.names, {k: v[:limit]
                                    for k, v in out.columns.items()})
    return out


def _centroids(batch: FeatureBatch, geom_field: str):
    col = batch.col(geom_field)
    if isinstance(col, PointColumn):
        return col.x, col.y
    b = col.bounds
    return (b[:, 0] + b[:, 2]) / 2, (b[:, 1] + b[:, 3]) / 2


def _col_floats(col):
    """Numeric view of a column (values or millis), or None."""
    vals = getattr(col, "values", None)
    if vals is None:
        vals = getattr(col, "millis", None)
    if vals is not None and vals.dtype.kind == "b":
        vals = vals.astype(np.float64)
    return None if vals is None else np.asarray(vals, np.float64)


def _gather(col, idx):
    """(valid, floats|None, col, idx) for rows `idx` of `col`; idx may
    hold -1 for a LEFT join's NULL-extended rows (never valid). With
    idx None the view covers the column directly."""
    if idx is None:
        idx = np.arange(col.n, dtype=np.int64)
    safe = np.where(idx < 0, 0, idx)
    valid = np.asarray(col.valid)[safe] & (idx >= 0)
    floats = _col_floats(col)
    return valid, (None if floats is None else floats[safe]), col, idx


def _group_hull(col, idx, ginv, ng):
    """Per-group convex hull (the reference's ConvexHull UDAF,
    geomesa-spark-sql/.../udaf/ConvexHull.scala): pool every group
    member's vertices, monotone-chain hull per group. NULL for empty
    groups."""
    from ..analytics.st_functions import convex_hull_points
    if idx is None:
        idx = np.arange(col.n, dtype=np.int64)
    safe = np.where(idx < 0, 0, idx)
    valid = np.asarray(col.valid)[safe] & (idx >= 0)
    out = np.empty(ng, dtype=object)
    out[:] = None
    # one argsort gives every group's member rows as a contiguous
    # segment — O(n log n) total, not an O(n) mask per group
    vrows = np.flatnonzero(valid)
    order = vrows[np.argsort(ginv[vrows], kind="stable")]
    gsorted = ginv[order]
    grid = np.arange(ng)
    starts = np.searchsorted(gsorted, grid)
    ends = np.searchsorted(gsorted, grid, side="right")
    if isinstance(col, PointColumn):
        xs, ys = col.x[safe], col.y[safe]
        for g in range(ng):
            rows = order[starts[g]:ends[g]]
            if len(rows):
                out[g] = convex_hull_points(
                    np.stack([xs[rows], ys[rows]], axis=1))
        return out
    for g in range(ng):
        rows = order[starts[g]:ends[g]]
        if not len(rows):
            continue
        coords = [np.vstack(col.value(int(safe[i])).coords_list())
                  for i in rows]
        out[g] = convex_hull_points(np.vstack(coords))
    return out


def _group_extent(col, idx, ginv, ng):
    """Per-group bounding envelope (ST_Extent): vectorized min/max
    folds over point coordinates or geometry bounds, one box polygon
    per group, NULL for empty groups. An envelope fold is associative,
    which is what lets the cluster tier merge per-shard extents
    exactly."""
    from ..geometry.base import Envelope
    if idx is None:
        idx = np.arange(col.n, dtype=np.int64)
    safe = np.where(idx < 0, 0, idx)
    valid = np.asarray(col.valid)[safe] & (idx >= 0)
    if isinstance(col, PointColumn):
        x, y = np.asarray(col.x, np.float64)[safe], \
            np.asarray(col.y, np.float64)[safe]
        bx = np.stack([x, y, x, y], axis=1)
    else:
        bx = np.asarray(col.bounds, np.float64)[safe]
    out = np.empty(ng, dtype=object)
    out[:] = None
    if not valid.any():
        return out
    g = ginv[valid]
    vb = bx[valid]
    # segment reduce: one argsort (releases the GIL — shard legs fold
    # their extents concurrently) + reduceat per bound, instead of the
    # scalar-looped ufunc.at
    order = np.argsort(g, kind="stable")
    gsorted = g[order]
    vb = vb[order]
    starts = np.flatnonzero(np.diff(gsorted, prepend=gsorted[0] - 1))
    present = gsorted[starts]
    lo = np.minimum.reduceat(vb[:, :2], starts, axis=0)
    hi = np.maximum.reduceat(vb[:, 2:], starts, axis=0)
    for i, gi in enumerate(present):
        out[gi] = Envelope(lo[i, 0], lo[i, 1],
                           hi[i, 0], hi[i, 1]).to_polygon()
    return out


def _equi_pairs(acol, bcol) -> np.ndarray:
    """(a_row, b_row) match pairs of an equi-join ON a.col = b.col:
    unify both sides' value domains (dictionary codes for strings),
    then a sorted-merge emits each code's cross product — no hash
    table, no per-row Python. NULL never equals NULL (SQL)."""
    from ..features.batch import StringColumn
    from ..index.zkeys import multi_arange
    a_is_str = isinstance(acol, StringColumn)
    b_is_str = isinstance(bcol, StringColumn)
    if a_is_str != b_is_str:
        raise ValueError("equi-join column types do not match")
    if a_is_str:
        # the columns are already dictionary-encoded with sorted
        # vocabs: intersect the vocabs (tiny) and remap codes — no
        # per-row string materialization
        common, ca, cb = np.intersect1d(acol.vocab.astype(str),
                                        bcol.vocab.astype(str),
                                        assume_unique=True,
                                        return_indices=True)
        if not len(common):
            return np.empty((0, 2), dtype=np.int64)
        amap = np.full(len(acol.vocab), -1, dtype=np.int64)
        amap[ca] = np.arange(len(common))
        bmap = np.full(len(bcol.vocab), -1, dtype=np.int64)
        bmap[cb] = np.arange(len(common))
        ac_all = np.where(acol.codes >= 0, amap[acol.codes], -1)
        bc_all = np.where(bcol.codes >= 0, bmap[bcol.codes], -1)
        a_rows = np.flatnonzero(ac_all >= 0)
        b_rows = np.flatnonzero(bc_all >= 0)
        if not len(a_rows) or not len(b_rows):
            return np.empty((0, 2), dtype=np.int64)
        ac, bc = ac_all[a_rows], bc_all[b_rows]
        a_keep = np.ones(len(a_rows), dtype=bool)
        b_keep = np.ones(len(b_rows), dtype=bool)
    else:
        af, bf = _col_floats(acol), _col_floats(bcol)
        if af is None or bf is None:
            raise ValueError("equi-join needs comparable column types")
        a_rows = np.flatnonzero(np.asarray(acol.valid))
        b_rows = np.flatnonzero(np.asarray(bcol.valid))
        if not len(a_rows) or not len(b_rows):
            return np.empty((0, 2), dtype=np.int64)
        ua, ainv = np.unique(af[a_rows], return_inverse=True)
        ub, binv = np.unique(bf[b_rows], return_inverse=True)
        common, ca, cb = np.intersect1d(ua, ub, assume_unique=True,
                                        return_indices=True)
        if not len(common):
            return np.empty((0, 2), dtype=np.int64)
        amap = np.full(len(ua), -1, dtype=np.int64)
        amap[ca] = np.arange(len(common))
        bmap = np.full(len(ub), -1, dtype=np.int64)
        bmap[cb] = np.arange(len(common))
        ac, bc = amap[ainv], bmap[binv]
        a_keep, b_keep = ac >= 0, bc >= 0
    ao = np.argsort(ac[a_keep], kind="stable")
    a_sorted, acodes = a_rows[a_keep][ao], ac[a_keep][ao]
    bo = np.argsort(bc[b_keep], kind="stable")
    b_sorted, bcodes = b_rows[b_keep][bo], bc[b_keep][bo]
    grid = np.arange(len(common))
    bstart = np.searchsorted(bcodes, grid)
    bend = np.searchsorted(bcodes, grid, side="right")
    s, e = bstart[acodes], bend[acodes]
    a_side = np.repeat(a_sorted, e - s)
    b_side = b_sorted[multi_arange(s, e)]
    return np.stack([a_side, b_side], axis=1).astype(np.int64)


def _factorize_gathered(col, idx):
    """_factorize over an index-gathered view (NULL-extended rows join
    the null group 0)."""
    from ..features.batch import StringColumn
    if idx is None:
        idx = np.arange(col.n, dtype=np.int64)
    safe = np.where(idx < 0, 0, idx)
    if isinstance(col, StringColumn):
        codes = col.codes[safe].astype(np.int64) + 1
        codes[idx < 0] = 0
        return codes
    valid = np.asarray(col.valid)[safe] & (idx >= 0)
    floats = _col_floats(col)
    if floats is None:
        raise ValueError(f"cannot GROUP BY column {col.name!r}")
    vals = floats[safe]
    codes = np.zeros(len(idx), dtype=np.int64)
    if valid.any():
        _, inv = np.unique(vals[valid], return_inverse=True)
        codes[valid] = inv.astype(np.int64) + 1
    return codes


class SqlEngine:
    """Executes SELECTs against one datastore's feature types."""

    def __init__(self, store):
        self.store = store

    def query(self, text: str) -> SqlResult:
        sel = parse_sql(text)
        reason = None
        cluster = self._cluster_store()
        if cluster is not None:
            from .distributed import try_distributed
            out, reason = try_distributed(self, cluster, sel, text)
            if out is not None:
                return out
        self.__dict__.pop("_join_order_note", None)
        res = self._join_query(sel) if sel.joins else \
            self._single_table(sel)
        if res.plan is None:
            res.plan = {"mode": ("cluster-materialize"
                                 if cluster is not None else "local"),
                        "distributed": False}
            if reason:
                res.plan["fallback_reason"] = str(reason)
                cost = getattr(reason, "cost", None)
                if cost:
                    res.plan["cost"] = cost
            note = self.__dict__.pop("_join_order_note", None)
            if note:
                res.plan["join_order"] = note
        return res

    def _cluster_store(self):
        """The store as a ClusterDataStore, or None — the gate for the
        distributed planner."""
        try:
            from ..cluster.coordinator import ClusterDataStore
        except ImportError:          # pragma: no cover
            return None
        return self.store if isinstance(self.store, ClusterDataStore) \
            else None

    # -- single table ------------------------------------------------------

    def _single_table(self, sel: SqlSelect) -> SqlResult:
        if sel.having and sel.group_by is None:
            raise ValueError("HAVING requires GROUP BY")
        where = (_strip_qualifier(sel.where, sel.alias)
                 if sel.where is not None else ast.Include())
        # scalar ST_* calls are per-row projections, not aggregates
        aggs = [i for i in sel.items if i.agg and i.agg != "st"]
        plain = [i for i in sel.items if not i.agg or i.agg == "st"]
        order = sel.order_by
        if order and "." in order:
            order = order.split(".", 1)[1]
        if sel.group_by is not None:
            keys = [k.split(".", 1)[1] if "." in k else k
                    for k in sel.group_by]
            for it in plain:
                name = it.expr.split(".")[-1]
                if name not in keys:
                    raise ValueError(f"column {it.expr!r} must appear in "
                                     f"GROUP BY or an aggregate")
            res = self.store.query(Query(sel.table, where))
            out = self._grouped(sel.items, keys, res.batch,
                                having=sel.having)
            # output names may keep the qualifier ('g.name'): accept
            # the raw ORDER BY target when the stripped one is absent
            if sel.order_by is not None and order not in out.columns \
                    and sel.order_by in out.columns:
                order = sel.order_by
            return _order_limit(out, order, sel.order_desc, sel.limit)
        if aggs and plain:
            raise ValueError("cannot mix aggregates and plain columns "
                             "without GROUP BY")
        q = Query(sel.table, where,
                  sort_by=None if aggs else order,
                  sort_desc=sel.order_desc,
                  max_features=None if aggs else sel.limit)
        res = self.store.query(q)
        if aggs:
            return self._aggregate(aggs, res.batch, res.n)
        return self._project(plain, res.batch, res.ids, sel.alias)

    @staticmethod
    def _reduce_item(it: SelectItem, ginv, ng: int, col, idx):
        """One aggregate over grouped rows (vectorized segment reduces:
        bincount / min.at / max.at; hull pooling for convex_hull). idx
        indirects into col (None = direct); -1 rows are NULL."""
        if it.agg not in ("count", "sum", "avg", "min", "max",
                          "convex_hull", "extent"):
            raise ValueError(f"not an aggregate: {it.name} (HAVING "
                             f"terms must aggregate or be group keys)")
        if it.agg == "count" and it.expr == "*":
            return np.bincount(ginv, minlength=ng).astype(np.int64)
        if it.agg == "convex_hull":
            return _group_hull(col, idx, ginv, ng)
        if it.agg == "extent":
            return _group_extent(col, idx, ginv, ng)
        valid, vals, _, _ = _gather(col, idx)
        if it.agg == "count":
            return np.bincount(ginv, weights=valid.astype(np.float64),
                               minlength=ng).astype(np.int64)
        if vals is None:
            raise ValueError(f"cannot aggregate column {it.expr}")
        nvalid = np.bincount(ginv, weights=valid.astype(np.float64),
                             minlength=ng)
        if it.agg in ("sum", "avg"):
            s = np.bincount(ginv, weights=np.where(valid, vals, 0.0),
                            minlength=ng)
            out = s if it.agg == "sum" else \
                np.divide(s, nvalid, out=np.zeros(ng), where=nvalid > 0)
        else:
            fill = np.inf if it.agg == "min" else -np.inf
            out = np.full(ng, fill)
            op = np.minimum if it.agg == "min" else np.maximum
            vr = np.flatnonzero(valid)
            if len(vr):
                # segment reduce via one argsort (releases the GIL, so
                # concurrent shard legs overlap) + reduceat, instead of
                # the scalar-looped ufunc.at
                order = vr[np.argsort(ginv[vr], kind="stable")]
                gs = ginv[order]
                starts = np.flatnonzero(
                    np.diff(gs, prepend=gs[0] - 1))
                out[gs[starts]] = op.reduceat(vals[order], starts)
        # SQL semantics: a group with no non-null values yields NULL
        res = np.empty(ng, dtype=object)
        for g in range(ng):
            res[g] = None if nvalid[g] == 0 else out[g]
        return res

    @staticmethod
    def _apply_having(out: SqlResult, having, compute) -> SqlResult:
        """Filter grouped output rows by the HAVING conjuncts. Each
        condition's aggregate reuses a select-list column when present,
        else `compute(item)` evaluates it over the same groups."""
        if not having:
            return out
        keep = np.ones(out.n, dtype=bool)
        for cond in having:
            if cond.item.name in out.columns:
                vals = out.columns[cond.item.name]
            else:
                vals = compute(cond.item)
            v = np.asarray(vals, dtype=object)
            ok = np.zeros(len(v), dtype=bool)
            for i, x in enumerate(v):
                if x is None:
                    continue
                ok[i] = {"=": x == cond.value,
                         "<>": x != cond.value,
                         "<": x < cond.value, ">": x > cond.value,
                         "<=": x <= cond.value,
                         ">=": x >= cond.value}[cond.op]
            keep &= ok
        return SqlResult(out.names,
                         {k: c[keep] for k, c in out.columns.items()})

    def _grouped(self, items: list[SelectItem], keys: list[str],
                 batch, having=None) -> SqlResult:
        """Grouped aggregation (GeoMesaSparkSQL.scala:212 grouped
        relations): factorize the key columns into dictionary codes,
        combine into one group id, and run vectorized segment reduces
        per aggregate — the columnar analog of a per-group shuffle."""
        names = [it.name for it in items]
        if batch is None or batch.n == 0:
            return SqlResult(names, {n: np.empty(0, object)
                                     for n in names})
        n = batch.n
        gid = np.zeros(n, dtype=np.int64)
        bound = 1
        for k in keys:
            codes, _ = _factorize(batch.col(k))
            cmax = int(codes.max()) + 1
            if bound > (1 << 60) // max(cmax, 1):
                # re-compact so multi-key composites never overflow
                _, gid = np.unique(gid, return_inverse=True)
                bound = int(gid.max()) + 1
            gid = gid * cmax + codes
            bound *= cmax
        if bound <= max(4 * n, 1 << 20):
            # small code domain: O(n) bincount compaction instead of
            # the O(n log n) argsort inside np.unique
            counts = np.bincount(gid, minlength=bound)
            present = np.flatnonzero(counts)
            remap = np.empty(bound, np.int64)
            remap[present] = np.arange(len(present), dtype=np.int64)
            ginv = remap[gid]
            member = np.empty(bound, np.int64)
            member[gid] = np.arange(n, dtype=np.int64)
            rep = member[present]   # any member row represents its group
            ng = len(present)
        else:
            uniq, rep, ginv = np.unique(gid, return_index=True,
                                        return_inverse=True)
            ng = len(uniq)

        def col_of(it):
            return batch.col(it.expr.split(".")[-1]) \
                if it.expr != "*" else None

        def key_values(it):
            col = col_of(it)
            return np.array([col.value(int(i)) for i in rep],
                            dtype=object)

        cols: dict[str, np.ndarray] = {}
        for it in items:
            if not it.agg:
                cols[it.name] = key_values(it)
                continue
            cols[it.name] = self._reduce_item(it, ginv, ng,
                                              col_of(it), None)

        def compute(it):
            if not it.agg and it.expr.split(".")[-1] in keys:
                return key_values(it)  # HAVING on a group key
            return self._reduce_item(it, ginv, ng, col_of(it), None)

        return self._apply_having(SqlResult(names, cols), having,
                                  compute)

    def _aggregate(self, items: list[SelectItem], batch, n: int) -> SqlResult:
        names, cols = [], {}
        for it in items:
            name = it.name
            names.append(name)
            if it.agg == "count" and it.expr == "*":
                cols[name] = np.array([n], dtype=np.int64)
                continue
            if it.agg in ("convex_hull", "extent"):
                if batch is None or n == 0:
                    cols[name] = np.array([None], dtype=object)
                else:
                    fn = _group_hull if it.agg == "convex_hull" \
                        else _group_extent
                    cols[name] = fn(
                        batch.col(it.expr.split(".")[-1]), None,
                        np.zeros(n, dtype=np.int64), 1)
                continue
            col = batch.col(it.expr.split(".")[-1]) if batch else None
            if it.agg == "count":
                # COUNT(col) skips nulls (SQL semantics)
                cols[name] = np.array(
                    [0 if col is None else int(col.valid.sum())],
                    dtype=np.int64)
                continue
            if col is None or n == 0:
                cols[name] = np.array([None], dtype=object)
                continue
            vals = getattr(col, "values", None)
            if vals is None:
                vals = getattr(col, "millis", None)
            if vals is None:
                raise ValueError(f"cannot aggregate column {it.expr}")
            vals = vals[col.valid]
            fn = {"min": np.min, "max": np.max, "sum": np.sum,
                  "avg": np.mean}[it.agg]
            cols[name] = np.array([fn(vals) if len(vals) else None])
        return SqlResult(names, cols)

    def _project(self, items: list[SelectItem], batch, ids,
                 alias: str) -> SqlResult:
        if batch is None:
            return SqlResult(["__fid__"], {"__fid__": np.empty(0, object)})
        names: list[str] = []
        cols: dict[str, np.ndarray] = {}

        def add(name: str, arr):
            names.append(name)
            cols[name] = arr

        star = any(i.expr == "*" for i in items)
        if star:
            add("__fid__", ids)
            for a in batch.sft.attributes:
                c = batch.col(a.name)
                add(a.name, np.array([c.value(i) for i in range(c.n)],
                                     dtype=object))
            return SqlResult(names, cols)
        for it in items:
            col_name = it.expr.split(".")[-1] if "." in it.expr else it.expr
            if col_name in ("__fid__", "id"):
                add(it.name, ids)
                continue
            if it.agg == "st" and col_name == "__const__":
                # all-literal constructor: one evaluation, broadcast
                from ..analytics.st_functions import SQL_SCALARS
                v = SQL_SCALARS[it.fn](*it.args)
                arr = np.empty(batch.n, dtype=object)
                arr.fill(v)
                add(it.name, arr)
                continue
            c = batch.col(col_name)
            vals = np.array([c.value(i) for i in range(c.n)],
                            dtype=object)
            if it.agg == "st":
                from ..analytics.st_functions import SQL_SCALARS
                fn = SQL_SCALARS[it.fn]
                vals = np.array([None if v is None else fn(v, *it.args)
                                 for v in vals], dtype=object)
            add(it.name, vals)
        return SqlResult(names, cols)

    # -- joins -------------------------------------------------------------

    def _join_query(self, sel: SqlSelect) -> SqlResult:
        if sel.having and sel.group_by is None:
            raise ValueError("HAVING requires GROUP BY")
        return self._join_query_inner(sel)

    def _join_query_inner(self, sel: SqlSelect) -> SqlResult:
        """Chained spatial joins (GeoMesaJoinRelation.buildScan analog,
        SQLRules.scala:270-360): each JOIN anchors to one preceding
        alias, runs a device join kernel, and expands the result rows;
        LEFT joins NULL-extend unmatched anchor rows. Single-side WHERE
        conjuncts push below the join — except conjuncts on a LEFT
        join's right side, which SQL applies AFTER NULL extension, so
        they evaluate post-join under three-valued logic."""
        aliases = [sel.alias] + [j.alias for j in sel.joins]
        if len(set(aliases)) != len(aliases):
            raise ValueError("duplicate table aliases in join")
        tables = {sel.alias: sel.table}
        for j in sel.joins:
            tables[j.alias] = j.table
        outer_aliases = {j.alias for j in sel.joins if j.outer}

        side_f: dict[str, list[ast.Filter]] = {a: [] for a in aliases}
        deferred: list[tuple[str, ast.Filter]] = []
        if sel.where is not None:
            conjuncts = (list(sel.where.children)
                         if isinstance(sel.where, ast.And) else [sel.where])
            for c in conjuncts:
                quals = _qualifier_of(c)
                if len(quals) != 1 or "" in quals:
                    raise ValueError("WHERE conjuncts must reference "
                                     "exactly one aliased table")
                a = next(iter(quals))
                if a not in side_f:
                    raise ValueError(f"unknown table qualifier {a!r} "
                                     f"(tables: {aliases})")
                if a in outer_aliases:
                    deferred.append((a, _strip_qualifier(c, a)))
                else:
                    side_f[a].append(_strip_qualifier(c, a))

        results = {}
        for a in aliases:
            fs = side_f[a]
            f = (ast.And(fs) if len(fs) > 1 else fs[0]) if fs \
                else ast.Include()
            results[a] = self.store.query(Query(tables[a], f))

        # COUNT(*)-only inner join: reduce on device, no pair arrays.
        # Only a well-formed ON (one side the joined alias, the other
        # the FROM alias) takes the shortcut — anything irregular falls
        # through to the pair path, which raises the proper errors.
        if (len(sel.joins) == 1 and not sel.joins[0].outer
                and not deferred and sel.group_by is None
                and sel.having is None and sel.joins[0].kind != "eq"
                and len(sel.items) == 1 and sel.items[0].agg == "count"
                and sel.items[0].expr == "*"):
            j = sel.joins[0]
            a_alias, a_col = j.left_prop.split(".", 1)
            b_alias, b_col = j.right_prop.split(".", 1)
            if {a_alias, b_alias} == {sel.alias, j.alias} \
                    and a_alias != b_alias:
                total = self._join_count(
                    j, results[a_alias], a_col, results[b_alias], b_col,
                    a_table=tables.get(a_alias))
                name = sel.items[0].name
                return SqlResult([name], {name: np.array([total])})

        # cost-based join ordering: greedy smallest-estimated-side
        # first over inner multi-join trees (estimates from the stats
        # sketches; bails to statement order when any side is cold or
        # the tree shape is irregular). Outer joins keep statement
        # order — NULL extension is order-sensitive.
        joins = list(sel.joins)
        if len(joins) >= 2 and not outer_aliases:
            from .planner import reorder_joins
            joins, note = reorder_joins(self.store, sel.alias, joins,
                                        tables, side_f)
            if note:
                self._join_order_note = note

        rows: dict[str, np.ndarray] = {
            sel.alias: np.arange(results[sel.alias].n, dtype=np.int64)}
        for j in joins:
            rows = self._apply_join(j, results, rows, tables)
        for a, f in deferred:
            keep = self._post_join_mask(f, results[a], rows[a])
            rows = {k: v[keep] for k, v in rows.items()}
        if sel.group_by is not None:
            out = self._grouped_join(sel, results, rows)
            order = sel.order_by
            if order is not None and order not in out.columns \
                    and order.split(".")[-1] in out.columns:
                order = order.split(".")[-1]
            return _order_limit(out, order, sel.order_desc, sel.limit)
        return self._project_join(sel, results, rows)

    def _grouped_join(self, sel: SqlSelect, results,
                      rows: dict[str, np.ndarray]) -> SqlResult:
        """GROUP BY over joined rows: factorize the (gathered) key
        columns, one composite group id per joined row, then the same
        vectorized segment reduces the single-table path uses. LEFT
        joins' NULL-extended rows land in the null group for keys on
        the outer side and contribute nothing to column aggregates."""
        names = [it.name for it in sel.items]
        nrows = len(next(iter(rows.values()))) if rows else 0
        keys = list(sel.group_by)
        for it in sel.items:
            # QUALIFIED comparison: a bare-name match would let the
            # same-named column of a different table through (its
            # per-group value is not constant)
            if not it.agg and it.expr not in keys:
                raise ValueError(f"column {it.expr!r} must appear in "
                                 f"GROUP BY or an aggregate")
        if nrows == 0:
            return SqlResult(names, {n: np.empty(0, object)
                                     for n in names})

        def split(q: str):
            if "." not in q:
                raise ValueError(f"join columns must be qualified: {q}")
            a, c = q.split(".", 1)
            if a not in rows:
                raise ValueError(f"unknown table qualifier {a!r} "
                                 f"(tables: {list(rows)})")
            return a, c

        def col_idx(it: SelectItem):
            if it.expr == "*":
                return None, None
            a, c = split(it.expr)
            if c in ("__fid__", "id"):
                if it.agg not in (None, "count"):
                    raise ValueError(f"cannot {it.agg} feature ids")
                from ..features.batch import NumericColumn
                nb = results[a].n
                return NumericColumn("__fid__", np.zeros(nb),
                                     np.ones(nb, dtype=bool)), rows[a]
            return results[a].batch.col(c), rows[a]

        gid = np.zeros(nrows, dtype=np.int64)
        for k in keys:
            a, c = split(k)
            if c in ("__fid__", "id"):
                codes = rows[a] + 1     # one group per feature; NULL=0
            else:
                codes = _factorize_gathered(results[a].batch.col(c),
                                            rows[a])
            gid = gid * (int(codes.max()) + 1) + codes
            _, gid = np.unique(gid, return_inverse=True)
        uniq, rep, ginv = np.unique(gid, return_index=True,
                                    return_inverse=True)
        ng = len(uniq)

        def key_values(it):
            a, c = split(it.expr)
            rep_idx = rows[a][rep]
            if c in ("__fid__", "id"):
                vals = [None if i < 0 else results[a].ids[int(i)]
                        for i in rep_idx]
            else:
                col = results[a].batch.col(c)
                vals = [None if i < 0 else col.value(int(i))
                        for i in rep_idx]
            return np.array(vals, dtype=object)

        cols: dict[str, np.ndarray] = {}
        for it in sel.items:
            if not it.agg:
                cols[it.name] = key_values(it)
                continue
            cols[it.name] = self._reduce_item(it, ginv, ng, *col_idx(it))

        def compute(it):
            if not it.agg and it.expr in keys:
                return key_values(it)  # HAVING on a group key
            return self._reduce_item(it, ginv, ng, *col_idx(it))

        return self._apply_having(SqlResult(names, cols), sel.having,
                                  compute)

    def _apply_join(self, join: SqlJoin, results,
                    rows: dict[str, np.ndarray],
                    tables: dict[str, str] | None = None
                    ) -> dict[str, np.ndarray]:
        """Expand the current result rows by one join: match the new
        table against its anchor alias, repeat matched rows, and (for
        LEFT joins) keep unmatched anchor rows with a -1 (NULL) index."""
        a_alias, a_col = join.left_prop.split(".", 1)   # first ON arg
        b_alias, b_col = join.right_prop.split(".", 1)  # second ON arg
        new = join.alias
        if a_alias == new and b_alias in rows:
            anchor = b_alias
            flip = True    # pairs arrive (new, anchor)
        elif b_alias == new and a_alias in rows:
            anchor = a_alias
            flip = False   # pairs arrive (anchor, new)
        else:
            raise ValueError(
                f"ON must reference {new!r} and one preceding table")
        if a_alias not in results or b_alias not in results:
            raise ValueError("ON predicate must reference joined tables")
        pairs = self._join_pairs(
            join, results[a_alias], a_col, results[b_alias], b_col,
            a_table=(tables or {}).get(a_alias))
        if flip and len(pairs):
            pairs = pairs[:, ::-1]

        from ..index.zkeys import multi_arange
        order = np.argsort(pairs[:, 0], kind="stable") if len(pairs) \
            else np.empty(0, np.int64)
        pa = pairs[order, 0] if len(pairs) else np.empty(0, np.int64)
        pb = pairs[order, 1] if len(pairs) else np.empty(0, np.int64)
        a_idx = rows[anchor]
        starts = np.searchsorted(pa, a_idx, side="left")
        ends = np.searchsorted(pa, a_idx, side="right")
        cnt = ends - starts
        cnt[a_idx < 0] = 0  # NULL-extended anchors match nothing
        out_cnt = np.maximum(cnt, 1) if join.outer else cnt
        rep = np.repeat(np.arange(len(a_idx), dtype=np.int64), out_cnt)
        total = int(out_cnt.sum())
        new_idx = np.full(total, -1, dtype=np.int64)
        off = np.cumsum(out_cnt) - out_cnt
        has = cnt > 0
        if has.any():
            dest = multi_arange(off[has], off[has] + cnt[has])
            src = multi_arange(starts[has], ends[has])
            new_idx[dest] = pb[src]
        out = {k: v[rep] for k, v in rows.items()}
        out[new] = new_idx
        return out

    def _device_xy(self, table: str, res, a_col: str):
        """The store's resident device coordinate columns for a query
        result that covers the FULL table in row order — lets the join
        kernels skip re-uploading coordinates (at 10M+ rows the
        host->device transfer costs more than the scan). Returns None
        when the result is a subset or the store has no resident point
        scan data."""
        from ..store.memory import InMemoryDataStore
        ds = self.store
        if not isinstance(ds, InMemoryDataStore):
            return None
        try:
            st = ds._state(table)
        except KeyError:
            return None
        if res.n != st.n or not st.sft.is_points:
            return None
        if a_col != st.sft.geom_field:
            return None  # scan_data holds the DEFAULT geometry only
        st.ensure_index()
        sd = st.scan_data
        if sd is None:
            return None
        return sd.xhi, sd.yhi

    def _join_count(self, join: SqlJoin, a_res, a_col: str,
                    b_res, b_col: str, a_table: str | None = None) -> int:
        """Total match count for one inner join WITHOUT materializing
        pairs: the count-reduce form of the device kernels, fed the
        store's resident coordinates when the side covers a full table
        (SELECT COUNT(*) FROM a JOIN b ON ... never pulls an (n, k)
        matrix to the host)."""
        if (a_res.n == 0 or b_res.n == 0
                or a_res.batch is None or b_res.batch is None):
            return 0
        from ..analytics.join import contains_join, dwithin_join
        if join.kind == "dwithin":
            ax, ay = _centroids(a_res.batch, a_col)
            bx, by = _centroids(b_res.batch, b_col)
            dev = (self._device_xy(a_table, a_res, a_col)
                   if a_table is not None else None)
            counts, _ = dwithin_join(ax, ay, bx, by, join.distance,
                                     counts_only=True, device_xy=dev)
        else:
            acol = a_res.batch.col(a_col)
            if not isinstance(acol, GeometryColumn):
                raise ValueError("contains join needs a polygon column "
                                 "as the first ON argument")
            bx, by = _centroids(b_res.batch, b_col)
            counts, _ = contains_join(acol.geoms, bx, by,
                                      counts_only=True)
        return int(counts.sum())

    def _join_pairs(self, join: SqlJoin, a_res, a_col: str,
                    b_res, b_col: str, a_table: str | None = None
                    ) -> np.ndarray:
        """(a_row, b_row) match pairs in ON-argument order, from the
        tiled device join kernels (analytics/join.py)."""
        if (a_res.n == 0 or b_res.n == 0
                or a_res.batch is None or b_res.batch is None):
            return np.empty((0, 2), dtype=np.int64)
        from ..analytics.join import contains_join, dwithin_join
        if join.kind == "eq":
            pairs = _equi_pairs(a_res.batch.col(a_col),
                                b_res.batch.col(b_col))
        elif join.kind == "dwithin":
            ax, ay = _centroids(a_res.batch, a_col)
            bx, by = _centroids(b_res.batch, b_col)
            # two LARGE sides: route through grid/quadtree spatial
            # partitioning (SpatialJoinStrategy -> zipPartitions,
            # SQLRules.scala:270, GeoMesaSparkSQL.scala:312-360) — the
            # direct kernel's work is O(|a| x |b|) and stops scaling
            # once both sides are big; per-cell joins bound it to
            # near-matching pairs
            if (len(ax) * len(bx) > _PARTITION_PAIR_BUDGET
                    and min(len(ax), len(bx)) > _PARTITION_MIN_SIDE):
                from ..analytics.partitioning import \
                    partitioned_dwithin_join
                pairs = partitioned_dwithin_join(ax, ay, bx, by,
                                                 join.distance)
            else:
                dev = (self._device_xy(a_table, a_res, a_col)
                       if a_table is not None else None)
                _, pairs = dwithin_join(ax, ay, bx, by, join.distance,
                                        device_xy=dev)
            # dwithin_join pairs are (a_idx, b_idx)
        else:
            # ST_Contains(a, b): a (polygons) contains b (points)
            acol = a_res.batch.col(a_col)
            if not isinstance(acol, GeometryColumn):
                raise ValueError("contains join needs a polygon column "
                                 "as the first ON argument")
            bx, by = _centroids(b_res.batch, b_col)
            _, pairs = contains_join(acol.geoms, bx, by)
            # contains_join pairs are (point_idx, poly_idx) = (b, a)
            if len(pairs):
                pairs = pairs[:, ::-1]
        if not len(pairs):
            return np.empty((0, 2), dtype=np.int64)
        return pairs

    def _post_join_mask(self, f: ast.Filter, res,
                        idx: np.ndarray) -> np.ndarray:
        """WHERE conjunct on a LEFT join's right side, applied after
        NULL extension: matched rows evaluate normally, NULL-extended
        rows take the conjunct's three-valued truth on an all-NULL row
        (only IS NULL-style predicates survive)."""
        from ..filters.evaluate import evaluate
        keep = np.zeros(len(idx), dtype=bool)
        matched = idx >= 0
        if matched.any() and res.batch is not None:
            row_ok = np.asarray(evaluate(f, res.batch), dtype=bool)
            keep[matched] = row_ok[idx[matched]]
        keep[~matched] = _null_truth(f) is True
        return keep

    def _project_join(self, sel: SqlSelect, results,
                      rows: dict[str, np.ndarray]) -> SqlResult:
        # scalar ST_* calls project per-row, like plain columns
        aggs = [i for i in sel.items if i.agg and i.agg != "st"]
        nrows = len(next(iter(rows.values()))) if rows else 0
        if aggs:
            if any(not i.agg or i.agg == "st" for i in sel.items):
                raise ValueError("cannot mix aggregates and plain "
                                 "columns without GROUP BY")
            # one implicit group over every joined row: the same
            # segment reduces the grouped path uses (COUNT/SUM/MIN/
            # MAX/AVG/convex_hull, NULL-extended rows skipped)
            cols = {}
            ginv = np.zeros(nrows, dtype=np.int64)
            for it in aggs:
                if it.expr == "*":
                    cols[it.name] = np.array([nrows])
                    continue
                if "." not in it.expr:
                    raise ValueError(
                        f"join columns must be qualified: {it.expr}")
                q, col = it.expr.split(".", 1)
                if q not in rows:
                    raise ValueError(f"unknown table qualifier {q!r}")
                idx = rows[q]
                if col in ("__fid__", "id"):
                    if it.agg != "count":
                        raise ValueError(f"cannot {it.agg} feature ids")
                    cols[it.name] = np.array([int((idx >= 0).sum())])
                    continue
                c = results[q].batch.col(col)
                cols[it.name] = self._reduce_item(it, ginv, 1, c, idx)
            return SqlResult([it.name for it in aggs], cols)
        names, cols = [], {}

        def add(name, arr):
            names.append(name)
            cols[name] = arr

        star = any(i.expr == "*" for i in sel.items)
        items = sel.items
        if star:
            items = [SelectItem(f"{a}.__fid__") for a in rows]
        for it in items:
            if "." not in it.expr:
                raise ValueError(f"join columns must be qualified: {it.expr}")
            q, col = it.expr.split(".", 1)
            if q not in rows:
                raise ValueError(f"unknown table qualifier {q!r} "
                                 f"(tables: {list(rows)})")
            res, idx = results[q], rows[q]
            out = np.empty(len(idx), dtype=object)
            m = idx >= 0
            if col in ("__fid__", "id"):
                out[m] = res.ids[idx[m]]
            else:
                c = res.batch.col(col)
                out[m] = [c.value(int(i)) for i in idx[m]]
            if it.agg == "st":
                from ..analytics.st_functions import SQL_SCALARS
                fn = SQL_SCALARS[it.fn]
                out = np.array([None if v is None else fn(v, *it.args)
                                for v in out], dtype=object)
            add(it.name if it.alias else it.expr, out)
        result = SqlResult(names, cols)
        order = sel.order_by
        return _order_limit(result, order, sel.order_desc, sel.limit)
