"""SQL execution: pushdown into the planner + device spatial joins.

Mirrors the reference's two catalyst rules
(/root/reference/geomesa-spark/geomesa-spark-sql/src/main/scala/org/
apache/spark/sql/SQLRules.scala):

- STContainsRule (:99): spatial/attribute predicates in WHERE are
  rewritten to Filter AST at parse time and handed to the store's
  planner as a Query — the same cost-based index selection the ECQL
  path gets, so `SELECT ... WHERE ST_Contains(...)` and the equivalent
  ECQL text produce identical plans and identical feature IDs.
- SpatialJoinStrategy (:270): `JOIN b ON ST_DWithin/ST_Contains/
  ST_Intersects` routes to the tiled device join kernels
  (analytics/join.py) instead of a nested-loop evaluation, with
  single-side WHERE conjuncts pushed below the join
  (GeoMesaJoinRelation.buildScan:312-360).

Aggregates (COUNT/MIN/MAX/SUM/AVG) reduce over the query result
columns; ORDER BY / LIMIT push into Query.sort_by / max_features.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterator

import numpy as np

from ..features.batch import (FeatureBatch, GeometryColumn, PointColumn)
from ..filters import ast
from ..index.api import Query
from .parser import SelectItem, SqlJoin, SqlSelect, parse_sql

__all__ = ["SqlEngine", "SqlResult"]


@dataclasses.dataclass
class SqlResult:
    """Columnar result table."""
    names: list[str]
    columns: dict[str, np.ndarray]

    @property
    def n(self) -> int:
        return 0 if not self.names else len(self.columns[self.names[0]])

    def rows(self) -> Iterator[tuple]:
        cols = [self.columns[n] for n in self.names]
        for i in range(self.n):
            yield tuple(c[i] for c in cols)

    def column(self, name: str) -> np.ndarray:
        return self.columns[name]


def _strip_qualifier(f: ast.Filter, alias: str) -> ast.Filter:
    """Rewrite 'alias.col' props to 'col' for single-table execution."""
    def fix(name: str) -> str:
        if "." in name:
            q, col = name.split(".", 1)
            if q != alias:
                raise ValueError(f"unknown table qualifier {q!r}")
            return col
        return name
    return _map_props(f, fix)


def _map_props(f: ast.Filter, fix) -> ast.Filter:
    if isinstance(f, (ast.And, ast.Or)):
        return type(f)([_map_props(c, fix) for c in f.children])
    if isinstance(f, ast.Not):
        return ast.Not(_map_props(f.child, fix))
    if hasattr(f, "prop"):
        # every Filter node (incl. SpatialPredicate subclasses, which
        # inherit the parent's dataclass fields) is a dataclass
        return dataclasses.replace(f, prop=fix(f.prop))
    return f


def _qualifier_of(f: ast.Filter) -> set[str]:
    """Table qualifiers referenced by the filter (empty = unqualified)."""
    out: set[str] = set()
    for node in ast.walk(f):
        prop = getattr(node, "prop", None)
        if prop and "." in prop:
            out.add(prop.split(".", 1)[0])
        elif prop:
            out.add("")
    return out


def _centroids(batch: FeatureBatch, geom_field: str):
    col = batch.col(geom_field)
    if isinstance(col, PointColumn):
        return col.x, col.y
    b = col.bounds
    return (b[:, 0] + b[:, 2]) / 2, (b[:, 1] + b[:, 3]) / 2


class SqlEngine:
    """Executes SELECTs against one datastore's feature types."""

    def __init__(self, store):
        self.store = store

    def query(self, text: str) -> SqlResult:
        sel = parse_sql(text)
        if sel.join is not None:
            return self._join_query(sel)
        return self._single_table(sel)

    # -- single table ------------------------------------------------------

    def _single_table(self, sel: SqlSelect) -> SqlResult:
        where = (_strip_qualifier(sel.where, sel.alias)
                 if sel.where is not None else ast.Include())
        aggs = [i for i in sel.items if i.agg]
        plain = [i for i in sel.items if not i.agg]
        if aggs and plain:
            raise ValueError("cannot mix aggregates and plain columns "
                             "(no GROUP BY support)")
        order = sel.order_by
        if order and "." in order:
            order = order.split(".", 1)[1]
        q = Query(sel.table, where,
                  sort_by=None if aggs else order,
                  sort_desc=sel.order_desc,
                  max_features=None if aggs else sel.limit)
        res = self.store.query(q)
        if aggs:
            return self._aggregate(aggs, res.batch, res.n)
        return self._project(plain, res.batch, res.ids, sel.alias)

    def _aggregate(self, items: list[SelectItem], batch, n: int) -> SqlResult:
        names, cols = [], {}
        for it in items:
            name = it.name
            names.append(name)
            if it.agg == "count" and it.expr == "*":
                cols[name] = np.array([n], dtype=np.int64)
                continue
            col = batch.col(it.expr.split(".")[-1]) if batch else None
            if it.agg == "count":
                # COUNT(col) skips nulls (SQL semantics)
                cols[name] = np.array(
                    [0 if col is None else int(col.valid.sum())],
                    dtype=np.int64)
                continue
            if col is None or n == 0:
                cols[name] = np.array([None], dtype=object)
                continue
            vals = getattr(col, "values", None)
            if vals is None:
                vals = getattr(col, "millis", None)
            if vals is None:
                raise ValueError(f"cannot aggregate column {it.expr}")
            vals = vals[col.valid]
            fn = {"min": np.min, "max": np.max, "sum": np.sum,
                  "avg": np.mean}[it.agg]
            cols[name] = np.array([fn(vals) if len(vals) else None])
        return SqlResult(names, cols)

    def _project(self, items: list[SelectItem], batch, ids,
                 alias: str) -> SqlResult:
        if batch is None:
            return SqlResult(["__fid__"], {"__fid__": np.empty(0, object)})
        names: list[str] = []
        cols: dict[str, np.ndarray] = {}

        def add(name: str, arr):
            names.append(name)
            cols[name] = arr

        star = any(i.expr == "*" for i in items)
        if star:
            add("__fid__", ids)
            for a in batch.sft.attributes:
                c = batch.col(a.name)
                add(a.name, np.array([c.value(i) for i in range(c.n)],
                                     dtype=object))
            return SqlResult(names, cols)
        for it in items:
            col_name = it.expr.split(".")[-1] if "." in it.expr else it.expr
            if col_name in ("__fid__", "id"):
                add(it.name, ids)
                continue
            c = batch.col(col_name)
            add(it.name, np.array([c.value(i) for i in range(c.n)],
                                  dtype=object))
        return SqlResult(names, cols)

    # -- joins -------------------------------------------------------------

    def _join_query(self, sel: SqlSelect) -> SqlResult:
        join = sel.join
        left_alias, right_alias = sel.alias, join.alias
        # push single-side WHERE conjuncts below the join
        left_f: list[ast.Filter] = []
        right_f: list[ast.Filter] = []
        if sel.where is not None:
            conjuncts = (list(sel.where.children)
                         if isinstance(sel.where, ast.And) else [sel.where])
            for c in conjuncts:
                quals = _qualifier_of(c)
                if quals <= {left_alias}:
                    left_f.append(_strip_qualifier(c, left_alias))
                elif quals <= {right_alias}:
                    right_f.append(_strip_qualifier(c, right_alias))
                else:
                    raise ValueError(
                        "WHERE conjuncts must reference one side only")

        def side(table, fs):
            f = (ast.And(fs) if len(fs) > 1 else fs[0]) if fs \
                else ast.Include()
            return self.store.query(Query(table, f))

        lres = side(sel.table, left_f)
        rres = side(join.table, right_f)
        if lres.batch is None or rres.batch is None \
                or lres.n == 0 or rres.n == 0:
            pairs = np.empty((0, 2), dtype=np.int64)
        else:
            pairs = self._join_pairs(sel, join, lres, rres)
        return self._project_join(sel, lres, rres, pairs,
                                  left_alias, right_alias)

    def _join_pairs(self, sel: SqlSelect, join: SqlJoin, lres, rres):
        """Pairs (left_row, right_row) from the device join kernels."""
        from ..analytics.join import contains_join, dwithin_join
        a_alias, a_col = join.left_prop.split(".", 1)   # first ON arg
        b_alias, b_col = join.right_prop.split(".", 1)  # second ON arg
        sides = {sel.alias: lres, join.alias: rres}
        if a_alias not in sides or b_alias not in sides:
            raise ValueError("ON predicate must reference both tables")
        a_res, b_res = sides[a_alias], sides[b_alias]
        a_is_left = a_alias == sel.alias

        if join.kind == "dwithin":
            ax, ay = _centroids(a_res.batch, a_col)
            bx, by = _centroids(b_res.batch, b_col)
            _, pairs = dwithin_join(ax, ay, bx, by, join.distance)
            # dwithin_join pairs are (a_idx, b_idx)
        else:
            # ST_Contains(a, b): a (polygons) contains b (points)
            acol = a_res.batch.col(a_col)
            if not isinstance(acol, GeometryColumn):
                raise ValueError("contains join needs a polygon column "
                                 "as the first ON argument")
            bx, by = _centroids(b_res.batch, b_col)
            _, pairs = contains_join(acol.geoms, bx, by)
            # contains_join pairs are (point_idx, poly_idx) = (b, a)
            if len(pairs):
                pairs = pairs[:, ::-1]
        if not a_is_left and len(pairs):
            pairs = pairs[:, ::-1]
        return pairs

    def _project_join(self, sel: SqlSelect, lres, rres, pairs,
                      la: str, ra: str) -> SqlResult:
        li = pairs[:, 0] if len(pairs) else np.empty(0, np.int64)
        ri = pairs[:, 1] if len(pairs) else np.empty(0, np.int64)
        aggs = [i for i in sel.items if i.agg]
        if aggs:
            if any(i.agg != "count" for i in aggs):
                raise ValueError("join aggregates support COUNT only")
            return SqlResult([aggs[0].name],
                             {aggs[0].name: np.array([len(pairs)])})
        names, cols = [], {}

        def add(name, arr):
            names.append(name)
            cols[name] = arr

        star = any(i.expr == "*" for i in sel.items)
        items = sel.items
        if star:
            items = [SelectItem(f"{la}.__fid__"), SelectItem(f"{ra}.__fid__")]
        for it in items:
            if "." not in it.expr:
                raise ValueError(f"join columns must be qualified: {it.expr}")
            q, col = it.expr.split(".", 1)
            if q == la:
                res, idx = lres, li
            elif q == ra:
                res, idx = rres, ri
            else:
                raise ValueError(f"unknown table qualifier {q!r} "
                                 f"(tables: {la!r}, {ra!r})")
            if col in ("__fid__", "id"):
                add(it.name if it.alias else it.expr, res.ids[idx])
            else:
                c = res.batch.col(col)
                add(it.name if it.alias else it.expr,
                    np.array([c.value(int(i)) for i in idx], dtype=object))
        out = SqlResult(names, cols)
        if sel.limit is not None and out.n > sel.limit:
            out = SqlResult(names, {k: v[:sel.limit]
                                    for k, v in cols.items()})
        return out
