"""Primary-side WAL shipper: stream log records to replicas over TCP.

One ``WalShipper`` embeds in the primary process next to its
``Journal``. Replicas connect and speak a tiny length-prefixed frame
protocol (the SocketBus framing — JSON header + raw payload):

- ``{"op": "hello"}`` -> the primary's log coordinates
  (``last_lsn`` / ``durable_lsn`` / ``oldest_lsn`` /
  ``checkpoint_lsn``), so the replica can decide between streaming
  catch-up and checkpoint bootstrap;
- ``{"op": "manifest"}`` -> the newest checkpoint's MANIFEST.json
  content (``{"lsn": 0}`` when none exists);
- ``{"op": "fetch_ckpt", "lsn": L, "file": name}`` -> that checkpoint
  file's bytes as the frame payload (pinned by LSN so a concurrent
  newer checkpoint + retention pass can't swap files mid-bootstrap);
- ``{"op": "stream", "from_lsn": N}`` -> the connection turns into a
  one-way record feed: ``{"lsn", "kind", "last_lsn", "durable_lsn"}``
  headers with the raw WAL payload, heartbeat frames
  (``{"heartbeat": true, ...}``) every poll tick while idle, or a
  terminal ``{"error": "compacted", ...}`` when ``from_lsn`` has been
  truncated away (the replica must re-bootstrap).

The shipper tails the live ``WriteAheadLog`` via ``records(from_lsn)``
— segment skipping makes each tail iteration O(segments past the
cursor) — and only rescans when ``last_lsn`` has actually advanced.
Records are shipped as written, durable or not; the ACK boundary
(which writes survive failover) is enforced by the router, which
compares a write's durable LSN against replica applied LSNs.
"""

from __future__ import annotations

import json
import os
import socketserver
import threading
import time

from ..metrics import metrics
from ..store.socketbus import ProtocolError, _recv_frame, _send_frame
from ..utils.properties import SystemProperty
from ..wal.log import list_segments
from ..wal.snapshot import checkpoint_dirs

__all__ = ["WalShipper", "REPL_POLL_MS"]

# how often a streaming connection polls the WAL for new records (also
# the heartbeat cadence while idle)
REPL_POLL_MS = SystemProperty("geomesa.repl.poll.ms", "20")


class WalShipper:
    """TCP server that ships a ``Journal``'s WAL to replicas.

    ``journal`` is the primary store's journal (``store.journal``);
    the shipper reads its WAL and serves checkpoint files from the same
    durable root. Start is implicit in construction; ``stop()`` closes
    the listener and every streaming connection.
    """

    def __init__(self, journal, host: str = "127.0.0.1", port: int = 0,
                 poll_ms: float | None = None, store=None,
                 registry=metrics):
        self.journal = journal
        self.wal = journal.wal
        self.root = journal.root
        # optional: the primary store itself, enabling the ``digest``
        # anti-entropy op (per-type row-count + content digest)
        self.store = store
        self.poll_s = ((REPL_POLL_MS.as_float() or 20.0)
                       if poll_ms is None else float(poll_ms)) / 1e3
        self._registry = registry
        self._stopped = threading.Event()
        self._conns: set = set()
        self._conns_lock = threading.Lock()

        shipper = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                with shipper._conns_lock:
                    shipper._conns.add(self.request)
                shipper._registry.counter("replication.ship.connections")
                try:
                    self.request.settimeout(30.0)
                    shipper._serve(self.request)
                except (ConnectionError, TimeoutError, OSError,
                        ProtocolError, json.JSONDecodeError):
                    pass  # peer gone or garbage: drop the connection
                finally:
                    with shipper._conns_lock:
                        shipper._conns.discard(self.request)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.05},
            name=f"wal-shipper:{self.port}", daemon=True)
        self._thread.start()

    # -- per-connection protocol -------------------------------------------

    def _serve(self, sock):
        while not self._stopped.is_set():
            header, _payload = _recv_frame(sock)
            op = header.get("op")
            if op == "hello":
                _send_frame(sock, self._coords())
            elif op == "manifest":
                _send_frame(sock, self._manifest())
            elif op == "fetch_ckpt":
                self._fetch_ckpt(sock, header)
            elif op == "digest":
                _send_frame(sock, self._digest())
            elif op == "stream":
                self._stream(sock, int(header.get("from_lsn", 1)))
                return  # streaming is terminal for the connection
            else:
                _send_frame(sock, {"error": f"unknown op {op!r}"})
                return

    def _coords(self) -> dict:
        segs = list_segments(self.wal.root)
        oldest = segs[0][0] if segs else self.wal.next_lsn
        ckpts = checkpoint_dirs(self.root)
        return {"last_lsn": self.wal.last_lsn,
                "durable_lsn": self.wal.durable_lsn,
                "oldest_lsn": oldest,
                "checkpoint_lsn": ckpts[-1][0] if ckpts else 0}

    def _manifest(self) -> dict:
        ckpts = checkpoint_dirs(self.root)
        if not ckpts:
            return {"lsn": 0, "types": []}
        _lsn, path = ckpts[-1]
        with open(os.path.join(path, "MANIFEST.json")) as f:
            return json.load(f)

    def _digest(self) -> dict:
        """Anti-entropy unit: per-type ``{rows, digest}`` bracketed by
        the WAL position before and after the computation. Only when
        the two LSNs agree (no concurrent writes) AND match the
        replica's applied LSN is the comparison meaningful — the
        replica-side scrubber enforces that."""
        if self.store is None:
            return {"error": "digest unavailable (shipper has no store)"}
        from ..integrity.verify import ids_digest
        pre = self.wal.last_lsn
        types = {}
        for name in self.store.get_type_names():
            rows, digest = ids_digest(self.store, name)
            types[name] = {"rows": rows, "digest": digest}
        return {"last_lsn_pre": pre, "last_lsn": self.wal.last_lsn,
                "types": types}

    def _fetch_ckpt(self, sock, header: dict):
        lsn = int(header.get("lsn", 0))
        name = os.path.basename(str(header.get("file", "")))
        path = os.path.join(self.root, "snapshots", f"ckpt-{lsn:020d}", name)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            # retention dropped this checkpoint mid-bootstrap: tell the
            # replica to restart from the (newer) manifest
            _send_frame(sock, {"error": "gone", "lsn": lsn, "file": name})
            return
        _send_frame(sock, {"bytes": len(raw)}, raw)

    def _stream(self, sock, from_lsn: int):
        segs = list_segments(self.wal.root)
        oldest = segs[0][0] if segs else self.wal.next_lsn
        if from_lsn < oldest:
            # records below `oldest` were checkpoint-truncated: the
            # replica's cursor points into compacted history
            ckpts = checkpoint_dirs(self.root)
            _send_frame(sock, {"error": "compacted", "oldest_lsn": oldest,
                               "checkpoint_lsn": ckpts[-1][0] if ckpts else 0})
            return
        cursor = from_lsn
        while not self._stopped.is_set():
            if self.wal.last_lsn >= cursor:
                for lsn, kind, payload in self.wal.records(cursor):
                    if self._stopped.is_set():
                        return
                    _send_frame(sock,
                                {"lsn": lsn, "kind": kind,
                                 "last_lsn": self.wal.last_lsn,
                                 "durable_lsn": self.wal.durable_lsn},
                                payload)
                    cursor = lsn + 1
                    self._registry.counter("replication.shipped.records")
                    self._registry.counter("replication.shipped.bytes",
                                           len(payload))
                continue  # re-check before sleeping: more may have landed
            _send_frame(sock, {"heartbeat": True,
                               "last_lsn": self.wal.last_lsn,
                               "durable_lsn": self.wal.durable_lsn})
            if self._stopped.wait(self.poll_s):
                return

    # -- lifecycle / admin --------------------------------------------------

    def status(self) -> dict:
        with self._conns_lock:
            n = len(self._conns)
        return {"role": "primary", "address": f"{self.host}:{self.port}",
                "connections": n, **self._coords()}

    def stop(self):
        self._stopped.set()
        self._server.shutdown()
        self._server.server_close()
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        self._thread.join(timeout=5.0)
