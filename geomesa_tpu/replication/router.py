"""ReplicatedDataStore: one store façade over a primary + N replicas.

Routing rules:

- WRITES go to the primary, then block until ``geomesa.repl.ack.
  replicas`` replicas have applied the write's LSN (bounded by
  ``geomesa.repl.ack.timeout.s``). A write that returns is
  *acknowledged*: its LSN is inside at least that many replica
  prefixes, so promotion of the most-caught-up replica can never
  lose it.
- READS fan across replicas round-robin under per-query staleness
  bounds — a replica is eligible when its LSN lag against the best
  known primary position is <= ``max_lag_lsn`` AND it was fully caught
  up within the last ``max_lag_s`` seconds AND its breaker admits the
  call. No eligible replica -> the primary serves the read (the
  bounded-staleness contract: results are never older than the bound,
  they just cost primary capacity).
- FAILOVER: a health probe against the primary (run every
  ``geomesa.repl.probe.ms``) that fails ``geomesa.repl.probe.failures``
  times in a row triggers promotion (when ``geomesa.repl.promote.auto``)
  of the attached replica with the highest applied LSN. Remaining
  replicas are detached — they were following a dead primary and
  cannot converge with the new one; re-attach requires a shipper on
  the new primary.

Per-replica read failures feed a ``BreakerBoard`` (and its latency
EWMA), so a wedged replica fast-fails out of the rotation the same way
a dead REST endpoint does.
"""

from __future__ import annotations

import threading
import time

from ..metrics import metrics
from ..resilience.breaker import BreakerBoard, CircuitOpenError
from ..store.api import DataStore
from ..utils.properties import SystemProperty
from .replica import Replica

__all__ = ["ReplicatedDataStore", "ReplicationAckTimeout",
           "ReplicationAckLost",
           "REPL_MAX_LAG_LSN", "REPL_MAX_LAG_S", "REPL_ACK_REPLICAS",
           "REPL_ACK_TIMEOUT_S", "REPL_PROMOTE_AUTO", "REPL_PROBE_MS",
           "REPL_PROBE_FAILURES"]

# default per-query staleness bounds (overridable per call)
REPL_MAX_LAG_LSN = SystemProperty("geomesa.repl.max.lag.lsn", "1000")
REPL_MAX_LAG_S = SystemProperty("geomesa.repl.max.lag.s", "10")
# how many replicas must hold a write before it is acknowledged
REPL_ACK_REPLICAS = SystemProperty("geomesa.repl.ack.replicas", "1")
REPL_ACK_TIMEOUT_S = SystemProperty("geomesa.repl.ack.timeout.s", "10")
# failure detector + promotion
REPL_PROMOTE_AUTO = SystemProperty("geomesa.repl.promote.auto", "true")
REPL_PROBE_MS = SystemProperty("geomesa.repl.probe.ms", "250")
REPL_PROBE_FAILURES = SystemProperty("geomesa.repl.probe.failures", "3")


class ReplicationAckTimeout(TimeoutError):
    """The primary accepted a write but too few replicas applied it in
    time. The write IS on the primary (and its WAL) — it is just not
    yet replication-acknowledged, so it may be lost if the primary
    fails before a replica catches up. Not retryable as-is: a blind
    retry would duplicate the write."""

    retryable = False


class ReplicationAckLost(ConnectionError):
    """A failover completed while this write awaited replication, and
    the promoted replica's applied prefix does NOT cover it: the write
    exists only on the deposed primary. Acking it would violate the
    zero-acked-loss contract — a zombie primary (listener gone, an
    established connection still accepting writes: the asymmetric
    partition a chaos kill produces) would otherwise keep collecting
    acks that the new primary never saw. Not retryable blindly: the
    old primary may still hold the write, so a retry after it rejoins
    could duplicate."""

    retryable = False


class ReplicatedDataStore(DataStore):
    """Primary + replicas behind one DataStore surface.

    ``primary`` is any DataStore (typically durable, with a
    ``WalShipper`` next to it — possibly reached via RemoteDataStore);
    ``replicas`` are ``Replica`` instances attached to that shipper.
    ``probe`` is a zero-arg callable returning truthy when the primary
    is healthy; defaults to ``primary.probe_health`` when present
    (RemoteDataStore has one), else no probing.
    """

    def __init__(self, primary: DataStore, replicas=(),
                 probe=None, ack_replicas: int | None = None,
                 max_lag_lsn: int | None = None,
                 max_lag_s: float | None = None,
                 auto_promote: bool | None = None,
                 probe_ms: float | None = None,
                 probe_failures: int | None = None,
                 registry=metrics, audit=None):
        self.audit = audit  # AuditLogger or None (global fallback)
        self.primary = primary
        self._replicas: list[Replica] = list(replicas)
        self._registry = registry
        self._breakers = BreakerBoard(registry=registry)
        self.ack_replicas = (REPL_ACK_REPLICAS.as_int() or 0
                             if ack_replicas is None else int(ack_replicas))
        self.ack_timeout_s = REPL_ACK_TIMEOUT_S.as_float() or 10.0
        self.max_lag_lsn = (REPL_MAX_LAG_LSN.as_int()
                            if max_lag_lsn is None else int(max_lag_lsn))
        self.max_lag_s = (REPL_MAX_LAG_S.as_float()
                          if max_lag_s is None else float(max_lag_s))
        self._auto_promote = (REPL_PROMOTE_AUTO.as_bool()
                              if auto_promote is None else bool(auto_promote))
        self._probe_s = ((REPL_PROBE_MS.as_float() or 250.0)
                         if probe_ms is None else float(probe_ms)) / 1e3
        self._probe_failures = (REPL_PROBE_FAILURES.as_int() or 3
                                if probe_failures is None
                                else int(probe_failures))
        self._lock = threading.RLock()
        self._ack_cond = threading.Condition()
        self._last_write_lsn = 0
        self._rr = 0                     # round-robin cursor
        self._promoted_to: str | None = None
        # applied prefix of the promoted replica, frozen at the moment
        # its stream was cut: the durability watermark acks compare
        # against once a failover has happened (None = no failover yet,
        # or promotion in flight and the cut point is not known yet)
        self._promote_cutoff: int | None = None
        self._failover_s: float | None = None
        self._primary_healthy = True
        self._probe = probe if probe is not None else getattr(
            primary, "probe_health", None)
        for r in self._replicas:
            r.on_apply = self._on_replica_apply
        self._probe_stop = threading.Event()
        self._probe_thread: threading.Thread | None = None
        if self._probe is not None:
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="repl-probe", daemon=True)
            self._probe_thread.start()

    # -- replica bookkeeping -------------------------------------------------

    def _on_replica_apply(self, _replica):
        with self._ack_cond:
            self._ack_cond.notify_all()

    def _attached(self) -> list[Replica]:
        with self._lock:
            return [r for r in self._replicas if r.attached]

    def _primary_lsn_estimate(self) -> int:
        """Best known primary log position: the local journal when the
        primary is in-process, else the max of our own acked writes and
        what replicas heard in stream heartbeats."""
        best = self._last_write_lsn
        journal = getattr(self.primary, "journal", None)
        if journal is not None:
            best = max(best, journal.wal.last_lsn)
        with self._lock:
            for r in self._replicas:
                best = max(best, r.primary_last_lsn)
        return best

    # -- write path ----------------------------------------------------------

    def _write_lsn(self, returned) -> int | None:
        """The WAL position of the write just issued: the server-stamped
        LSN for remote primaries, the local journal tail otherwise."""
        if isinstance(returned, int):
            return returned
        journal = getattr(self.primary, "journal", None)
        if journal is not None:
            return journal.wal.last_lsn
        return None

    def _ack_state(self, lsn: int):
        """One consistent snapshot deciding an ack wait. Returns
        ``True`` (acked), ``False`` (keep waiting), or raises.
        Promotion is checked FIRST and under the same lock that
        ``promote()`` mutates: once a failover has begun, replica
        counts no longer mean anything (``_replicas`` is cleared), and
        a write is durable iff the promoted replica's frozen applied
        prefix covers its lsn — never because ``need`` degraded to 0."""
        with self._lock:
            promoted = self._promoted_to is not None
            cutoff = self._promote_cutoff
            attached = [r for r in self._replicas if r.attached]
        if promoted:
            if cutoff is None:
                return False  # promotion in flight; cut point pending
            if lsn <= cutoff:
                return True
            self._registry.counter("replication.ack.lost")
            raise ReplicationAckLost(
                f"write lsn {lsn} was on the deposed primary only: "
                f"failover promoted at applied lsn {cutoff}")
        need = min(self.ack_replicas, len(attached))
        if need <= 0:
            return True
        return sum(1 for r in attached if r.applied_lsn >= lsn) >= need

    def _await_ack(self, lsn: int | None):
        if not lsn:
            return
        with self._lock:
            self._last_write_lsn = max(self._last_write_lsn, lsn)
        if self._ack_state(lsn):
            return
        self._registry.counter("replication.ack.waits")
        deadline = time.monotonic() + self.ack_timeout_s
        with self._ack_cond:
            while True:
                if self._ack_state(lsn):
                    return
                left = deadline - time.monotonic()
                if left <= 0:
                    self._registry.counter("replication.ack.timeouts")
                    raise ReplicationAckTimeout(
                        f"write lsn {lsn}: not enough replicas applied "
                        f"within {self.ack_timeout_s}s")
                self._ack_cond.wait(left)

    def create_schema(self, sft, spec=None):
        out = self.primary.create_schema(sft, spec)
        self._await_ack(self._write_lsn(out))
        return out

    def remove_schema(self, type_name: str):
        out = self.primary.remove_schema(type_name)
        self._await_ack(self._write_lsn(out))
        return out

    def write(self, type_name: str, batch, **kwargs):
        out = self.primary.write(type_name, batch, **kwargs)
        self._await_ack(self._write_lsn(out))
        return out

    def delete(self, type_name: str, ids):
        out = self.primary.delete(type_name, ids)
        self._await_ack(self._write_lsn(out))
        return out

    # -- read path -----------------------------------------------------------

    def _eligible(self, max_lag_lsn, max_lag_s) -> list[Replica]:
        p_lsn = self._primary_lsn_estimate()
        out = []
        with self._lock:
            replicas = list(self._replicas)
            start = self._rr
            self._rr += 1
        for i in range(len(replicas)):
            r = replicas[(start + i) % len(replicas)]
            if not r.attached:
                continue
            if max_lag_lsn is not None and r.lag_lsn(p_lsn) > max_lag_lsn:
                continue
            if max_lag_s is not None and r.lag_s() > max_lag_s:
                continue
            out.append(r)
        return out

    def _read(self, op, *args, max_lag_lsn=None, max_lag_s=None, **kwargs):
        bound_lsn = self.max_lag_lsn if max_lag_lsn is None else max_lag_lsn
        bound_s = self.max_lag_s if max_lag_s is None else max_lag_s
        candidates = self._eligible(bound_lsn, bound_s)
        for r in candidates:
            breaker = self._breakers.get(r.name)
            try:
                breaker.acquire()
            except CircuitOpenError:
                continue
            t0 = time.perf_counter()
            try:
                out = getattr(r, op)(*args, **kwargs)
            except Exception:
                breaker.failure()
                continue
            breaker.success()
            self._breakers.observe(r.name, time.perf_counter() - t0)
            self._registry.counter("replication.reads.replica")
            return out
        # staleness bound violated everywhere (or every replica failed):
        # the primary is the freshness backstop
        self._registry.counter(
            "replication.reads.primary" if not self._replicas
            else "replication.reads.fallback")
        return getattr(self.primary, op)(*args, **kwargs)

    def query(self, q, type_name=None, explain_out=None,
              max_lag_lsn=None, max_lag_s=None):
        from ..audit import audit_query, delegated_scope
        t0 = time.perf_counter()
        with delegated_scope():
            out = self._read("query", q, type_name,
                             explain_out=explain_out,
                             max_lag_lsn=max_lag_lsn,
                             max_lag_s=max_lag_s)
        audit_query(self.audit, "replicated",
                    getattr(q, "type_name", None) or type_name or "",
                    str(getattr(q, "filter", q)),
                    getattr(q, "hints", {}) or {}, 0.0,
                    (time.perf_counter() - t0) * 1000,
                    int(getattr(out, "n", 0)), index="replicated")
        return out

    def query_stream(self, q, type_name=None, batch_rows=None,
                     max_lag_lsn=None, max_lag_s=None):
        """Streamed read through the same bounded-staleness routing:
        the chosen member's batch generator is returned as-is. Errors
        *opening* the stream fail over to the next eligible member;
        mid-stream errors surface typed to the consumer (failing over
        mid-stream could re-deliver rows)."""
        return self._read("query_stream", q, type_name,
                          batch_rows=batch_rows,
                          max_lag_lsn=max_lag_lsn, max_lag_s=max_lag_s)

    def query_count(self, q, type_name=None,
                    max_lag_lsn=None, max_lag_s=None) -> int:
        from ..audit import audit_query, delegated_scope
        t0 = time.perf_counter()
        with delegated_scope():
            n = self._read("query_count", q, type_name,
                           max_lag_lsn=max_lag_lsn, max_lag_s=max_lag_s)
        audit_query(self.audit, "replicated",
                    getattr(q, "type_name", None) or type_name or "",
                    str(getattr(q, "filter", q)),
                    getattr(q, "hints", {}) or {}, 0.0,
                    (time.perf_counter() - t0) * 1000, int(n),
                    index="replicated")
        return n

    def count(self, type_name: str,
              max_lag_lsn=None, max_lag_s=None) -> int:
        return self._read("count", type_name,
                          max_lag_lsn=max_lag_lsn, max_lag_s=max_lag_s)

    # aggregate scans ride the same bounded-staleness fan-out: the
    # cluster tier scatters these per shard group, and a replica that
    # satisfies the lag bound serves them exactly (sketches/grids/bin
    # chunks over its applied prefix)
    def stats_query(self, type_name: str, stat_spec: str, ecql=None,
                    max_lag_lsn=None, max_lag_s=None):
        return self._read("stats_query", type_name, stat_spec, ecql,
                          max_lag_lsn=max_lag_lsn, max_lag_s=max_lag_s)

    def density(self, type_name: str, ecql, bbox, width: int, height: int,
                weight_attr: str | None = None,
                max_lag_lsn=None, max_lag_s=None):
        kwargs = {} if weight_attr is None else {"weight_attr": weight_attr}
        return self._read("density", type_name, ecql, bbox, width, height,
                          max_lag_lsn=max_lag_lsn, max_lag_s=max_lag_s,
                          **kwargs)

    def bin_query(self, type_name: str, ecql, track: str | None = None,
                  label: str | None = None, sort: bool = False,
                  max_lag_lsn=None, max_lag_s=None) -> bytes:
        return self._read("bin_query", type_name, ecql, track=track,
                          label=label, sort=sort,
                          max_lag_lsn=max_lag_lsn, max_lag_s=max_lag_s)

    def arrow_ipc(self, type_name: str, ecql="INCLUDE",
                  sort_by: str | None = None,
                  max_lag_lsn=None, max_lag_s=None) -> bytes:
        return self._read("arrow_ipc", type_name, ecql, sort_by=sort_by,
                          max_lag_lsn=max_lag_lsn, max_lag_s=max_lag_s)

    # -- materialized-cache faces (aggregate view over the group) ------------
    # No ``pushdown_version`` here on purpose: reads fan out to whichever
    # member satisfies the lag bound, so there is no single exact version
    # to stamp an ETag with — that face stays on the members.

    def cache_status(self) -> dict:
        members: dict[str, dict] = {}
        cs = getattr(self.primary, "cache_status", None)
        if callable(cs):
            try:
                members["primary"] = cs()
            except Exception as ex:  # remote primary may be down
                members["primary"] = {"error": str(ex)}
        for r in self._replicas:
            try:
                members[r.name] = r.cache_status()
            except Exception as ex:
                members[r.name] = {"error": str(ex)}
        return {"role": "replicated", "max_lag_lsn": self.max_lag_lsn,
                "members": members}

    def invalidate_cache(self, type_name: str | None = None) -> int:
        n = 0
        inv = getattr(self.primary, "invalidate_cache", None)
        if callable(inv):
            try:
                n += int(inv(type_name))
            except Exception:
                pass
        for r in self._replicas:
            try:
                n += int(r.invalidate_cache(type_name))
            except Exception:
                pass
        return n

    def get_schema(self, type_name: str):
        try:
            return self.primary.get_schema(type_name)
        except (ConnectionError, TimeoutError, OSError):
            return self._read("get_schema", type_name,
                              max_lag_lsn=None, max_lag_s=None)

    def get_type_names(self) -> list[str]:
        try:
            return self.primary.get_type_names()
        except (ConnectionError, TimeoutError, OSError):
            return self._read("get_type_names",
                              max_lag_lsn=None, max_lag_s=None)

    # -- failover ------------------------------------------------------------

    def _probe_loop(self):
        fails = 0
        first_fail_at = 0.0
        while not self._probe_stop.is_set():
            if self._probe_stop.wait(self._probe_s):
                return
            try:
                ok = bool(self._probe())
            except Exception:
                ok = False
            self._primary_healthy = ok
            if ok:
                fails = 0
                continue
            if fails == 0:
                first_fail_at = time.monotonic()
            fails += 1
            if fails >= self._probe_failures and self._auto_promote:
                try:
                    self.promote()
                finally:
                    with self._lock:
                        self._failover_s = time.monotonic() - first_fail_at
                    self._registry.gauge("replication.failover.seconds",
                                         self._failover_s)
                return  # the probed primary is gone; detector's job done

    def promote(self, name: str | None = None) -> dict:
        """Promote the most-caught-up attached replica (or the one
        called ``name``) to primary. Detaches the rest. Idempotent per
        failover: a second call with no attached replicas raises."""
        with self._lock:
            candidates = [r for r in self._replicas if r.attached]
            if name is not None:
                candidates = [r for r in candidates if r.name == name]
            if not candidates:
                raise ValueError("no attached replica to promote")
            best = max(candidates, key=lambda r: r.applied_lsn)
            others = [r for r in self._replicas if r is not best]
            self._replicas = []
            self._promoted_to = best.name
            self._primary_healthy = True
        self._probe_stop.set()
        best.promote()
        # the stream is cut: best's applied prefix is now final. Freeze
        # it as the ack watermark and wake waiters BEFORE the slow
        # detach joins — pending acks must resolve against the cutoff,
        # not hang behind replica thread teardown.
        with self._lock:
            self._promote_cutoff = best.applied_lsn
        self.primary = best
        with self._ack_cond:
            self._ack_cond.notify_all()
        for r in others:
            r.stop()
        with self._ack_cond:
            self._ack_cond.notify_all()  # release waiters to re-check
        self._registry.counter("replication.failovers")
        return {"promoted": best.name, "applied_lsn": best.applied_lsn,
                "detached": [r.name for r in others]}

    # -- admin ---------------------------------------------------------------

    def replication_status(self) -> dict:
        p_lsn = self._primary_lsn_estimate()
        with self._lock:
            replicas = list(self._replicas)
            promoted = self._promoted_to
            failover_s = self._failover_s
        entries = []
        for r in replicas:
            st = r.status()
            st["lag_lsn"] = r.lag_lsn(p_lsn)
            st["breaker"] = self._breakers.get(r.name).state
            st["eligible"] = (r.attached
                              and (self.max_lag_lsn is None
                                   or st["lag_lsn"] <= self.max_lag_lsn)
                              and (self.max_lag_s is None
                                   or r.lag_s() <= self.max_lag_s))
            self._registry.gauge(f"replication.lag.lsn.{r.name}",
                                 st["lag_lsn"])
            entries.append(st)
        self._registry.gauge("replication.replicas", len(replicas))
        out = {"role": "router",
               "primary": {"type": type(self.primary).__name__,
                           "healthy": self._primary_healthy,
                           "lsn": p_lsn},
               "ack_replicas": self.ack_replicas,
               "max_lag_lsn": self.max_lag_lsn,
               "max_lag_s": self.max_lag_s,
               "replicas": entries,
               "read_latency": self._breakers.latencies()}
        if promoted:
            out["promoted_to"] = promoted
            if failover_s is not None:
                out["failover_seconds"] = round(failover_s, 3)
        return out

    def close(self):
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=2.0)
        with self._lock:
            replicas = list(self._replicas)
        for r in replicas:
            r.stop()
        close = getattr(self.primary, "close", None)
        if callable(close):
            close()
