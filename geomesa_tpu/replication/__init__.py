"""Replication: WAL-shipped read replicas with bounded staleness.

The reference scales reads by delegating replication to its backing
key-value stores (Accumulo/HBase/Bigtable tablet replication) and by
the Lambda architecture's stream/persistent split; this rebuild owns
its storage tier, so replication is built here from the two subsystems
that already exist — the durability WAL (monotonic LSNs, checkpoint
manifests, idempotent redo) and the resilience layer (reconnect with
backoff, breakers, health probes):

- ``shipper.py``  — primary side: a TCP server that streams WAL
  records to replicas from a negotiated LSN and serves checkpoint
  files for bootstrap;
- ``sync.py``     — replica-side client: LSN negotiation, checkpoint
  bootstrap, streaming catch-up frames;
- ``replica.py``  — a read-only ``DataStore`` continuously applying
  the shipped records through the idempotent redo path;
- ``router.py``   — ``ReplicatedDataStore``: writes to the primary
  (acknowledged once replicated), reads fanned across replicas under
  per-query staleness bounds, promote-on-failure.

Emits ``replication.*`` metrics; admin surface on ``/rest/replication``
and ``tools replication status|promote``.
"""

from .replica import ReadOnlyReplicaError, Replica
from .router import ReplicatedDataStore, ReplicationAckTimeout
from .shipper import WalShipper
from .sync import ReplClient, bootstrap_from_checkpoint

__all__ = ["WalShipper", "Replica", "ReadOnlyReplicaError",
           "ReplicatedDataStore", "ReplicationAckTimeout",
           "ReplClient", "bootstrap_from_checkpoint"]
